"""Fully device-resident bandwidth saturation: the north-star composition.

BASELINE.json's north star names three terms to fuse into the device step:
topology latency (ops/round_step.py, fused), the interface token-bucket
bandwidth term (ops/bandwidth.py, exact twin), and queue admission.  This
module composes bucket pacing + drop-tail queue admission + the interface
refill-task lifetime into ONE device program with all state in HBM — the
same architectural end-state ops/phold_device.py demonstrates for the
scheduler, here for the bandwidth pipeline (reference hot path:
network_interface.c:421-455 receive loop, :121-183 self-suspending refill,
router_queue_static.c drop-tail).

The model is an EXACT twin of the engine's interface dynamics for
constant-bit-rate inbound flows (one packet of fixed size per 1 ms tick per
source), including the subtle parts:

* the refill task refills only while it is alive, and it stays alive
  exactly while the queue is non-empty after the tick's final drain
  (network_interface.py _has_pending_work / _ensure_refill_scheduled);
* a tick's arrival drains with PRE-refill tokens when the refill event
  shares its timestamp (the event order tuple puts the arrival first when
  the sender's host id is lower);
* whole-packet token spending (TokenBucket.try_consume) and drop-tail
  admission against a packet-capacity queue (StaticQueue).

tests/test_saturate_device.py pins this down three ways: bit-identical
device vs numpy twins, closed-form saturation rates, and — the strong one —
exact delivered/dropped counts against the REAL engine running a blast
source/sink pair through the full interface/router/socket stack.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core import defs
from .bandwidth import bucket_params


@jax.jit
def saturate_run(first_tick: jnp.ndarray,   # int64 [H] first arrival tick
                 n_pkts: jnp.ndarray,       # int64 [H] packets per flow
                 size: jnp.ndarray,         # int64 scalar: packet bytes
                 refill: jnp.ndarray,       # int64 [H] bytes per tick
                 capacity: jnp.ndarray,     # int64 [H] bucket cap bytes
                 qcap_pkts: jnp.ndarray,    # int64 scalar: queue capacity
                 ticks: jnp.ndarray,        # int64 scalar: tick count
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                            jnp.ndarray]:
    """Run the saturation model for ``ticks`` 1 ms ticks entirely on device.

    Per tick and host: one packet arrives while the flow is active
    (first_tick <= t < first_tick + n_pkts); drop-tail admission; drain
    with pre-refill tokens; if the refill task is alive, refill then drain
    again; the task stays alive iff the queue is non-empty afterwards.

    Returns (delivered, dropped, queue, tokens) per host.
    """
    h = first_tick.shape[0]

    def tick_body(t, state):
        tokens, queue, alive, delivered, dropped = state
        arr = ((t >= first_tick) & (t < first_tick + n_pkts)) \
            .astype(jnp.int64)
        # drop-tail admission (StaticQueue.enqueue).  ``queue`` here is the
        # TOTAL backlog; whenever it is non-empty the interface keeps one
        # peeked packet staged OUTSIDE the router queue
        # (router.py peek_deliverable), so the drop check sees queue-1 and
        # the effective capacity is qcap + 1.
        space = qcap_pkts + 1 - queue
        admit = jnp.minimum(arr, jnp.maximum(space, 0))
        dropped = dropped + (arr - admit)
        queue = queue + admit
        # arrival-triggered drain: pre-refill tokens (arrival orders before
        # the tick's refill event)
        n1 = jnp.minimum(queue, tokens // size)
        queue = queue - n1
        tokens = tokens - n1 * size
        delivered = delivered + n1
        # refill task fires only while alive; drains again after refilling
        tok_ref = jnp.minimum(capacity, tokens + refill)
        tokens = jnp.where(alive, tok_ref, tokens)
        n2 = jnp.where(alive, jnp.minimum(queue, tokens // size),
                       jnp.int64(0))
        queue = queue - n2
        tokens = tokens - n2 * size
        delivered = delivered + n2
        alive = queue > 0
        return tokens, queue, alive, delivered, dropped

    zeros = jnp.zeros(h, dtype=jnp.int64)
    tokens0 = capacity.astype(jnp.int64)
    state = (tokens0, zeros, jnp.zeros(h, dtype=bool), zeros, zeros)
    tokens, queue, _alive, delivered, dropped = jax.lax.fori_loop(
        jnp.int64(0), ticks, tick_body, state)
    return delivered, dropped, queue, tokens


def saturate_run_numpy(first_tick: np.ndarray, n_pkts: np.ndarray,
                       size: int, refill: np.ndarray, capacity: np.ndarray,
                       qcap_pkts: int, ticks: int):
    """Bit-identical host twin — the parity oracle for the device loop."""
    h = len(first_tick)
    tokens = capacity.astype(np.int64).copy()
    queue = np.zeros(h, dtype=np.int64)
    alive = np.zeros(h, dtype=bool)
    delivered = np.zeros(h, dtype=np.int64)
    dropped = np.zeros(h, dtype=np.int64)
    for t in range(ticks):
        arr = ((t >= first_tick) & (t < first_tick + n_pkts)) \
            .astype(np.int64)
        admit = np.minimum(arr, np.maximum(qcap_pkts + 1 - queue, 0))
        dropped += arr - admit
        queue += admit
        n1 = np.minimum(queue, tokens // size)
        queue -= n1
        tokens -= n1 * size
        delivered += n1
        tok_ref = np.minimum(capacity, tokens + refill)
        tokens = np.where(alive, tok_ref, tokens)
        n2 = np.where(alive, np.minimum(queue, tokens // size), 0)
        queue -= n2
        tokens -= n2 * size
        delivered += n2
        alive = queue > 0
    return delivered, dropped, queue, tokens


class DeviceSaturate:
    """Convenience wrapper: H independent CBR flows into H throttled
    receivers, parameterized the way the engine is (KiB/s bandwidths)."""

    def __init__(self, bw_down_kibps: np.ndarray, payload_bytes: int = 958,
                 qcap_pkts: int = 1024):
        refill, capacity = bucket_params(np.asarray(bw_down_kibps))
        self.refill = refill.astype(np.int64)
        self.capacity = capacity.astype(np.int64)
        self.size = payload_bytes + defs.CONFIG_HEADER_SIZE_UDPIPETH
        self.qcap_pkts = qcap_pkts

    def run_device(self, first_tick: np.ndarray, n_pkts: np.ndarray,
                   ticks: int):
        out = saturate_run(jnp.asarray(first_tick, dtype=jnp.int64),
                           jnp.asarray(n_pkts, dtype=jnp.int64),
                           jnp.int64(self.size),
                           jnp.asarray(self.refill),
                           jnp.asarray(self.capacity),
                           jnp.int64(self.qcap_pkts), jnp.int64(ticks))
        jax.block_until_ready(out)
        return tuple(np.asarray(o) for o in out)

    def run_numpy(self, first_tick: np.ndarray, n_pkts: np.ndarray,
                  ticks: int):
        return saturate_run_numpy(first_tick, n_pkts, self.size,
                                  self.refill, self.capacity,
                                  self.qcap_pkts, ticks)
