"""Device-resident onion-relay cell forwarding: the flagship workload's
traffic pattern with ALL state in HBM.

apps/tor.py models Tor's network behavior through the full engine (cells,
circuits, streams over the userspace TCP stack).  This module is the
device-resident counterpart for the dominant traffic term — bulk cell
delivery server→exit→middle→guard→client across circuits that CONTEND for
shared relay bandwidth — composing the three north-star kernels in one
``lax.while_loop`` program:

* per-edge latency (cells in flight live in a [L, F] ring buffer indexed
  by arrival tick — the device analog of the delivery event queue);
* per-node token buckets (1 ms refill ticks, byte capacities from the same
  ``bucket_params`` the engine's interfaces use);
* bandwidth allocation across circuits sharing a relay: exact greedy in
  circuit-id order via STATIC segment cumsums — flows are grouped by
  receiving node at build time, so the per-tick allocation is one cumsum +
  two gathers, no sorting and no data-dependent shapes.

Like ops/phold_device.py and ops/saturate_device.py, the numbers this
produces are honest about what they are: a model workload (no TCP control
loop, no cell crypto) showing the architecture's throughput when the host
is out of the per-event path.  Correctness gates: a bit-identical numpy
twin and cell conservation (every injected cell is delivered exactly once)
in tests/test_torcells_device.py.

Shapes: C circuits × 5 stages = F flows.  Stage s of circuit c is paced by
node route[c, s] (route = [server, exit, middle, guard, client]); a cell
leaving stage s<4 arrives at stage s+1 after latency_ticks[node_s,
node_{s+1}]; leaving stage 4 means delivered.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core import defs
from .bandwidth import bucket_params

CELL_WIRE_BYTES = 512 + defs.CONFIG_HEADER_SIZE_TCPIPETH

# Arrival-ring element dtype for the execution plane: per-step per-flow cell
# counts (bounded by bucket capacity / cell size — a 10 Gbit/s host at a
# 100 ms granule is ~230k cells, nowhere near 2**31).  int32 halves the
# [ring_len, F] state bytes, which is the fixed per-dispatch copy cost on
# backends where the carried state cannot alias (PJRT CPU).  The kernels are
# dtype-polymorphic over the ring argument, so int64 callers (older tests,
# external users) keep working.
RING_DTYPE = np.int32


def build_flows(route: np.ndarray,          # int32 [C, 5] node per stage
                latency_ticks: np.ndarray,  # int64 [H, H]
                ) -> dict:
    """Precompute the static flow layout: flows sorted by (paced node,
    circuit id), segment offsets per node, and each flow's onward hop
    latency.  Pure numpy; runs once at model build."""
    c, stages = route.shape
    flow_circ = np.repeat(np.arange(c, dtype=np.int64), stages)
    flow_stage = np.tile(np.arange(stages, dtype=np.int64), c)
    flow_node = route[flow_circ, flow_stage].astype(np.int64)
    # greedy allocation order: by paced node, then circuit id (a node never
    # paces two stages of the same circuit: servers/relays/clients occupy
    # disjoint node ranges and relay picks are distinct).  Onward latencies
    # are >= 1 tick, so a cell can never traverse two stages in one tick —
    # matching the engine, where a forwarded cell is a new arrival event.
    order = np.lexsort((flow_stage, flow_circ, flow_node))
    flow_circ, flow_stage, flow_node = (flow_circ[order], flow_stage[order],
                                        flow_node[order])
    # onward latency: stage s -> s+1 edge; last stage delivers (0)
    nxt = np.where(flow_stage < stages - 1,
                   route[flow_circ, np.minimum(flow_stage + 1, stages - 1)],
                   route[flow_circ, flow_stage])
    lat = latency_ticks[flow_node, nxt].astype(np.int64)
    lat = np.where(flow_stage < stages - 1, np.maximum(lat, 1), 0)
    # successor flow index (same circuit, next stage) in sorted space
    flat_id = flow_circ * stages + flow_stage
    pos_of = np.empty(c * stages, dtype=np.int64)
    pos_of[flat_id] = np.arange(c * stages)
    succ = np.where(flow_stage < stages - 1,
                    pos_of[np.minimum(flat_id + 1, c * stages - 1)], -1)
    # segment start offset of each flow's node group (for the cumsum trick)
    seg_start_of_flow = np.zeros(c * stages, dtype=np.int64)
    starts = np.flatnonzero(np.r_[True, flow_node[1:] != flow_node[:-1]])
    seg_id = np.cumsum(np.r_[0, (flow_node[1:] != flow_node[:-1])
                             .astype(np.int64)])
    seg_start_of_flow = starts[seg_id]
    return {
        "flow_circ": flow_circ, "flow_stage": flow_stage,
        "flow_node": flow_node, "flow_lat": lat, "flow_succ": succ,
        "seg_start": seg_start_of_flow,
    }


from functools import partial


@partial(jax.jit, static_argnames=("ring_len",))
def torcells_run(queued0: jnp.ndarray,     # int64 [F] initial cells/flow
                 flow_node: jnp.ndarray,   # int64 [F] paced node
                 flow_lat: jnp.ndarray,    # int64 [F] onward latency ticks
                 flow_succ: jnp.ndarray,   # int64 [F] successor flow or -1
                 seg_start: jnp.ndarray,   # int64 [F] node-segment start
                 refill: jnp.ndarray,      # int64 [H] bytes per tick
                 capacity: jnp.ndarray,    # int64 [H] bucket cap bytes
                 ring_len: int,            # static: max latency + 1
                 max_ticks: jnp.ndarray,   # int64 scalar
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run until every cell is delivered (or max_ticks).  Returns
    (delivered[F] on last-stage flows, ticks_run, total_forwards)."""
    f = queued0.shape[0]
    h = refill.shape[0]
    size = jnp.int64(CELL_WIRE_BYTES)
    is_last = flow_succ < 0

    # successor-space arrival latency: arr_lat[j] = onward latency of j's
    # predecessor (succ is injective over chains, so scatter-add == set).
    # Cells in flight live in a [L, F] HISTORY of per-step successor-space
    # send vectors, consumed by a GATHER at hist[(t - arr_lat) mod L, j] —
    # no full-buffer scatter per step.  (The previous formulation scattered
    # into an arrival ring at computed (slot, succ) indices; XLA:CPU
    # materializes a copy of the whole [L, F] operand per scatter, which
    # was ~95% of the flagship device-plane's flush wall — VERDICT r4 weak
    # #2.  The gather form writes one row per step via dynamic-update-slice,
    # which aliases in place on every backend.)
    arr_lat = jnp.zeros(f, jnp.int64).at[jnp.maximum(flow_succ, 0)].add(
        jnp.where(is_last, jnp.int64(0), flow_lat))
    cols = jnp.arange(f)

    def body(state):
        t, queued, hist, tokens, delivered, forwards = state
        # arrivals: my predecessor's sends from arr_lat steps ago (columns
        # with no predecessor are never written, so they gather zeros)
        arr = hist[jnp.mod(t - arr_lat, ring_len), cols]
        queued = queued + arr
        # refill buckets
        tokens = jnp.minimum(capacity, tokens + refill)
        cap_cells = tokens[flow_node] // size
        # greedy allocation in static flow order within each node segment:
        # served = clip(capacity_at_segment - cells_before_me, 0, queued)
        csum = jnp.cumsum(queued)
        before = csum - queued - jnp.where(
            seg_start > 0, csum[jnp.maximum(seg_start - 1, 0)],
            jnp.int64(0)) * (seg_start > 0)
        served = jnp.clip(cap_cells - before, 0, queued)
        queued = queued - served
        spent = jax.ops.segment_sum(served * size, flow_node,
                                    num_segments=h)
        tokens = tokens - spent
        # departures: last stage delivers, others arrive at successor after
        # their edge latency
        delivered = delivered + jnp.where(is_last, served, 0)
        v = jnp.zeros(f, jnp.int64).at[jnp.maximum(flow_succ, 0)].add(
            jnp.where(is_last, jnp.int64(0), served))
        hist = hist.at[jnp.mod(t, ring_len)].set(v)
        forwards = forwards + jnp.sum(served)
        return t + 1, queued, hist, tokens, delivered, forwards

    total = jnp.sum(queued0)

    def cond(state):
        t, _queued, _ring, _tok, delivered, _f = state
        # delivered-vs-total instead of summing the [L, F] ring each tick
        return (jnp.sum(delivered) < total) & (t < max_ticks)

    ring0 = jnp.zeros((ring_len, f), dtype=jnp.int64)
    state = (jnp.int64(0), queued0, ring0, capacity.astype(jnp.int64),
             jnp.zeros(f, dtype=jnp.int64), jnp.int64(0))
    t, _q, _r, _tok, delivered, forwards = jax.lax.while_loop(
        cond, body, state)
    return delivered, t, forwards


def _step_window_impl(t0: jnp.ndarray,         # int64 scalar: next tick
                      queued: jnp.ndarray,     # int64 [F]
                      ring: jnp.ndarray,       # int64 [L, F]
                      tokens: jnp.ndarray,     # int64 [H]
                      delivered: jnp.ndarray,  # int64 [F]
                      target: jnp.ndarray,     # int64 [F] (last-stage rows)
                      done_tick: jnp.ndarray,  # int64 [F], -1 = not done
                      node_sent: jnp.ndarray,  # int64 [H] cumulative bytes
                      inject: jnp.ndarray,     # int64 [F] new cells @ t0
                      inject_target: jnp.ndarray,  # int64 [F] target adds
                      n_ticks: jnp.ndarray,    # int64 scalar (dynamic)
                      idle_ticks: jnp.ndarray,  # int64 scalar: skipped
                                                # empty ticks to fold in
                      flow_node: jnp.ndarray, flow_lat: jnp.ndarray,
                      flow_succ: jnp.ndarray, seg_start: jnp.ndarray,
                      refill: jnp.ndarray, capacity: jnp.ndarray,
                      ring_len: int):
    """Advance the cell model by EXACTLY n_ticks, carrying ALL state in HBM
    across dispatches — the execution-plane variant of torcells_run (state
    tensors are donated, so each round's dispatch updates in place; the host
    only uploads the tiny inject vectors and downloads the small
    delivered/done/node_sent summaries it needs for wakeups/trackers).

    Per-tick math is IDENTICAL to torcells_run's body (pinned bit-for-bit by
    tests/test_device_plane.py's windowed-vs-run parity case), plus:
    * per-flow completion ticks (done_tick records the first tick a
      last-stage flow's delivered count reached its target — the engine
      turns these into deterministic wake events);
    * per-node cumulative sent bytes (tracker/heartbeat feed).

    The caller chooses what a "tick" means: DeviceTrafficPlane passes
    refill/capacity/latencies pre-scaled to coarse steps (its ``granule``),
    so one loop iteration covers several milliseconds — that keeps BOTH the
    [ring_len, F] arrival ring small on multi-second-latency topologies and
    the sequential step count low (the per-step ring update walks the whole
    ring buffer, so state bytes x steps is the real cost on every backend).

    Returns the updated state tuple plus total forwards this window."""
    f = queued.shape[0]
    h = refill.shape[0]
    size = jnp.int64(CELL_WIRE_BYTES)
    is_last = flow_succ < 0
    queued = queued + inject
    target = target + inject_target
    # fold skipped idle ticks (the plane had no cells anywhere, so the only
    # state evolution was bucket refill — exact because refill is capped).
    # The send history must be cleared across an idle jump: banking requires
    # every cell delivered, so all past sends were consumed — but a jumped t
    # would otherwise re-read stale rows on wrap (lax.cond: the zeroing pass
    # only runs when ticks were actually banked).
    tokens = jnp.minimum(capacity, tokens + refill * idle_ticks)
    ring = jax.lax.cond(idle_ticks > 0,
                        lambda hh: jnp.zeros_like(hh),
                        lambda hh: hh, ring)
    # successor-space arrival latency (see torcells_run): hist rows are
    # per-step send vectors; arrivals are a gather, the only write is one
    # row DUS — nothing scatters into the big buffer
    arr_lat = jnp.zeros(f, jnp.int64).at[jnp.maximum(flow_succ, 0)].add(
        jnp.where(is_last, jnp.int64(0), flow_lat))
    cols = jnp.arange(f)

    def body(state):
        t, queued, hist, tokens, delivered, target, done_tick, node_sent, \
            forwards = state
        arr = hist[jnp.mod(t - arr_lat, ring_len), cols]
        queued = queued + arr
        tokens = jnp.minimum(capacity, tokens + refill)
        cap_cells = tokens[flow_node] // size
        csum = jnp.cumsum(queued)
        before = csum - queued - jnp.where(
            seg_start > 0, csum[jnp.maximum(seg_start - 1, 0)],
            jnp.int64(0)) * (seg_start > 0)
        served = jnp.clip(cap_cells - before, 0, queued)
        queued = queued - served
        spent = jax.ops.segment_sum(served * size, flow_node,
                                    num_segments=h)
        tokens = tokens - spent
        node_sent = node_sent + spent
        delivered = delivered + jnp.where(is_last, served, 0)
        newly_done = (is_last & (target > 0) & (done_tick < 0)
                      & (delivered >= target))
        done_tick = jnp.where(newly_done, t, done_tick)
        v = jnp.zeros(f, jnp.int64).at[jnp.maximum(flow_succ, 0)].add(
            jnp.where(is_last, jnp.int64(0), served))
        # cast to the carried ring dtype: DeviceTrafficPlane keeps the ring
        # int32 (RING_DTYPE) — per-step per-flow cell counts are bounded by
        # bucket capacity / cell size, far below 2**31 — which halves the
        # per-dispatch state-copy bytes, the fixed cost of every dispatch
        hist = hist.at[jnp.mod(t, ring_len)].set(v.astype(hist.dtype))
        forwards = forwards + jnp.sum(served)
        return (t + 1, queued, hist, tokens, delivered, target, done_tick,
                node_sent, forwards)

    end = t0 + n_ticks

    def cond(state):
        return state[0] < end

    state = (t0, queued, ring, tokens, delivered, target, done_tick,
             node_sent, jnp.int64(0))
    return jax.lax.while_loop(cond, body, state)


@partial(jax.jit, static_argnames=("ring_len",),
         donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def torcells_step_window(t0, queued, ring, tokens, delivered, target,
                         done_tick, node_sent, inject, inject_target,
                         n_ticks, idle_ticks, flow_node, flow_lat,
                         flow_succ, seg_start, refill, capacity,
                         ring_len: int):
    """The jitted windowed step (see _step_window_impl for the contract)."""
    return _step_window_impl(t0, queued, ring, tokens, delivered, target,
                             done_tick, node_sent, inject, inject_target,
                             n_ticks, idle_ticks, flow_node, flow_lat,
                             flow_succ, seg_start, refill, capacity,
                             ring_len)


# ---------------------------------------------------------------------------
# Packed flush buffer: the dispatch's ENTIRE host-facing summary in one
# int64 vector, so collect is ONE device->host transfer instead of four
# (delivered + done_tick + node_sent + forwards).  Delta-compacted with a
# device-side cursor: only chains that completed THIS window and only nodes
# whose sent-byte counter moved occupy slots; the header carries the counts.
#
# Layout ([5 + 2C + 2H] int64, C = chains, H = nodes):
#   [0] forwards this window
#   [1] cumulative delivered cells summed over chain-exit flows
#   [2] n_done   — chains newly completed this window
#   [3] n_nodes  — nodes with a nonzero sent-byte delta this window
#   [4] t_stop   — the absolute step the kernel actually advanced to (the
#                  final target, or an earlier sub-window boundary when the
#                  superwindow loop halted at a completion — see
#                  _step_span_impl); carried in the flush so the host never
#                  pays a second device read to learn where a multi-round
#                  dispatch stopped
#   [5        : 5+n_done]        newly-done chain indices (ascending)
#   [5+C      : 5+C+n_done]      their completion steps
#   [5+2C     : 5+2C+n_nodes]    touched node indices (ascending)
#   [5+2C+H   : 5+2C+H+n_nodes]  their sent-byte deltas
# ---------------------------------------------------------------------------

FLUSH_HEADER = 5


def flush_len(n_chains: int, n_nodes: int,
              cap_chains: Optional[int] = None,
              cap_nodes: Optional[int] = None) -> int:
    """Packed flush buffer length.  With caps (ISSUE 16 delta-compacted
    flush) the chain/node sections carry at most ``cap_chains``/
    ``cap_nodes`` entries — the header counts stay TRUE, so an
    overflowing window is detectable (flush_overflowed) and re-read
    through the full-length kernel."""
    c = n_chains if cap_chains is None else min(cap_chains, n_chains)
    h = n_nodes if cap_nodes is None else min(cap_nodes, n_nodes)
    return FLUSH_HEADER + 2 * c + 2 * h


def _pack_flush_jnp(forwards, delivered_sum, t_stop, newly, done_last,
                    sent_delta, cap_chains: Optional[int] = None,
                    cap_nodes: Optional[int] = None):
    """newly bool [C], done_last int64 [C], sent_delta int64 [H] -> packed
    buffer.  Compaction is a cumsum-cursor scatter; out-of-range slots (the
    unselected lanes) are dropped on device.  With caps the buffer is the
    CAPPED length and entries past a cap are dropped — the header still
    carries the true counts, so the host can tell a capped buffer lost
    entries and fall back to the full-length kernel (delta-compacted
    flush, ISSUE 16: quiet lanes stop costing readback bytes)."""
    c = newly.shape[0]
    h = sent_delta.shape[0]
    cc = c if cap_chains is None else min(int(cap_chains), c)
    hh = h if cap_nodes is None else min(int(cap_nodes), h)
    length = flush_len(c, h, cap_chains, cap_nodes)
    touched = sent_delta != 0
    pos_c = jnp.cumsum(newly.astype(jnp.int64)) - 1
    pos_h = jnp.cumsum(touched.astype(jnp.int64)) - 1
    oob = jnp.int64(length)
    sel_c = newly & (pos_c < cc)
    sel_h = touched & (pos_h < hh)
    buf = jnp.zeros(length, jnp.int64)
    buf = buf.at[0].set(forwards)
    buf = buf.at[1].set(delivered_sum)
    buf = buf.at[2].set(jnp.sum(newly.astype(jnp.int64)))
    buf = buf.at[3].set(jnp.sum(touched.astype(jnp.int64)))
    buf = buf.at[4].set(t_stop)
    base = jnp.int64(FLUSH_HEADER)
    buf = buf.at[jnp.where(sel_c, base + pos_c, oob)].set(
        jnp.arange(c, dtype=jnp.int64), mode="drop")
    buf = buf.at[jnp.where(sel_c, base + cc + pos_c, oob)].set(
        done_last, mode="drop")
    buf = buf.at[jnp.where(sel_h, base + 2 * cc + pos_h, oob)].set(
        jnp.arange(h, dtype=jnp.int64), mode="drop")
    buf = buf.at[jnp.where(sel_h, base + 2 * cc + hh + pos_h, oob)].set(
        sent_delta, mode="drop")
    return buf


def pack_flush_np(forwards, delivered_sum, t_stop, newly, done_last,
                  sent_delta):
    """Bit-identical host twin of _pack_flush_jnp."""
    c = len(newly)
    h = len(sent_delta)
    buf = np.zeros(flush_len(c, h), np.int64)
    buf[0] = forwards
    buf[1] = delivered_sum
    ci = np.flatnonzero(newly)
    ni = np.flatnonzero(sent_delta)
    buf[2] = len(ci)
    buf[3] = len(ni)
    buf[4] = t_stop
    base = FLUSH_HEADER
    buf[base:base + len(ci)] = ci
    buf[base + c:base + c + len(ci)] = np.asarray(done_last)[ci]
    buf[base + 2 * c:base + 2 * c + len(ni)] = ni
    buf[base + 2 * c + h:base + 2 * c + h + len(ni)] = \
        np.asarray(sent_delta)[ni]
    return buf


def flush_overflowed(buf: np.ndarray, cap_chains: int,
                     cap_nodes: int) -> bool:
    """True when a CAPPED flush buffer lost entries: the header carries the
    true per-window counts, so overflow is one comparison — the caller then
    re-runs the same inputs through the full-length kernel (legal on the
    non-donating CPU path, where the inputs are still alive)."""
    return int(buf[2]) > int(cap_chains) or int(buf[3]) > int(cap_nodes)


def parse_flush(buf: np.ndarray, n_chains: int, n_nodes: int,
                cap_chains: Optional[int] = None,
                cap_nodes: Optional[int] = None):
    """(forwards, delivered_sum, t_stop, done_chains, done_steps, node_idx,
    node_delta) from a packed flush buffer — the ONE host-side reader.
    Pass the caps the buffer was packed with (if any); callers must check
    flush_overflowed FIRST — parsing an overflowed capped buffer would
    silently drop completions/deltas."""
    cc = n_chains if cap_chains is None else min(int(cap_chains), n_chains)
    hh = n_nodes if cap_nodes is None else min(int(cap_nodes), n_nodes)
    base = FLUSH_HEADER
    n_done = min(int(buf[2]), cc)
    n_touch = min(int(buf[3]), hh)
    return (int(buf[0]), int(buf[1]), int(buf[4]),
            buf[base:base + n_done],
            buf[base + cc:base + cc + n_done],
            buf[base + 2 * cc:base + 2 * cc + n_touch],
            buf[base + 2 * cc + hh:
                base + 2 * cc + hh + n_touch])


def _step_span_impl(t0, queued, ring, tokens, delivered, target,
                    done_tick, node_sent, inject, inject_target,
                    targets, idle_ticks, flow_node, flow_lat,
                    flow_succ, seg_start, refill, capacity,
                    ring_len: int):
    """The SUPERWINDOW step: advance the cell model from ``t0`` through the
    ascending absolute step boundaries in ``targets`` (padded by repeating
    the final boundary, so the array shape stays static), HALTING at the
    end of the first sub-window in which any chain newly completed.

    Each ``targets[i-1]..targets[i]`` span is one virtual engine round's
    dispatch (device_plane negotiates the list by replaying the K=1 round
    recurrence); running them fused amortizes the per-dispatch launch +
    state-copy cost K-fold.  The halt rule is what keeps a K-round launch
    bit-identical to K separate launches: a completion wakes its client at
    the launching round's barrier under K=1, and anything that client does
    (close a socket, activate another flow) must see plane state advanced
    exactly to that round — so the kernel refuses to run past it.  The
    reached boundary comes back in the flush header (t_stop), one transfer.

    Per-tick math is byte-for-byte the _step_window_impl body (pinned by
    tests/test_superwindow.py's span-vs-sequential-windows parity case).
    Returns the same 9-tuple, with [0] = the boundary actually reached."""
    f = queued.shape[0]
    h = refill.shape[0]
    p = targets.shape[0]
    size = jnp.int64(CELL_WIRE_BYTES)
    is_last = flow_succ < 0
    queued = queued + inject
    target = target + inject_target
    tokens = jnp.minimum(capacity, tokens + refill * idle_ticks)
    ring = jax.lax.cond(idle_ticks > 0,
                        lambda hh: jnp.zeros_like(hh),
                        lambda hh: hh, ring)
    arr_lat = jnp.zeros(f, jnp.int64).at[jnp.maximum(flow_succ, 0)].add(
        jnp.where(is_last, jnp.int64(0), flow_lat))
    cols = jnp.arange(f)
    end = targets[p - 1]

    def body(state):
        (t, idx, halt, span_done, queued, hist, tokens, delivered, target,
         done_tick, node_sent, forwards) = state
        arr = hist[jnp.mod(t - arr_lat, ring_len), cols]
        queued = queued + arr
        tokens = jnp.minimum(capacity, tokens + refill)
        cap_cells = tokens[flow_node] // size
        csum = jnp.cumsum(queued)
        before = csum - queued - jnp.where(
            seg_start > 0, csum[jnp.maximum(seg_start - 1, 0)],
            jnp.int64(0)) * (seg_start > 0)
        served = jnp.clip(cap_cells - before, 0, queued)
        queued = queued - served
        spent = jax.ops.segment_sum(served * size, flow_node,
                                    num_segments=h)
        tokens = tokens - spent
        node_sent = node_sent + spent
        delivered = delivered + jnp.where(is_last, served, 0)
        newly_done = (is_last & (target > 0) & (done_tick < 0)
                      & (delivered >= target))
        done_tick = jnp.where(newly_done, t, done_tick)
        v = jnp.zeros(f, jnp.int64).at[jnp.maximum(flow_succ, 0)].add(
            jnp.where(is_last, jnp.int64(0), served))
        hist = hist.at[jnp.mod(t, ring_len)].set(v.astype(hist.dtype))
        forwards = forwards + jnp.sum(served)
        # sub-window bookkeeping: at a boundary, halt iff this span saw a
        # completion; otherwise roll into the next span with a clean flag
        span_done = span_done | jnp.any(newly_done)
        boundary = (t + 1) == targets[jnp.minimum(idx, p - 1)]
        halt = boundary & span_done
        idx = jnp.where(boundary, idx + 1, idx)
        span_done = span_done & ~boundary
        return (t + 1, idx, halt, span_done, queued, hist, tokens,
                delivered, target, done_tick, node_sent, forwards)

    def cond(state):
        return (state[0] < end) & ~state[2]

    state = (t0, jnp.int64(0), jnp.bool_(False), jnp.bool_(False),
             queued, ring, tokens, delivered, target, done_tick,
             node_sent, jnp.int64(0))
    out = jax.lax.while_loop(cond, body, state)
    return (out[0], *out[4:])


def _step_span_flush_impl(t0, queued, ring, tokens, delivered, target,
                          done_tick, node_sent, inject, inject_target,
                          targets, idle_ticks, flow_node, flow_lat,
                          flow_succ, seg_start, refill, capacity,
                          last_flow, ring_len: int,
                          cap_chains: Optional[int] = None,
                          cap_nodes: Optional[int] = None):
    """Superwindow step + packed flush in ONE dispatch: the 9-tuple of
    _step_span_impl with the packed flush buffer appended as [9].
    ``last_flow`` [C] maps each chain to its exit flow row.  With caps
    the flush is the capped (delta-compacted) buffer — see
    _pack_flush_jnp."""
    done_in_last = done_tick[last_flow]
    node_sent_in = node_sent
    out = _step_span_impl(t0, queued, ring, tokens, delivered, target,
                          done_tick, node_sent, inject, inject_target,
                          targets, idle_ticks, flow_node, flow_lat,
                          flow_succ, seg_start, refill, capacity,
                          ring_len)
    done_last = out[6][last_flow]
    newly = (done_last >= 0) & (done_in_last < 0)
    flush = _pack_flush_jnp(out[8], jnp.sum(out[4][last_flow]), out[0],
                            newly, done_last, out[7] - node_sent_in,
                            cap_chains, cap_nodes)
    return (*out, flush)


# Two jit wrappers over the SAME flush program, picked by backend
# (step_window_flush_for_backend): donation aliases the carried state in
# place on TPU/GPU, but on the PJRT CPU client a donated call executes
# SYNCHRONOUSLY (measured: 114 ms launch vs 0.33 ms undonated for the same
# kernel) AND still copies the buffers — so the CPU backend uses the
# non-donating variant, which is what lets the dispatch actually compute
# behind the round's host work.
torcells_step_window_flush = partial(
    jax.jit, static_argnames=("ring_len",),
    donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))(_step_span_flush_impl)

torcells_step_window_flush_nodonate = partial(
    jax.jit, static_argnames=("ring_len",))(_step_span_flush_impl)

# Delta-compacted flush variant (ISSUE 16): same program with the flush
# buffer capped to the tuned lane counts.  Non-donating ONLY — overflow
# recovery re-runs the same inputs through the full-length kernel, which
# requires the carried state to still be alive after the launch; that is
# exactly the property the CPU dispatch path already has (see above), and
# device_plane only engages caps on that path.
torcells_step_window_flush_capped = partial(
    jax.jit, static_argnames=("ring_len", "cap_chains", "cap_nodes"))(
        _step_span_flush_impl)


def step_window_flush_for_backend():
    """The flush-step jit appropriate for the default backend (see note
    above): donating on accelerators, non-donating on CPU."""
    if jax.default_backend() == "cpu":
        return torcells_step_window_flush_nodonate
    return torcells_step_window_flush


# Fleet plane (ISSUE 18): the SAME span/flush program vmapped over a
# leading batch axis so one launch advances W independent simulations.
# Every operand — carried state, injections, superwindow targets, AND the
# static flow tables — carries its own lane row (lanes are independent
# scenarios padded to a shared shape class; tables differ per lane).  The
# batching rules keep per-lane semantics exact: the while_loop's cond
# becomes "any lane still below its span end" with finished lanes
# select()-frozen at their halt state, and every body op is int64
# cumsum/min/clip/segment arithmetic — bit-identical per lane to the
# unbatched kernel, which is what lets the fleet digest-gate against the
# serial path.  Never donating: the fleet runs on the CPU dispatch path
# (see the backend note above) and the driver re-pads carried real-shaped
# state per dispatch.
@partial(jax.jit, static_argnames=("ring_len",))
def torcells_step_span_flush_batched(t0, queued, ring, tokens, delivered,
                                     target, done_tick, node_sent, inject,
                                     inject_target, targets, idle_ticks,
                                     flow_node, flow_lat, flow_succ,
                                     seg_start, refill, capacity, last_flow,
                                     ring_len: int):
    """[W]-leading-axis twin of torcells_step_window_flush: 10-tuple with
    every element batched ([W] t_stop/forwards scalars, [W, F] columns,
    [W, L, F] rings, [W, flush_len] flush buffers)."""
    fn = partial(_step_span_flush_impl, ring_len=ring_len)
    return jax.vmap(fn)(t0, queued, ring, tokens, delivered, target,
                        done_tick, node_sent, inject, inject_target,
                        targets, idle_ticks, flow_node, flow_lat,
                        flow_succ, seg_start, refill, capacity, last_flow)


def torcells_step_span_batched_numpy(t0, queued, ring, tokens, delivered,
                                     target, done_tick, node_sent, inject,
                                     inject_target, targets, idle_ticks,
                                     flow_node, flow_lat, flow_succ,
                                     seg_start, refill, capacity, last_flow,
                                     ring_len: int):
    """Host twin of torcells_step_span_flush_batched: lanes looped through
    the unbatched numpy flush twin and re-stacked (same 10-tuple/leading-
    axis contract) — the parity oracle for the vmapped program."""
    outs = [torcells_step_window_numpy_flush(
        np.int64(t0[w]), queued[w], ring[w], tokens[w], delivered[w],
        target[w], done_tick[w], node_sent[w], inject[w], inject_target[w],
        targets[w], int(idle_ticks[w]), flow_node[w], flow_lat[w],
        flow_succ[w], seg_start[w], refill[w], capacity[w], last_flow[w],
        ring_len) for w in range(len(t0))]
    return tuple(np.stack([np.asarray(o[i]) for o in outs])
                 for i in range(10))


def torcells_step_span_numpy(t0, queued, ring, tokens, delivered, target,
                             done_tick, node_sent, inject, inject_target,
                             targets, idle_ticks, flow_node, flow_lat,
                             flow_succ, seg_start, refill, capacity,
                             ring_len: int):
    """Bit-identical host twin of _step_span_impl (same boundary/halt
    rule) — the parity oracle and the --device-plane=numpy execution
    mode's superwindow step."""
    f = len(queued)
    h = len(refill)
    size = CELL_WIRE_BYTES
    is_last = flow_succ < 0
    queued = queued + inject
    target = target + inject_target
    tokens = np.minimum(capacity, tokens + refill * int(idle_ticks))
    if int(idle_ticks) > 0:
        ring = np.zeros_like(ring)   # idle jump: stale send history cleared
    arr_lat = np.zeros(f, dtype=np.int64)
    np.add.at(arr_lat, np.maximum(flow_succ, 0),
              np.where(is_last, 0, flow_lat))
    cols = np.arange(f)
    bounds = [int(x) for x in np.asarray(targets)]
    end = bounds[-1]
    forwards = 0
    t = int(t0)
    idx = 0
    span_done = False
    while t < end:
        arr = ring[(t - arr_lat) % ring_len, cols]
        queued = queued + arr
        tokens = np.minimum(capacity, tokens + refill)
        cap_cells = tokens[flow_node] // size
        csum = np.cumsum(queued)
        seg_base = np.where(seg_start > 0, csum[np.maximum(seg_start - 1, 0)],
                            0) * (seg_start > 0)
        before = csum - queued - seg_base
        served = np.clip(cap_cells - before, 0, queued)
        queued = queued - served
        spent = np.bincount(flow_node, weights=served * size,
                            minlength=h).astype(np.int64)
        tokens = tokens - spent
        node_sent = node_sent + spent
        delivered = delivered + np.where(is_last, served, 0)
        newly_done = (is_last & (target > 0) & (done_tick < 0)
                      & (delivered >= target))
        done_tick = np.where(newly_done, t, done_tick)
        v = np.zeros(f, dtype=np.int64)
        np.add.at(v, np.maximum(flow_succ, 0), np.where(is_last, 0, served))
        ring[t % ring_len] = v
        forwards += int(served.sum())
        span_done = span_done or bool(newly_done.any())
        t += 1
        if t == bounds[min(idx, len(bounds) - 1)]:
            idx += 1
            if span_done:
                break
            span_done = False
    return (np.int64(t), queued, ring, tokens, delivered, target, done_tick,
            node_sent, np.int64(forwards))


def torcells_step_window_numpy_flush(t0, queued, ring, tokens, delivered,
                                     target, done_tick, node_sent, inject,
                                     inject_target, targets, idle_ticks,
                                     flow_node, flow_lat, flow_succ,
                                     seg_start, refill, capacity, last_flow,
                                     ring_len: int):
    """Host twin of torcells_step_window_flush (same 10-tuple contract,
    same ``targets`` superwindow boundaries)."""
    done_in_last = np.asarray(done_tick)[last_flow].copy()
    node_sent_in = np.asarray(node_sent).copy()
    out = torcells_step_span_numpy(t0, queued, ring, tokens, delivered,
                                   target, done_tick, node_sent, inject,
                                   inject_target, targets, idle_ticks,
                                   flow_node, flow_lat, flow_succ,
                                   seg_start, refill, capacity, ring_len)
    done_last = out[6][last_flow]
    newly = (done_last >= 0) & (done_in_last < 0)
    flush = pack_flush_np(int(out[8]), int(out[4][last_flow].sum()),
                          int(out[0]), newly, done_last,
                          out[7] - node_sent_in)
    return (*out, flush)


def torcells_step_window_numpy(t0, queued, ring, tokens, delivered, target,
                               done_tick, node_sent, inject, inject_target,
                               n_ticks, idle_ticks, flow_node, flow_lat,
                               flow_succ, seg_start, refill, capacity,
                               ring_len: int):
    """Bit-identical host twin of torcells_step_window (same rule, same
    ring, same completion/byte accounting) — the parity gate's oracle and
    the --device-plane=numpy execution mode."""
    f = len(queued)
    h = len(refill)
    size = CELL_WIRE_BYTES
    is_last = flow_succ < 0
    queued = queued + inject
    target = target + inject_target
    tokens = np.minimum(capacity, tokens + refill * int(idle_ticks))
    if int(idle_ticks) > 0:
        ring = np.zeros_like(ring)   # idle jump: stale send history cleared
    arr_lat = np.zeros(f, dtype=np.int64)
    np.add.at(arr_lat, np.maximum(flow_succ, 0),
              np.where(is_last, 0, flow_lat))
    cols = np.arange(f)
    forwards = 0
    t = int(t0)
    for _ in range(int(n_ticks)):
        arr = ring[(t - arr_lat) % ring_len, cols]
        queued = queued + arr
        tokens = np.minimum(capacity, tokens + refill)
        cap_cells = tokens[flow_node] // size
        csum = np.cumsum(queued)
        seg_base = np.where(seg_start > 0, csum[np.maximum(seg_start - 1, 0)],
                            0) * (seg_start > 0)
        before = csum - queued - seg_base
        served = np.clip(cap_cells - before, 0, queued)
        queued = queued - served
        spent = np.bincount(flow_node, weights=served * size,
                            minlength=h).astype(np.int64)
        tokens = tokens - spent
        node_sent = node_sent + spent
        delivered = delivered + np.where(is_last, served, 0)
        newly_done = (is_last & (target > 0) & (done_tick < 0)
                      & (delivered >= target))
        done_tick = np.where(newly_done, t, done_tick)
        v = np.zeros(f, dtype=np.int64)
        np.add.at(v, np.maximum(flow_succ, 0), np.where(is_last, 0, served))
        ring[t % ring_len] = v
        forwards += int(served.sum())
        t += 1
    return (np.int64(t), queued, ring, tokens, delivered, target, done_tick,
            node_sent, np.int64(forwards))


# ---------------------------------------------------------------------------
# Multi-chip execution plane: the flow table sharded over a device mesh
# lives in shadow_tpu/parallel/mesh/ (partition.py chain partitioner +
# padded layout, exchange.py BvN permutation-leg exchange + shard_map
# superwindow kernel, meshplane.py DeviceTrafficPlane attachment) — the
# single definition of the shard placement contract.  The PR-7
# replicated-ring/full-psum kernels that used to live here were retired by
# the mesh plane; tests/test_meshplane.py is their parity suite.
# ---------------------------------------------------------------------------


def torcells_run_numpy(queued0, flow_node, flow_lat, flow_succ, seg_start,
                       refill, capacity, ring_len: int, max_ticks: int):
    """Bit-identical host twin (same allocation rule, same ring)."""
    f = len(queued0)
    h = len(refill)
    size = CELL_WIRE_BYTES
    is_last = flow_succ < 0
    queued = queued0.astype(np.int64).copy()
    ring = np.zeros((ring_len, f), dtype=np.int64)
    tokens = capacity.astype(np.int64).copy()
    delivered = np.zeros(f, dtype=np.int64)
    arr_lat = np.zeros(f, dtype=np.int64)
    np.add.at(arr_lat, np.maximum(flow_succ, 0),
              np.where(is_last, 0, flow_lat))
    cols = np.arange(f)
    forwards = 0
    t = 0
    total = int(queued0.sum())
    while delivered.sum() < total and t < max_ticks:
        arr = ring[(t - arr_lat) % ring_len, cols]
        queued += arr
        tokens = np.minimum(capacity, tokens + refill)
        cap_cells = tokens[flow_node] // size
        csum = np.cumsum(queued)
        seg_base = np.where(seg_start > 0, csum[np.maximum(seg_start - 1, 0)],
                            0) * (seg_start > 0)
        before = csum - queued - seg_base
        served = np.clip(cap_cells - before, 0, queued)
        queued -= served
        spent = np.bincount(flow_node, weights=served * size,
                            minlength=h).astype(np.int64)
        tokens -= spent
        delivered += np.where(is_last, served, 0)
        v = np.zeros(f, dtype=np.int64)
        np.add.at(v, np.maximum(flow_succ, 0), np.where(is_last, 0, served))
        ring[t % ring_len] = v
        forwards += int(served.sum())
        t += 1
    return delivered, t, forwards


class DeviceTorCells:
    """Build a circuits-over-relays instance and run it device-resident."""

    def __init__(self, n_relays: int, n_circuits: int, seed: int = 7,
                 relay_bw_kibps: int = 2048, edge_bw_kibps: int = 1 << 20,
                 max_latency_ms: int = 120):
        rng = np.random.default_rng(seed)
        # nodes: [clients | relays | servers] — clients/servers effectively
        # unthrottled, relays are the contended resource
        n_clients = n_circuits
        n_servers = max(1, n_circuits // 50)
        h = n_clients + n_relays + n_servers
        lat = rng.integers(2, max_latency_ms, size=(h, h)).astype(np.int64)
        np.fill_diagonal(lat, 1)
        bw = np.full(h, edge_bw_kibps, dtype=np.int64)
        bw[n_clients:n_clients + n_relays] = relay_bw_kibps
        refill, cap = bucket_params(bw)
        self.refill = refill.astype(np.int64)
        self.capacity = cap.astype(np.int64)
        # routes: distinct guard/middle/exit per circuit
        route = np.empty((n_circuits, 5), dtype=np.int64)
        route[:, 4] = np.arange(n_circuits)                       # client
        route[:, 0] = n_clients + n_relays + rng.integers(
            0, n_servers, size=n_circuits)                        # server
        picks = rng.random((n_circuits, n_relays)).argsort(axis=1)[:, :3]
        route[:, 1:4] = n_clients + picks                         # e, m, g
        self.flows = build_flows(route, lat)
        self.ring_len = int(max_latency_ms) + 2
        self.n_flows = n_circuits * 5
        self.route = route

    def _args(self, cells_per_circuit: int):
        fl = self.flows
        queued0 = np.where(fl["flow_stage"] == 0, cells_per_circuit, 0) \
            .astype(np.int64)
        return queued0, fl

    def run_device(self, cells_per_circuit: int, max_ticks: int):
        queued0, fl = self._args(cells_per_circuit)
        out = torcells_run(jnp.asarray(queued0),
                           jnp.asarray(fl["flow_node"]),
                           jnp.asarray(fl["flow_lat"]),
                           jnp.asarray(fl["flow_succ"]),
                           jnp.asarray(fl["seg_start"]),
                           jnp.asarray(self.refill),
                           jnp.asarray(self.capacity),
                           self.ring_len, jnp.int64(max_ticks))
        jax.block_until_ready(out)
        delivered, ticks, forwards = (np.asarray(o) for o in out)
        return delivered, int(ticks), int(forwards)

    def run_numpy(self, cells_per_circuit: int, max_ticks: int):
        queued0, fl = self._args(cells_per_circuit)
        d, t, fw = torcells_run_numpy(queued0, fl["flow_node"],
                                      fl["flow_lat"], fl["flow_succ"],
                                      fl["seg_start"], self.refill,
                                      self.capacity, self.ring_len,
                                      max_ticks)
        return d, t, fw
