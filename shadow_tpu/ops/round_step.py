"""The per-round device kernel: all packet hops in a window as one jitted step.

Reference hot path (worker.c:243-304 ``worker_sendPacket``): for EACH packet,
look up path reliability, draw a uniform, maybe drop, look up path latency,
schedule delivery.  That is a per-packet scalar pipeline; on TPU the same
work is one batched step over the round's whole packet set:

    latency  = L[src_row, dst_row]          # int64 ns gather
    rel      = R[src_row, dst_row]          # f32 gather
    u        = threefry(drop_key, uid)      # counter-based, order-independent
    keep     = bootstrap | rel >= 1 | u <= rel
    deliver  = send_time + latency          # int64 ns, exact

Determinism contract: the uniform is keyed by the packet uid, not execution
order, and is the bitwise-identical construction the CPU policies use
(core/rng.py), so the CPU and TPU schedulers drop exactly the same packets
and compute exactly the same delivery times (int64 ns math on device; x64
is enabled by the ops package __init__).

Dynamic per-round packet counts vs XLA static shapes (SURVEY.md §7 hard
part d): batches are padded to power-of-two buckets with a validity mask, so
each bucket size compiles once and is reused.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.rng import threefry2x32_jnp

MIN_BUCKET = 256


def bucket_size(n: int) -> int:
    """Smallest power-of-two bucket >= n (min MIN_BUCKET) — bounds the number
    of distinct compiled shapes to log2(max_batch)."""
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def _uniform_from_uid(key_lo: jnp.ndarray, key_hi: jnp.ndarray,
                      uid_lo: jnp.ndarray, uid_hi: jnp.ndarray) -> jnp.ndarray:
    """f32 uniform in [0,1) from the 64-bit drop key and 64-bit packet uid.
    Same 24-bit-mantissa construction as core.rng.uniform_np, so comparisons
    against f32 reliability values decide identically on CPU and device."""
    x0, _ = threefry2x32_jnp(key_lo, key_hi, uid_lo, uid_hi)
    return (x0 >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


@partial(jax.jit, donate_argnums=())
def packet_hop_step(latency_ns: jnp.ndarray,     # int64 [A, A]
                    reliability: jnp.ndarray,    # f32   [A, A]
                    src_rows: jnp.ndarray,       # int32 [N]
                    dst_rows: jnp.ndarray,       # int32 [N]
                    uid_lo: jnp.ndarray,         # uint32 [N]
                    uid_hi: jnp.ndarray,         # uint32 [N]
                    send_times: jnp.ndarray,     # int64 [N]
                    valid: jnp.ndarray,          # bool  [N]
                    key_lo: jnp.ndarray,         # uint32 scalar
                    key_hi: jnp.ndarray,         # uint32 scalar
                    bootstrap_end: jnp.ndarray,  # int64 scalar
                    barrier: jnp.ndarray,        # int64 scalar (round end clamp)
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One device step for a padded packet batch.

    Returns (deliver_times int64 [N], keep bool [N]).  Invalid (padding) lanes
    come back keep=False.  The barrier clamp mirrors the cross-host push clamp
    (reference scheduler_policy_host_steal.c:225-242) — a safety net that
    never fires when lookahead == min path latency.
    """
    lat = latency_ns[src_rows, dst_rows]
    rel = reliability[src_rows, dst_rows]
    return _finish_hop(lat, rel, uid_lo, uid_hi, send_times, valid,
                       key_lo, key_hi, bootstrap_end, barrier)


def _finish_hop(lat, rel, uid_lo, uid_hi, send_times, valid,
                key_lo, key_hi, bootstrap_end, barrier):
    """Post-gather hop math — ONE definition so every kernel layout
    (single-device, batch-sharded, matrix-sharded) encodes the identical
    CPU/TPU determinism contract."""
    u = _uniform_from_uid(key_lo, key_hi, uid_lo, uid_hi)
    bootstrapping = send_times < bootstrap_end
    keep = (bootstrapping | (rel >= jnp.float32(1.0)) | (u <= rel)) & valid
    deliver = jnp.maximum(send_times + lat, barrier)
    return deliver, keep


@jax.jit
def packet_hop_step_packed(latency_ns: jnp.ndarray,   # int64 [A, A]
                           reliability: jnp.ndarray,  # f32   [A, A]
                           packed: jnp.ndarray,       # int64 [1+B, 3]
                           key_lo: jnp.ndarray, key_hi: jnp.ndarray,
                           bootstrap_end: jnp.ndarray,
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Packed-layout hop step: ONE host->device array per flush instead of
    six, and zero per-call scalar uploads.  Row 0 is a header: (valid row
    count n, round barrier ns, 0).  Data row layout: word0 = (src_row << 32)
    | dst_row, word1 = the packet uid (uint64 bit pattern), word2 = send
    time ns.  The validity mask is derived on-device (iota < n), so padding
    costs no transfer; outputs stay PADDED — callers slice host-side after
    materializing, because a device-side [:n] slice would be a second
    dispatched op per flush (measured ~140us each on the CPU backend).
    Same math as packet_hop_step via _finish_hop — bit-identical decisions."""
    n = packed[0, 0].astype(jnp.int32)
    barrier = packed[0, 1]
    w0 = packed[1:, 0]
    uid = packed[1:, 1]
    send_times = packed[1:, 2]
    src = (w0 >> jnp.int64(32)).astype(jnp.int32)
    dst = (w0 & jnp.int64(0xFFFFFFFF)).astype(jnp.int32)
    # arithmetic >> then mask == logical shift for the uint64 bit pattern
    uid_lo = (uid & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    uid_hi = ((uid >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    valid = jnp.arange(w0.shape[0], dtype=jnp.int32) < n
    lat = latency_ns[src, dst]
    rel = reliability[src, dst]
    return _finish_hop(lat, rel, uid_lo, uid_hi, send_times, valid,
                       key_lo, key_hi, bootstrap_end, barrier)


class PacketHopKernel:
    """Host-side wrapper owning the device-resident topology tensors and the
    drop key; turns a round's (src_row, dst_row, uid, send_time) arrays into
    (deliver_time, keep) numpy arrays with one device call."""

    # >0: batches below this size are computed with the bitwise-identical
    # vectorized numpy path instead of a device call (uniform_np and the jnp
    # threefry are the same cipher — asserted by tests/test_rng.py — so
    # results are indistinguishable).  The default dropped 4096 -> 0 in r4:
    # the packed header-row upload (no per-call scalars), unsliced padded
    # outputs, and the asynchronous launch/consume split cut the measured
    # per-dispatch tax to one ~30us jit call (CPU backend), at which point
    # always-device measured FASTER than any bypass mix on tor200 (5.57s vs
    # 5.69-5.75s).  ``--tpu-device-threshold N`` restores a bypass for
    # environments with pathological dispatch round trips (remote tunnels).
    DEVICE_THRESHOLD = 0

    def __init__(self, topology, drop_key: int, bootstrap_end_ns: int,
                 device_threshold: Optional[int] = None):
        lat, rel = topology.device_tensors()
        self.latency = lat
        self.reliability = rel
        # host-side copies for the small-batch path
        self.latency_np = np.asarray(topology.latency_ns)
        self.reliability_np = np.asarray(topology.reliability,
                                         dtype=np.float32)
        kv = int(drop_key) & 0xFFFFFFFFFFFFFFFF
        self.drop_key = kv
        self.key_lo = jnp.uint32(kv & 0xFFFFFFFF)
        self.key_hi = jnp.uint32((kv >> 32) & 0xFFFFFFFF)
        self.bootstrap_end = jnp.int64(bootstrap_end_ns)
        self.bootstrap_end_ns = int(bootstrap_end_ns)
        self.device_calls = 0
        self.host_calls = 0
        if device_threshold is not None:
            self.DEVICE_THRESHOLD = device_threshold
        # distinct padded batch shapes seen = XLA recompile count (the
        # engine heartbeat reports this; SURVEY.md §7 hard part d)
        self.buckets_seen: set = set()

    def _step_numpy(self, src_rows, dst_rows, uids, send_times,
                    barrier_ns: int) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized host path for small rounds — same math, same cipher,
        same f32 comparison as the device kernel, so the decision per packet
        is identical bit for bit."""
        from ..core.rng import uniform_np
        lat = self.latency_np[src_rows, dst_rows]
        rel = self.reliability_np[src_rows, dst_rows]
        u = uniform_np(self.drop_key, uids.astype(np.uint64))
        send_times = send_times.astype(np.int64, copy=False)
        keep = ((send_times < self.bootstrap_end_ns)
                | (rel >= np.float32(1.0))
                | (u.astype(np.float32) <= rel))
        deliver = np.maximum(send_times + lat, np.int64(barrier_ns))
        self.host_calls += 1
        return deliver, keep

    def _padded_batch(self, src_rows, dst_rows, uids, send_times, b: int):
        """Pad the round's arrays to bucket size b and split 64-bit uids
        into the (lo, hi) u32 pair the threefry kernel consumes."""
        n = len(src_rows)

        def pad(a, fill=0):
            out = np.full(b, fill, dtype=a.dtype)
            out[:n] = a
            return out

        uids = np.asarray(uids, dtype=np.uint64)
        valid = np.zeros(b, dtype=bool)
        valid[:n] = True
        return (pad(np.asarray(src_rows, dtype=np.int32)),
                pad(np.asarray(dst_rows, dtype=np.int32)),
                pad((uids & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
                pad((uids >> np.uint64(32)).astype(np.uint32)),
                pad(np.asarray(send_times, dtype=np.int64)),
                valid)

    def _pack(self, src_rows, dst_rows, uids, send_times, b: int,
              barrier_ns: int) -> np.ndarray:
        """Assemble the [1+b, 3] int64 packed batch (header row 0 carries
        n and the barrier — see packet_hop_step_packed's layout)."""
        n = len(src_rows)
        packed = np.zeros((1 + b, 3), dtype=np.int64)
        packed[0, 0] = n
        packed[0, 1] = barrier_ns
        packed[1:n + 1, 0] = ((np.asarray(src_rows, dtype=np.int64) << 32)
                              | np.asarray(dst_rows, dtype=np.int64))
        packed[1:n + 1, 1] = np.asarray(uids, dtype=np.uint64).view(np.int64)
        packed[1:n + 1, 2] = np.asarray(send_times, dtype=np.int64)
        return packed

    def launch(self, src_rows: np.ndarray, dst_rows: np.ndarray,
               uids: np.ndarray, send_times: np.ndarray,
               barrier_ns: int) -> Tuple[np.ndarray, np.ndarray]:
        """Dispatch one chunk WITHOUT materializing the result: returns
        (deliver, keep) that may be unfinished PADDED device arrays (length
        >= N; callers slice to their row count after np.asarray).  The
        caller converts with np.asarray when it actually needs the values
        (the engine does so at the next round boundary), so device compute
        overlaps host-side work.  The numpy bypass path (DEVICE_THRESHOLD)
        returns finished exact-length host arrays with the same interface."""
        n = len(src_rows)
        if n == 0:
            return (np.empty(0, dtype=np.int64), np.empty(0, dtype=bool))
        if n < self.DEVICE_THRESHOLD:
            return self._step_numpy(np.asarray(src_rows), np.asarray(dst_rows),
                                    np.asarray(uids), np.asarray(send_times),
                                    barrier_ns)
        b = bucket_size(n)
        self.buckets_seen.add(b)
        packed = self._pack(src_rows, dst_rows, uids, send_times, b,
                            barrier_ns)
        deliver, keep = packet_hop_step_packed(
            self.latency, self.reliability, packed,
            self.key_lo, self.key_hi, self.bootstrap_end)
        self.device_calls += 1
        return deliver, keep

    def step(self, src_rows: np.ndarray, dst_rows: np.ndarray,
             uids: np.ndarray, send_times: np.ndarray,
             barrier_ns: int) -> Tuple[np.ndarray, np.ndarray]:
        """Synchronous variant of launch (materialized, exact-length)."""
        n = len(src_rows)
        deliver, keep = self.launch(src_rows, dst_rows, uids, send_times,
                                    barrier_ns)
        return np.asarray(deliver)[:n], np.asarray(keep)[:n]


# ---------------------------------------------------------------------------
# Multi-chip round step: the packet batch is sharded across the mesh (the
# simulator's data-parallel axis); the path matrices are replicated (attached
# vertex counts are small even for 10k-host graphs — SURVEY.md §3.5) or, for
# huge graphs, row-sharded with an all-gather.  ShardedPacketHopKernel is
# the ONE sharding entry point for packet hops (mesh construction comes
# from parallel/mesh.device_mesh, shared with the traffic plane); the
# step builders below are its internals.  (The standalone
# make_sharded_hop_step / make_2d_sharded_hop_step demo builders were
# test-only and retired with the mesh plane — the driver dryrun and
# tests/test_scaleout.py now exercise the kernel class and the mesh
# plane's own collectives instead.)
# ---------------------------------------------------------------------------

def _make_matrix_sharded_hop_step(mesh, axis: str = "pkt"):
    """Row-sharded variant for graphs whose [A, A] path matrices exceed one
    chip's HBM (SURVEY.md §7 stage 10): each device holds A/D rows of the
    latency/reliability matrices; the packet batch is replicated; every
    device gathers the entries whose src row it owns and a psum over the
    mesh assembles the full result (one ICI collective per round, the
    device-side analog of the scheduler's cross-thread barrier merge).

    The mesh size must divide the row count; callers pad the matrices up to
    a multiple first (ShardedPacketHopKernel does this when constructed
    with shard_matrix=True — padded rows are never indexed because src rows
    always reference real attached vertices).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def step(latency_ns, reliability, src_rows, dst_rows,
             uid_lo, uid_hi, send_times, valid,
             key_lo, key_hi, bootstrap_end, barrier):

        def shard_body(lat_shard, rel_shard, src, dst):
            rows_per = lat_shard.shape[0]
            shard = jax.lax.axis_index(axis)
            local = src - shard * rows_per
            mine = (local >= 0) & (local < rows_per)
            idx = jnp.clip(local, 0, rows_per - 1)
            lat = jnp.where(mine, lat_shard[idx, dst], jnp.int64(0))
            rel = jnp.where(mine, rel_shard[idx, dst], jnp.float32(0.0))
            # each packet's row lives on exactly one shard -> psum assembles
            return (jax.lax.psum(lat, axis), jax.lax.psum(rel, axis))

        lat, rel = shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P(), P()),
            out_specs=(P(), P()))(latency_ns, reliability,
                                  src_rows, dst_rows)
        return _finish_hop(lat, rel, uid_lo, uid_hi, send_times, valid,
                           key_lo, key_hi, bootstrap_end, barrier)

    return jax.jit(step)


class ShardedPacketHopKernel(PacketHopKernel):
    """Multi-device kernel: same .step API as PacketHopKernel, over a 1-D
    device mesh (``--tpu-devices N``).

    Two layouts:
    * default — the padded batch is sharded over the mesh, path matrices
      replicated on every chip (cheapest when the matrices fit in HBM);
    * ``shard_matrix=True`` (``--tpu-shard-matrix``) — the matrices are
      row-sharded across the mesh (each chip holds A/D rows, padded up to a
      multiple of D) and the batch is replicated; per-packet entries are
      assembled with a psum.  This is the HBM scale-out path for graphs
      whose [A, A] tensors exceed one chip.
    """

    def __init__(self, topology, drop_key: int, bootstrap_end_ns: int,
                 n_devices: int, shard_matrix: bool = False):
        super().__init__(topology, drop_key, bootstrap_end_ns)
        from jax.sharding import NamedSharding, PartitionSpec as P
        # mesh construction (pool selection incl. the virtual-CPU-mesh
        # fallback) has ONE definition, shared with the traffic plane
        from ..parallel.mesh import device_mesh
        self.mesh = device_mesh(n_devices, axis_names=("pkt",))
        self.n_devices = n_devices
        self.shard_matrix = shard_matrix
        self._batch_sharding = NamedSharding(self.mesh, P("pkt"))
        self._replicated = NamedSharding(self.mesh, P())
        if shard_matrix:
            lat = np.asarray(self.latency)
            rel = np.asarray(self.reliability)
            rows = lat.shape[0]
            padded = -(-rows // n_devices) * n_devices
            if padded != rows:
                # padded rows are never indexed: src rows always reference
                # real attached vertices
                lat = np.pad(lat, ((0, padded - rows), (0, 0)))
                rel = np.pad(rel, ((0, padded - rows), (0, 0)))
            row_sharding = NamedSharding(self.mesh, P("pkt", None))
            self.latency = jax.device_put(lat, row_sharding)
            self.reliability = jax.device_put(rel, row_sharding)
            self._step = _make_matrix_sharded_hop_step(self.mesh,
                                                        axis="pkt")
            self._batch_placement = self._replicated
        else:
            self.latency = jax.device_put(self.latency, self._replicated)
            self.reliability = jax.device_put(self.reliability,
                                              self._replicated)
            self._step = _make_batch_sharded_2out(self.mesh, "pkt")
            self._batch_placement = self._batch_sharding

    def launch(self, src_rows, dst_rows, uids, send_times, barrier_ns):
        # the mesh layouts keep their explicit-sharding step; deliveries are
        # still returned unmaterialized (jax arrays, PADDED — callers slice
        # host-side after np.asarray, same contract as the packed kernel),
        # so consume-side overlap applies here too
        return self.step_sharded(src_rows, dst_rows, uids, send_times,
                                 barrier_ns)

    def step(self, src_rows, dst_rows, uids, send_times, barrier_ns):
        n = len(src_rows)
        deliver, keep = self.step_sharded(src_rows, dst_rows, uids,
                                          send_times, barrier_ns)
        return np.asarray(deliver)[:n], np.asarray(keep)[:n]

    def step_sharded(self, src_rows, dst_rows, uids, send_times, barrier_ns):
        n = len(src_rows)
        if n == 0:
            return (np.empty(0, dtype=np.int64), np.empty(0, dtype=bool))
        if n < self.DEVICE_THRESHOLD:
            # same numpy bypass contract as the single-device kernel
            # (--tpu-device-threshold applies to every layout)
            return self._step_numpy(np.asarray(src_rows), np.asarray(dst_rows),
                                    np.asarray(uids), np.asarray(send_times),
                                    barrier_ns)
        # bucket must also be divisible by the mesh axis
        b = max(bucket_size(n), self.n_devices * MIN_BUCKET)
        if b % self.n_devices:
            b = -(-b // self.n_devices) * self.n_devices
        self.buckets_seen.add(b)
        batch = self._padded_batch(src_rows, dst_rows, uids, send_times, b)
        put = partial(jax.device_put, device=self._batch_placement)
        deliver, keep = self._step(
            self.latency, self.reliability,
            *(put(a) for a in batch),
            self.key_lo, self.key_hi, self.bootstrap_end,
            jnp.int64(barrier_ns))
        self.device_calls += 1
        return deliver, keep


def _make_batch_sharded_2out(mesh, axis: str):
    """Batch-sharded step WITHOUT the global-min collective: the engine's
    next-window time comes from the host-side event queues, so paying an
    ICI reduction per round for an unused value would be waste.  (The
    engine's window times come from the host event queues.)"""
    from jax.sharding import NamedSharding, PartitionSpec as P
    batch = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return jax.jit(packet_hop_step,
                   in_shardings=(repl, repl, batch, batch, batch, batch,
                                 batch, batch, repl, repl, repl, repl),
                   out_shardings=(batch, batch))
