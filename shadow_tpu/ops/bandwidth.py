"""Device-side token-bucket admission: the bandwidth term of the north star.

Reference semantics being modeled (host/network_interface.c:421-455 receive
loop + :93-95/:207-214 refill):

* each host's receive bucket holds ``tokens`` bytes, capacity
  ``refill * CAPACITY_FACTOR + MTU``, and gains ``refill`` bytes at every
  1 ms boundary while there is pending work;
* arriving packets drain in FIFO order; a packet is delivered when the
  bucket covers its full size, otherwise it waits for the refill tick that
  covers it.  The capacity cap only binds across idle gaps (a bucket never
  accumulates past ``capacity``).

The kernel computes one round's per-packet admission time for EVERY host at
once: the batch is pre-sorted by (dst_row, arrival, order) so each host's
packets form a contiguous FIFO run, and a single ``lax.scan`` walks the
sorted batch carrying ``(dst, tick, tokens)`` — exact whole-packet bucket
semantics, including the idle-gap cap, in one fused device pass.  Per-round
batches are padded to power-of-two buckets like the hop kernel, so shapes
compile once.

Exactness is asserted bit-for-bit against the event-driven host
implementation (the TokenBucket class the CPU policies use) by
tests/test_bandwidth_ops.py.

Why this kernel is NOT wired into the tpu policy's flush as a replacement
for the event-driven interface drain: the exactness boundary is the
interface's self-suspending refill task (network_interface.c:121-183).
One task refills BOTH the send and receive buckets each tick and stays
scheduled only while any work is pending — so receive-side pacing decided
ahead-of-time on device would still have to reproduce the task's side
effects on the *send* bucket (and its scheduling lifetime) to keep state
digests identical to the CPU policies, which means running the event
machinery anyway.  Batch pacing is therefore exact only for the isolated
FIFO-bucket regime this kernel models (what the parity test pins down);
the full composition — hop latency + bucket pacing + drop-tail overflow
fused on device — is demonstrated where it is architecturally honest: the
fully device-resident model in ops/saturate_device.py, where ALL interface
state lives in HBM and there is no host twin to stay bit-equal with.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core import defs, stime

# >>> simgen:begin region=token-bucket-kernel spec=293c930bb679 body=ae8bb8568cdc
REFILL_NS = 1000000   # == defs.INTERFACE_REFILL_INTERVAL_NS (1 ms)
# <<< simgen:end region=token-bucket-kernel


def bucket_params(rate_kibps: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vector twin of host/network_interface.py TokenBucket.__init__."""
    time_factor = stime.SIM_TIME_SEC // REFILL_NS
    refill = (np.asarray(rate_kibps).astype(np.int64) * 1024) // time_factor
    capacity = refill * defs.INTERFACE_CAPACITY_FACTOR + defs.CONFIG_MTU
    return refill, capacity


@jax.jit
def admit_sorted(dst_rows: jnp.ndarray,      # int32 [N] sorted ascending
                 sizes: jnp.ndarray,         # int64 [N] packet bytes
                 arrive: jnp.ndarray,        # int64 [N] ns, sorted within dst
                 valid: jnp.ndarray,         # bool  [N]
                 tokens0: jnp.ndarray,       # int64 [H] fill at each host's
                                             #   first arrival in the batch
                 refill: jnp.ndarray,        # int64 [H] bytes per 1ms tick
                 capacity: jnp.ndarray,      # int64 [H] bucket cap
                 ) -> jnp.ndarray:
    """FIFO token-bucket admission times for a dst-sorted batch.

    Exact recurrence per host run (= the event-driven drain):
        start_i = max(arrive_i, admit_{i-1})
        avail   = min(cap, tokens + refill * (tick(start_i) - tick_state))
        admit_i = start_i                    if avail >= size_i
                = (tick(start_i)+k)*REFILL   with k = ceil((size-avail)/refill)
    carrying (dst, tick_state, tokens, admit) across the scan; the carry
    resets from tokens0 whenever dst changes (new host's run begins).
    """
    def step(carry, x):
        prev_dst, tick_state, tok, prev_admit = carry
        dst, size, arr, ok = x
        new_seg = dst != prev_dst
        tick_state = jnp.where(new_seg, arr // REFILL_NS, tick_state)
        tok = jnp.where(new_seg, tokens0[dst], tok)
        prev_admit = jnp.where(new_seg, jnp.int64(0), prev_admit)
        ref = jnp.maximum(refill[dst], jnp.int64(1))
        cap = capacity[dst]
        start = jnp.maximum(arr, prev_admit)
        stick = start // REFILL_NS
        avail = jnp.minimum(cap, tok + ref * (stick - tick_state))
        kneed = jnp.maximum(size - avail, jnp.int64(0))
        k = (kneed + ref - 1) // ref
        admit = jnp.where(kneed > 0, (stick + k) * REFILL_NS, start)
        tok_after = jnp.minimum(cap, avail + k * ref) - size
        new_tick = jnp.where(kneed > 0, stick + k, stick)
        # invalid (padding) lanes leave the carry untouched
        out_carry = (jnp.where(ok, dst, prev_dst),
                     jnp.where(ok, new_tick, tick_state),
                     jnp.where(ok, tok_after, tok),
                     jnp.where(ok, admit, prev_admit))
        return out_carry, jnp.where(ok, admit, jnp.int64(0))

    init = (jnp.int32(-1), jnp.int64(0), jnp.int64(0), jnp.int64(0))
    _, admits = jax.lax.scan(step, init,
                             (dst_rows, sizes, arrive, valid))
    return admits


class BandwidthKernel:
    """Host-side wrapper: sorts a round's batch by (dst, arrival, order),
    runs :func:`admit_sorted`, and scatters results back to batch order."""

    def __init__(self, rate_down_kibps: np.ndarray):
        refill, capacity = bucket_params(rate_down_kibps)
        self.refill = jnp.asarray(refill)
        self.capacity = jnp.asarray(capacity)
        self.capacity_np = capacity
        self.device_calls = 0

    def admit(self, dst_rows: np.ndarray, sizes: np.ndarray,
              arrive: np.ndarray, tokens0: np.ndarray) -> np.ndarray:
        """Admission time per packet (batch order)."""
        n = len(dst_rows)
        if n == 0:
            return np.empty(0, dtype=np.int64)
        b = 1 << max(8, int(np.ceil(np.log2(n))))
        order = np.lexsort((np.arange(n), arrive, dst_rows))
        inv = np.empty(n, dtype=np.int64)
        inv[order] = np.arange(n)

        def pad(a, fill=0):
            out = np.full(b, fill, dtype=a.dtype)
            out[:n] = a
            return out

        valid = np.zeros(b, dtype=bool)
        valid[:n] = True
        admits = admit_sorted(
            jnp.asarray(pad(dst_rows[order].astype(np.int32))),
            jnp.asarray(pad(sizes[order].astype(np.int64))),
            jnp.asarray(pad(arrive[order].astype(np.int64))),
            jnp.asarray(valid),
            jnp.asarray(np.asarray(tokens0, dtype=np.int64)),
            self.refill, self.capacity)
        self.device_calls += 1
        # simjit: disable=SIM302 -- designed collect: admit() is a synchronous batch query (one launch, one read); no dispatch window exists here
        return np.asarray(admits)[:n][inv]
