"""Kernel-plane protocol tables, generated from the authoritative spec.

The device kernels operate on integer state ids and coefficient arrays,
not on the Python plane's string states or the C plane's enums.  This
module is the kernel plane's copy of the protocol surfaces that the
other two planes also carry — the TCP state universe (tuple index ==
C-plane ``TcpState`` id), the legal state-transition pairs, and the
congestion-control coefficient families — materialized by simgen from
``spec/protocol_spec.json`` exactly like the twin regions in
``core/defs.py`` and ``native/dataplane.cc``.  simtwin's SIM201/SIM203
passes hold this module to the same cross-plane agreement as the
runtime planes.
"""

from __future__ import annotations

import numpy as np

# >>> simgen:begin region=protocol-tables spec=f421682bce6f body=1585a58dc283
# TCP state universe, reference-enum order; the tuple index IS
# the C-plane TcpState id.
TCP_STATES = (
    "closed",
    "listen",
    "syn_sent",
    "syn_received",
    "established",
    "fin_wait_1",
    "fin_wait_2",
    "closing",
    "time_wait",
    "close_wait",
    "last_ack",
)

# Legal (from, to) transition pairs; "?" = unguarded.
TCP_TRANSITIONS = (
    ("?", "closed"),
    ("?", "established"),
    ("?", "listen"),
    ("?", "syn_received"),
    ("?", "syn_sent"),
    ("?", "time_wait"),
    ("close_wait", "last_ack"),
    ("established", "close_wait"),
    ("established", "fin_wait_1"),
    ("fin_wait_1", "closing"),
    ("fin_wait_1", "fin_wait_2"),
    ("fin_wait_1", "time_wait"),
    ("syn_received", "established"),
    ("syn_received", "fin_wait_1"),
)

# Congestion-control coefficient families + config-token kind ids.
CUBIC_C = 0.4
CUBIC_BETA = 0.7
CUBICX_C = 0.6
CUBICX_BETA = 0.85
CC_KIND_IDS = {"aimd": 1, "cubic": 2, "cubicx": 3, "reno": 0}
# (C, beta) per kind id; non-cubic kinds carry the cubic defaults (unused)
CC_COEFFS = {
    1: (CUBIC_C, CUBIC_BETA),  # aimd
    2: (CUBIC_C, CUBIC_BETA),  # cubic
    3: (CUBICX_C, CUBICX_BETA),  # cubicx
    0: (CUBIC_C, CUBIC_BETA),  # reno
}
# <<< simgen:end region=protocol-tables

ANY_STATE = "?"          # an assignment no state guard encloses


def state_id(name: str) -> int:
    """C-plane TcpState id for a state name (255 for the '?' wildcard,
    matching the C transition table's encoding)."""
    if name == ANY_STATE:
        return 255
    return TCP_STATES.index(name)


def transition_matrix() -> np.ndarray:
    """Boolean [n_states+1, n_states] allow-matrix: row ``i`` = from-state
    id (last row = the '?' wildcard), column = to-state id."""
    n = len(TCP_STATES)
    m = np.zeros((n + 1, n), dtype=np.bool_)
    for frm, to in TCP_TRANSITIONS:
        row = n if frm == ANY_STATE else TCP_STATES.index(frm)
        m[row, TCP_STATES.index(to)] = True
    return m


def cc_coefficients() -> np.ndarray:
    """[n_kinds, 2] float64 (C, beta) rows indexed by CC_KIND_IDS, built
    from the generated CC_COEFFS table — a new spec variant lands here
    via `make gen` with no hand edit."""
    n = max(CC_KIND_IDS.values()) + 1
    out = np.zeros((n, 2))
    for kind_id, (c, beta) in CC_COEFFS.items():
        out[kind_id] = (c, beta)
    return out
