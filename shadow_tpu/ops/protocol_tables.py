"""Kernel-plane protocol tables, generated from the authoritative spec.

The device kernels operate on integer state ids and coefficient arrays,
not on the Python plane's string states or the C plane's enums.  This
module is the kernel plane's copy of the protocol surfaces that the
other two planes also carry — the TCP state universe (tuple index ==
C-plane ``TcpState`` id), the legal state-transition pairs, and the
congestion-control coefficient families — materialized by simgen from
``spec/protocol_spec.json`` exactly like the twin regions in
``core/defs.py`` and ``native/dataplane.cc``.  simtwin's SIM201/SIM203
passes hold this module to the same cross-plane agreement as the
runtime planes.
"""

from __future__ import annotations

import numpy as np

# >>> simgen:begin region=protocol-tables spec=293c930bb679 body=d9f495f010ac
# TCP state universe, reference-enum order; the tuple index IS
# the C-plane TcpState id.
TCP_STATES = (
    "closed",
    "listen",
    "syn_sent",
    "syn_received",
    "established",
    "fin_wait_1",
    "fin_wait_2",
    "closing",
    "time_wait",
    "close_wait",
    "last_ack",
)

# Legal (from, to) transition pairs; "?" = unguarded.
TCP_TRANSITIONS = (
    ("?", "closed"),
    ("?", "established"),
    ("?", "listen"),
    ("?", "syn_received"),
    ("?", "syn_sent"),
    ("?", "time_wait"),
    ("close_wait", "last_ack"),
    ("established", "close_wait"),
    ("established", "fin_wait_1"),
    ("fin_wait_1", "closing"),
    ("fin_wait_1", "fin_wait_2"),
    ("fin_wait_1", "time_wait"),
    ("syn_received", "established"),
    ("syn_received", "fin_wait_1"),
)

# Congestion-control coefficient families + config-token kind ids.
CUBIC_C = 0.4
CUBIC_BETA = 0.7
CUBICX_C = 0.6
CUBICX_BETA = 0.85
CC_KIND_IDS = {"aimd": 1, "bbrx": 4, "cubic": 2, "cubicx": 3, "reno": 0}
# (C, beta) per kind id; non-cubic kinds carry the cubic defaults (unused)
CC_COEFFS = {
    1: (CUBIC_C, CUBIC_BETA),  # aimd
    4: (CUBIC_C, CUBIC_BETA),  # bbrx
    2: (CUBIC_C, CUBIC_BETA),  # cubic
    3: (CUBICX_C, CUBICX_BETA),  # cubicx
    0: (CUBIC_C, CUBIC_BETA),  # reno
}
# <<< simgen:end region=protocol-tables

# >>> simgen:begin region=kernel-logic spec=293c930bb679 body=f02981e31cd7
# bbrx estimator parameters (mirrors descriptor/tcp_cong.py)
BBRX_BETA_DEN = 8
BBRX_BETA_NUM = 7
BBRX_BW_CAP_BPS = 1000000000000
BBRX_CYCLE_LEN = 8
BBRX_CYCLE_NS = 25000000
BBRX_GAIN_CRUISE_NUM = 4
BBRX_GAIN_DEN = 4
BBRX_GAIN_DOWN_NUM = 3
BBRX_GAIN_UP_NUM = 5
BBRX_MIN_CWND_SEGMENTS = 4
BBRX_RTT_CAP_NS = 1000000000
BBRX_RTT_FLOOR_NS = 100000


# protocol-update logic, generated from the spec's expression IR;
# elementwise over int64 arrays (device-vs-numpy parity is pinned in tests)

def bbrx_bdp_bytes_np(btl_bw_bps, min_rtt_ns):
    """bandwidth-delay product; the /1000 then /1e6 split keeps the intermediate below 2**63 at the bw/rtt caps"""
    return (((btl_bw_bps // 1000) * np.minimum(min_rtt_ns, 1000000000)) // 1000000)


def bbrx_btl_bw_np(btl_bw_bps, bw_sample_bps):
    """bottleneck-bandwidth max filter"""
    return np.maximum(btl_bw_bps, bw_sample_bps)


def bbrx_bw_decay_np(btl_bw_bps):
    """multiplicative bandwidth-estimate decay on loss"""
    return ((btl_bw_bps * 7) // 8)


def bbrx_bw_sample_np(acked_bytes, interval_ns):
    """delivery-rate sample in bytes/sec from one ACK's bytes over the inter-ACK interval, capped"""
    return np.minimum(((acked_bytes * 1000000000) // np.maximum(interval_ns, 1)), 1000000000000)


def bbrx_gain_num_np(cycle_idx):
    """gain numerator for the cycle phase: probe up, drain down, then cruise (BBR's 5/4, 3/4, 1.0 x6 over BBRX_GAIN_DEN)"""
    return np.where((cycle_idx == 0), 5, np.where((cycle_idx == 1), 3, 4))


def bbrx_inflight_cap_np(bdp_bytes, gain_num, mss):
    """cwnd = max(gain * bdp, floor segments)"""
    return np.maximum(((bdp_bytes * gain_num) // 4), (4 * mss))


def bbrx_min_rtt_np(min_rtt_ns, interval_ns):
    """min-RTT filter over floored inter-ACK intervals"""
    return np.minimum(min_rtt_ns, np.maximum(interval_ns, 100000))


def bbrx_next_cycle_np(cycle_idx):
    """pacing-gain cycle advance"""
    return ((cycle_idx + 1) % 8)


def recovery_cwnd_np(ssthresh, mss):
    """fast-recovery window inflation (ssthresh + 3*mss)"""
    return (ssthresh + (3 * mss))


def rto_backoff_np(rto_ns):
    """exponential backoff on retransmission timeout"""
    return np.minimum((rto_ns * 2), 120000000000)


def rto_from_estimate_np(srtt_ns, rttvar_ns):
    """RTO = clamp(srtt + 4*rttvar) into [RTO_MIN, RTO_MAX]"""
    return np.maximum(200000000, np.minimum((srtt_ns + (4 * rttvar_ns)), 120000000000))


def rttvar_update_np(srtt_ns, rttvar_ns, sample_ns):
    """RFC 6298 RTT variance over the PRE-update srtt; |err| spelled max-min so every plane stays in non-negative int64"""
    return np.where((srtt_ns == 0), (sample_ns // 2), (((3 * rttvar_ns) + (np.maximum(sample_ns, srtt_ns) - np.minimum(sample_ns, srtt_ns))) // 4))


def srtt_update_np(srtt_ns, sample_ns):
    """RFC 6298 smoothed RTT; first sample seeds the filter"""
    return np.where((srtt_ns == 0), sample_ns, (((7 * srtt_ns) + sample_ns) // 8))


def ssthresh_after_loss_np(cwnd, mss):
    """ssthresh = max(cwnd/2, 2*mss) on loss (RFC 5681)"""
    return np.maximum((cwnd // 2), (2 * mss))
# <<< simgen:end region=kernel-logic

ANY_STATE = "?"          # an assignment no state guard encloses


def state_id(name: str) -> int:
    """C-plane TcpState id for a state name (255 for the '?' wildcard,
    matching the C transition table's encoding)."""
    if name == ANY_STATE:
        return 255
    return TCP_STATES.index(name)


def transition_matrix() -> np.ndarray:
    """Boolean [n_states+1, n_states] allow-matrix: row ``i`` = from-state
    id (last row = the '?' wildcard), column = to-state id."""
    n = len(TCP_STATES)
    m = np.zeros((n + 1, n), dtype=np.bool_)
    for frm, to in TCP_TRANSITIONS:
        row = n if frm == ANY_STATE else TCP_STATES.index(frm)
        m[row, TCP_STATES.index(to)] = True
    return m


def cc_coefficients() -> np.ndarray:
    """[n_kinds, 2] float64 (C, beta) rows indexed by CC_KIND_IDS, built
    from the generated CC_COEFFS table — a new spec variant lands here
    via `make gen` with no hand edit."""
    n = max(CC_KIND_IDS.values()) + 1
    out = np.zeros((n, 2))
    for kind_id, (c, beta) in CC_COEFFS.items():
        out[kind_id] = (c, beta)
    return out
