"""Fully device-resident PHOLD: the end-state of the north-star design.

PHOLD (reference src/test/phold/test_phold.c; apps/phold.py is the
engine-driven twin) is the classic PDES scheduler benchmark: a fixed
population of messages bounces between hosts, each hop at the receiver's
time plus the path latency.  Because every event is a packet hop, the
ENTIRE simulation — event selection, RNG, latency lookup, time advance —
fits on the device: message state lives in HBM, rounds are conservative
lookahead windows exactly like the engine's (window = min latency), and a
``lax.while_loop`` steps windows with zero host round-trips.

This is the design target the tpu scheduler policy converges to as more
per-event work moves on-device: the engine's round loop with the host
removed from the hot path.  The numbers it produces are honest about what
they are — a model workload with all state device-resident — and give the
throughput ceiling of the architecture on this chip.

Semantics (deterministic): message m at host h with ripeness time t
forwards to dst = threefry(seed, hop_counter) % (H-1) skipping self, and
arrives at t + latency[h, dst].  A window processes every message with
t < window_end; remaining messages keep their state.  Event count = total
hops executed.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.rng import threefry2x32_jnp


@jax.jit
def phold_run(latency_ns: jnp.ndarray,     # int64 [H, H]
              msg_host: jnp.ndarray,       # int32 [M] current host per msg
              msg_time: jnp.ndarray,       # int64 [M] ripeness time
              key: jnp.ndarray,            # uint32 [2] threefry key
              horizon_ns: jnp.ndarray,     # int64 scalar (traced, so one
                                           #   compile serves any horizon)
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run PHOLD to ``horizon_ns`` entirely on device.

    Returns (msg_host, msg_time, hops): final message placement/times and
    the total number of hops (= events) executed.
    """
    n_hosts = latency_ns.shape[0]
    lookahead = jnp.min(jnp.where(latency_ns > 0, latency_ns,
                                  jnp.int64(2**62)))

    def window_body(state):
        host, time, hops, counter = state
        start = jnp.min(time)
        end = start + lookahead
        ripe = time < end
        # deterministic per-message draw keyed by (msg index, hop round)
        m = host.shape[0]
        idx = jnp.arange(m, dtype=jnp.uint32)
        x0, _ = threefry2x32_jnp(key[0], key[1], idx,
                                 jnp.uint32(counter) + jnp.zeros_like(idx))
        # random peer, never self (classic PHOLD population conservation)
        k = (x0 % jnp.uint32(n_hosts - 1)).astype(jnp.int32)
        dst = jnp.where(k >= host, k + 1, k)
        lat = latency_ns[host, dst]
        host = jnp.where(ripe, dst, host)
        time = jnp.where(ripe, time + lat, time)
        hops = hops + jnp.sum(ripe.astype(jnp.int64))
        return host, time, hops, counter + 1

    def window_cond(state):
        _host, time, _hops, _counter = state
        return jnp.min(time) < horizon_ns

    host, time, hops, _ = jax.lax.while_loop(
        window_cond, window_body,
        (msg_host, msg_time, jnp.int64(0), jnp.uint32(0)))
    return host, time, hops


def phold_run_numpy(latency_ns: np.ndarray, msg_host: np.ndarray,
                    msg_time: np.ndarray, key_lo: int, key_hi: int,
                    horizon_ns: int):
    """Bit-identical host twin (same cipher, same window logic) — the
    parity oracle for the device loop."""
    from ..core.rng import threefry2x32_np

    host = msg_host.astype(np.int64).copy()
    time = msg_time.astype(np.int64).copy()
    n_hosts = latency_ns.shape[0]
    pos = latency_ns[latency_ns > 0]
    lookahead = int(pos.min()) if pos.size else 1
    hops = 0
    counter = 0
    m = len(host)
    idx = np.arange(m, dtype=np.uint32)
    while time.min() < horizon_ns:
        end = time.min() + lookahead
        ripe = time < end
        x0, _ = threefry2x32_np(np.uint32(key_lo), np.uint32(key_hi),
                                idx, np.full(m, counter, dtype=np.uint32))
        k = (x0 % np.uint32(n_hosts - 1)).astype(np.int64)
        dst = np.where(k >= host, k + 1, k)
        lat = latency_ns[host, dst]
        host = np.where(ripe, dst, host)
        time = np.where(ripe, time + lat, time)
        hops += int(ripe.sum())
        counter += 1
    return host, time, hops


class DevicePhold:
    """Convenience wrapper: build a PHOLD instance and run it on device."""

    def __init__(self, n_hosts: int, n_msgs: int, seed: int = 7,
                 min_latency_ms: float = 1.0, max_latency_ms: float = 150.0):
        rng = np.random.default_rng(seed)
        lat = rng.integers(int(min_latency_ms * 1e6),
                           int(max_latency_ms * 1e6),
                           size=(n_hosts, n_hosts)).astype(np.int64)
        np.fill_diagonal(lat, 0)
        self.latency = jnp.asarray(lat)
        self.latency_np = lat
        self.msg_host = rng.integers(0, n_hosts, size=n_msgs).astype(np.int32)
        self.msg_time = np.zeros(n_msgs, dtype=np.int64)
        self.key_lo = 0xDEADBEEF
        self.key_hi = 0x12345678
        self.key = jnp.asarray(np.array([self.key_lo, self.key_hi],
                                        dtype=np.uint32))

    def run_device(self, horizon_ns: int):
        host, time, hops = phold_run(self.latency,
                                     jnp.asarray(self.msg_host),
                                     jnp.asarray(self.msg_time),
                                     self.key, jnp.int64(horizon_ns))
        jax.block_until_ready((host, time, hops))
        return np.asarray(host), np.asarray(time), int(hops)

    def run_numpy(self, horizon_ns: int):
        return phold_run_numpy(self.latency_np, self.msg_host, self.msg_time,
                               self.key_lo, self.key_hi, horizon_ns)
