"""Device data plane: JAX kernels for the per-round packet step.

Importing this package enables jax x64 mode: simulation timestamps are
nanoseconds since boot (int64 — a one-hour simulation is 3.6e12 ns, far past
int32), and event-order parity with the CPU policies requires exact integer
time math on device.  TPUs support int64; we use float32/bfloat16 for all
non-time quantities so the MXU/VPU paths stay fast.
"""

import jax

jax.config.update("jax_enable_x64", True)
