"""Tracker: per-host metrics and heartbeat logging.

Capability of the reference's Tracker (host/tracker.c): processing/delay
time, per-interface packet/byte counters with local/remote and
data/control/retransmit splits (:25-49), socket buffer stats, allocation
tallies, and periodic heartbeat log lines.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict

from ..core.logger import get_logger
from ..routing.address import LOCALHOST_IP


def format_heartbeat_line(name: str, vals: Dict) -> str:
    """THE ``[shadow-heartbeat]`` line — one spelling shared by
    Tracker.heartbeat (live hosts) and HostTable.heartbeat_row (quiet
    table rows), so the two surfaces can never drift apart and
    tools/plot_log.py parses one shape."""
    return (f"[shadow-heartbeat] [{name}] "
            f"rx={vals['rx']} tx={vals['tx']} "
            f"rx_pkts={vals['rx_pkts']} tx_pkts={vals['tx_pkts']} "
            f"retrans={vals['retrans']} drops={vals['drops']} "
            f"proc_ms={vals['proc_ms']:.3f}")


class _Counters:
    __slots__ = ("packets_total", "bytes_total", "packets_control",
                 "bytes_control", "packets_data", "bytes_data",
                 "packets_retrans", "bytes_retrans")

    def __init__(self):
        self.packets_total = 0
        self.bytes_total = 0
        self.packets_control = 0
        self.bytes_control = 0
        self.packets_data = 0
        self.bytes_data = 0
        self.packets_retrans = 0
        self.bytes_retrans = 0

    def add(self, packet, retransmit: bool = False) -> None:
        n = packet.total_size
        self.packets_total += 1
        self.bytes_total += n
        if packet.payload_size == 0:
            self.packets_control += 1
            self.bytes_control += n
        else:
            self.packets_data += 1
            self.bytes_data += n
        if retransmit:
            self.packets_retrans += 1
            self.bytes_retrans += n

    def snapshot(self) -> Dict[str, int]:
        return {k: getattr(self, k) for k in self.__slots__}


class Tracker:
    def __init__(self, host):
        self.host = host
        self.processing_ns = 0
        self.delay_ns = 0
        self.delay_count = 0
        # split local (loopback) vs remote, in vs out
        self.in_local = _Counters()
        self.in_remote = _Counters()
        self.out_local = _Counters()
        self.out_remote = _Counters()
        self.drops = 0
        self.allocated_bytes = 0
        self.deallocated_bytes = 0
        self.socket_stats: Dict[int, Dict[str, int]] = defaultdict(dict)
        # authoritative external counter feeds, folded in lazily:
        # _native -> (NativePlane, hid): the C data plane's counters;
        # _device_feed -> (DeviceTrafficPlane, node indices): the device
        # plane's vectorized per-node byte deltas (pull_device)
        self._native = None
        self._device_feed = None

    def add_input_bytes(self, packet, iface_ip: int) -> None:
        c = self.in_local if iface_ip == LOCALHOST_IP else self.in_remote
        c.add(packet)

    def add_output_bytes(self, packet, iface_ip: int, retransmit: bool = False) -> None:
        c = self.out_local if iface_ip == LOCALHOST_IP else self.out_remote
        # TCP marks retransmissions on the packet (the reference's split
        # comes from packet delivery-status flags too, tracker.c:25-49)
        c.add(packet, retransmit or packet.retransmit)

    def add_drop(self, packet) -> None:
        self.drops += 1

    def add_processing_time(self, ns: int) -> None:
        self.processing_ns += ns

    def add_virtual_delay(self, ns: int) -> None:
        self.delay_ns += ns
        self.delay_count += 1

    def update_socket_stats(self, handle: int, rx_buf: int, rx_len: int,
                            tx_buf: int, tx_len: int) -> None:
        self.socket_stats[handle] = {"rx_buffer": rx_buf, "rx_length": rx_len,
                                     "tx_buffer": tx_buf, "tx_length": tx_len}

    def heartbeat_values(self) -> Dict:
        """The heartbeat payload, computed once: the legacy log line is
        formatted from THIS dict and the metrics registry records the same
        dict, so the two surfaces can never disagree (ISSUE 3 promotion —
        tools/plot_log.py keeps scraping the line against the same
        values)."""
        r_in, r_out = self.in_remote, self.out_remote
        return {"rx": r_in.bytes_total, "tx": r_out.bytes_total,
                "rx_pkts": r_in.packets_total,
                "tx_pkts": r_out.packets_total,
                "retrans": r_out.packets_retrans, "drops": self.drops,
                "proc_ms": round(self.processing_ns / 1e6, 3)}

    def pull_device(self) -> None:
        """Fold pending device-plane byte deltas into the counters (no-op
        unless this host contributes plane nodes): the device plane's
        collects accumulate per-node deltas in ONE numpy array, and the
        per-host split happens here, only when something actually reads
        the tracker (heartbeat, state digest, teardown)."""
        feed = self._device_feed
        if feed is not None:
            plane, nodes = feed
            plane.pull_tracker_nodes(self, nodes)

    def heartbeat(self, now: int) -> None:
        if self._native is not None:
            # native dataplane: the authoritative counters live in C
            plane, hid = self._native
            plane.sync_tracker(hid, self)
        self.pull_device()
        # the owning engine's registry when attached (robust against
        # another engine re-installing the global between construction and
        # shutdown, e.g. interleaved parity runs); the global otherwise
        registry = getattr(getattr(self.host, "engine", None),
                           "metrics", None)
        if registry is None:
            from ..obs.metrics import get_metrics
            registry = get_metrics()
        level = getattr(self.host.params, "heartbeat_log_level", None) \
            or "message"
        log = get_logger()
        emit = log.would_log(level)
        if not emit and not registry.enabled:
            return                  # 10k silent hosts pay only the pulls
        vals = self.heartbeat_values()
        registry.record_host_heartbeat(self.host.name, vals)
        if not emit:
            # the log line is filtered out: skip the format entirely —
            # the registry record above carries the same values
            return
        log.log(level, "tracker",
                format_heartbeat_line(self.host.name, vals),
                sim_time=now)
