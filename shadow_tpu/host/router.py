"""Upstream router with pluggable queue management (AQM).

Capability of the reference's Router (host/router.c) + its three queue
managers: the router models the host's upstream ISP buffer on the receive
side.  Arriving packets are enqueued (the AQM may drop); the network
interface dequeues while it has bandwidth tokens.

Queue disciplines (vtable router.c:26-37):
  * codel  — RFC 8289 CoDel AQM (default; router_queue_codel.c)
  * single — one-packet buffer (router_queue_single.c)
  * static — fixed-capacity drop-tail FIFO (router_queue_static.c)
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from ..core.worker import current_worker

# >>> simgen:begin region=router-static spec=293c930bb679 body=424e965b21b5
STATIC_CAPACITY = 1024  # packets (reference router_queue_static.c)
# <<< simgen:end region=router-static


class QueueManager:
    """Interface: enqueue(packet, now) -> bool admitted; dequeue(now) ->
    packet|None; peek() -> packet|None."""

    def enqueue(self, packet, now: int) -> bool:
        raise NotImplementedError

    def dequeue(self, now: int):
        raise NotImplementedError

    def peek(self):
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class SingleQueue(QueueManager):
    """1-packet buffer; new arrivals drop while occupied
    (router_queue_single.c)."""

    def __init__(self):
        self._slot = None

    def enqueue(self, packet, now: int) -> bool:
        if self._slot is not None:
            return False
        self._slot = packet
        return True

    def dequeue(self, now: int):
        p, self._slot = self._slot, None
        return p

    def peek(self):
        return self._slot

    def __len__(self):
        return 0 if self._slot is None else 1


class StaticQueue(QueueManager):
    """Fixed-capacity drop-tail FIFO (router_queue_static.c)."""

    def __init__(self, capacity_packets: int = STATIC_CAPACITY):
        self.capacity = capacity_packets
        self._q = deque()

    def enqueue(self, packet, now: int) -> bool:
        if len(self._q) >= self.capacity:
            return False
        self._q.append(packet)
        return True

    def dequeue(self, now: int):
        return self._q.popleft() if self._q else None

    def peek(self):
        return self._q[0] if self._q else None

    def __len__(self):
        return len(self._q)


class CoDelQueue(QueueManager):
    """RFC 8289 Controlled Delay AQM (router_queue_codel.c).

    Parameters match the reference: target sojourn 10 ms, interval 100 ms
    (:34-48); drop-next control law interval/sqrt(count) (:198-205); hard
    size cap to bound memory like the kernel's implementation.
    """

    # >>> simgen:begin region=codel-params spec=293c930bb679 body=eb7dab75d865
    TARGET_NS = 10000000
    INTERVAL_NS = 100000000
    HARD_LIMIT = 1000  # packets
    # <<< simgen:end region=codel-params

    def __init__(self):
        self._q = deque()              # (enqueue_time, packet)
        self.dropping = False
        self.drop_next = 0
        self.drop_count = 0
        self.last_drop_count = 0
        self.total_drops = 0
        self._first_above_time = 0

    def __len__(self):
        return len(self._q)

    def enqueue(self, packet, now: int) -> bool:
        if len(self._q) >= self.HARD_LIMIT:
            self.total_drops += 1
            return False
        self._q.append((now, packet))
        return True

    def peek(self):
        return self._q[0][1] if self._q else None

    def _control_law(self, t: int, count: int) -> int:
        import math
        return t + int(self.INTERVAL_NS / math.sqrt(max(1, count)))

    def _do_dequeue(self, now: int):
        """Returns (packet, ok_to_drop)."""
        if not self._q:
            self._first_above_time = 0
            return None, False
        enq_time, packet = self._q.popleft()
        sojourn = now - enq_time
        if sojourn < self.TARGET_NS or not self._q_has_backlog():
            self._first_above_time = 0
            return packet, False
        if self._first_above_time == 0:
            self._first_above_time = now + self.INTERVAL_NS
            return packet, False
        return packet, now >= self._first_above_time

    def _q_has_backlog(self) -> bool:
        # kernel codel only considers drop when backlog > MTU; approximate
        # with >1 packet queued.
        return len(self._q) >= 1

    def dequeue(self, now: int):
        packet, ok_to_drop = self._do_dequeue(now)
        if packet is None:
            self.dropping = False
            return None
        if self.dropping:
            if not ok_to_drop:
                self.dropping = False
            else:
                while now >= self.drop_next and self.dropping:
                    packet.add_status("ROUTER_DROPPED")
                    self.total_drops += 1
                    self.drop_count += 1
                    packet, ok_to_drop = self._do_dequeue(now)
                    if packet is None:
                        self.dropping = False
                        return None
                    if not ok_to_drop:
                        self.dropping = False
                    else:
                        self.drop_next = self._control_law(self.drop_next, self.drop_count)
        elif ok_to_drop:
            packet.add_status("ROUTER_DROPPED")
            self.total_drops += 1
            packet, _ = self._do_dequeue(now)
            if packet is None:
                return None
            self.dropping = True
            delta = self.drop_count - self.last_drop_count
            self.drop_count = 1
            if delta > 1 and now - self.drop_next < 16 * self.INTERVAL_NS:
                self.drop_count = delta
            self.drop_next = self._control_law(now, self.drop_count)
            self.last_drop_count = self.drop_count
        return packet


def make_queue(kind: str) -> QueueManager:
    if kind == "codel":
        return CoDelQueue()
    if kind == "single":
        return SingleQueue()
    if kind == "static":
        return StaticQueue()
    raise ValueError(f"unknown router queue kind {kind!r}")


class Router:
    """The upstream-ISP attachment point of an interface (router.c)."""

    def __init__(self, queue: QueueManager, interface=None):
        self.queue = queue
        self.interface = interface
        # Staging slot: the AQM's dequeue both drops and returns packets, so
        # the interface peeks the *actual* next deliverable packet here (and
        # charges bandwidth tokens for its true size) before taking it.
        self._staged = None

    def enqueue(self, packet) -> None:
        """Arrival from the internet core (router.c:104-122): AQM admit or
        drop, then nudge the interface to start receiving if this is the
        first buffered packet."""
        iface = self.interface
        if iface is not None:
            now = iface.host.now
        else:
            w = current_worker()
            now = w.now if w is not None else 0
        was_empty = len(self.queue) == 0
        admitted = self.queue.enqueue(packet, now)
        if not admitted:
            packet.add_status("ROUTER_DROPPED")
            return
        if was_empty and self.interface is not None:
            self.interface.on_router_ready()

    def dequeue(self, now: int):
        if self._staged is not None:
            p, self._staged = self._staged, None
            return p
        return self.queue.dequeue(now)

    def peek_deliverable(self, now: int):
        """The next packet that WILL be delivered (AQM drops applied), left
        staged until :meth:`dequeue` takes it.  Lets the interface size its
        token spend to the delivered packet, not a packet the AQM is about
        to drop."""
        if self._staged is None:
            self._staged = self.queue.dequeue(now)
        return self._staged

    def peek(self):
        if self._staged is not None:
            return self._staged
        return self.queue.peek()

    def __len__(self) -> int:
        return len(self.queue) + (1 if self._staged is not None else 0)
