"""Host CPU delay model.

Capability of the reference's CPU (host/cpu.c): converts measured wall-clock
execution time into virtual CPU delay by the ratio of the simulated host's
frequency to the machine's frequency (cpu.c:26-47), and blocks event
execution when accumulated delay exceeds a threshold (cpu_isBlocked; used by
event.c:75-84 to defer events).  Disabled when frequency == 0 or
threshold < 0 (the common configuration).
"""

from __future__ import annotations

import time as _walltime


class CPU:
    def __init__(self, frequency_khz: int, raw_frequency_khz: int,
                 threshold_ns: int, precision_ns: int):
        self.frequency_khz = frequency_khz
        self.raw_frequency_khz = raw_frequency_khz or frequency_khz or 1
        self.threshold_ns = threshold_ns
        self.precision_ns = max(1, precision_ns)
        self.now = 0
        self.time_cpu_available = 0
        self._measure_start = None

    @property
    def enabled(self) -> bool:
        return self.frequency_khz > 0 and self.threshold_ns >= 0

    def start_measurement(self) -> None:
        if self.enabled:
            self._measure_start = _walltime.perf_counter_ns()

    def stop_measurement(self) -> None:
        if self.enabled and self._measure_start is not None:
            elapsed = _walltime.perf_counter_ns() - self._measure_start
            self._measure_start = None
            self.add_delay(elapsed)

    def add_delay(self, raw_ns: int) -> None:
        """Scale measured ns by frequency ratio and round to precision."""
        if not self.enabled:
            return
        scaled = raw_ns * self.raw_frequency_khz / self.frequency_khz
        q = int(scaled / self.precision_ns) * self.precision_ns
        self.time_cpu_available += q

    def update_time(self, now: int) -> None:
        self.now = now
        if self.time_cpu_available < now:
            self.time_cpu_available = now

    def get_delay(self) -> int:
        return max(0, self.time_cpu_available - self.now)

    def is_blocked(self) -> bool:
        return self.enabled and self.get_delay() > self.threshold_ns
