"""NetworkInterface: token-bucket bandwidth shaping + qdisc + socket binding.

Capability parity with the reference's hot-path component
(host/network_interface.c):

* **Token buckets** for up/down bandwidth: refill every 1 ms with
  rate/1000 bytes, capacity = refill + MTU (:93-95, :207-214).  The refill
  task is self-suspending: it only stays scheduled while there is pending
  work (:121-183), so idle interfaces cost nothing.
* **Binding table** (protocol, port, peer_ip, peer_port) → socket
  (:255-335) with wildcard peer fallback, used to deliver arriving packets.
* **Receive loop** drains the upstream router while tokens last (:421-455).
* **Send loop** drains bound sockets by qdisc — round-robin or
  FIFO-by-packet-priority (:466-517) — and hands packets to
  ``worker.send_packet`` (the reference goes through router_forward,
  router.c:96-102).  Loopback destinations short-circuit with a local task
  (:519-579).
* pcap capture hook per packet in/out (:337-373).

This class is the source of truth for bandwidth state under every scheduler
policy.  A vectorized device twin of the token-bucket admission math lives
in ops/bandwidth.py (parity-tested against this implementation); wiring it
into the tpu policy's round step — so bandwidth drops are decided on device
— is the remaining north-star integration (BASELINE.json).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional, Tuple

from ..core import defs, stime
from ..core.logger import get_logger
from ..core.task import Task
from ..routing.address import LOCALHOST_IP
from ..core.worker import current_worker


class TokenBucket:
    __slots__ = ("bytes_refill", "bytes_capacity", "bytes_remaining")

    def __init__(self, rate_kibps: int):
        # bytes per 1ms interval (network_interface.c:199-205)
        time_factor = stime.SIM_TIME_SEC // defs.INTERFACE_REFILL_INTERVAL_NS
        self.bytes_refill = (rate_kibps * 1024) // time_factor
        self.bytes_capacity = (self.bytes_refill * defs.INTERFACE_CAPACITY_FACTOR
                               + defs.CONFIG_MTU)
        self.bytes_remaining = self.bytes_capacity

    def refill(self) -> None:
        self.bytes_remaining = min(self.bytes_remaining + self.bytes_refill,
                                   self.bytes_capacity)

    def try_consume(self, nbytes: int) -> bool:
        if self.bytes_remaining >= nbytes:
            self.bytes_remaining -= nbytes
            return True
        return False


class NetworkInterface:
    def __init__(self, host, address, bw_down_kibps: int, bw_up_kibps: int,
                 qdisc: str = "fifo", pcap_writer=None):
        self.host = host
        self.address = address            # routing.address.Address
        self.is_loopback = address.ip == LOCALHOST_IP
        self.qdisc = qdisc
        self.send_bucket = TokenBucket(bw_up_kibps)
        self.receive_bucket = TokenBucket(bw_down_kibps)
        self.router = None                # set for eth ifaces by Host
        self.pcap = pcap_writer
        # (protocol, port, peer_ip, peer_port) -> socket; wildcard peer = (0,0)
        self._bindings: Dict[Tuple[str, int, int, int], object] = {}
        # sockets with queued outbound packets, FIFO arrival order for RR
        # (deque preserves order; the set makes the membership test O(1))
        self._ready_senders: deque = deque()
        self._ready_set: set = set()
        self._refill_scheduled = False
        # local delivery buffer for loopback/self-directed packets
        self._arrivals: deque = deque()
        self._receive_pending = False

    # -- binding table (network_interface.c:255-335) -----------------------
    @staticmethod
    def _key(protocol: str, port: int, peer_ip: int = 0, peer_port: int = 0):
        return (protocol, port, peer_ip, peer_port)

    def associate(self, socket, protocol: str, port: int, peer_ip: int = 0,
                  peer_port: int = 0) -> None:
        key = self._key(protocol, port, peer_ip, peer_port)
        self._bindings[key] = socket
        # back-reference so Socket.close can drop every binding it holds
        # (a wildcard bind associates on multiple interfaces)
        assoc = getattr(socket, "_associations", None)
        if assoc is not None and (self, key) not in assoc:
            assoc.append((self, key))

    def disassociate(self, protocol: str, port: int, peer_ip: int = 0,
                     peer_port: int = 0) -> None:
        key = self._key(protocol, port, peer_ip, peer_port)
        sock = self._bindings.get(key)
        if sock is not None:
            self.disassociate_key(key, sock)

    def disassociate_key(self, key, sock) -> None:
        """Single removal point for binding entries: drops ``key`` only if
        it still refers to ``sock`` (a stale pair must not evict another
        socket's live binding)."""
        if self._bindings.get(key) is sock:
            del self._bindings[key]
        assoc = getattr(sock, "_associations", None)
        if assoc and (self, key) in assoc:
            assoc.remove((self, key))

    def is_associated(self, protocol: str, port: int, peer_ip: int = 0,
                      peer_port: int = 0) -> bool:
        return self._key(protocol, port, peer_ip, peer_port) in self._bindings

    def lookup_socket(self, packet):
        """Specific (4-tuple) binding first, then wildcard-peer listener."""
        protocol = "tcp" if packet.is_tcp() else "udp"
        s = self._bindings.get(self._key(protocol, packet.dst_port,
                                         packet.src_ip, packet.src_port))
        if s is None:
            s = self._bindings.get(self._key(protocol, packet.dst_port))
        return s

    # -- refill task (network_interface.c:121-183) -------------------------
    def _has_pending_work(self) -> bool:
        if self._ready_senders:
            return True
        if self.router is not None and self.router.peek() is not None:
            return True
        if self._arrivals:
            return True
        return False

    def _ensure_refill_scheduled(self) -> None:
        if self._refill_scheduled or self.is_loopback:
            return
        w = current_worker()
        if w is None:
            return
        self._refill_scheduled = True
        w.schedule_task(Task(_refill_task, self, None, name="iface_refill"),
                        defs.INTERFACE_REFILL_INTERVAL_NS, dst_host=self.host)

    def _on_refill(self) -> None:
        self._refill_scheduled = False
        self.send_bucket.refill()
        self.receive_bucket.refill()
        self.receive_packets()
        self.send_packets()
        if self._has_pending_work():
            self._ensure_refill_scheduled()

    # -- receive path ------------------------------------------------------
    def on_router_ready(self) -> None:
        """First packet buffered upstream: start draining."""
        self.receive_packets()
        if self._has_pending_work():
            self._ensure_refill_scheduled()

    def push_arrival(self, packet) -> None:
        """Loopback / self-directed arrival bypassing the router."""
        self._arrivals.append(packet)
        self.receive_packets()
        if self._has_pending_work():
            self._ensure_refill_scheduled()

    def receive_packets(self) -> None:
        """Drain arrivals while bandwidth tokens allow
        (network_interface.c:421-455).  Loopback is unthrottled."""
        w = current_worker()
        now = w.now if w is not None else 0
        bootstrapping = w.is_bootstrapping() if w is not None else False
        while True:
            src = None
            if self._arrivals:
                packet = self._arrivals[0]
                src = "local"
            elif self.router is not None:
                # peek the packet that will actually be delivered (the AQM
                # may drop queued packets on the way) so the token spend
                # matches the delivered bytes exactly
                packet = self.router.peek_deliverable(now)
                src = "router"
            else:
                packet = None
            if packet is None:
                return
            unthrottled = self.is_loopback or bootstrapping
            if not unthrottled and not self.receive_bucket.try_consume(packet.total_size):
                return  # out of tokens; refill task will resume us
            if src == "local":
                self._arrivals.popleft()
            else:
                packet = self.router.dequeue(now)
            packet.add_status("RCV_INTERFACE_RECEIVED")
            if self.pcap is not None:
                self.pcap.write_packet(now, packet)
            self._deliver(packet)

    def _deliver(self, packet) -> None:
        sock = self.lookup_socket(packet)
        if sock is None:
            packet.add_status("RCV_INTERFACE_DROPPED")
            self.host.tracker.add_drop(packet)
            return
        sock.push_in_packet(packet)
        self.host.tracker.add_input_bytes(packet, self.address.ip)

    # -- send path ---------------------------------------------------------
    def wants_send(self, socket) -> None:
        """A socket has queued outbound data (network_interface.c:581)."""
        if socket not in self._ready_set:
            self._ready_set.add(socket)
            self._ready_senders.append(socket)
        self.send_packets()
        if self._has_pending_work():
            self._ensure_refill_scheduled()

    def _select_socket(self):
        """qdisc: rr = rotate ready list; fifo = lowest packet priority
        first (network_interface.c:466-517)."""
        while self._ready_senders:
            if self.qdisc == "rr":
                s = self._ready_senders[0]
                if s.peek_out_packet() is None:
                    self._ready_senders.popleft()
                    self._ready_set.discard(s)
                    continue
                return s
            best, best_prio = None, None
            for s in self._ready_senders:
                p = s.peek_out_packet()
                if p is None:
                    continue
                if best_prio is None or p.priority < best_prio:
                    best, best_prio = s, p.priority
            if best is None:
                self._ready_senders.clear()
                self._ready_set.clear()
                return None
            return best
        return None

    def send_packets(self) -> None:
        w = current_worker()
        if w is None:
            return
        bootstrapping = w.is_bootstrapping()
        while True:
            sock = self._select_socket()
            if sock is None:
                return
            packet = sock.peek_out_packet()
            unthrottled = self.is_loopback or bootstrapping
            if not unthrottled and not self.send_bucket.try_consume(packet.total_size):
                return
            sock.pull_out_packet()
            if self.qdisc == "rr" and self._ready_senders \
                    and self._ready_senders[0] is sock:
                self._ready_senders.rotate(-1)
            packet.add_status("SND_INTERFACE_SENT")
            self.host.tracker.add_output_bytes(packet, self.address.ip)
            if self.pcap is not None:
                self.pcap.write_packet(w.now, packet)
            dst_ip = packet.dst_ip
            if self.is_loopback or dst_ip == self.address.ip:
                # local short-circuit (network_interface.c:519-547): schedule
                # a self-delivery task after a minimal 1-tick delay to keep
                # event ordering honest.
                target = self.host.interface_for_ip(dst_ip) or self
                w.schedule_task(
                    Task(_local_delivery_task, target, packet, name="local_deliver"),
                    1, dst_host=self.host)
            else:
                w.send_packet(packet)


def _refill_task(iface: NetworkInterface, _arg) -> None:
    iface._on_refill()


def _local_delivery_task(iface: NetworkInterface, packet) -> None:
    iface.push_arrival(packet)
