"""Host: everything a virtual node owns.

Capability parity with the reference's Host (host/host.c struct :47-105 and
host_setup :162-220): per-host params, the IP->interface map (loopback +
eth), the upstream Router with AQM, CPU model, Tracker, process list, the
virtual descriptor table, per-host deterministic RNG, and the counters that
feed the global event order (event sequence) and qdisc tiebreaks (packet
priority).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..core.logger import get_logger
from ..core.rng import RandomSource
from ..routing.address import LOCALHOST_IP, Address
from .cpu import CPU
from .network_interface import NetworkInterface
from .router import Router, make_queue
from .tracker import Tracker

# >>> simgen:begin region=port-alloc spec=293c930bb679 body=00a7ffddc53c
MIN_EPHEMERAL_PORT = 10000
MAX_PORT = 65535
# <<< simgen:end region=port-alloc


class HostParams:
    """Knobs resolved from config + CLI defaults (configuration.h host attrs
    cascaded per master.c:336-377)."""

    def __init__(self, name: str, bw_down_kibps: int, bw_up_kibps: int,
                 qdisc: str = "fifo", router_queue: str = "codel",
                 tcp_cc: Optional[str] = None,
                 recv_buf_size: int = 174760, send_buf_size: int = 131072,
                 autotune_recv: bool = True, autotune_send: bool = True,
                 cpu_frequency_khz: int = 0, cpu_threshold_ns: int = -1,
                 cpu_precision_ns: int = 200, interface_buffer: int = 1024000,
                 heartbeat_interval_sec: int = 0, log_pcap: bool = False,
                 pcap_dir: Optional[str] = None, ip_hint: Optional[str] = None,
                 city_hint: Optional[str] = None, country_hint: Optional[str] = None,
                 geocode_hint: Optional[str] = None, type_hint: Optional[str] = None,
                 log_level: Optional[str] = None,
                 heartbeat_log_level: Optional[str] = None):
        self.name = name
        self.bw_down_kibps = bw_down_kibps
        self.bw_up_kibps = bw_up_kibps
        self.qdisc = qdisc
        self.router_queue = router_queue
        # per-host congestion-control override (<host tcpcc="...">);
        # None = the engine-wide --tcp-congestion-control choice
        self.tcp_cc = tcp_cc
        self.recv_buf_size = recv_buf_size
        self.send_buf_size = send_buf_size
        self.autotune_recv = autotune_recv
        self.autotune_send = autotune_send
        self.cpu_frequency_khz = cpu_frequency_khz
        self.cpu_threshold_ns = cpu_threshold_ns
        self.cpu_precision_ns = cpu_precision_ns
        self.interface_buffer = interface_buffer
        self.heartbeat_interval_sec = heartbeat_interval_sec
        self.log_pcap = log_pcap
        self.pcap_dir = pcap_dir
        self.ip_hint = ip_hint
        self.city_hint = city_hint
        self.country_hint = country_hint
        self.geocode_hint = geocode_hint
        self.type_hint = type_hint
        # per-host log filter (reference per-host loglevel attr)
        self.log_level = log_level
        self.heartbeat_log_level = heartbeat_log_level


class Host:
    # C data plane back-reference; an instance attribute when
    # parallel/native_plane.py attaches.  Class-level default so the hot
    # wake paths (process.py _schedule_continue/_dispatch) read it as a
    # plain attribute instead of paying getattr's missing-attr exception
    # per wake on python-plane runs.
    native_plane = None

    def __init__(self, host_id: int, params: HostParams, root_key: int):
        self.id = host_id
        self.name = params.name
        self.params = params
        self.random = RandomSource(root_key).spawn("host", host_id)
        self.cpu = CPU(params.cpu_frequency_khz, 0, params.cpu_threshold_ns,
                       params.cpu_precision_ns) if params.cpu_frequency_khz else None
        self.tracker = Tracker(self)
        self.interfaces: Dict[int, NetworkInterface] = {}
        self.default_address: Optional[Address] = None
        self.processes: List = []
        # descriptor table (host.c:492+): handle -> Descriptor
        self._descriptors: Dict[int, object] = {}
        self._next_handle = 1000  # leave room below for stdio-like handles
        self._next_port = MIN_EPHEMERAL_PORT
        # deterministic counters
        self._event_seq = 0
        self._packet_counter = 0
        self._packet_priority = 0
        self._process_id_counter = 1000
        self.engine = None  # set on registration
        # virtual clock mirror: the executing worker stamps the event time
        # here so host-side code (TCP, router) reads the clock with one
        # attribute access instead of a thread-local lookup
        self.now = 0
        # topology matrix row, cached by Engine.add_host at attach time
        self.topo_row: int = 0

    # -- setup (host_setup :162-220) --------------------------------------
    def setup(self, engine, eth_address: Address) -> None:
        self.engine = engine
        self.default_address = eth_address
        lo_addr = Address(self.id, LOCALHOST_IP, f"{self.name}-lo", is_local=True)
        pcap = None
        if self.params.log_pcap:
            from ..utils.pcap import PcapWriter
            pcap = PcapWriter.for_host(self.params.pcap_dir or engine.data_directory,
                                       self.name)
        lo = NetworkInterface(self, lo_addr, 0, 0, qdisc=self.params.qdisc,
                              pcap_writer=None)
        eth = NetworkInterface(self, eth_address, self.params.bw_down_kibps,
                               self.params.bw_up_kibps, qdisc=self.params.qdisc,
                               pcap_writer=pcap)
        eth.router = Router(make_queue(self.params.router_queue), eth)
        self.interfaces[LOCALHOST_IP] = lo
        self.interfaces[eth_address.ip] = eth

    def boot(self) -> None:
        """Per-host boot hook (host_boot :372-390).  Heartbeats are no
        longer scheduled here: ONE engine-level sweep event per distinct
        interval heartbeats every owned host in a single pass (ISSUE 10
        batched control plane; Engine._schedule_heartbeat_sweeps) — a
        10k-host run pays one event + one bulk C tracker snapshot per
        interval instead of 10k events with a C round-trip each."""

    # -- addressing --------------------------------------------------------
    @property
    def ip(self) -> int:
        return self.default_address.ip

    def interface_for_ip(self, ip: int) -> Optional[NetworkInterface]:
        iface = self.interfaces.get(ip)
        if iface is None and ip in (0, None):
            iface = self.interfaces.get(self.default_address.ip)
        return iface

    # -- deterministic counters -------------------------------------------
    def next_event_sequence(self) -> int:
        self._event_seq += 1
        return self._event_seq

    def next_packet_uid(self) -> int:
        """Globally unique, deterministic: (host_id << 40) | per-host count.
        Keys the order-independent packet drop draw."""
        self._packet_counter += 1
        return (self.id << 40) | self._packet_counter

    def next_packet_priority(self) -> int:
        self._packet_priority += 1
        return self._packet_priority

    def next_process_id(self) -> int:
        self._process_id_counter += 1
        return self._process_id_counter

    # -- descriptor table --------------------------------------------------
    def descriptor_table_add(self, desc) -> int:
        handle = self._next_handle
        self._next_handle += 1
        self._descriptors[handle] = desc
        return handle

    def descriptor_table_get(self, handle: int):
        return self._descriptors.get(handle)

    def descriptor_table_remove(self, handle: int) -> None:
        self._descriptors.pop(handle, None)

    def allocate_handle(self) -> int:
        h = self._next_handle
        self._next_handle += 1
        return h

    def register_descriptor(self, desc) -> None:
        """Single registration point for descriptors constructed with a
        pre-allocated handle (allocate_handle + constructor)."""
        self._descriptors[desc.handle] = desc

    # -- port management ---------------------------------------------------
    def allocate_ephemeral_port(self, protocol: str, iface_ip: int,
                                ifaces=None) -> int:
        """Deterministic ephemeral port scan (reference uses host random;
        we scan from a rotating cursor for speed and determinism).  Pass
        ``ifaces`` to require the port free on several interfaces at once
        (wildcard binds claim every interface)."""
        check = ifaces if ifaces is not None else [self.interface_for_ip(iface_ip)]
        for _ in range(MAX_PORT - MIN_EPHEMERAL_PORT + 1):
            port = self._next_port
            self._next_port += 1
            if self._next_port > MAX_PORT:
                self._next_port = MIN_EPHEMERAL_PORT
            if all(i is None or not i.is_associated(protocol, port)
                   for i in check):
                return port
        raise OSError("EADDRINUSE: ephemeral ports exhausted")

    def autobind_socket(self, sock, dst_ip: int) -> None:
        """Implicit bind on send/connect without bind() (socket.c behavior)."""
        src_ip = LOCALHOST_IP if dst_ip == LOCALHOST_IP else self.default_address.ip
        port = self.allocate_ephemeral_port(sock.kind, src_ip)
        sock.bind_to(src_ip, port)
        iface = self.interface_for_ip(src_ip)
        if iface is not None:
            iface.associate(sock, sock.kind, port)

    # -- process registry --------------------------------------------------
    def add_process(self, process) -> None:
        self.processes.append(process)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Host({self.name}#{self.id})"
