"""Plot tool: simulation log -> throughput + engine-heartbeat figures.

The reference ships src/tools/plot-shadow.py (parse the log, plot per-host
throughput and resource usage over time); this is its analog over
tools/parse_log.py's record stream:

* panel 1/2: per-host rx/tx rate between tracker heartbeats (KiB/s over
  virtual time) — parse_log.plot_log's figure;
* panel 3: engine heartbeats — wall-clock progress and max RSS against
  virtual time (the reference plots its getrusage heartbeats the same way).

Usage: python -m shadow_tpu.tools.plot_log <log> [out.png]
Exit 1 if matplotlib is unavailable (the simulator itself never needs it).
"""

from __future__ import annotations

import re
import sys
from typing import Iterable, List

from .parse_log import iter_records, plot_log

_HB = re.compile(
    r"\[engine-heartbeat\] rounds=(\d+) simtime=([\d.]+)s wall=([\d.]+)s"
    r".*? maxrss_mb=(\d+)")


def engine_heartbeats(lines: Iterable[str]) -> List[dict]:
    out = []
    for rec in iter_records(lines):
        m = _HB.search(rec["text"])
        if m:
            out.append({"rounds": int(m.group(1)),
                        "sim_s": float(m.group(2)),
                        "wall_s": float(m.group(3)),
                        "maxrss_mb": int(m.group(4))})
    return out


def plot_heartbeats(lines: Iterable[str], out_path: str) -> bool:
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib not available; skipping plot", file=sys.stderr)
        return False
    hbs = engine_heartbeats(lines)
    if not hbs:
        return False
    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=(10, 6), sharex=True)
    sim = [h["sim_s"] for h in hbs]
    ax1.plot(sim, [h["wall_s"] for h in hbs], marker="o")
    ax1.set_ylabel("wall time (s)")
    ax2.plot(sim, [h["maxrss_mb"] for h in hbs], marker="o", color="tab:red")
    ax2.set_ylabel("max RSS (MB)")
    ax2.set_xlabel("virtual time (s)")
    fig.suptitle("shadow_tpu engine heartbeats")
    fig.savefig(out_path, dpi=120)
    return True


def main(argv: List[str]) -> int:
    if len(argv) < 1:
        print("usage: python -m shadow_tpu.tools.plot_log <log> [out.png]",
              file=sys.stderr)
        return 2
    path = argv[0]
    out = argv[1] if len(argv) > 1 else "shadow_plot.png"
    with open(path) as f:
        lines = f.readlines()
    ok = plot_log(lines, out)
    hb_out = out.rsplit(".", 1)[0] + "_heartbeats.png"
    plot_heartbeats(lines, hb_out)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
