"""mkscenario: build, inspect, and run generated scale scenarios.

The scale tier's scenario generators (shadow_tpu/scale/genscen.py) emit
``Configuration`` objects directly — this CLI is the operator surface:

    python -m shadow_tpu.tools.mkscenario star100k --summary
    python -m shadow_tpu.tools.mkscenario star2k --xml > star2k.xml
    python -m shadow_tpu.tools.mkscenario star100k --run \
        [--stop-time N] [--device-plane numpy] [--metrics path.jsonl]

``--summary`` (default) prints one JSON line of scenario shape +
content digest; ``--xml`` dumps legacy XML (refused above 50k hosts —
emitting multi-megabyte XML is exactly what the generators exist to
avoid; the ``<flow>`` element round-trips through configuration.parse_xml
for the sizes where XML makes sense); ``--run`` executes the scenario
with the host table on and prints the run's scale metrics, propagating
the child engine's exit code.  ``--seed N`` pins BOTH the seeded
families' structural draws (tor circuits, cdn/swarm partner graphs) and
the engine seed, so a fuzz-discovered scenario replays from the CLI.
"""

from __future__ import annotations

import json
import sys
from typing import List

from ..core.configuration import Configuration

XML_HOST_CAP = 50_000


def config_to_xml(cfg: Configuration) -> str:
    """Legacy-schema XML for a generated Configuration (small scenarios,
    interchange/debugging).  Only the fields the generators emit."""
    total = sum(h.quantity for h in cfg.hosts)
    if total > XML_HOST_CAP:
        raise ValueError(
            f"refusing to emit XML for {total} hosts (> {XML_HOST_CAP}); "
            "run the Configuration directly (--run) instead")
    lines = [f'<shadow stoptime="{int(cfg.stop_time_sec)}">']
    for hc in cfg.hosts:
        attrs = [f'id="{hc.id}"']
        if hc.quantity != 1:
            attrs.append(f'quantity="{hc.quantity}"')
        if hc.bandwidth_down_kibps:
            attrs.append(f'bandwidthdown="{hc.bandwidth_down_kibps}"')
        if hc.bandwidth_up_kibps:
            attrs.append(f'bandwidthup="{hc.bandwidth_up_kibps}"')
        body = []
        for pc in hc.processes:
            p = [f'plugin="{pc.plugin}"']
            if pc.start_time_sec:
                p.append(f'starttime="{pc.start_time_sec:g}"')
            if pc.stop_time_sec:
                p.append(f'stoptime="{pc.stop_time_sec:g}"')
            if pc.arguments:
                p.append(f'arguments="{pc.arguments}"')
            body.append(f'    <process {" ".join(p)} />')
        for fc in hc.flows:
            f = [f'dest="{fc.dest}"', f'starttime="{fc.start_time_sec:g}"',
                 f'down="{fc.down_bytes}"']
            if fc.up_bytes:
                f.append(f'up="{fc.up_bytes}"')
            if fc.path:
                f.append(f'path="{fc.path}"')
            if fc.stagger_waves > 1:
                f.append(f'staggerwaves="{fc.stagger_waves}"')
                f.append(f'staggerstep="{fc.stagger_step_sec:g}"')
            if fc.tor_path_seed is not None:
                f.append(f'torpathseed="{fc.tor_path_seed}"')
                f.append(f'torrelays="{fc.tor_relays}"')
                f.append(f'torrelayprefix="{fc.tor_relay_prefix}"')
                f.append(f'torservers="{fc.tor_servers}"')
                f.append(f'torserverprefix="{fc.tor_server_prefix}"')
            if fc.dest_seed is not None:
                f.append(f'destseed="{fc.dest_seed}"')
                f.append(f'destcount="{fc.dest_count}"')
                f.append(f'destprefix="{fc.dest_prefix}"')
            body.append(f'    <flow {" ".join(f)} />')
        if body:
            lines.append(f'  <host {" ".join(attrs)}>')
            lines.extend(body)
            lines.append('  </host>')
        else:
            lines.append(f'  <host {" ".join(attrs)} />')
    lines.append('</shadow>')
    return "\n".join(lines) + "\n"


def summarize(cfg: Configuration) -> dict:
    from ..scale.genscen import config_digest
    return {
        "hosts": sum(h.quantity for h in cfg.hosts),
        "groups": len(cfg.hosts),
        "processes": cfg.total_process_count(),
        "flows": sum(h.quantity * len(h.flows) for h in cfg.hosts),
        "stop_time_sec": cfg.stop_time_sec,
        "digest": config_digest(cfg),
    }


def run_scenario(cfg: Configuration, argv: List[str]) -> int:
    """Execute a generated scenario with scale defaults: host table on,
    heartbeats off (quiet rows stay rows), pure-Python control plane."""
    from ..core.controller import run_simulation
    from ..core.logger import SimLogger, set_logger
    from ..core.options import build_parser, Options
    import dataclasses
    ns = build_parser().parse_args(["dummy.xml"] + argv)
    set_logger(SimLogger(level=ns.log_level or "message"))
    opts = Options()
    for f in dataclasses.fields(Options):
        v = getattr(ns, f.name, None)
        if v is not None:
            setattr(opts, f.name, v)
    opts.config_path = None
    if ns.stop_time_sec is not None:
        cfg.stop_time_sec = ns.stop_time_sec
    opts.stop_time_sec = int(cfg.stop_time_sec)
    opts.host_table = "on"
    if "--heartbeat-frequency" not in argv:
        opts.heartbeat_interval_sec = 0
    return run_simulation(opts, cfg)


def main(argv: List[str]) -> int:
    from ..scale.genscen import NAMED, build, family_fn
    if not argv or argv[0].startswith("-"):
        print(f"usage: python -m shadow_tpu.tools.mkscenario "
              f"{{{','.join(sorted(NAMED))}}} [--summary|--xml|--run] "
              "[--seed N] [run options]", file=sys.stderr)
        return 2
    name, rest = argv[0], argv[1:]
    overrides = {}
    seed_args = [a for a in rest
                 if a == "--seed" or a.startswith("--seed=")]
    if seed_args:
        # --seed parameterizes the scenario BUILDER for the seeded
        # families (tor/cdn/swarm path+partner draws) so fuzz-discovered
        # scenarios replay from the CLI; run_scenario parses the same flag
        # again for the engine seed, so one value pins both draws.  Both
        # argparse spellings (--seed N / --seed=N) must hit the builder —
        # a silently-skipped override would replay a DIFFERENT scenario.
        import inspect
        try:
            # LAST occurrence wins, matching run_scenario's argparse —
            # builder and engine must never read different seeds
            a = seed_args[-1]
            seed = int(a.partition("=")[2]) if "=" in a \
                else int(rest[len(rest) - 1 - rest[::-1].index("--seed")
                              + 1])
        except (IndexError, ValueError):
            print("error: --seed needs an integer", file=sys.stderr)
            return 2
        try:
            if "seed" in inspect.signature(family_fn(name)).parameters:
                overrides["seed"] = seed
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    try:
        cfg = build(name, **overrides)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if "--xml" in rest:
        try:
            sys.stdout.write(config_to_xml(cfg))
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        return 0
    if "--run" in rest:
        # the child engine's exit code propagates verbatim — a failed
        # fuzz replay must fail the CLI, not report rc 0
        return run_scenario(cfg, [a for a in rest if a != "--run"])
    print(json.dumps({"scenario": name, **summarize(cfg)}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
