"""Trace report: summarize a flight-recorder trace file so CI and humans
read the same numbers.

Input is the Chrome trace-event JSON ``--trace PATH`` writes (obs/trace.py);
output is ONE JSON document on stdout:

* ``top_spans_by_self_time`` — per span name: count, total, self (total
  minus same-track children), mean — the profile's headline table;
* ``per_round_phase`` — wall totals of the engine's round phases
  (collect / dispatch.launch / round / flush / log.flush) plus per-round
  means, i.e. the BENCH phase columns recomputed from the trace itself;
* ``overlap_efficiency`` — device.inflight (device compute hidden behind
  host work) vs device.collect (exposed wait), the pipeline's honesty
  number;
* ``tracks`` — per (shard, thread) event counts, so a sharded run's merge
  is checkable at a glance (one entry per shard track).

``--metrics`` switches the input to a ``--metrics PATH`` JSONL stream
(obs/metrics.py): the report is the run's FINAL summary scrape (the
steady-state plane/engine/policy numbers CI gates key on —
``plane.rounds_per_launch``, ``plane.overlap_efficiency``, the
``engine.host_exec_*`` split) plus the scrape-record count, so
``make bench-smoke`` asserts the perf machinery from the same artifact a
production ``--metrics`` run writes.

``--compare A B`` diffs two metrics runs column-wise (ISSUE 10: the perf-PR
review artifact): every numeric key of the two final summaries side by
side with delta and ratio, keys present on one side only called out, so a
before/after pair of ``--metrics`` files turns into the regression table a
reviewer reads directly.

Usage: python -m shadow_tpu.tools.trace_report <trace.json> [--pretty]
       python -m shadow_tpu.tools.trace_report --metrics <metrics.jsonl>
       python -m shadow_tpu.tools.trace_report --compare <A.jsonl> <B.jsonl>
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict
from typing import Dict, Iterable, List

ROUND_PHASES = ("collect", "dispatch.launch", "round", "flush", "log.flush",
                "checkpoint.write", "exchange")


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        blob = json.load(f)
    if isinstance(blob, dict):
        events = blob.get("traceEvents", [])
    else:                      # bare-array form is legal Chrome JSON too
        events = blob
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    return [e for e in events if e.get("ph") != "M"]


def self_times(events: Iterable[dict]) -> Dict[str, Dict[str, float]]:
    """Aggregate complete ('X') spans by name with self-time: duration
    minus the duration of spans nested inside them on the same track
    (computed with a containment stack per track, the standard flame-graph
    fold).  A span that merely OVERLAPS its predecessor — starts inside it
    but ends after, like the async ``device.inflight`` window stretching
    from one round's launch into the next round's collect — is not a
    child: it neither discounts the enclosing span's self-time nor becomes
    a parent for later spans."""
    by_track: Dict[tuple, List[dict]] = defaultdict(list)
    for e in events:
        if e.get("ph") == "X":
            by_track[(e.get("pid", 0), e.get("tid", ""))].append(e)
    agg: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"count": 0, "total_us": 0.0, "self_us": 0.0})
    for track_events in by_track.values():
        track_events.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: List[tuple] = []     # (end_ts, name) of open CONTAINED spans
        for e in track_events:
            ts, dur = e["ts"], e.get("dur", 0.0)
            end = ts + dur
            while stack and ts >= stack[-1][0]:
                stack.pop()
            contained = not stack or end <= stack[-1][0] + 1e-6
            if stack and contained:  # true child: charge parent self-time
                agg[stack[-1][1]]["self_us"] -= dur
            a = agg[e["name"]]
            a["count"] += 1
            a["total_us"] += dur
            a["self_us"] += dur
            if contained:
                stack.append((end, e["name"]))
    return dict(agg)


def summarize(events: List[dict]) -> Dict:
    events = [e for e in events if e.get("ph") != "M"]
    spans = self_times(events)
    # name tiebreak + pre-sorted input: the headline table stays
    # byte-stable across runs even when two spans measure equal self-time
    top = sorted(
        ({"name": name, "count": int(v["count"]),
          "total_ms": round(v["total_us"] / 1e3, 3),
          "self_ms": round(max(v["self_us"], 0.0) / 1e3, 3),
          "mean_us": round(v["total_us"] / max(v["count"], 1), 1)}
         for name, v in sorted(spans.items())),
        key=lambda r: (-r["self_ms"], r["name"]))
    rounds = spans.get("round", {}).get("count", 0)
    phases: Dict[str, Dict[str, float]] = {}
    for name in ROUND_PHASES:
        v = spans.get(name)
        if not v:
            continue
        phases[name] = {"total_ms": round(v["total_us"] / 1e3, 3),
                        "mean_us": round(v["total_us"] / max(v["count"], 1),
                                         1)}
    inflight = spans.get("device.inflight", {}).get("total_us", 0.0)
    blocked = spans.get("device.collect", {}).get("total_us", 0.0)
    tracks: Dict[str, int] = defaultdict(int)
    sim_min = sim_max = None
    for e in events:
        tracks[f"{e.get('pid', 0)}:{e.get('tid', '')}"] += 1
        sim = e.get("args", {}).get("sim_ns")
        if isinstance(sim, (int, float)) and sim >= 0:
            sim_min = sim if sim_min is None else min(sim_min, sim)
            sim_max = sim if sim_max is None else max(sim_max, sim)
    return {
        "events": len(events),
        "rounds": int(rounds),
        "tracks": dict(sorted(tracks.items())),
        "shards": sorted({e.get("pid", 0) for e in events}),
        "sim_span_s": (round((sim_max - sim_min) / 1e9, 3)
                       if sim_min is not None else None),
        "top_spans_by_self_time": top[:15],
        "per_round_phase": phases,
        "device": {
            "inflight_ms": round(inflight / 1e3, 3),
            "collect_blocked_ms": round(blocked / 1e3, 3),
            "overlap_efficiency": round(inflight / (inflight + blocked), 4)
            if (inflight + blocked) else None,
        },
    }


def summarize_metrics(records: List[dict]) -> Dict:
    """Report over a metrics JSONL stream: the final summary record's
    scrape (flat metric -> value) + stream shape.  Raises ValueError when
    the stream has no summary record (a crashed run never writes one — CI
    must see that as a failure, not an empty report)."""
    summaries = [r for r in records if r.get("summary")]
    if not summaries:
        raise ValueError("no summary record (run did not finish?)")
    final = summaries[-1]
    return {
        "scrape_records": len(records) - len(summaries),
        "rounds": final.get("round"),
        "sim_time_ns": final.get("sim_time_ns"),
        "final": final.get("metrics", {}),
    }


def compare_metrics(a_records: List[dict], b_records: List[dict]) -> Dict:
    """Column-wise diff of two metrics runs' final summaries.  Numeric
    keys carry (a, b, delta, ratio); non-numeric keys compare by equality;
    keys on one side only land in ``only_a``/``only_b`` — nothing is
    silently dropped.  Ratio is b/a (>1 = B larger), None when a == 0."""
    fa = summarize_metrics(a_records)["final"]
    fb = summarize_metrics(b_records)["final"]
    num = (int, float)
    columns: Dict[str, Dict] = {}
    changed: Dict[str, Dict] = {}
    for key in sorted(set(fa) & set(fb)):
        va, vb = fa[key], fb[key]
        if isinstance(va, num) and isinstance(vb, num) \
                and not isinstance(va, bool) and not isinstance(vb, bool):
            row = {"a": va, "b": vb, "delta": round(vb - va, 6),
                   "ratio": round(vb / va, 4) if va else None}
            columns[key] = row
            if row["delta"]:
                changed[key] = row
        elif va != vb:
            changed[key] = columns[key] = {"a": va, "b": vb}
    return {
        "keys_compared": len(set(fa) & set(fb)),
        "only_a": sorted(set(fa) - set(fb)),
        "only_b": sorted(set(fb) - set(fa)),
        "changed": changed,
        "columns": columns,
    }


def main(argv: List[str]) -> int:
    usage = ("usage: python -m shadow_tpu.tools.trace_report "
             "<trace.json> [--pretty] | --metrics <metrics.jsonl> | "
             "--compare <A.jsonl> <B.jsonl>")
    if not argv:
        print(usage, file=sys.stderr)
        return 2
    pretty = "--pretty" in argv
    metrics_mode = "--metrics" in argv
    compare_mode = "--compare" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(usage, file=sys.stderr)
        return 2
    if compare_mode:
        if len(paths) != 2:
            print(usage, file=sys.stderr)
            return 2
        from ..obs.metrics import read_metrics_file
        try:
            report = compare_metrics(read_metrics_file(paths[0]),
                                     read_metrics_file(paths[1]))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot compare metrics: {e}", file=sys.stderr)
            return 1
        json.dump(report, sys.stdout, indent=2 if pretty else None,
                  sort_keys=True)
        print()
        return 0
    path = paths[0]
    if metrics_mode:
        from ..obs.metrics import read_metrics_file
        try:
            report = summarize_metrics(read_metrics_file(path))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot read metrics {path!r}: {e}",
                  file=sys.stderr)
            return 1
        json.dump(report, sys.stdout, indent=2 if pretty else None,
                  sort_keys=True)
        print()
        return 0
    try:
        events = load_events(path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot read trace {path!r}: {e}", file=sys.stderr)
        return 1
    report = summarize(events)
    json.dump(report, sys.stdout, indent=2 if pretty else None,
              sort_keys=True)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
