"""Trace report: summarize a flight-recorder trace file so CI and humans
read the same numbers.

Input is the Chrome trace-event JSON ``--trace PATH`` writes (obs/trace.py);
output is ONE JSON document on stdout:

* ``top_spans_by_self_time`` — per span name: count, total, self (total
  minus same-track children), mean — the profile's headline table;
* ``per_round_phase`` — wall totals of the engine's round phases
  (collect / dispatch.launch / round / flush / log.flush) plus per-round
  means, i.e. the BENCH phase columns recomputed from the trace itself;
* ``overlap_efficiency`` — device.inflight (device compute hidden behind
  host work) vs device.collect (exposed wait), the pipeline's honesty
  number;
* ``tracks`` — per (shard, thread) event counts, so a sharded run's merge
  is checkable at a glance (one entry per shard track).

``--metrics`` switches the input to a ``--metrics PATH`` JSONL stream
(obs/metrics.py): the report is the run's FINAL summary scrape (the
steady-state plane/engine/policy numbers CI gates key on —
``plane.rounds_per_launch``, ``plane.overlap_efficiency``, the
``engine.host_exec_*`` split) plus the scrape-record count, so
``make bench-smoke`` asserts the perf machinery from the same artifact a
production ``--metrics`` run writes.

``--compare A B`` diffs two metrics runs column-wise (ISSUE 10: the perf-PR
review artifact): every numeric key of the two final summaries side by
side with delta and ratio, keys present on one side only called out, so a
before/after pair of ``--metrics`` files turns into the regression table a
reviewer reads directly.

``--trend`` renders the persistent perf-trend ledger
(``BENCH_HISTORY.jsonl``, ISSUE 15 / shadow_tpu/prof/ledger.py): rows
grouped by family, every numeric column as a sparkline over the recorded
rounds plus latest-vs-best-known delta, and regression flags for the
columns whose good direction is known — the next perf regression is
caught by rereading THIS report, not CHANGES.md.

Usage: python -m shadow_tpu.tools.trace_report <trace.json> [--pretty]
       python -m shadow_tpu.tools.trace_report --metrics <metrics.jsonl>
       python -m shadow_tpu.tools.trace_report --compare <A.jsonl> <B.jsonl>
       python -m shadow_tpu.tools.trace_report --trend <BENCH_HISTORY.jsonl>
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict
from typing import Dict, Iterable, List, Optional

ROUND_PHASES = ("collect", "dispatch.launch", "round", "flush", "log.flush",
                "checkpoint.write", "exchange")


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        blob = json.load(f)
    if isinstance(blob, dict):
        events = blob.get("traceEvents", [])
    else:                      # bare-array form is legal Chrome JSON too
        events = blob
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    return [e for e in events if e.get("ph") != "M"]


def self_times(events: Iterable[dict]) -> Dict[str, Dict[str, float]]:
    """Aggregate complete ('X') spans by name with self-time: duration
    minus the duration of spans nested inside them on the same track
    (computed with a containment stack per track, the standard flame-graph
    fold).  A span that merely OVERLAPS its predecessor — starts inside it
    but ends after, like the async ``device.inflight`` window stretching
    from one round's launch into the next round's collect — is not a
    child: it neither discounts the enclosing span's self-time nor becomes
    a parent for later spans."""
    by_track: Dict[tuple, List[dict]] = defaultdict(list)
    for e in events:
        if e.get("ph") == "X":
            by_track[(e.get("pid", 0), e.get("tid", ""))].append(e)
    agg: Dict[str, Dict[str, float]] = defaultdict(
        lambda: {"count": 0, "total_us": 0.0, "self_us": 0.0})
    for track_events in by_track.values():
        track_events.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: List[tuple] = []     # (end_ts, name) of open CONTAINED spans
        for e in track_events:
            ts, dur = e["ts"], e.get("dur", 0.0)
            end = ts + dur
            while stack and ts >= stack[-1][0]:
                stack.pop()
            contained = not stack or end <= stack[-1][0] + 1e-6
            if stack and contained:  # true child: charge parent self-time
                agg[stack[-1][1]]["self_us"] -= dur
            a = agg[e["name"]]
            a["count"] += 1
            a["total_us"] += dur
            a["self_us"] += dur
            if contained:
                stack.append((end, e["name"]))
    return dict(agg)


def summarize(events: List[dict]) -> Dict:
    events = [e for e in events if e.get("ph") != "M"]
    spans = self_times(events)
    # name tiebreak + pre-sorted input: the headline table stays
    # byte-stable across runs even when two spans measure equal self-time
    top = sorted(
        ({"name": name, "count": int(v["count"]),
          "total_ms": round(v["total_us"] / 1e3, 3),
          "self_ms": round(max(v["self_us"], 0.0) / 1e3, 3),
          "mean_us": round(v["total_us"] / max(v["count"], 1), 1)}
         for name, v in sorted(spans.items())),
        key=lambda r: (-r["self_ms"], r["name"]))
    rounds = spans.get("round", {}).get("count", 0)
    phases: Dict[str, Dict[str, float]] = {}
    for name in ROUND_PHASES:
        v = spans.get(name)
        if not v:
            continue
        phases[name] = {"total_ms": round(v["total_us"] / 1e3, 3),
                        "mean_us": round(v["total_us"] / max(v["count"], 1),
                                         1)}
    inflight = spans.get("device.inflight", {}).get("total_us", 0.0)
    blocked = spans.get("device.collect", {}).get("total_us", 0.0)
    tracks: Dict[str, int] = defaultdict(int)
    sim_min = sim_max = None
    for e in events:
        tracks[f"{e.get('pid', 0)}:{e.get('tid', '')}"] += 1
        sim = e.get("args", {}).get("sim_ns")
        if isinstance(sim, (int, float)) and sim >= 0:
            sim_min = sim if sim_min is None else min(sim_min, sim)
            sim_max = sim if sim_max is None else max(sim_max, sim)
    return {
        "events": len(events),
        "rounds": int(rounds),
        "tracks": dict(sorted(tracks.items())),
        "shards": sorted({e.get("pid", 0) for e in events}),
        "sim_span_s": (round((sim_max - sim_min) / 1e9, 3)
                       if sim_min is not None else None),
        "top_spans_by_self_time": top[:15],
        "per_round_phase": phases,
        "device": {
            "inflight_ms": round(inflight / 1e3, 3),
            "collect_blocked_ms": round(blocked / 1e3, 3),
            "overlap_efficiency": round(inflight / (inflight + blocked), 4)
            if (inflight + blocked) else None,
        },
    }


def summarize_metrics(records: List[dict]) -> Dict:
    """Report over a metrics JSONL stream: the final summary record's
    scrape (flat metric -> value) + stream shape.  Raises ValueError when
    the stream has no summary record (a crashed run never writes one — CI
    must see that as a failure, not an empty report)."""
    summaries = [r for r in records if r.get("summary")]
    if not summaries:
        raise ValueError("no summary record (run did not finish?)")
    final = summaries[-1]
    metrics = final.get("metrics", {})
    # histogram digest table (ISSUE 15): the percentile columns pulled
    # up next to each other so a human reads tails without digging
    # through the flat scrape's nested dicts
    hists = {
        name: {k: v[k] for k in ("count", "mean", "p50", "p95", "p99",
                                 "min", "max") if k in v}
        for name, v in sorted(metrics.items())
        if isinstance(v, dict) and "count" in v and v["count"]}
    return {
        "scrape_records": len(records) - len(summaries),
        "rounds": final.get("round"),
        "sim_time_ns": final.get("sim_time_ns"),
        "histograms": hists,
        "final": metrics,
    }


def compare_metrics(a_records: List[dict], b_records: List[dict]) -> Dict:
    """Column-wise diff of two metrics runs' final summaries.  Numeric
    keys carry (a, b, delta, ratio); non-numeric keys compare by equality;
    keys on one side only land in ``only_a``/``only_b`` — nothing is
    silently dropped.  Ratio is b/a (>1 = B larger), None when a == 0."""
    fa = summarize_metrics(a_records)["final"]
    fb = summarize_metrics(b_records)["final"]
    num = (int, float)
    columns: Dict[str, Dict] = {}
    changed: Dict[str, Dict] = {}
    for key in sorted(set(fa) & set(fb)):
        va, vb = fa[key], fb[key]
        if isinstance(va, num) and isinstance(vb, num) \
                and not isinstance(va, bool) and not isinstance(vb, bool):
            row = {"a": va, "b": vb, "delta": round(vb - va, 6),
                   "ratio": round(vb / va, 4) if va else None}
            columns[key] = row
            if row["delta"]:
                changed[key] = row
        elif va != vb:
            changed[key] = columns[key] = {"a": va, "b": vb}
    return {
        "keys_compared": len(set(fa) & set(fb)),
        "only_a": sorted(set(fa) - set(fb)),
        "only_b": sorted(set(fb) - set(fa)),
        "changed": changed,
        "columns": columns,
    }


# -- perf-trend ledger rendering (ISSUE 15) ---------------------------------

_SPARK = "▁▂▃▄▅▆▇█"

# which direction is GOOD, per column-name pattern.  Higher-better is
# matched FIRST (sim_sec_per_wall_sec ends in _sec but is a rate);
# unknown columns still render, they just carry no regression verdict.
_HIGHER_BETTER = ("sim_sec_per_wall", "per_sec", "fraction", "efficiency",
                  "rounds_per_launch", "events", "completed", "forwards",
                  "occupancy")
_LOWER_BETTER = ("_sec", "_us", "_ns", "_ms", "_mb", "bytes",
                 "host_bounces", "model_stale", "violations", "recoveries",
                 "demoted", "findings", "problems", "_rc")


def _direction(col: str) -> Optional[str]:
    c = col.lower()
    # specific names first: cut_fraction is the partitioner's cross-shard
    # hop share — LOWER is better, despite the generic "fraction" rule
    if "cut_fraction" in c:
        return "lower"
    if any(p in c for p in _HIGHER_BETTER):
        return "higher"
    if any(p in c for p in _LOWER_BETTER):
        return "lower"
    return None


def _sparkline(values: List[float]) -> str:
    lo, hi = min(values), max(values)
    if hi == lo:
        return _SPARK[3] * len(values)
    return "".join(
        _SPARK[min(int((v - lo) / (hi - lo) * (len(_SPARK) - 1)),
                   len(_SPARK) - 1)] for v in values)


def summarize_trend(records: List[dict], last_n: int = 16,
                    regress_pct: float = 10.0) -> Dict:
    """Render the ledger: rows grouped by family (record ``row`` key),
    each numeric column as (latest, best-known, delta, sparkline) over
    the recorded history, regression-flagged when the good direction is
    known and the latest value is >``regress_pct``% worse than the best.
    Raises ValueError on an empty ledger — CI must see that as a
    failure, not an empty trajectory."""
    if not records:
        raise ValueError("ledger is empty (no rows ever appended?)")
    by_row: Dict[str, List[dict]] = defaultdict(list)
    for rec in records:
        by_row[rec.get("row", "?")].append(rec)
    rows: Dict[str, Dict] = {}
    regressions: List[str] = []
    for name, recs in sorted(by_row.items()):
        recs = sorted(recs, key=lambda r: r.get("ts", ""))
        cols: Dict[str, List[float]] = defaultdict(list)
        for rec in recs:
            for col, v in (rec.get("cols") or {}).items():
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    cols[col].append(float(v))
        col_out: Dict[str, Dict] = {}
        row_regs: List[str] = []
        for col, vals in sorted(cols.items()):
            vals = vals[-last_n:]
            direction = _direction(col)
            latest = vals[-1]
            best = max(vals) if direction == "higher" else min(vals)
            entry = {
                "latest": latest,
                "best": best,
                "delta_vs_best": round(latest - best, 6),
                "spark": _sparkline(vals),
                "n": len(vals),
                "direction": direction,
            }
            if direction is not None and len(vals) >= 2:
                scale = abs(best) if best else 1.0
                worse = (best - latest if direction == "higher"
                         else latest - best)
                entry["regressed"] = bool(
                    worse / scale * 100.0 > regress_pct)
                if entry["regressed"]:
                    row_regs.append(col)
            else:
                entry["regressed"] = None
            col_out[col] = entry
        rows[name] = {
            "n": len(recs),
            "first_ts": recs[0].get("ts"),
            "last_ts": recs[-1].get("ts"),
            "latest_sha": recs[-1].get("sha"),
            "boxes": sorted({r.get("box") for r in recs}),
            "columns": col_out,
            "regressions": row_regs,
        }
        regressions.extend(f"{name}:{c}" for c in row_regs)
    return {"rows": rows, "row_families": sorted(by_row),
            "records": len(records), "regressions": regressions}


def main(argv: List[str]) -> int:
    usage = ("usage: python -m shadow_tpu.tools.trace_report "
             "<trace.json> [--pretty] | --metrics <metrics.jsonl> | "
             "--compare <A.jsonl> <B.jsonl> | "
             "--trend <BENCH_HISTORY.jsonl>")
    if not argv:
        print(usage, file=sys.stderr)
        return 2
    pretty = "--pretty" in argv
    metrics_mode = "--metrics" in argv
    compare_mode = "--compare" in argv
    trend_mode = "--trend" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(usage, file=sys.stderr)
        return 2
    if trend_mode:
        from ..prof.ledger import load_history
        try:
            report = summarize_trend(load_history(paths[0]))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot render trend {paths[0]!r}: {e}",
                  file=sys.stderr)
            return 1
        json.dump(report, sys.stdout, indent=2 if pretty else None,
                  sort_keys=True, ensure_ascii=False)
        print()
        return 0
    if compare_mode:
        if len(paths) != 2:
            print(usage, file=sys.stderr)
            return 2
        from ..obs.metrics import read_metrics_file
        try:
            report = compare_metrics(read_metrics_file(paths[0]),
                                     read_metrics_file(paths[1]))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot compare metrics: {e}", file=sys.stderr)
            return 1
        json.dump(report, sys.stdout, indent=2 if pretty else None,
                  sort_keys=True)
        print()
        return 0
    path = paths[0]
    if metrics_mode:
        from ..obs.metrics import read_metrics_file
        try:
            report = summarize_metrics(read_metrics_file(path))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot read metrics {path!r}: {e}",
                  file=sys.stderr)
            return 1
        json.dump(report, sys.stdout, indent=2 if pretty else None,
                  sort_keys=True)
        print()
        return 0
    try:
        events = load_events(path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot read trace {path!r}: {e}", file=sys.stderr)
        return 1
    report = summarize(events)
    json.dump(report, sys.stdout, indent=2 if pretty else None,
              sort_keys=True)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
