"""Log parsing/plotting: the reference's tools/parse-shadow.py +
plot-shadow.py + strip_log_for_compare.py, for shadow_tpu log output.

Line format (core/logger.py LogRecord.format):
    <wall_s> [<thread>] <HH:MM:SS.ns|n/a> [<level>] [<domain>] <text>

Heartbeats (host/tracker.py):
    ... [tracker] [shadow-heartbeat] [<host>] rx=N tx=N rx_pkts=N tx_pkts=N
        retrans=N drops=N proc_ms=F

Three entry points (also usable as a library):
    parse  <log>           -> summary JSON on stdout (per-host totals,
                              throughput time series, sim/wall ratio)
    strip  <log>           -> canonical lines for determinism diffing
                              (wall time + thread removed — the reference's
                              strip_log_for_compare.py)
    plot   <log> <out.png> -> throughput/heartbeat plots (needs matplotlib)
"""

from __future__ import annotations

import json
import re
import sys
from collections import defaultdict
from typing import Dict, Iterable, List, Optional

LINE_RE = re.compile(
    r"^(?P<wall>\d+\.\d+) \[(?P<thread>[^\]]*)\] (?P<sim>\S+) "
    r"\[(?P<level>[^\]]*)\] \[(?P<domain>[^\]]*)\] (?P<text>.*)$")
HEARTBEAT_RE = re.compile(
    r"\[shadow-heartbeat\] \[(?P<host>[^\]]+)\] rx=(?P<rx>\d+) tx=(?P<tx>\d+) "
    r"rx_pkts=(?P<rx_pkts>\d+) tx_pkts=(?P<tx_pkts>\d+) "
    r"retrans=(?P<retrans>\d+) drops=(?P<drops>\d+) proc_ms=(?P<proc_ms>[\d.]+)")
FINISH_RE = re.compile(
    r"simulation finished: (?P<rounds>\d+) rounds, (?P<events>\d+) events, "
    r"(?P<wall>[\d.]+)s wall")


def parse_sim_time(text: str) -> Optional[float]:
    """'HH:MM:SS.ns' -> seconds; 'n/a' -> None."""
    if text == "n/a":
        return None
    try:
        h, m, rest = text.split(":")
        s, _, ns = rest.partition(".")
        return int(h) * 3600 + int(m) * 60 + int(s) + (int(ns) / 1e9 if ns else 0.0)
    except ValueError:
        return None


def iter_records(lines: Iterable[str]):
    for line in lines:
        m = LINE_RE.match(line.rstrip("\n"))
        if m:
            yield m.groupdict()


def parse_log(lines: Iterable[str]) -> Dict:
    """Aggregate a run's log into the reference parse-shadow.py-style
    summary: per-host heartbeat series + totals + run info."""
    hosts: Dict[str, List[Dict]] = defaultdict(list)
    info: Dict = {}
    last_sim = 0.0
    for rec in iter_records(lines):
        sim_t = parse_sim_time(rec["sim"])
        if sim_t is not None:
            last_sim = max(last_sim, sim_t)
        hb = HEARTBEAT_RE.search(rec["text"])
        if hb:
            d = {k: (float(v) if k == "proc_ms" else int(v)) if k != "host" else v
                 for k, v in hb.groupdict().items()}
            d["time_s"] = sim_t
            hosts[hb.group("host")].append(d)
            continue
        fin = FINISH_RE.search(rec["text"])
        if fin:
            info = {"rounds": int(fin.group("rounds")),
                    "events": int(fin.group("events")),
                    "wall_s": float(fin.group("wall"))}
    totals = {}
    for host, series in hosts.items():
        last = series[-1]
        totals[host] = {"rx_bytes": last["rx"], "tx_bytes": last["tx"],
                        "rx_pkts": last["rx_pkts"], "tx_pkts": last["tx_pkts"],
                        "retrans": last["retrans"], "drops": last["drops"]}
    out = {
        "hosts": totals,
        "num_hosts": len(totals),
        "total_rx_bytes": sum(t["rx_bytes"] for t in totals.values()),
        "total_tx_bytes": sum(t["tx_bytes"] for t in totals.values()),
        "total_retrans": sum(t["retrans"] for t in totals.values()),
        "total_drops": sum(t["drops"] for t in totals.values()),
        "sim_seconds": last_sim,
        "run": info,
        "series": {h: s for h, s in hosts.items()},
    }
    if info.get("wall_s"):
        out["sim_sec_per_wall_sec"] = last_sim / info["wall_s"]
    return out


def strip_log(lines: Iterable[str]) -> Iterable[str]:
    """Canonical form for determinism diffing: drop wall time and thread
    (nondeterministic), keep (sim time, level, domain, text) — the exact
    transformation of the reference's strip_log_for_compare.py."""
    for rec in iter_records(lines):
        text = rec["text"]
        # engine heartbeats are wall-clock-gated (fire after N wall seconds):
        # both their presence and their content are nondeterministic, exactly
        # like the reference's getrusage heartbeats its strip tool drops
        if text.startswith("[engine-heartbeat]"):
            continue
        # wall-clock durations inside message text are nondeterministic too
        text = re.sub(r"[\d.]+s wall", "<wall>s wall", text)
        text = re.sub(r"\(host_exec [\d.]+s, flush [\d.]+s\)",
                      "(host_exec <s>, flush <s>)", text)
        yield f"{rec['sim']} [{rec['level']}] [{rec['domain']}] {text}"


def plot_log(lines: Iterable[str], out_path: str) -> bool:
    """Throughput-over-time plot per host; returns False if matplotlib is
    unavailable (plot-shadow.py equivalent)."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib not available; skipping plot", file=sys.stderr)
        return False
    summary = parse_log(lines)
    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=(10, 8), sharex=True)
    for host, series in summary["series"].items():
        ts = [p["time_s"] for p in series if p["time_s"] is not None]
        rx = [p["rx"] for p in series if p["time_s"] is not None]
        tx = [p["tx"] for p in series if p["time_s"] is not None]
        if not ts:
            continue
        # cumulative -> rate between heartbeats
        rx_rate = [0.0] + [(b - a) / max(t2 - t1, 1e-9) / 1024
                           for a, b, t1, t2 in zip(rx, rx[1:], ts, ts[1:])]
        tx_rate = [0.0] + [(b - a) / max(t2 - t1, 1e-9) / 1024
                           for a, b, t1, t2 in zip(tx, tx[1:], ts, ts[1:])]
        ax1.plot(ts, rx_rate, alpha=0.6, label=host if len(summary["series"]) <= 12 else None)
        ax2.plot(ts, tx_rate, alpha=0.6)
    ax1.set_ylabel("rx KiB/s")
    ax2.set_ylabel("tx KiB/s")
    ax2.set_xlabel("virtual time (s)")
    if len(summary["series"]) <= 12:
        ax1.legend(fontsize=8)
    fig.suptitle("shadow_tpu per-host throughput")
    fig.savefig(out_path, dpi=120)
    return True


def main(argv: List[str]) -> int:
    if len(argv) < 2:
        print("usage: python -m shadow_tpu.tools.parse_log "
              "{parse|strip|plot} <log> [out.png]", file=sys.stderr)
        return 2
    cmd, path = argv[0], argv[1]
    with open(path) as f:
        lines = f.readlines()
    if cmd == "parse":
        summary = parse_log(lines)
        summary.pop("series")  # keep stdout JSON compact
        json.dump(summary, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    if cmd == "strip":
        for line in strip_log(lines):
            print(line)
        return 0
    if cmd == "plot":
        out = argv[2] if len(argv) > 2 else "shadow_plot.png"
        return 0 if plot_log(lines, out) else 1
    print(f"unknown command {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
