"""Benchmark workload-config generator: the five BASELINE.md configs.

The reference's benchmark plan (BASELINE.json "configs") names five
workloads; this module generates runnable shadow_tpu XML configs for each,
parameterized so tests use small instances and benchmarks use full scale:

  1. two_host_echo()          — 2-host tgen echo (resource/examples analog)
  2. star_bulk(100)           — 100-host bulk transfer, single-AS star
  3. tor_network(1000, ...)   — 1k-relay Tor overlay, python:tor app
  4. tor_network(10000, topology=...) — 10k-host Tor on the reference's
     Internet GraphML (pass /root/reference/resource/topology.graphml.xml.xz)
  5. bitcoin_network(5000)    — 5k-node Bitcoin gossip

Usage: ``python -m shadow_tpu.tools.workloads <name> [> config.xml]`` with
name in {echo2, star100, tor1k, tor10k, btc5k} or programmatically.

Determinism: all random structure (peer graphs, circuit paths) comes from a
seeded numpy Generator, so a config built with the same arguments is
byte-identical.
"""

from __future__ import annotations

import sys
from typing import List, Optional

import numpy as np


def two_host_echo(stoptime: int = 60) -> str:
    return f"""<shadow stoptime="{stoptime}">
  <plugin id="tgen" path="python:tgen" />
  <host id="server" bandwidthdown="102400" bandwidthup="102400">
    <process plugin="tgen" starttime="1" arguments="server 80" />
  </host>
  <host id="client" bandwidthdown="10240" bandwidthup="5120">
    <process plugin="tgen" starttime="2" arguments="client server 80 1024:1048576" />
  </host>
</shadow>
"""


def star_bulk(n_clients: int = 100, stoptime: int = 600,
              bulk_bytes: int = 10 * 1024 * 1024,
              device_data: bool = False) -> str:
    """Single-AS star: one big server, n clients each pulling bulk_bytes.
    ``device_data=True`` promotes the bulk phase to the device-resident
    traffic plane (the tgen handshake still runs over real TCP)."""
    dev = " device" if device_data else ""
    lines = [f'<shadow stoptime="{stoptime}">',
             '  <plugin id="tgen" path="python:tgen" />',
             '  <host id="server" bandwidthdown="1048576" bandwidthup="1048576">',
             '    <process plugin="tgen" starttime="1" arguments="server 80" />',
             '  </host>']
    for i in range(n_clients):
        lines.append(
            f'  <host id="client{i}" bandwidthdown="102400" bandwidthup="51200">\n'
            f'    <process plugin="tgen" starttime="2" '
            f'arguments="client server 80 256:{bulk_bytes}{dev}" />\n'
            '  </host>')
    lines.append('</shadow>')
    return "\n".join(lines) + "\n"


def tor_network(n_relays: int = 1000, n_clients: Optional[int] = None,
                n_servers: Optional[int] = None, stoptime: int = 600,
                streams_per_client: int = 3, stream_spec: str = "512:51200",
                topology_path: Optional[str] = None, seed: int = 42,
                dirauth: bool = False, device_data: bool = False) -> str:
    """Tor overlay: relays + clients with random 3-hop paths + destinations.

    Mirrors the shape of the reference's Tor experiments (shadow-plugin-tor
    topologies: ~10% exits/guards, ~1 client per relay, few fat servers).

    ``dirauth=True`` adds the directory bootstrap phase: a directory
    authority host, relays publishing bandwidth-weighted descriptors, and
    clients fetching the consensus and picking their own weighted paths
    (instead of config-assigned ones) — real Tor's startup behavior.

    ``device_data=True`` marks every client for the device-resident traffic
    plane (circuit build stays on the simulated control plane; the bulk
    download advances in HBM — parallel/device_plane.py).  Composes with
    ``dirauth=True``: auto: consensus paths are predicted at startup and
    cross-checked at runtime (resolve_auto_routes/check_route)."""
    # dirauth + device_data now compose: the device plane predicts each
    # auto: client's consensus path at startup from the config-determined
    # consensus and the client's derived path stream, and the runtime
    # cross-checks the fetched route (parallel/device_plane.py
    # resolve_auto_routes / check_route)
    rng = np.random.default_rng(seed)
    n_clients = n_clients if n_clients is not None else max(1, n_relays)
    n_servers = n_servers if n_servers is not None else max(1, n_relays // 20)
    lines = [f'<shadow stoptime="{stoptime}">']
    if topology_path:
        lines.append(f'  <topology path="{topology_path}" />')
    lines.append('  <plugin id="tor" path="python:tor" />')
    if dirauth:
        lines.append(
            '  <host id="dirauth" bandwidthdown="1048576" bandwidthup="1048576">\n'
            '    <process plugin="tor" starttime="1" arguments="dirauth 9030" />\n'
            '  </host>')
    for i in range(n_relays):
        relay_args, relay_start = "relay 9001", 1
        if dirauth:
            bw = int(rng.integers(50, 1000))
            relay_args, relay_start = f"relay 9001 dirauth:9030 {bw}", 2
        lines.append(
            f'  <host id="relay{i}" bandwidthdown="102400" bandwidthup="102400">\n'
            f'    <process plugin="tor" starttime="{relay_start}" '
            f'arguments="{relay_args}" />\n'
            '  </host>')
    for i in range(n_servers):
        lines.append(
            f'  <host id="dest{i}" bandwidthdown="1048576" bandwidthup="1048576">\n'
            f'    <process plugin="tor" starttime="1" arguments="server 80" />\n'
            '  </host>')
    for i in range(n_clients):
        if dirauth:
            path_s = "auto:dirauth:9030"
        else:
            path = rng.choice(n_relays, size=min(3, n_relays), replace=False)
            path_s = ",".join(f"relay{int(r)}" for r in path)
        dest = int(rng.integers(0, n_servers))
        start = 5 + int(rng.integers(0, 30))
        dev = " device" if device_data else ""
        lines.append(
            f'  <host id="torclient{i}" bandwidthdown="51200" bandwidthup="10240">\n'
            f'    <process plugin="tor" starttime="{start}" '
            f'arguments="client 9050 {path_s} dest{dest} 80 '
            f'{streams_per_client} {stream_spec}{dev}" />\n'
            '  </host>')
    lines.append('</shadow>')
    return "\n".join(lines) + "\n"


def bitcoin_network(n_nodes: int = 5000, n_peers: int = 8,
                    n_miners: int = 10, stoptime: int = 600,
                    block_interval: int = 60, block_bytes: int = 1_000_000,
                    blocks_per_miner: int = 3, seed: int = 42) -> str:
    """Bitcoin gossip: each node dials n_peers random earlier nodes (the
    standard random-graph construction; guarantees a connected overlay)."""
    rng = np.random.default_rng(seed)
    lines = [f'<shadow stoptime="{stoptime}">',
             '  <plugin id="btc" path="python:bitcoin" />']
    miners = set(int(x) for x in
                 rng.choice(n_nodes, size=min(n_miners, n_nodes),
                            replace=False))
    for i in range(n_nodes):
        if i == 0:
            peers = "-"
        else:
            k = min(n_peers, i)
            chosen = rng.choice(i, size=k, replace=False)
            peers = ",".join(f"node{int(p)}" for p in chosen)
        mine = (f" mine {block_interval} {block_bytes} {blocks_per_miner}"
                if i in miners else "")
        start = 1 + (i % 20)  # staggered boot, 20 waves
        lines.append(
            f'  <host id="node{i}" bandwidthdown="102400" bandwidthup="102400">\n'
            f'    <process plugin="btc" starttime="{start}" '
            f'arguments="{peers}{mine}" />\n'
            '  </host>')
    lines.append('</shadow>')
    return "\n".join(lines) + "\n"


def _reference_topology() -> str:
    """The Internet-scale GraphML for the tor10k workload: from
    $SHADOW_TPU_TOPOLOGY, or the conventional reference checkout path."""
    import os
    path = os.environ.get("SHADOW_TPU_TOPOLOGY",
                          "/root/reference/resource/topology.graphml.xml.xz")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"tor10k needs an Internet-scale GraphML topology; {path} does "
            "not exist — set $SHADOW_TPU_TOPOLOGY to one")
    return path


NAMED = {
    "echo2": lambda: two_host_echo(),
    "star100": lambda: star_bulk(100),
    "tor1k": lambda: tor_network(1000),
    "tor10k": lambda: tor_network(10000, topology_path=_reference_topology()),
    "btc5k": lambda: bitcoin_network(5000),
}


def main(argv: List[str]) -> int:
    if len(argv) < 1 or argv[0] not in NAMED:
        print(f"usage: python -m shadow_tpu.tools.workloads "
              f"{{{','.join(NAMED)}}}", file=sys.stderr)
        return 2
    sys.stdout.write(NAMED[argv[0]]())
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
