"""simfuzz CLI: seeded scenario fuzzing, repro replay, corpus regression.

Usage::

    simfuzz --seeds 25 [--seed-base 0] [--timeout-sec 240]
            [--wall-cap-sec 0] [--fault-inject KIND[:MODE]]
            [--repro-dir DIR] [--no-shrink] [--shrink-budget 40]
            [--in-process] [--out results.json]
    simfuzz --spec PATH           # fuzz one pinned spec/repro file
    simfuzz --repro PATH          # replay a repro file
    simfuzz --corpus [DIR]        # replay the checked-in regression set
    simfuzz --spec-only --seeds N # print the drawn specs, run nothing

Exit codes: 0 = every gate held (for ``--repro``: the file's expectation
was met), 1 = violations found (or expectation missed), 2 = usage/file
errors.  Prints ONE summary JSON line last, like bench.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time as _walltime
from typing import Dict, List, Optional

from . import SPEC_VERSION
from .gen import draw_spec, spec_digest
from .oracles import check
from .runner import (InProcessRunner, SubprocessRunner, child_main,
                     parse_fault)
from .shrink import shrink

CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "corpus")


def _say(msg: str) -> None:
    print(f"simfuzz: {msg}", file=sys.stderr, flush=True)


def write_repro(spec: Dict, violation: Dict, path: str) -> None:
    """A self-contained repro file: the minimal spec, the violation it
    reproduces, and the expectation ``--repro`` judges against."""
    blob = {"version": SPEC_VERSION, "tool": "simfuzz",
            "expect": "violation", "violation": violation, "spec": spec,
            "spec_digest": spec_digest(spec)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(blob, f, indent=1, sort_keys=True)
        f.write("\n")


def replay_file(path: str, runner) -> int:
    """Replay one repro/corpus file; rc 0 iff its expectation holds."""
    try:
        with open(path, "r") as f:
            blob = json.load(f)
    except (OSError, ValueError) as e:
        _say(f"cannot read repro {path}: {e}")
        return 2
    spec = blob.get("spec")
    if not isinstance(spec, dict):
        _say(f"{path}: no spec")
        return 2
    expect = blob.get("expect", "clean")
    viols = check(spec, runner.run(spec))
    if expect == "violation":
        want = (blob.get("violation") or {}).get("oracle")
        hit = [v for v in viols if v["oracle"] == want]
        print(json.dumps({"repro": path, "expect": expect,
                          "oracle": want, "reproduced": bool(hit),
                          "violations": viols}))
        if hit:
            _say(f"{path}: reproduced {want}: {hit[0]['detail'][:200]}")
            return 0
        _say(f"{path}: expected {want} violation did NOT reproduce")
        return 1
    print(json.dumps({"repro": path, "expect": expect,
                      "violations": viols}))
    if viols:
        _say(f"{path}: {len(viols)} violation(s) on a spec expected "
             "clean (regression!)")
        return 1
    return 0


def corpus_files(directory: str) -> List[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(os.path.join(directory, n)
                  for n in os.listdir(directory) if n.endswith(".json"))


def fuzz(args, runner) -> int:
    t0 = _walltime.monotonic()
    fault = parse_fault(args.fault_inject) if args.fault_inject else None
    seeds_run = 0
    all_violations: List[Dict] = []
    repros: List[str] = []
    wall_capped = False
    if args.spec:
        with open(args.spec, "r") as f:
            pinned = json.load(f)
        if "spec" in pinned and "family" not in pinned:
            pinned = pinned["spec"]       # accept repro files too
        targets = [(int(pinned.get("seed", 0)), pinned)]
    else:
        targets = [(args.seed_base + i, None) for i in range(args.seeds)]
    prefetched = None
    if hasattr(runner, "run_specs") and not args.spec_only:
        # --batched (ISSUE 18): draw the WHOLE seed list up front and
        # fleet-run every spec's mode matrix in one two-phase pass (the
        # batchable modes as concurrent vmapped lanes, the rest warm and
        # serial); the judge/shrink loop below then reads the prefetched
        # results instead of running per seed.  Verdicts are
        # digest-identical to the subprocess path — same specs, same
        # run_one_mode, same oracles.
        drawn = []
        for seed, pinned in targets:
            spec = pinned if pinned is not None else draw_spec(seed)
            if fault:
                spec["fault_inject"] = fault
            drawn.append(spec)
        _say(f"batched: {len(drawn)} specs over the fleet plane")
        prefetched = runner.run_specs(drawn)
        targets = [(seed, spec)
                   for (seed, _), spec in zip(targets, drawn)]
    for idx, (seed, pinned) in enumerate(targets):
        if args.wall_cap_sec and \
                _walltime.monotonic() - t0 > args.wall_cap_sec:
            wall_capped = True
            _say(f"wall cap {args.wall_cap_sec}s reached after "
                 f"{seeds_run} seeds; stopping early (honestly reported)")
            break
        spec = pinned if pinned is not None else draw_spec(seed)
        if fault and prefetched is None:
            spec["fault_inject"] = fault
        if args.spec_only:
            print(json.dumps(spec))
            seeds_run += 1
            continue
        results = prefetched[idx] if prefetched is not None \
            else runner.run(spec)
        viols = check(spec, results)
        seeds_run += 1
        modes_run = sum(1 for r in results if not r.get("skipped"))
        _say(f"seed {seed} [{spec['family']}]: {modes_run} modes, "
             f"{len(viols)} violation(s)")
        if not viols:
            continue
        all_violations.extend(
            dict(v, seed=seed, family=spec["family"]) for v in viols)
        target = viols[0]
        if args.no_shrink:
            small, final = spec, target
        else:
            _say(f"seed {seed}: shrinking {target['oracle']} violation "
                 f"({target['detail'][:120]})")
            # a wall-capped run bounds the shrink too (best-so-far repro
            # beats losing the violation to the caller's outer kill)
            deadline = (t0 + args.wall_cap_sec) if args.wall_cap_sec \
                else None
            small, final, runs = shrink(spec, target, runner,
                                        budget=args.shrink_budget,
                                        log=_say, deadline=deadline)
            _say(f"seed {seed}: shrunk in {runs} runs -> "
                 f"{len(small['modes'])} modes, params {small['params']}")
        path = os.path.join(args.repro_dir,
                            f"seed{seed}-{final['oracle']}.json")
        write_repro(small, final, path)
        repros.append(path)
        _say(f"seed {seed}: repro written to {path} "
             f"(replay: simfuzz --repro {path})")
        if args.stop_on_violation:
            break
    wall = _walltime.monotonic() - t0
    summary = {"simfuzz": {"seeds": seeds_run,
                           "requested_seeds": len(targets),
                           "wall_capped": wall_capped,
                           "violations": len(all_violations),
                           "repros": repros,
                           "fault_inject": args.fault_inject or None,
                           "wall_sec": round(wall, 1)},
               "pass": not all_violations}
    if prefetched is not None:
        # fleet attribution (ISSUE 18): N-up plane throughput plus the
        # plane's own launch-amortization/occupancy/compile counters
        summary["simfuzz"]["fleet"] = dict(
            runner.plane_stats(),
            lanes_requested=getattr(args, "lanes", 0),
            batched_modes=runner.batched_modes,
            serial_modes=runner.serial_modes,
            seeds_per_sec=round(seeds_run / wall, 3) if wall else 0.0)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(dict(summary, violations=all_violations), f,
                      indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(summary), flush=True)
    return 1 if all_violations else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="simfuzz",
        description="seeded scenario fuzzing over the shadow-tpu engine "
                    "(digest stability/parity, event conservation, "
                    "supervision cleanliness, mesh invariants, rc/log "
                    "hygiene)")
    p.add_argument("--seeds", type=int, default=10,
                   help="number of seeded scenarios to run")
    p.add_argument("--seed-base", type=int, default=0, dest="seed_base")
    p.add_argument("--timeout-sec", type=float, default=240.0,
                   dest="timeout_sec",
                   help="wall bound per scenario child (killed + "
                        "reported on overrun, never a hang)")
    p.add_argument("--wall-cap-sec", type=float, default=0.0,
                   dest="wall_cap_sec",
                   help="stop drawing new seeds past this total wall "
                        "(0 = run all; the cap is reported, not hidden)")
    p.add_argument("--fault-inject", default="", dest="fault_inject",
                   help="drift one mode's reported oracle inputs "
                        "(digest-drift:MODE | events-drift:MODE | "
                        "supervision-drift:MODE | rc-drift:MODE) or "
                        "drive the engine harness (engine:TOKEN) — the "
                        "caught-shrunk-replayed drill")
    p.add_argument("--repro-dir", default="simfuzz-repros",
                   dest="repro_dir")
    p.add_argument("--no-shrink", action="store_true", dest="no_shrink")
    p.add_argument("--shrink-budget", type=int, default=40,
                   dest="shrink_budget")
    p.add_argument("--stop-on-violation", action="store_true",
                   dest="stop_on_violation")
    p.add_argument("--in-process", action="store_true", dest="in_process",
                   help="run scenarios in this process (tests/corpus; "
                        "production fuzzing uses bounded children)")
    p.add_argument("--batched", action="store_true",
                   help="run the whole seed list in-process over the "
                        "fleet plane (ISSUE 18): batchable modes as "
                        "concurrent vmapped lanes, the rest warm and "
                        "serial — digest-identical to the subprocess "
                        "path, >= 5x the seeds/sec")
    p.add_argument("--lanes", type=int, default=8,
                   help="concurrent fleet lanes with --batched")
    p.add_argument("--spec-only", action="store_true", dest="spec_only",
                   help="print the drawn specs as JSON, run nothing")
    p.add_argument("--out", default=None,
                   help="write the full result record here as JSON")
    p.add_argument("--spec", default=None, metavar="PATH",
                   help="fuzz ONE pinned spec file (or a repro file's "
                        "spec) instead of drawing seeds — the "
                        "debug-a-scenario entry")
    p.add_argument("--repro", default=None, metavar="PATH",
                   help="replay one repro file and judge its expectation")
    p.add_argument("--corpus", nargs="?", const=CORPUS_DIR, default=None,
                   metavar="DIR",
                   help="replay every corpus file (default: the "
                        "checked-in fuzz/corpus/ regression set)")
    p.add_argument("--child", nargs=2, metavar=("IN", "OUT"),
                   default=None, help=argparse.SUPPRESS)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.child:
        return child_main(args.child[0], args.child[1])
    if args.batched:
        # env must be pinned before jax initializes (the fleet cli owns
        # the one shared helper) so phase-2 mesh modes see the virtual
        # device mesh in-process, exactly like subprocess children do
        from ..fleet.cli import setup_fleet_env
        setup_fleet_env()
        from .runner import BatchedRunner
        runner = BatchedRunner(lanes=args.lanes)
    elif args.in_process:
        runner = InProcessRunner()
    else:
        runner = SubprocessRunner(timeout_sec=args.timeout_sec)
    if args.repro:
        return replay_file(args.repro, runner)
    if args.corpus is not None:
        files = corpus_files(args.corpus)
        if not files:
            _say(f"no corpus files under {args.corpus}")
            return 2
        rcs = [replay_file(f, runner) for f in files]
        bad = sum(1 for rc in rcs if rc)
        print(json.dumps({"corpus": args.corpus, "files": len(files),
                          "failed": bad, "pass": not bad}), flush=True)
        return 1 if bad else 0
    return fuzz(args, runner)


if __name__ == "__main__":
    sys.exit(main())
