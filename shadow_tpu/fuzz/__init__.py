"""simfuzz: seeded scenario fuzzing for the engine's standing invariants.

Shadow's pitch is *varied real workloads* over a PDES core; this package
turns "scenario diversity" into a standing differential test instead of a
demo gallery (ROADMAP item 4).  From one integer seed it derives a
randomized-but-deterministic scenario — family (star/tor/cdn/swarm/phold/
app mix), host counts, bandwidth/latency/loss draws, optional generated
topology, plugin apps from ``apps/registry.py`` — plus a CLI-mode matrix
(device-vs-numpy twins, K=1-vs-K=8 superwindows, HostTable on/off,
serial/threaded/``--processes``, sharded mesh), runs the scenario short in
a bounded subprocess, and checks a pluggable oracle set: repeat-run digest
stability, cross-mode digest parity, event-count conservation,
``engine.supervision`` cleanliness, mesh invariants, and rc/log hygiene.

On a violation the scenario is SHRUNK (drop modes/apps/topology, halve
hosts/stoptime/bytes, re-verifying each step) to a minimal reproducer and
written as a self-contained repro file that ``simfuzz --repro PATH``
replays; failing seeds live in ``fuzz/corpus/`` as a regression set the
tier-1 suite replays.

Layout: gen.py (seed -> spec -> Configuration + mode matrix), runner.py
(in-process + bounded-subprocess execution), oracles.py (the invariant
set), shrink.py (minimizer), cli.py (``simfuzz`` console entry /
``python -m shadow_tpu.fuzz``).
"""

SPEC_VERSION = 1
