"""Seeded scenario generation: one integer seed -> a self-contained spec.

A *spec* is a JSON-able dict — the unit the fuzzer runs, shrinks, and
checks into ``fuzz/corpus/``.  Everything derives from the seed through
``numpy.random.default_rng``, so the same seed always yields the same
spec, and ``build_config(spec)`` rebuilds the identical ``Configuration``
in any process (the scale generators' override fidelity — a rejected
unknown kwarg, scale/genscen.py — is what makes the replay trustworthy).

Spec shape::

    {"version": 1, "seed": 7,
     "family": "star|tor|cdn|swarm|phold|appmix",
     "params": {...},            # genscen builder kwargs (flow families)
     "apps": [{host-group}...],  # plugin app groups (appmix / ride-alongs)
     "topology": null | {"vertices": V, "seed": s,
                          "max_latency_ms": L, "loss_pct": p},
     "stoptime": 24,
     "modes": [{mode}...],       # the CLI matrix this spec runs under
     "fault_inject": null | {...}}   # see runner.apply_fault

The mode matrix is derived from the family, not drawn, so every axis the
acceptance gate names (device-vs-numpy, K=1-vs-K=8, table-on/off, mesh)
is engaged across any handful of seeds.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import SPEC_VERSION

FLOW_FAMILIES = ("star", "tor", "cdn", "swarm")
ALL_FAMILIES = FLOW_FAMILIES + ("phold", "appmix")


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------

def make_graphml(topo: Dict) -> str:
    """A complete graph of ``vertices`` vertices (+ self loops) with seeded
    latency/loss draws — small enough to inline as ``topology_text``,
    varied enough that hop latencies and the derived lookahead differ per
    seed.  Deterministic: same dict, byte-identical text."""
    v = int(topo["vertices"])
    rng = np.random.default_rng(int(topo["seed"]))
    max_lat = float(topo.get("max_latency_ms", 60.0))
    loss = float(topo.get("loss_pct", 0.0)) / 100.0
    lines = [
        '<?xml version="1.0" encoding="UTF-8"?>',
        '<graphml xmlns="http://graphml.graphdrawing.org/xmlns">',
        '  <key id="d5" for="edge" attr.name="latency" attr.type="double"/>',
        '  <key id="d6" for="edge" attr.name="packetloss"'
        ' attr.type="double"/>',
        '  <graph edgedefault="undirected">',
    ]
    for i in range(v):
        lines.append(f'    <node id="v{i}" />')
    for i in range(v):
        for j in range(i, v):
            lat = 1.0 if i == j else round(
                float(rng.uniform(2.0, max_lat)), 3)
            pl = 0.0 if i == j else round(float(rng.uniform(0.0, loss)), 5)
            lines.append(
                f'    <edge source="v{i}" target="v{j}">'
                f'<data key="d5">{lat}</data>'
                f'<data key="d6">{pl}</data></edge>')
    lines.append('  </graph>')
    lines.append('</graphml>')
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# mode matrices
# ---------------------------------------------------------------------------

def _mode(name: str, **kw) -> Dict:
    m = {"name": name, "policy": "global", "workers": 0, "processes": 0,
         "device_plane": "device", "superwindow_rounds": 8,
         "tpu_devices": 1, "host_table": "on", "dataplane": "python",
         "device_plane_sync": False, "exchange_mode": "auto",
         "device_autotune": "on", "events_comparable": True}
    m.update(kw)
    return m


def flow_modes(rng) -> List[Dict]:
    """The flow-family matrix: device/numpy twins, K=1-vs-K=8, repeat-run
    stability, and the sharded mesh (skipped gracefully under <2
    devices) — with the ``--exchange-mode`` axis forced each way on the
    SAME drawn mesh size (ISSUE 15), so the cross-mode digest-parity
    oracle covers the cost-model-driven scheduler decision for free:
    whatever auto picks, the fused and multi-leg-ppermute kernels must
    land the identical digest."""
    d = int(rng.integers(2, 5))
    modes = [
        _mode("base"),
        _mode("base-repeat", repeat_of="base"),
        _mode("numpy", device_plane="numpy"),
        _mode("k1", superwindow_rounds=1),
        _mode("mesh", tpu_devices=d),
        _mode("mesh-fused", tpu_devices=d, exchange_mode="fused"),
        _mode("mesh-ppermute", tpu_devices=d, exchange_mode="ppermute"),
    ]
    if rng.integers(0, 2):
        modes.append(_mode("sync", device_plane_sync=True))
    # the auto-tuner axis (ISSUE 16): every mode above runs with the
    # tuner's default-on behavior; this leg forces the hand defaults, so
    # the cross-mode digest oracle pins tuned-vs-untuned parity for free.
    # Appended AFTER all rng draws — the draw stream (and thus every
    # historical seed's scenario) is unchanged.
    modes.append(_mode("autotune-off", device_autotune="off"))
    # recovery axes (ISSUE 17), appended after all draws for the same
    # reason (the mesh-lost leg reuses the d drawn above — no new draw):
    # checkpoint+--resume faces the digest-parity oracle, and the
    # self-healing drills (mid-run device loss re-shard, demote ->
    # probation -> re-promotion) must land the SAME digest as the
    # fault-free base — recovery is a detour, never a different simulation
    modes.append(_mode("resume", resume=True))
    modes.append(_mode("mesh-lost", tpu_devices=d,
                       engine_fault="device-lost:3"))
    modes.append(_mode("demote-repromote",
                       engine_fault="demote-repromote:2",
                       repromote_after=3))
    return modes


def app_modes(rng, n_hosts: int) -> List[Dict]:
    """The plugin-app matrix: HostTable on/off, the native-vs-python data
    plane differential (table off only — the C plane declines while
    unmaterialized rows exist), a threaded scheduler, and ``--processes``
    sharding."""
    modes = [
        _mode("base"),
        _mode("base-repeat", repeat_of="base"),
        _mode("table-off", host_table="off"),
        _mode("native-auto", host_table="off", dataplane="auto"),
        _mode("threaded", host_table="off", policy="host", workers=2,
              events_comparable=False),
    ]
    if n_hosts >= 4 and rng.integers(0, 2):
        modes.append(_mode("procs", processes=2, events_comparable=False))
    # recovery axes (ISSUE 17), appended AFTER all rng draws so every
    # historical seed's scenario replays unchanged: checkpoint+--resume
    # parity, and — when the host count supports sharding — a SIGKILL'd
    # shard resurrected mid-run that must still land the base digest.
    modes.append(_mode("resume", resume=True))
    if n_hosts >= 4:
        modes.append(_mode("procs-resurrect", processes=2,
                           events_comparable=False,
                           engine_fault="shard-exit-resurrect:1:2",
                           max_resurrections=3))
    # the spec-defined CC family (ISSUE 19), appended AFTER all rng draws
    # so every historical seed's scenario replays unchanged.  bbrx takes a
    # legitimately different trajectory from the reno-default legs, so the
    # pair carries its own digest_group: the parity/events oracles compare
    # bbrx-vs-bbrx (the generated logic must land one digest across the
    # table on/off axis), never bbrx-vs-base.
    modes.append(_mode("bbrx", tcpcc="bbrx", digest_group="bbrx"))
    modes.append(_mode("bbrx-table-off", tcpcc="bbrx", host_table="off",
                       digest_group="bbrx"))
    return modes


# ---------------------------------------------------------------------------
# family draws
# ---------------------------------------------------------------------------

def _draw_flow_params(family: str, rng) -> Dict:
    stagger = int(rng.integers(1, 4))
    common = dict(stagger_waves=stagger,
                  stagger_step_sec=float(rng.integers(1, 3)))
    if family == "star":
        return dict(common, n_clients=int(rng.integers(12, 70)),
                    down_bytes=int(rng.integers(8, 65)) * 1024,
                    up_bytes=int(rng.integers(0, 3)) * 1024)
    if family == "tor":
        return dict(common, n_hosts=int(rng.integers(40, 130)),
                    down_bytes=int(rng.integers(8, 49)) * 1024,
                    up_bytes=int(rng.integers(1, 3)) * 1024,
                    seed=int(rng.integers(1, 1 << 30)))
    if family == "cdn":
        return dict(common, n_clients=int(rng.integers(20, 90)),
                    n_origins=int(rng.integers(2, 5)),
                    down_bytes=int(rng.integers(16, 129)) * 1024,
                    up_bytes=int(rng.integers(0, 2)) * 1024,
                    seed=int(rng.integers(1, 1 << 30)))
    if family == "swarm":
        return dict(common, n_peers=int(rng.integers(16, 60)),
                    pieces=int(rng.integers(1, 4)),
                    piece_bytes=int(rng.integers(8, 49)) * 1024,
                    seed=int(rng.integers(1, 1 << 30)))
    raise ValueError(f"not a flow family: {family}")


def _draw_apps(rng, suffix: str = "") -> List[Dict]:
    """A coherent plugin-app set from the registry: an echo pair, a tgen
    star, or a phold group (the classic PDES event stress)."""
    kind = ("echo", "tgen", "phold")[int(rng.integers(0, 3))]
    if kind == "phold" and suffix:
        # the phold app's peer naming hardcodes the bare "phold" group id,
        # so a second phold set can neither rename nor coexist (two groups
        # claiming "phold1" reject at setup — fuzz-found at seed 66);
        # remap ONLY this case so every other seed's draw stream is
        # untouched
        kind = "echo"
    bw = int(rng.integers(10, 101)) * 1024
    if kind == "echo":
        proto = ("udp", "tcp")[int(rng.integers(0, 2))]
        port = 8000 + int(rng.integers(0, 100))
        n_msg = int(rng.integers(3, 9))
        size = int(rng.integers(1, 5)) * 512
        return [
            {"id": f"esrv{suffix}", "quantity": 1, "bw": bw,
             "plugin": "echo", "start": 1.0,
             "args": f"{proto} server {port}"},
            # a quantity-1 host keeps its bare id as its name
            {"id": f"ecli{suffix}", "quantity": int(rng.integers(1, 4)),
             "bw": bw, "plugin": "echo", "start": 2.0,
             "args": f"{proto} client esrv{suffix} {port} {n_msg} {size}"},
        ]
    if kind == "tgen":
        port = 80
        size = int(rng.integers(8, 200)) * 1024
        return [
            {"id": f"tsrv{suffix}", "quantity": 1, "bw": 4 * bw,
             "plugin": "tgen", "start": 1.0, "args": f"server {port}"},
            {"id": f"tcli{suffix}", "quantity": int(rng.integers(1, 5)),
             "bw": bw, "plugin": "tgen", "start": 2.0,
             "args": f"client tsrv{suffix} {port} 1024:{size}"},
        ]
    n = int(rng.integers(4, 13))
    return [
        {"id": "phold", "quantity": n, "bw": bw, "plugin": "phold",
         "start": 1.0,
         "args": f"{n} {int(rng.integers(1, 3))} 9000"},
    ]


# ---------------------------------------------------------------------------
# spec drawing + config build
# ---------------------------------------------------------------------------

def draw_spec(seed: int) -> Dict:
    """One integer seed -> a complete, self-contained scenario spec."""
    rng = np.random.default_rng(seed)
    family = ALL_FAMILIES[int(rng.integers(0, len(ALL_FAMILIES)))]
    stoptime = int(rng.integers(14, 27))
    spec: Dict = {"version": SPEC_VERSION, "seed": int(seed),
                  "family": family, "params": {}, "apps": [],
                  "topology": None, "stoptime": stoptime,
                  "engine_seed": int(rng.integers(1, 1000)),
                  "fault_inject": None}
    if family in FLOW_FAMILIES:
        spec["params"] = _draw_flow_params(family, rng)
        # a ride-along plugin pair exercises mixed table promotion
        # (quiet flow rows + materialized app hosts in one run)
        if rng.integers(0, 100) < 30:
            spec["apps"] = _draw_apps(rng, suffix="x")
        loss = 0.0          # flow chains model lossless bulk transfer
        spec["modes"] = flow_modes(rng)
    elif family == "phold":
        spec["params"] = dict(n_hosts=int(rng.integers(6, 25)),
                              msgs_in_flight=int(rng.integers(1, 3)),
                              bw_kibps=int(rng.integers(10, 101)) * 1024)
        loss = float(rng.integers(0, 3)) / 2.0
        spec["modes"] = app_modes(rng, spec["params"]["n_hosts"])
    else:
        spec["apps"] = _draw_apps(rng)
        if rng.integers(0, 2):
            spec["apps"] += _draw_apps(rng, suffix="b")
        loss = float(rng.integers(0, 3)) / 2.0
        n_hosts = sum(a["quantity"] for a in spec["apps"])
        spec["modes"] = app_modes(rng, n_hosts)
    if rng.integers(0, 2):
        spec["topology"] = {"vertices": int(rng.integers(2, 6)),
                            "seed": int(rng.integers(1, 1 << 30)),
                            "max_latency_ms": float(rng.integers(10, 81)),
                            "loss_pct": loss}
    return spec


def build_config(spec: Dict):
    """Rebuild the spec's ``Configuration`` (deterministic, any
    process)."""
    from ..core.configuration import (Configuration, HostConfig,
                                      ProcessConfig)
    from ..scale import genscen

    fam = spec["family"]
    if fam == "appmix":
        cfg = Configuration(stop_time_sec=spec["stoptime"])
    elif fam == "phold":
        cfg = genscen.build("phold", stoptime=spec["stoptime"],
                            **spec["params"])
    else:
        cfg = genscen.build(fam, stoptime=spec["stoptime"],
                            **spec["params"])
    cfg.stop_time_sec = spec["stoptime"]
    for app in spec.get("apps", []):
        hc = HostConfig(id=app["id"], quantity=int(app["quantity"]),
                        bandwidth_down_kibps=int(app["bw"]),
                        bandwidth_up_kibps=int(app["bw"]))
        hc.processes.append(ProcessConfig(
            plugin=f"python:{app['plugin']}",
            start_time_sec=float(app["start"]),
            arguments=app["args"]))
        cfg.hosts.append(hc)
    topo = spec.get("topology")
    if topo:
        cfg.topology_text = make_graphml(topo)
    return cfg


def spec_digest(spec: Dict) -> str:
    """Content digest of a spec (corpus dedupe key).  Built on the
    CONFIG digest — which covers FlowConfig fields and app argv — plus
    the mode matrix and fault spec, so two specs differing only in flow
    params or modes never collide."""
    import hashlib
    import json

    from ..scale.genscen import config_digest
    blob = json.dumps({"config": config_digest(build_config(spec)),
                       "modes": spec["modes"],
                       "fault": spec.get("fault_inject")},
                      sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()
