"""Shrink a violating spec to a minimal reproducer.

Greedy delta-debugging over the spec's structure: each candidate
transformation (drop a mode, drop an app group, drop the topology, halve
a numeric parameter, halve the stoptime) is applied ONE at a time and the
spec re-run; the candidate is kept only if the SAME oracle still fires.
Candidates are generated in a fixed order and the loop runs to a
fixpoint, so the minimal repro for a given (spec, violation, runner) is
deterministic.  Total re-runs are bounded by ``budget`` — a shrink is an
optimization, never a place to wedge.
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Tuple

from .oracles import check

# per-family floors the halving steps respect (below these, builders
# reject or the shape degenerates away from what it reproduces)
_PARAM_FLOORS = {
    "n_clients": 2, "n_hosts": 6, "n_peers": 4, "n_origins": 1,
    "pieces": 1, "msgs_in_flight": 1, "stagger_waves": 1,
    "down_bytes": 1024, "up_bytes": 0, "piece_bytes": 1024,
    "bw_kibps": 1024,
}
_HALVE_KEYS = tuple(sorted(_PARAM_FLOORS))


def _candidates(spec: Dict) -> List[Tuple[str, Dict]]:
    """All single-step reductions of ``spec``, in fixed order."""
    out: List[Tuple[str, Dict]] = []
    # 1. drop one mode (keep >= 2 so cross-mode oracles stay meaningful)
    if len(spec["modes"]) > 2:
        for i, m in enumerate(spec["modes"]):
            cand = copy.deepcopy(spec)
            del cand["modes"][i]
            out.append((f"drop mode {m['name']}", cand))
    # 2. drop one app group
    for i, app in enumerate(spec.get("apps", [])):
        cand = copy.deepcopy(spec)
        del cand["apps"][i]
        out.append((f"drop app {app['id']}", cand))
    # 3. drop the generated topology
    if spec.get("topology"):
        cand = copy.deepcopy(spec)
        cand["topology"] = None
        out.append(("drop topology", cand))
    # 4. halve numeric params (floored); small values also step by one
    #    so the minimum can land exactly on a failure boundary halving
    #    jumps over (40 -> 20 -> 10 -> 5 can never reach 4)
    for key in _HALVE_KEYS:
        val = spec["params"].get(key)
        floor = _PARAM_FLOORS[key]
        if isinstance(val, int) and val > floor:
            cand = copy.deepcopy(spec)
            cand["params"][key] = max(floor, val // 2)
            out.append((f"halve {key} to {cand['params'][key]}", cand))
            if val <= 8 and val - 1 != cand["params"][key]:
                dec = copy.deepcopy(spec)
                dec["params"][key] = val - 1
                out.append((f"reduce {key} to {val - 1}", dec))
    # 5. halve the stoptime (floor 6: starts at ~2s + staggers must fit)
    if spec["stoptime"] > 6:
        cand = copy.deepcopy(spec)
        cand["stoptime"] = max(6, spec["stoptime"] // 2)
        out.append((f"halve stoptime to {cand['stoptime']}", cand))
    return out


def _still_fails(spec: Dict, oracle: str, runner) -> Optional[Dict]:
    """Re-run the candidate; return the matching violation (same oracle)
    or None."""
    for v in check(spec, runner.run(spec)):
        if v["oracle"] == oracle:
            return v
    return None


def shrink(spec: Dict, violation: Dict, runner, budget: int = 40,
           log: Optional[Callable[[str], None]] = None,
           deadline: Optional[float] = None) -> Tuple[Dict, Dict, int]:
    """Minimize ``spec`` while ``violation['oracle']`` keeps firing.

    Returns ``(minimal_spec, final_violation, runs_used)``.  The runner
    must be the same kind the violation was found with (results, and so
    the violation, can depend on the execution surface).  ``deadline``
    (a ``time.monotonic()`` timestamp) stops the loop between candidate
    runs — a wall-capped caller (fuzz-smoke, the bench leg) gets its
    best-so-far repro instead of losing the violation to an outer
    kill."""
    import time as _walltime
    oracle = violation["oracle"]
    current = copy.deepcopy(spec)
    final = violation
    runs = 0
    progress = True
    while progress and runs < budget:
        progress = False
        for desc, cand in _candidates(current):
            if runs >= budget:
                break
            if deadline is not None and _walltime.monotonic() >= deadline:
                if log:
                    log("shrink: wall cap reached; keeping the "
                        "best-so-far repro")
                return current, final, runs
            runs += 1
            got = _still_fails(cand, oracle, runner)
            if got is not None:
                if log:
                    log(f"shrink: kept '{desc}' ({oracle} still fires)")
                current, final = cand, got
                progress = True
                break       # restart candidate scan from the smaller spec
    return current, final, runs
