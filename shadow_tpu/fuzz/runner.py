"""Execute a spec's mode matrix and report per-mode results.

Two execution surfaces over ONE code path:

* :func:`run_modes` — in-process: build the spec's ``Configuration``
  fresh per mode, run it, capture digest/events/supervision/metrics and
  the log tail.  Used by the tier-1 gates, corpus replay, and the
  subprocess child.
* :class:`SubprocessRunner` — production fuzzing: each spec runs in a
  BOUNDED child (``python -m shadow_tpu.fuzz --child IN OUT``, the
  bench-multichip pattern: killed + reported on overrun, never a hang),
  with the virtual device mesh forced on CPU so the sharded-mesh mode is
  exercised even where no accelerator pool exists.

``apply_fault`` implements the fuzz-level fault harness (ISSUE 13): a
deliberately drifted oracle INPUT — perturbing the reported digest/
events/supervision/rc of one named mode — that the oracle set must
catch, the shrinker minimize, and ``--repro`` replay.  ``engine:*``
faults pass through to ``Options.fault_inject`` instead (the ISSUE-2
harness), driving REAL supervised recoveries.
"""

from __future__ import annotations

import io
import json
import os
import time as _walltime
import traceback
from typing import Dict, List, Optional

from .gen import build_config

# metrics keys copied into each mode result (oracle surfaces)
_SCRAPE_KEYS_PREFIX = ("mesh.",)
_SCRAPE_KEYS = ("plane.circuits", "plane.completed", "plane.forwards",
                "scale.materialized_hosts", "scale.table_rows")


def _mode_options(spec: Dict, mode: Dict):
    from ..core.options import Options
    opts = Options(
        scheduler_policy=mode.get("policy", "global"),
        workers=int(mode.get("workers", 0)),
        processes=int(mode.get("processes", 0)),
        stop_time_sec=int(spec["stoptime"]),
        seed=int(spec.get("engine_seed", 1)),
        host_table=mode.get("host_table", "on"),
        dataplane=mode.get("dataplane", "python"),
        tcp_congestion_control=mode.get("tcpcc", "reno"),
        device_plane=mode.get("device_plane", "device"),
        superwindow_rounds=int(mode.get("superwindow_rounds", 8)),
        device_plane_sync=bool(mode.get("device_plane_sync", False)),
        exchange_mode=mode.get("exchange_mode", "auto"),
        device_autotune=mode.get("device_autotune", "on"),
        tpu_devices=int(mode.get("tpu_devices", 1)),
        heartbeat_interval_sec=0,
        log_level="warning")
    fault = spec.get("fault_inject") or {}
    if fault.get("kind") == "engine":
        opts.fault_inject = fault["spec"]
    # per-MODE recovery drills (ISSUE 17): the mode itself carries an
    # engine fault (device-lost, demote-repromote, shard-exit-resurrect)
    # plus the healing knobs — the run must self-heal back to rc 0 and
    # the base digest, which the ordinary parity oracle then pins.
    if mode.get("engine_fault"):
        opts.fault_inject = mode["engine_fault"]
    if mode.get("max_resurrections") is not None:
        opts.max_resurrections = int(mode["max_resurrections"])
    if mode.get("repromote_after"):
        opts.repromote_after = int(mode["repromote_after"])
    return opts


def _mesh_skip_reason(mode: Dict) -> Optional[str]:
    if int(mode.get("tpu_devices", 1)) <= 1:
        return None
    import jax
    n = len(jax.devices())
    if n < 2:
        return f"mesh mode needs >= 2 devices, {n} visible"
    return None


def _run_resume_mode(spec: Dict, opts, out: Dict) -> None:
    """The checkpoint+``--resume`` leg (ISSUE 17): a writer pass
    snapshots every few rounds into a scratch dir, then a FRESH
    controller resumes from the newest good snapshot.  Resume is
    replay-based and digest-verified at the snapshot boundary, so the
    resumed run's digest/events face the ordinary parity oracles — no
    special-casing.  If the run is too short to land a snapshot the
    second pass simply replays plain (still a valid parity sample)."""
    import glob
    import tempfile

    from ..core.checkpoint import state_digest
    from ..core.controller import Controller

    with tempfile.TemporaryDirectory(prefix="simfuzz-ck-") as ckdir:
        opts.checkpoint_every_rounds = 4
        opts.checkpoint_dir = ckdir
        writer = Controller(opts, build_config(spec))
        rc = writer.run()
        if rc != 0:
            out["rc"] = rc
            return
        opts.checkpoint_every_rounds = 0
        if glob.glob(os.path.join(ckdir, "checkpoint_r*.ckpt")):
            opts.resume_path = ckdir
        ctrl = Controller(opts, build_config(spec))
        out["rc"] = ctrl.run()
        eng = ctrl.engine
        out["digest"] = state_digest(eng)
        out["events"] = eng.events_executed
        out["rounds"] = eng.rounds_executed
        out["supervision"] = eng.supervision.summary()


def run_one_mode(spec: Dict, mode: Dict, lane=None) -> Dict:
    """Run the spec under one mode.  Never raises: harness errors land in
    the result as rc=-1 + traceback (the rc/log oracle fails them).

    With ``lane`` (a :class:`shadow_tpu.fleet.FleetLane`, ISSUE 18) the
    mode runs as a fleet batch lane: the engine's device dispatches ride
    the shared vmapped plane and the log capture moves to a THREAD-local
    logger so concurrent lanes keep separate tails.  Everything else —
    digest, events, supervision, scrape — is the identical code path,
    which is what makes batched verdicts digest-identical to the
    subprocess path."""
    from ..core.checkpoint import state_digest
    from ..core.controller import Controller
    from ..core.logger import SimLogger, set_logger, set_thread_logger

    out: Dict = {"mode": mode["name"],
                 "repeat_of": mode.get("repeat_of"),
                 "events_comparable": bool(
                     mode.get("events_comparable", True)),
                 "digest_group": mode.get("digest_group", "base"),
                 "engine_fault": mode.get("engine_fault"),
                 "skipped": None, "rc": None, "digest": None,
                 "events": None, "rounds": None, "supervision": None,
                 "scrape": {}, "log_tail": "", "wall_sec": None}
    reason = _mesh_skip_reason(mode)
    if reason:
        out["skipped"] = reason
        return out
    buf = io.StringIO()
    if lane is not None:
        set_thread_logger(SimLogger(stream=buf, level="warning"))
    else:
        set_logger(SimLogger(stream=buf, level="warning"))
    t0 = _walltime.perf_counter()
    try:
        cfg = build_config(spec)
        opts = _mode_options(spec, mode)
        if lane is not None:
            opts._fleet_lane = lane
        if mode.get("resume"):
            _run_resume_mode(spec, opts, out)
        elif opts.processes >= 2:
            from ..parallel.procs import ProcsController
            pc = ProcsController(opts, cfg)
            out["rc"] = pc.run()
            out["digest"] = pc.digest
            out["events"] = pc.events_executed
            out["supervision"] = pc.supervision.summary()
        else:
            ctrl = Controller(opts, cfg)
            out["rc"] = ctrl.run()
            eng = ctrl.engine
            out["digest"] = state_digest(eng)
            out["events"] = eng.events_executed
            out["rounds"] = eng.rounds_executed
            out["supervision"] = eng.supervision.summary()
            scrape = eng.metrics.scrape()
            out["scrape"] = {
                k: v for k, v in sorted(scrape.items())
                if k in _SCRAPE_KEYS
                or k.startswith(_SCRAPE_KEYS_PREFIX)}
    except Exception:
        out["rc"] = -1
        buf.write("\n" + traceback.format_exc())
    finally:
        if lane is not None:
            set_thread_logger(None)
    out["wall_sec"] = round(_walltime.perf_counter() - t0, 3)
    out["log_tail"] = buf.getvalue()[-2000:]
    return out


def apply_fault(spec: Dict, result: Dict) -> Dict:
    """The fuzz-level fault harness: deterministically drift ONE named
    mode's reported oracle inputs so the pipeline (catch -> shrink ->
    repro) is drilled end to end.  ``engine:*`` faults are applied at
    options build instead; everything else matches on the mode name."""
    fault = spec.get("fault_inject") or {}
    kind = fault.get("kind")
    if not kind or kind == "engine":
        return result
    if fault.get("mode") not in (result["mode"], "*"):
        return result
    if result["skipped"]:
        return result
    if kind == "digest-drift" and result["digest"]:
        result["digest"] = "drift-" + result["digest"][:56]
    elif kind == "events-drift" and result["events"] is not None:
        result["events"] += 1
    elif kind == "supervision-drift" and result["supervision"] is not None:
        result["supervision"] = dict(result["supervision"])
        result["supervision"]["recoveries"] += 1
        result["supervision"]["dispatch_recoveries"] += 1
    elif kind == "rc-drift":
        result["rc"] = 7
    return result


def parse_fault(spec_str: str) -> Dict:
    """``digest-drift:MODE | events-drift:MODE | supervision-drift:MODE |
    rc-drift:MODE | engine:ENGINE-FAULT`` (MODE is a mode name or ``*``;
    ENGINE-FAULT is a core/supervision.py --fault-inject token)."""
    kind, _, rest = spec_str.partition(":")
    if kind == "engine":
        if not rest:
            raise ValueError("fault engine: needs an engine fault token")
        from ..core.supervision import parse_fault_inject
        parse_fault_inject(rest)      # validate eagerly
        return {"kind": "engine", "spec": rest}
    if kind in ("digest-drift", "events-drift", "supervision-drift",
                "rc-drift"):
        return {"kind": kind, "mode": rest or "*"}
    raise ValueError(f"unknown fuzz fault kind {kind!r}")


def run_modes(spec: Dict, modes: Optional[List[Dict]] = None) -> List[Dict]:
    """Run every mode of the spec in this process, fault drift applied."""
    results = []
    for mode in (modes if modes is not None else spec["modes"]):
        results.append(apply_fault(spec, run_one_mode(spec, mode)))
    return results


def mode_batchable(spec: Dict, mode: Dict) -> bool:
    """Modes the fleet plane can carry as a batch lane (ISSUE 18):
    single-process, single-threaded, single-device python-dataplane runs
    with no engine fault — the shapes whose device dispatches are plain
    span/flush kernel calls the vmapped program reproduces bit-exactly.
    Everything else (mesh, procs, threaded, native, engine-fault drills)
    runs in phase 2, sequentially in the same process, still sharing the
    warm jit cache.  ``resume`` modes ARE batchable: both controller
    passes ride the same lane back to back."""
    fault = spec.get("fault_inject") or {}
    if fault.get("kind") == "engine" or mode.get("engine_fault"):
        return False
    return (int(mode.get("workers", 0)) == 0
            and int(mode.get("processes", 0)) == 0
            and int(mode.get("tpu_devices", 1)) == 1
            and mode.get("device_plane", "device") == "device"
            and mode.get("dataplane", "python") == "python"
            and mode.get("policy", "global") == "global")


# ---------------------------------------------------------------------------
# bounded subprocess execution
# ---------------------------------------------------------------------------

def child_env(n_dev: int = 8) -> Dict[str, str]:
    """Child env: CPU-pinned with the virtual device mesh (the same mesh
    the test suite and bench-multichip use), so mesh modes run anywhere;
    a pre-pinned accelerator environment is left alone."""
    import tempfile

    env = os.environ.copy()
    if env.get("JAX_PLATFORMS", "").strip() in ("", "cpu"):
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_dev}"
            ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # ONE persistent XLA compile cache shared by every child (ISSUE 18):
    # without it each child re-compiles the identical span/flush kernels
    # from scratch, which dominated the 25-seeds/374s subprocess wall.
    # Thresholds at 0 so even the fast CPU compiles are cached; a caller
    # that already pinned a cache dir keeps it.
    if "JAX_COMPILATION_CACHE_DIR" not in env:
        cache = os.path.join(tempfile.gettempdir(), "shadow-tpu-xla-cache")
        try:
            os.makedirs(cache, exist_ok=True)
            env["JAX_COMPILATION_CACHE_DIR"] = cache
            env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           "0")
            env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES",
                           "0")
        except OSError:
            pass    # an unwritable tmpdir just means no cache sharing
    return env


def child_main(in_path: str, out_path: str) -> int:
    """``python -m shadow_tpu.fuzz --child IN OUT``: run the spec file's
    modes, write the result list as JSON.  rc 0 even on violations — the
    PARENT judges; a nonzero rc means the harness itself broke."""
    with open(in_path, "r") as f:
        spec = json.load(f)
    results = run_modes(spec)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"spec_seed": spec.get("seed"), "results": results}, f)
    os.replace(tmp, out_path)
    return 0


class SubprocessRunner:
    """Run each spec's whole mode matrix in ONE bounded child process
    (modes share the child's XLA compile cache; a wedged scenario is
    killed at ``timeout_sec`` and reported as a timeout result, never a
    hang — the bench-multichip subprocess pattern)."""

    def __init__(self, timeout_sec: float = 240.0, n_dev: int = 8):
        self.timeout_sec = float(timeout_sec)
        self.n_dev = n_dev

    def run(self, spec: Dict) -> List[Dict]:
        import subprocess
        import sys
        import tempfile

        with tempfile.TemporaryDirectory(prefix="simfuzz-") as td:
            in_path = os.path.join(td, "spec.json")
            out_path = os.path.join(td, "results.json")
            with open(in_path, "w") as f:
                json.dump(spec, f)
            cmd = [sys.executable, "-m", "shadow_tpu.fuzz", "--child",
                   in_path, out_path]
            try:
                proc = subprocess.run(
                    cmd, env=child_env(self.n_dev),
                    timeout=self.timeout_sec, capture_output=True,
                    text=True, cwd=os.path.dirname(
                        os.path.dirname(os.path.dirname(
                            os.path.abspath(__file__)))))
            except subprocess.TimeoutExpired:
                return [{"mode": "<child>", "repeat_of": None,
                         "events_comparable": False, "skipped": None,
                         "rc": None, "timeout": True, "digest": None,
                         "events": None, "rounds": None,
                         "supervision": None, "scrape": {},
                         "log_tail": f"child exceeded the "
                                     f"{self.timeout_sec:.0f}s bound and "
                                     "was killed",
                         "wall_sec": self.timeout_sec}]
            if proc.returncode != 0 or not os.path.exists(out_path):
                return [{"mode": "<child>", "repeat_of": None,
                         "events_comparable": False, "skipped": None,
                         "rc": proc.returncode, "digest": None,
                         "events": None, "rounds": None,
                         "supervision": None, "scrape": {},
                         "log_tail": (proc.stdout + proc.stderr)[-2000:],
                         "wall_sec": None}]
            with open(out_path, "r") as f:
                return json.load(f)["results"]


class InProcessRunner:
    """Same contract as SubprocessRunner, no child (tests/corpus)."""

    def run(self, spec: Dict) -> List[Dict]:
        return run_modes(spec)


class BatchedRunner:
    """``simfuzz --batched`` (ISSUE 18): the whole seed list's mode
    matrices in ONE process over the fleet plane.

    Two phases.  Phase 1 fans every spec's *batchable* modes (see
    :func:`mode_batchable`) out as fleet lanes — N concurrent engines
    whose device dispatches merge into vmapped launches, sharing one
    compiled program per shape class.  Phase 2 runs the remaining modes
    (mesh/procs/threaded/native/fault drills) sequentially, still inside
    the warm process so nothing recompiles.  Per-spec result lists come
    back in mode order with fault drift applied — byte-for-byte the
    shape SubprocessRunner returns, so the oracle set and the shrinker
    are reused unchanged."""

    def __init__(self, lanes: int = 8, use_numpy: bool = False):
        from ..fleet.driver import FleetDriver
        self.driver = FleetDriver(lanes=lanes, use_numpy=use_numpy)
        self.batched_modes = 0
        self.serial_modes = 0

    def plane_stats(self) -> Dict:
        return self.driver.plane.metrics()

    def run_specs(self, specs: List[Dict]) -> List[List[Dict]]:
        jobs = []
        slots = []
        table: List[List[Optional[Dict]]] = [
            [None] * len(spec["modes"]) for spec in specs]
        for si, spec in enumerate(specs):
            for mi, mode in enumerate(spec["modes"]):
                if mode_batchable(spec, mode):
                    jobs.append(lambda lane, s=spec, m=mode:
                                run_one_mode(s, m, lane=lane))
                    slots.append((si, mi))
        for (si, mi), result in zip(slots, self.driver.run(jobs)):
            table[si][mi] = result
        self.batched_modes += len(jobs)
        for si, spec in enumerate(specs):
            for mi, mode in enumerate(spec["modes"]):
                if table[si][mi] is None:
                    table[si][mi] = run_one_mode(spec, mode)
                    self.serial_modes += 1
        return [[apply_fault(spec, r) for r in rows]
                for spec, rows in zip(specs, table)]

    def run(self, spec: Dict) -> List[Dict]:
        """Single-spec entry (shrink candidates, --repro, --corpus):
        the same two-phase path at fleet width 1."""
        return self.run_specs([spec])[0]
