"""The pluggable oracle set: every invariant a fuzzed scenario must hold.

Each oracle is a function ``(spec, results) -> [violation...]`` over the
per-mode result dicts runner.py produces; a violation is a dict
``{"oracle", "detail", "modes"}``.  The set mirrors the invariants every
PR already swears by in tests — digest determinism and cross-mode parity,
event-count conservation, supervision cleanliness, mesh exactness, rc/log
hygiene — applied to scenarios nobody hand-wrote.

``check(spec, results)`` runs the spec's oracle subset (default: all) and
returns the merged violation list, most fundamental first.
"""

from __future__ import annotations

from typing import Callable, Dict, List

Violation = Dict
_ORACLES: Dict[str, Callable] = {}


def oracle(name: str):
    def deco(fn):
        _ORACLES[name] = fn
        return fn
    return deco


def _v(name: str, detail: str, modes: List[str]) -> Violation:
    return {"oracle": name, "detail": detail, "modes": modes}


def _live(results: List[Dict]) -> List[Dict]:
    """Modes that actually ran to completion (skipped/errored modes are
    the rc oracle's business, not parity's)."""
    return [r for r in results
            if not r.get("skipped") and r.get("rc") == 0]


def _by_group(live: List[Dict]) -> Dict[str, List[Dict]]:
    """Partition results by digest group.  A mode that legitimately takes
    a different trajectory (the bbrx CC legs, ISSUE 19) carries its own
    ``digest_group``; parity/conservation hold WITHIN each group, never
    across them.  Absent key = the historical "base" group, so old corpus
    records replay unchanged."""
    groups: Dict[str, List[Dict]] = {}
    for r in live:
        groups.setdefault(r.get("digest_group") or "base", []).append(r)
    return groups


@oracle("rc_log")
def oracle_rc_log(spec: Dict, results: List[Dict]) -> List[Violation]:
    """Every non-skipped mode exits rc 0 inside its wall bound, with no
    tracebacks or critical lines in the log."""
    out = []
    for r in results:
        if r.get("skipped"):
            continue
        if r.get("timeout"):
            out.append(_v("rc_log", r.get("log_tail", "timeout"),
                          [r["mode"]]))
            continue
        if r.get("rc") != 0:
            out.append(_v("rc_log", f"rc={r.get('rc')}: "
                          f"{r.get('log_tail', '')[-300:]}", [r["mode"]]))
            continue
        tail = r.get("log_tail") or ""
        for marker in ("Traceback (most recent call last)", "[critical]"):
            if marker in tail:
                out.append(_v("rc_log", f"{marker!r} in log: "
                              f"{tail[-300:]}", [r["mode"]]))
                break
    return out


@oracle("stability")
def oracle_stability(spec: Dict, results: List[Dict]) -> List[Violation]:
    """Repeat runs of the same mode are bit-identical: same digest, same
    event count (seeded determinism is the whole contract)."""
    by_name = {r["mode"]: r for r in _live(results)}
    out = []
    for r in _live(results):
        base = by_name.get(r.get("repeat_of") or "")
        if base is None:
            continue
        if r["digest"] != base["digest"]:
            out.append(_v("stability",
                          f"repeat digest {r['digest']!r} != "
                          f"{base['digest']!r}", [base["mode"], r["mode"]]))
        if r["events"] != base["events"]:
            out.append(_v("stability",
                          f"repeat events {r['events']} != "
                          f"{base['events']}", [base["mode"], r["mode"]]))
    return out


@oracle("parity")
def oracle_parity(spec: Dict, results: List[Dict]) -> List[Violation]:
    """Cross-mode digest parity: every mode of the matrix — device/numpy
    twins, K=1/K=8, table on/off, threaded, procs, mesh — ends in the
    same state digest, within its digest group (a group per legitimate
    trajectory: base, bbrx)."""
    out = []
    for _, live in sorted(_by_group(
            [r for r in _live(results) if r.get("digest")]).items()):
        if len(live) < 2:
            continue
        ref = live[0]
        for r in live[1:]:
            if r["digest"] != ref["digest"]:
                out.append(_v("parity",
                              f"{r['mode']} digest {r['digest']!r} != "
                              f"{ref['mode']} {ref['digest']!r}",
                              [ref["mode"], r["mode"]]))
    return out


@oracle("events")
def oracle_events(spec: Dict, results: List[Dict]) -> List[Violation]:
    """Event-count conservation across the serial single-process modes
    (device/numpy, K=1/K=8, table on/off execute the identical event
    stream; threaded/procs modes are digest-checked only).  Conservation
    holds within each digest group — a different CC trajectory schedules
    a different event stream."""
    out = []
    for _, live in sorted(_by_group(
            [r for r in _live(results)
             if r.get("events_comparable")
             and r.get("events") is not None]).items()):
        if len(live) < 2:
            continue
        ref = live[0]
        for r in live[1:]:
            if r["events"] != ref["events"]:
                out.append(_v("events",
                              f"{r['mode']} executed {r['events']} events "
                              f"!= {ref['mode']}'s {ref['events']}",
                              [ref["mode"], r["mode"]]))
    return out


@oracle("supervision")
def oracle_supervision(spec: Dict, results: List[Dict]) -> List[Violation]:
    """engine.supervision stays clean: zero watchdog fires, demotions, or
    recoveries in a healthy run (an ``engine:*`` fault spec — or a mode
    carrying its own ``engine_fault`` recovery drill, ISSUE 17 — flips
    the expectation: the drilled detour is judged by the parity oracle
    landing the base digest, not by a zero-recoveries ledger)."""
    fault = (spec.get("fault_inject") or {})
    expect_recoveries = fault.get("kind") == "engine"
    out = []
    for r in _live(results):
        sup = r.get("supervision")
        if sup is None:
            continue
        n = sup.get("recoveries", 0)
        if expect_recoveries or r.get("engine_fault"):
            continue            # drills are judged by their own tests
        if n:
            out.append(_v("supervision",
                          f"{r['mode']}: {n} recoveries in a healthy run: "
                          f"{sup}", [r["mode"]]))
    return out


@oracle("mesh")
def oracle_mesh(spec: Dict, results: List[Dict]) -> List[Violation]:
    """Sharded-mesh invariants: cross-shard forwards never transit the
    host, the plane never silently demotes, occupancy stays sane.  Modes
    drilling their own engine fault (ISSUE 17) are exempt — a drilled
    device loss legitimately reshapes the mesh mid-run, and the parity
    oracle already pins its end digest against the fault-free base."""
    out = []
    for r in _live(results):
        if r.get("engine_fault"):
            continue
        sc = r.get("scrape") or {}
        if "mesh.host_bounces" not in sc:
            continue
        if sc["mesh.host_bounces"] != 0:
            out.append(_v("mesh", f"{r['mode']}: host_bounces="
                          f"{sc['mesh.host_bounces']}", [r["mode"]]))
        if sc.get("mesh.demoted"):
            out.append(_v("mesh", f"{r['mode']}: sharded plane demoted",
                          [r["mode"]]))
        occ_min = sc.get("mesh.occupancy_min", 0)
        occ_mean = sc.get("mesh.occupancy_mean", 0)
        if not (0 < occ_min <= occ_mean <= 1.0001):
            out.append(_v("mesh",
                          f"{r['mode']}: occupancy insane (min={occ_min}, "
                          f"mean={occ_mean})", [r["mode"]]))
    return out


@oracle("completion")
def oracle_completion(spec: Dict, results: List[Dict]) -> List[Violation]:
    """Flow-completion conservation: every mode sees the same circuit
    count and completes the same number of them (completion inside the
    stoptime is scenario-dependent; its CONSISTENCY is not).  Judged
    within each digest group, like parity."""
    out = []
    for _, live in sorted(_by_group(
            [r for r in _live(results)
             if "plane.circuits" in (r.get("scrape") or {})]).items()):
        if len(live) < 2:
            continue
        ref = live[0]
        for r in live[1:]:
            for key in ("plane.circuits", "plane.completed"):
                if r["scrape"].get(key) != ref["scrape"].get(key):
                    out.append(_v("completion",
                                  f"{r['mode']} {key}="
                                  f"{r['scrape'].get(key)} != "
                                  f"{ref['mode']}'s "
                                  f"{ref['scrape'].get(key)}",
                                  [ref["mode"], r["mode"]]))
    return out


ORACLE_ORDER = ("rc_log", "stability", "parity", "events", "supervision",
                "mesh", "completion")


def check(spec: Dict, results: List[Dict]) -> List[Violation]:
    names = spec.get("oracles") or ORACLE_ORDER
    out: List[Violation] = []
    for name in ORACLE_ORDER:
        if name in names:
            out.extend(_ORACLES[name](spec, results))
    return out
