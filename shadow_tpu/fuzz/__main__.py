import sys

from .cli import main

sys.exit(main(sys.argv[1:]))
