"""simfleet CLI (ISSUE 18): drive the vmapped many-scenarios-per-chip
fleet plane.

Usage::

    simfleet smoke [--lanes 8] [--seeds 8] [--seed-base 0] [--numpy]
                   [--out PATH]

``smoke`` is the CI gate (``make fleet-smoke``): draw a bounded mixed
scenario set from the fuzz generator, run each scenario's base mode
twice — serially (the reference) and as fleet lanes over ONE shared
vmapped plane — and require bit-identical digests plus a real batched
launch count.  Prints ONE summary JSON line last, like bench.py; exit
0 = digest-gated pass, 1 = mismatch or no launches, 2 = usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time as _walltime
from typing import List, Optional


def _say(msg: str) -> None:
    print(f"simfleet: {msg}", file=sys.stderr, flush=True)


def setup_fleet_env(n_dev: int = 8) -> None:
    """In-process twin of ``fuzz.runner.child_env``: CPU-pin and force
    the virtual device mesh BEFORE jax initializes, so phase-2 mesh
    modes run anywhere.  A process that already imported jax (or pinned
    an accelerator platform) is left alone."""
    if "jax" in sys.modules:
        return
    if os.environ.get("JAX_PLATFORMS", "").strip() in ("", "cpu"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_dev}"
            ).strip()


def cmd_smoke(args) -> int:
    setup_fleet_env()
    from ..fuzz.gen import draw_spec
    from ..fuzz.runner import mode_batchable, run_one_mode
    from .driver import FleetDriver

    t0 = _walltime.monotonic()
    picks = []
    for i in range(args.seeds):
        seed = args.seed_base + i
        spec = draw_spec(seed)
        mode = next((m for m in spec["modes"]
                     if mode_batchable(spec, m) and not m.get("resume")),
                    None)
        if mode is None:
            _say(f"seed {seed} [{spec['family']}]: no batchable mode, "
                 "skipped")
            continue
        picks.append((seed, spec, mode))
    if not picks:
        _say("no batchable scenarios drawn; widen --seeds")
        return 2
    _say(f"{len(picks)} scenarios "
         f"({', '.join(sorted({s['family'] for _, s, _ in picks}))}): "
         "serial reference pass")
    serial = [run_one_mode(spec, mode) for _, spec, mode in picks]
    t1 = _walltime.monotonic()
    _say(f"fleet pass: {args.lanes} lanes"
         + (" (numpy twin)" if args.numpy else ""))
    driver = FleetDriver(lanes=args.lanes, use_numpy=args.numpy)
    jobs = [lambda lane, s=spec, m=mode: run_one_mode(s, m, lane=lane)
            for _, spec, mode in picks]
    fleet = driver.run(jobs)
    t2 = _walltime.monotonic()
    rows = []
    matched = True
    for (seed, spec, mode), ref, got in zip(picks, serial, fleet):
        ok = (ref["digest"] == got["digest"] and ref["rc"] == got["rc"]
              and ref["events"] == got["events"])
        matched = matched and ok
        rows.append({"seed": seed, "family": spec["family"],
                     "mode": mode["name"], "rc": got["rc"],
                     "digest_match": ok})
        if not ok:
            _say(f"seed {seed} [{spec['family']}] DIGEST MISMATCH: "
                 f"serial rc={ref['rc']} digest={ref['digest']} vs "
                 f"fleet rc={got['rc']} digest={got['digest']}")
    stats = driver.plane.metrics()
    launched = stats["fleet.launches"] > 0
    if not launched:
        _say("no batched launches fired — the fleet plane was never "
             "exercised (gate fails closed)")
    # the runtime half of the SIM305 compile-budget contract: measured
    # cache counts vs the checked-in [tool.simjit.budget] table, failing
    # on either direction of drift (growth past the budget, or a
    # budgeted metric the run no longer reports)
    from ..analysis.simjit import crosscheck_budget, load_runtime_budget
    from ..parallel.device_plane import DeviceTrafficPlane
    budget = load_runtime_budget(os.getcwd())
    measured = {
        "fleet.compiles": int(stats.get("fleet.compiles", 0)),
        "device_plane.sharded_variants":
            int(DeviceTrafficPlane.sharded_variants_high_water),
    }
    if args.numpy:
        # the numpy twin compiles nothing by design — the budget
        # contract is about the jit path
        budget_problems: List[str] = []
    elif not budget:
        _say("no [tool.simjit.budget] runtime entries found; "
             "compile-budget cross-check skipped")
        budget_problems = []
    else:
        budget_problems = crosscheck_budget(
            measured, budget, require_nonzero=("fleet.compiles",))
        for p in budget_problems:
            _say(f"compile-budget drift: {p}")
    ok = matched and launched and not budget_problems
    summary = {"simfleet": {
        "lanes": args.lanes,
        "scenarios": len(picks),
        "families": sorted({s["family"] for _, s, _ in picks}),
        "digest_match": matched,
        "serial_wall_sec": round(t1 - t0, 2),
        "fleet_wall_sec": round(t2 - t1, 2),
        "numpy": bool(args.numpy),
        "rows": rows,
        "budget_measured": measured,
        "budget_problems": budget_problems,
        **stats},
        "pass": ok}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="simfleet",
        description="many simulations per chip: N scenarios advanced by "
                    "one vmapped device program (ROADMAP 3)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sm = sub.add_parser(
        "smoke", help="bounded mixed fleet, digest-gated against serial")
    sm.add_argument("--lanes", type=int, default=8,
                    help="concurrent fleet lanes")
    sm.add_argument("--seeds", type=int, default=8,
                    help="scenarios to draw (fuzz generator seeds)")
    sm.add_argument("--seed-base", type=int, default=0, dest="seed_base")
    sm.add_argument("--numpy", action="store_true",
                    help="drive the batched numpy twin instead of the "
                         "vmapped jit program (kernel-parity debugging)")
    sm.add_argument("--out", default=None,
                    help="also write the summary JSON here")
    sm.set_defaults(fn=cmd_smoke)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
