"""FleetPlane: the shared vmapped traffic plane (ISSUE 18, ROADMAP 3).

Every device-mode run today owns the whole chip and pays ~320 us of
launch overhead per dispatch; this module batches N independent
scenarios into ONE stacked program so one launch advances all of them.
The split that unlocks it: per-scenario plane *state* stays with each
lane (real-shaped, carried between dispatches by the lane's own
DeviceTrafficPlane), while the *compiled program* is shared per shape
class — scenarios whose padded shapes coincide ride the same jit entry.

Shape classes generalize the ``pad_state`` contract from
``mesh/partition.py`` to a leading batch axis: flows/nodes/chains and
the targets vector are padded up to power-of-two buckets with INERT
rows (padding flows are their own zero-cell segment with no successor
and target 0 — identically zero forever, so pad -> step -> unpad is
bit-exact), while ``ring_len`` stays EXACT per class (the arrival
ring's mod-slot layout is position-dependent; length-padding it would
re-address history carried between dispatches).  When chain padding is
needed the flow axis is padded by at least one row so the padded
``last_flow`` entries can point at a guaranteed-zero flow (keeping the
flush header's ``delivered_sum`` exact).

Batch width per class is STICKY (starts at the first launch's
power-of-two, only grows); under-full launches are topped up with
cached inert filler lanes whose targets equal their base step — the
vmapped while_loop freezes them before the first iteration.  Sticky
width + fillers is what makes lane re-arm compile-free: the jit cache
key (shapes, width, ring_len) never changes for a living class, and
``FleetPlane.compiles`` counts exactly the (class, width) pairs XLA
ever saw — the re-arm drill asserts on it.

Lanes at different rounds coexist in one program: each lane submits its
OWN superwindow targets vector and gets back its OWN ``t_stop``
(per-lane halt flag in the batched while cond), which the lane's
engine maps back through its own ``_SuperPlan`` exactly as in the
serial path.  All kernel math is int64 integer arithmetic, so each
batched lane is bit-identical to the unbatched kernel — the property
the fleet digest gate (``simfleet smoke``, ``simfuzz --batched``)
rides on.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np


def _pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << int(n - 1).bit_length()


def _pad_vec(a: np.ndarray, n: int, fill: int = 0) -> np.ndarray:
    a = np.asarray(a)
    out = np.full(n, fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


def _repack_flush(buf: np.ndarray, pad_c: int, pad_h: int, c: int,
                  h: int) -> np.ndarray:
    """Re-section a padded-class flush buffer [5+2*pad_c+2*pad_h] to the
    lane's real [5+2c+2h] layout.  Padding chains never complete and
    padding nodes never carry deltas, so every recorded index is < c/h
    and the true header counts are <= c/h — a straight section copy."""
    from ..ops.torcells_device import FLUSH_HEADER, flush_len
    buf = np.asarray(buf)
    if pad_c == c and pad_h == h:
        return buf.copy()
    out = np.zeros(flush_len(c, h), np.int64)
    out[:FLUSH_HEADER] = buf[:FLUSH_HEADER]
    n_done = int(buf[2])
    n_touch = int(buf[3])
    base = FLUSH_HEADER
    out[base:base + n_done] = buf[base:base + n_done]
    out[base + c:base + c + n_done] = buf[base + pad_c:base + pad_c + n_done]
    out[base + 2 * c:base + 2 * c + n_touch] = \
        buf[base + 2 * pad_c:base + 2 * pad_c + n_touch]
    out[base + 2 * c + h:base + 2 * c + h + n_touch] = \
        buf[base + 2 * pad_c + pad_h:base + 2 * pad_c + pad_h + n_touch]
    return out


class _ShapeClass:
    """One padded shape bucket: (flows, nodes, chains, targets) padded to
    powers of two, ring_len exact.  Owns the sticky batch width and the
    cached inert filler row every under-full launch is topped up with."""

    __slots__ = ("key", "f2", "h2", "c2", "p2", "ring_len", "width",
                 "_filler")

    def __init__(self, f2: int, h2: int, c2: int, p2: int, ring_len: int):
        self.key = (f2, h2, c2, p2, ring_len)
        self.f2 = f2
        self.h2 = h2
        self.c2 = c2
        self.p2 = p2
        self.ring_len = ring_len
        self.width = 0          # sticky: set at first launch, only grows
        self._filler = None

    def filler_row(self) -> tuple:
        """The inert lane: zero state, targets all equal to the base step
        (the batched while cond is false for it before the first
        iteration), tables shaped like a member with no traffic."""
        if self._filler is None:
            from ..ops.torcells_device import RING_DTYPE
            f2, h2, c2, p2 = self.f2, self.h2, self.c2, self.p2
            i64 = np.int64
            self._filler = (
                i64(0),                                   # t0
                np.zeros(f2, i64),                        # queued
                np.zeros((self.ring_len, f2), RING_DTYPE),  # ring
                np.zeros(h2, i64),                        # tokens
                np.zeros(f2, i64),                        # delivered
                np.zeros(f2, i64),                        # target
                np.full(f2, -1, i64),                     # done_tick
                np.zeros(h2, i64),                        # node_sent
                np.zeros(f2, i64),                        # inject
                np.zeros(f2, i64),                        # inject_target
                np.zeros(p2, i64),                        # targets (== t0)
                i64(0),                                   # idle_ticks
                np.full(f2, h2 - 1, i64),                 # flow_node
                np.zeros(f2, i64),                        # flow_lat
                np.full(f2, -1, i64),                     # flow_succ
                np.arange(f2, dtype=i64),                 # seg_start
                np.zeros(h2, i64),                        # refill
                np.zeros(h2, i64),                        # capacity
                np.full(c2, f2 - 1, i64),                 # last_flow
            )
        return self._filler


class _Submit:
    """One lane's staged dispatch: the 19 padded kernel operands, filled
    in with its batch row (or an error) by the launching thread."""

    __slots__ = ("lane", "args", "result", "error")

    def __init__(self, lane: "FleetLane", args: tuple):
        self.lane = lane
        self.args = args
        self.result: Optional[tuple] = None
        self.error: Optional[BaseException] = None


class FleetLane:
    """Per-scenario handle: attaches to the scenario's DeviceTrafficPlane
    (via ``options._fleet_lane``), pads its real-shaped dispatches into
    the shape class, and blocks until the shared batched launch returns
    its row.  ``dispatch`` is synchronous — the already-digest-pinned
    ``--device-plane-sync`` shape — so the owning engine sees exactly
    the serial plane contract."""

    __slots__ = ("plane", "name", "cls", "shape", "_tables", "dispatches")

    def __init__(self, plane: "FleetPlane", name: str):
        self.plane = plane
        self.name = name
        self.cls: Optional[_ShapeClass] = None
        self.shape: Optional[Tuple[int, int, int, int, int]] = None
        self._tables = None
        self.dispatches = 0

    # -- driver-facing lifecycle ------------------------------------------
    def begin(self) -> None:
        self.plane._lane_begin()

    def end(self) -> None:
        self.plane._lane_end()

    # -- device-plane-facing ----------------------------------------------
    def attach_plane(self, dev_plane) -> None:
        """Join (or re-join: a --resume second pass re-attaches with the
        same shapes) the shape class for this plane's flow table and
        cache the padded static tables."""
        f, h, c = dev_plane.n_flows, dev_plane.n_nodes, dev_plane.n_chains
        p, ring_len = dev_plane.superwindow_rounds, dev_plane.ring_len
        self.shape = (f, h, c, p, ring_len)
        self.cls = self.plane._class_for(f, h, c, p, ring_len)
        f2, h2, c2 = self.cls.f2, self.cls.h2, self.cls.c2
        i64 = np.int64
        self._tables = (
            _pad_vec(np.asarray(dev_plane.flow_node, i64), f2, h2 - 1),
            _pad_vec(np.asarray(dev_plane.flow_lat_steps, i64), f2, 0),
            _pad_vec(np.asarray(dev_plane.flow_succ, i64), f2, -1),
            # padding flows are each their own (empty) segment
            np.concatenate([np.asarray(dev_plane.seg_start, i64),
                            np.arange(f, f2, dtype=i64)]),
            _pad_vec(np.asarray(dev_plane.refill_step, i64), h2, 0),
            _pad_vec(np.asarray(dev_plane.capacity_step, i64), h2, 0),
            # padded chains exit through a guaranteed-zero padding flow
            _pad_vec(np.asarray(dev_plane.last_flow, i64), c2, f2 - 1),
        )

    def dispatch(self, state: tuple, inject, inject_target, tvec,
                 idle: int) -> tuple:
        """Pad the real-shaped dispatch into the class, ride the shared
        launch, return the real-shaped synchronous numpy 10-tuple the
        serial kernel call would have produced."""
        assert self.cls is not None, "lane dispatched before attach_plane"
        f, h, c, _p, ring_len = self.shape
        cls = self.cls
        f2, h2 = cls.f2, cls.h2
        i64 = np.int64
        ring = np.asarray(state[2])
        ring_p = np.zeros((ring_len, f2), ring.dtype)
        ring_p[:, :f] = ring
        tvec = np.asarray(tvec, i64)
        args = (
            i64(state[0]),
            _pad_vec(np.asarray(state[1], i64), f2),
            ring_p,
            _pad_vec(np.asarray(state[3], i64), h2),
            _pad_vec(np.asarray(state[4], i64), f2),
            _pad_vec(np.asarray(state[5], i64), f2),
            _pad_vec(np.asarray(state[6], i64), f2, -1),
            _pad_vec(np.asarray(state[7], i64), h2),
            _pad_vec(np.asarray(inject, i64), f2),
            _pad_vec(np.asarray(inject_target, i64), f2),
            # extra target slots repeat the final boundary (never
            # reached: the lane's span ends at its own targets[-1])
            _pad_vec(tvec, cls.p2, int(tvec[-1])),
            i64(idle),
            *self._tables,
        )
        sub = _Submit(self, args)
        self.plane._submit(sub)
        if sub.error is not None:
            raise sub.error
        r = sub.result
        flush = _repack_flush(r[9], cls.c2, cls.h2, c, h)
        self.dispatches += 1
        return (i64(r[0]),
                np.ascontiguousarray(r[1][:f]),
                np.ascontiguousarray(r[2][:, :f]),
                np.ascontiguousarray(r[3][:h]),
                np.ascontiguousarray(r[4][:f]),
                np.ascontiguousarray(r[5][:f]),
                np.ascontiguousarray(r[6][:f]),
                np.ascontiguousarray(r[7][:h]),
                i64(r[8]),
                flush)

    def metrics(self) -> Dict:
        """fleet.* scrape source (registered per engine by the device
        plane's lane hook; namespace documented in obs/metrics.py)."""
        return self.plane.metrics()


class FleetPlane:
    """The shared batching executor: shape classes, the all-live-lanes
    barrier, and the vmapped launches.

    Barrier contract: every live lane (begin()..end()) eventually either
    submits a dispatch or ends.  A submission parks its lane; when every
    live lane has one parked submission, the LAST parker launches the
    whole batch (grouped per shape class, one vmapped call each) with
    the lock released around the device work, distributes per-lane rows,
    and wakes everyone.  A lane ending mid-wait re-checks the barrier,
    so host-heavy lanes delay launches but can never deadlock them."""

    def __init__(self, use_numpy: bool = False):
        self._cv = threading.Condition(threading.Lock())
        self._live = 0
        self._pending: List[_Submit] = []
        self._launching = False
        self._classes: Dict[tuple, _ShapeClass] = {}
        self._compiled: set = set()
        self._use_numpy = bool(use_numpy)
        self._lanes_created = 0
        self.lanes_peak = 0
        self.launches = 0
        self.lane_dispatches = 0
        self.compiles = 0
        self._occupancy_sum = 0.0

    # -- lane construction -------------------------------------------------
    def lane(self, name: Optional[str] = None) -> FleetLane:
        with self._cv:
            self._lanes_created += 1
            label = name or f"lane-{self._lanes_created}"
        return FleetLane(self, label)

    def _class_for(self, f: int, h: int, c: int, p: int,
                   ring_len: int) -> _ShapeClass:
        c2 = _pow2(c)
        h2 = _pow2(h)
        # chain padding needs at least one guaranteed-zero flow row for
        # the padded last_flow entries (delivered_sum stays exact)
        f2 = _pow2(f + 1) if c2 > c else _pow2(f)
        p2 = _pow2(p)
        key = (f2, h2, c2, p2, ring_len)
        with self._cv:
            cls = self._classes.get(key)
            if cls is None:
                cls = self._classes[key] = _ShapeClass(f2, h2, c2, p2,
                                                       ring_len)
            return cls

    # -- barrier -----------------------------------------------------------
    def _lane_begin(self) -> None:
        with self._cv:
            self._live += 1
            self.lanes_peak = max(self.lanes_peak, self._live)

    def _lane_end(self) -> None:
        with self._cv:
            self._live -= 1
            self._maybe_launch_locked()

    def _submit(self, sub: _Submit) -> None:
        with self._cv:
            self._pending.append(sub)
            self.lane_dispatches += 1
            self._maybe_launch_locked()
            while sub.result is None and sub.error is None:
                self._cv.wait()

    def _maybe_launch_locked(self) -> None:
        """Launch when every live lane is parked (lock held on entry and
        exit; RELEASED around the device call — the batch is snapshotted
        first, so late submissions start the next generation)."""
        if self._launching or not self._pending \
                or len(self._pending) < self._live:
            return
        batch, self._pending = self._pending, []
        self._launching = True
        self._cv.release()
        try:
            self._run_batch(batch)
        finally:
            self._cv.acquire()
            self._launching = False
            self._cv.notify_all()
            # submissions that arrived during the launch may already
            # satisfy the next barrier (e.g. the last other lane ended)
            self._maybe_launch_locked()

    # -- launching ---------------------------------------------------------
    def _run_batch(self, batch: List[_Submit]) -> None:
        """One barrier generation: group per shape class, launch each
        group as one vmapped program, scatter rows back (called with the
        barrier lock released)."""
        groups: Dict[tuple, List[_Submit]] = {}
        for sub in batch:
            groups.setdefault(sub.lane.cls.key, []).append(sub)
        for key in sorted(groups):
            subs = groups[key]
            try:
                self._launch_class(self._classes[key], subs)
            except BaseException as e:  # noqa: BLE001 - scatter to lanes
                for sub in subs:
                    if sub.result is None:
                        sub.error = e

    def _launch_class(self, cls: _ShapeClass, subs: List[_Submit]) -> None:
        width = max(cls.width, _pow2(len(subs)))
        rows = [s.args for s in subs]
        filler = cls.filler_row()
        rows.extend([filler] * (width - len(rows)))
        stacked = tuple(
            np.asarray([r[i] for r in rows])
            if np.ndim(rows[0][i]) == 0
            else np.stack([r[i] for r in rows])
            for i in range(19))
        if self._use_numpy:
            from ..ops.torcells_device import torcells_step_span_batched_numpy
            out = torcells_step_span_batched_numpy(
                *stacked, ring_len=cls.ring_len)
        else:
            from ..ops.torcells_device import torcells_step_span_flush_batched
            out = torcells_step_span_flush_batched(
                *stacked, ring_len=cls.ring_len)
        out = tuple(np.asarray(a) for a in out)
        with self._cv:
            cls.width = width
            if (cls.key, width) not in self._compiled:
                self._compiled.add((cls.key, width))
                self.compiles += 1
            self.launches += 1
            self._occupancy_sum += len(subs) / width
        for w, sub in enumerate(subs):
            sub.result = tuple(a[w] for a in out)

    # -- stats -------------------------------------------------------------
    def metrics(self) -> Dict:
        """The fleet.* scrape namespace (see obs/metrics.py): how many
        lanes rode the plane, how full launches ran, and how many lane
        dispatches each device launch amortized."""
        with self._cv:
            launches = self.launches
            amortized = self.lane_dispatches / launches if launches else 0.0
            occupancy = self._occupancy_sum / launches if launches else 0.0
            return {
                "fleet.lanes": self.lanes_peak,
                "fleet.lane_occupancy": round(occupancy, 4),
                "fleet.launches": launches,
                "fleet.lane_dispatches": self.lane_dispatches,
                "fleet.launches_amortized": round(amortized, 4),
                "fleet.shape_classes": len(self._classes),
                "fleet.compiles": self.compiles,
            }

    def stats(self) -> Dict:
        return self.metrics()
