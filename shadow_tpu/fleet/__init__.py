"""simfleet (ISSUE 18): many simulations per chip.

One compiled device program — the span/flush kernel family vmapped over
a leading batch axis — advances N *independent* scenarios per launch.
The package separates per-scenario plane STATE (each lane's arrival
ring, halt flag, flush section) from the SHARED compiled program
(scenario shapes bucketed into padded shape classes), which is the
refactor ROADMAP item 3 names and the serving shape the paper's
"thousands of simulated hosts" pitch scales out to: parameter sweeps,
CI matrices and simfuzz's mode matrix become batch lanes instead of one
subprocess each, digest-identical to the serial path.

* :mod:`shadow_tpu.fleet.plane` — FleetPlane (the shared batching
  executor: shape classes, sticky batch width, barrier, compile
  counter) and FleetLane (per-scenario handle: pad/dispatch/unpad).
* :mod:`shadow_tpu.fleet.driver` — FleetDriver: N lane threads
  round-robin over a job queue with per-lane attach/detach (a finished
  lane re-arms with the next queued scenario without recompiling).
* :mod:`shadow_tpu.fleet.cli` — the ``simfleet`` console entry
  (``simfleet smoke``: bounded mixed fleet, digest-gated vs serial).
"""

from .driver import FleetDriver
from .plane import FleetLane, FleetPlane

__all__ = ["FleetDriver", "FleetLane", "FleetPlane"]
