"""FleetDriver (ISSUE 18): N lane threads round-robin over a job queue.

Each worker thread pulls the next queued scenario job, arms a fresh
FleetLane for it (begin/end bracket the barrier's live count), and runs
the job with the lane — a finished lane is re-armed with the next
queued scenario WITHOUT recompiling: the new lane joins the same shape
class, whose sticky width keeps the jit cache key unchanged
(``FleetPlane.compiles`` is the proof the re-arm drill asserts on).

Jobs are plain callables ``fn(lane) -> result`` so both customers wrap
the same engine entry point: ``simfuzz --batched`` wraps
``fuzz.runner.run_one_mode(spec, mode, lane=lane)`` and ``simfleet
smoke`` wraps the same call for its digest gate.  The GIL serializes
the lanes' host work; the win is the shared compile cache plus the
batched launches amortizing the per-dispatch overhead N-up.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from .plane import FleetPlane


class FleetDriver:
    def __init__(self, lanes: int = 8, plane: Optional[FleetPlane] = None,
                 use_numpy: bool = False):
        self.lanes = max(1, int(lanes))
        self.plane = plane if plane is not None \
            else FleetPlane(use_numpy=use_numpy)

    def run(self, jobs: List[Callable]) -> List:
        """Run every job, at most ``lanes`` concurrently, preserving
        result order.  A job's exception is re-raised (the first by job
        index) after every worker has drained — lanes end in a finally,
        so one failing scenario can never wedge the barrier."""
        n = len(jobs)
        results: List = [None] * n
        errors: List = [None] * n
        cursor = {"next": 0}
        feed_lock = threading.Lock()

        def _worker() -> None:
            while True:
                with feed_lock:
                    i = cursor["next"]
                    if i >= n:
                        return
                    cursor["next"] = i + 1
                lane = self.plane.lane()
                lane.begin()
                try:
                    results[i] = jobs[i](lane)  # simlint: disable=SIM102 -- each slot i is claimed by exactly one worker under feed_lock; the spawner reads only after join()
                except BaseException as e:  # noqa: BLE001 - reported below
                    errors[i] = e  # simlint: disable=SIM102 -- same slot-ownership + join() ordering as results[i]
                finally:
                    lane.end()

        threads = [threading.Thread(target=_worker, name=f"fleet-{w}",
                                    daemon=True)
                   for w in range(min(self.lanes, max(n, 1)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in errors:
            if e is not None:
                raise e
        return results
