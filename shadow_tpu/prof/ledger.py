"""BENCH_HISTORY.jsonl — the persistent perf-trend ledger.

Every bench row used to die with the run that produced it (the BENCH_r*
files are hand-curated snapshots; the trajectory between them was
literally empty).  The ledger fixes that at the cheapest possible layer:
bench.py appends ONE JSON line per flagship/sharded row, keyed by box
hostname + git sha + UTC timestamp, and ``trace_report --trend`` renders
the trajectory (per-column sparklines, regression flags vs the
best-known value) so a regression is caught by the repo, not by a human
rereading CHANGES.md.

Records are append-only and line-delimited: a crashed bench still leaves
every earlier row readable, and the file diffs cleanly in review.  Only
scalar columns are kept (nested dicts are flattened one level) so the
trend report can treat every column numerically.
"""

from __future__ import annotations

import json
import os
import subprocess
import time as _walltime
from typing import Dict, List, Optional

LEDGER_VERSION = 1


def repo_git_sha(cwd: Optional[str] = None) -> Optional[str]:
    """Short git sha of the repo containing this package (None when git
    or the repo is unavailable — callers record 'unknown', not a crash)."""
    from . import repo_root
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd or repo_root(),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def default_history_path() -> str:
    from . import HISTORY_BASENAME, repo_root
    return os.path.join(repo_root(), HISTORY_BASENAME)


def _flatten_cols(row: Dict) -> Dict:
    """Scalar columns only, nested dicts flattened ONE level with a dotted
    prefix (the bench rows' ``plane`` sub-dict); deeper nesting and lists
    are dropped — the trend report is column-wise."""
    out: Dict = {}
    for k, v in row.items():
        if isinstance(v, (int, float, bool)) or v is None \
                or isinstance(v, str):
            out[k] = v
        elif isinstance(v, dict):
            for k2, v2 in v.items():
                if isinstance(v2, (int, float, bool)):
                    out[f"{k}.{k2}"] = v2
    return out


def append_row(path: str, name: str, cols: Dict,
               box: Optional[str] = None,
               sha: Optional[str] = None) -> Dict:
    """Append one ledger record; returns it.  ``name`` identifies the row
    family (``tor10k_device_plane_native_long``, ``multichip``, ...) so
    the trend groups like with like across rounds."""
    import platform

    rec = {
        "v": LEDGER_VERSION,
        "ts": _walltime.strftime("%Y-%m-%dT%H:%M:%SZ",
                                 _walltime.gmtime()),
        "box": box or platform.node(),
        "sha": sha or repo_git_sha() or "unknown",
        "row": name,
        "cols": _flatten_cols(cols),
    }
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec


def append_bench_rows(rows: Dict[str, Dict],
                      path: Optional[str] = None) -> int:
    """Bench-side helper: append every present row dict under its name.
    Never raises — a broken ledger must not fail a bench that already
    measured everything (the error lands on stderr instead)."""
    import sys

    path = path or default_history_path()
    sha = repo_git_sha() or "unknown"
    n = 0
    for name, row in rows.items():
        if not isinstance(row, dict):
            continue
        try:
            append_row(path, name, row, sha=sha)
            n += 1
        except OSError as e:
            print(f"bench history append failed for {name}: {e}",
                  file=sys.stderr)
    return n


def load_history(path: str) -> List[Dict]:
    """Parse the ledger back (skips blank lines; a malformed line raises
    — the ledger is append-only JSON lines, corruption must be loud)."""
    out: List[Dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
