"""The measured per-box cost model: schema, digest stamping, fingerprint
refusal, and the query surface the schedulers consult.

A ``COSTMODEL.json`` is produced by ``simprof calibrate`` (calibrate.py)
and carries three measurement tables:

* ``collectives`` — per-collective LAUNCH cost in microseconds, keyed by
  ``"<kind>"`` -> ``"<D>x<width>"`` (kind in ppermute / all_to_all /
  psum; the ~320 us launch floor PR 9 measured on the virtual CPU mesh
  is what these tables quantify per device count and slot width);
* ``step_kernel`` — device step-kernel cost per tick at measured flow
  counts (linear-fit for interpolation: ``us_per_step(a + b*flows)``);
* ``transfer`` — fixed dispatch upload + flush readback cost per launch.

The model is **per box**: it carries a backend fingerprint (platform,
machine, cpu count, jax version, hostname) and a sha256 digest over the
whole payload.  :func:`load_model` REFUSES a model whose digest or
fingerprint does not match — a stale or foreign model silently
mis-scheduling would be worse than the heuristic — and
:func:`load_for_engine` degrades that refusal into a loud log line plus
heuristic fallback, never a crash.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

# measured/predicted ratio band outside which a launch counts as
# model-stale evidence (prof.model_stale); wide because shared-tenant CPU
# boxes swing, and because the measured span upper-bounds the kernel wall
DEFAULT_BAND = 6.0

_REQUIRED_KEYS = ("version", "fingerprint", "git_sha", "band",
                  "collectives", "step_kernel", "transfer", "digest")
_FINGERPRINT_KEYS = ("platform", "machine", "node", "cpus", "jax")
_COLLECTIVE_KINDS = ("ppermute", "all_to_all", "psum")


class CostModelError(Exception):
    """A model that must not be used: schema, digest, or fingerprint."""


def box_fingerprint() -> Dict:
    """The facts a measurement is only valid under: backend platform,
    machine/hostname, cpu count, jax version.  Deliberately NOT the
    visible device count — on CPU that is an XLA flag (the virtual test
    mesh), not hardware."""
    import multiprocessing
    import platform

    import jax

    return {"platform": jax.default_backend(),
            "machine": platform.machine(),
            "node": platform.node(),
            "cpus": multiprocessing.cpu_count(),
            "jax": jax.__version__}


def payload_digest(data: Dict) -> str:
    """sha256 over the canonical JSON of everything but the stamp itself
    — a hand-edited or truncated model fails the load, loudly."""
    body = {k: v for k, v in data.items() if k != "digest"}
    blob = json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def build_model(measurements: Dict, fingerprint: Optional[Dict] = None,
                git_sha: Optional[str] = None,
                wall_sec: Optional[float] = None,
                band: float = DEFAULT_BAND,
                truncated: bool = False) -> Dict:
    """Wrap raw calibration measurements into the stamped model dict."""
    if fingerprint is None:
        fingerprint = box_fingerprint()
    if git_sha is None:
        from .ledger import repo_git_sha
        git_sha = repo_git_sha() or "unknown"
    data = {
        "version": SCHEMA_VERSION,
        "fingerprint": fingerprint,
        "git_sha": git_sha,
        "wall_sec": round(wall_sec, 2) if wall_sec is not None else None,
        "band": float(band),
        "truncated": bool(truncated),
        "collectives": measurements.get("collectives", {}),
        "step_kernel": measurements.get("step_kernel", {"points": []}),
        "transfer": measurements.get("transfer", {}),
    }
    data["digest"] = payload_digest(data)
    return data


def validate_schema(data: Dict) -> List[str]:
    """Schema problems as strings (empty = valid).  Shared by load_model
    and ``simprof check``."""
    problems: List[str] = []
    for k in _REQUIRED_KEYS:
        if k not in data:
            problems.append(f"missing key {k!r}")
    if problems:
        return problems
    if data["version"] != SCHEMA_VERSION:
        problems.append(f"version {data['version']!r} != {SCHEMA_VERSION}")
    fp = data["fingerprint"]
    if not isinstance(fp, dict):
        problems.append("fingerprint is not a dict")
    else:
        for k in _FINGERPRINT_KEYS:
            if k not in fp:
                problems.append(f"fingerprint missing {k!r}")
    coll = data["collectives"]
    if not isinstance(coll, dict):
        problems.append("collectives is not a dict")
    else:
        for kind, table in coll.items():
            if kind not in _COLLECTIVE_KINDS:
                problems.append(f"unknown collective kind {kind!r}")
                continue
            for key, us in (table or {}).items():
                ok = isinstance(us, (int, float)) and us >= 0
                parts = str(key).split("x")
                ok = ok and len(parts) == 2 and all(
                    p.isdigit() for p in parts)
                if not ok:
                    problems.append(
                        f"collectives[{kind}][{key!r}] malformed")
    pts = (data["step_kernel"] or {}).get("points", [])
    if not isinstance(pts, list):
        problems.append("step_kernel.points is not a list")
    else:
        for p in pts:
            if not (isinstance(p, dict) and "flows" in p
                    and "us_per_step" in p):
                problems.append(f"step_kernel point malformed: {p!r}")
    tr = data["transfer"]
    if not isinstance(tr, dict):
        problems.append("transfer is not a dict")
    else:
        for k, v in tr.items():
            if not isinstance(v, (int, float)):
                problems.append(f"transfer[{k!r}] not numeric")
    if not (isinstance(data["band"], (int, float)) and data["band"] > 1):
        problems.append(f"band {data['band']!r} must be > 1")
    return problems


def save_model(path: str, data: Dict) -> None:
    """Atomic write (tmp + rename), stable key order, trailing newline."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load_model(path: str,
               fingerprint: Optional[Dict] = None) -> "CostModel":
    """Load + verify a model file.  Raises :class:`CostModelError` on a
    schema problem, a digest mismatch (tampered/corrupt payload), or a
    fingerprint mismatch (a model calibrated on another box/backend) —
    refusal is the contract, fallback is the CALLER's job
    (:func:`load_for_engine`)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CostModelError(f"{path}: unreadable: {e}") from e
    problems = validate_schema(data)
    if problems:
        raise CostModelError(f"{path}: invalid schema: "
                             + "; ".join(problems[:4]))
    if payload_digest(data) != data["digest"]:
        raise CostModelError(
            f"{path}: digest mismatch — the measurement table was edited "
            "or truncated after calibration (re-run simprof calibrate)")
    here = fingerprint if fingerprint is not None else box_fingerprint()
    theirs = data["fingerprint"]
    drift = [k for k in _FINGERPRINT_KEYS if theirs.get(k) != here.get(k)]
    if drift:
        detail = ", ".join(
            f"{k}: {theirs.get(k)!r} != {here.get(k)!r}" for k in drift)
        raise CostModelError(
            f"{path}: fingerprint mismatch ({detail}) — this model was "
            "calibrated on a different box/backend; refusing to schedule "
            "from it (re-run simprof calibrate here)")
    return CostModel(data, path=path)


def default_model_path() -> str:
    """Resolution order: $SHADOW_COSTMODEL, then the repo-root
    ``COSTMODEL.json`` next to bench.py (the checked-in per-box model)."""
    env = os.environ.get("SHADOW_COSTMODEL", "").strip()
    if env:
        return env
    from . import COSTMODEL_BASENAME, repo_root
    return os.path.join(repo_root(), COSTMODEL_BASENAME)


def load_for_engine(options) -> Tuple[Optional["CostModel"], str]:
    """The run-time entry point: resolve the model path from the options
    (``--cost-model``) or the default, load it, and degrade every
    refusal into (None, status) with ONE loud log line — the consumers
    (mesh exchange decision, per-launch attribution) fall back to the
    pre-model heuristics, they never crash on a bad model file."""
    path = (getattr(options, "cost_model", "") or "").strip() \
        or default_model_path()
    if not os.path.exists(path):
        return None, "absent"
    from ..core.logger import get_logger
    try:
        return load_model(path), "loaded"
    except CostModelError as e:
        get_logger().warning(
            "prof", f"cost model refused: {e} — falling back to the "
            "heuristic exchange schedule and skipping launch attribution")
        return None, "refused"


class CostModel:
    """Query surface over a verified model dict."""

    def __init__(self, data: Dict, path: Optional[str] = None):
        self.data = data
        self.path = path
        self.band = float(data.get("band") or DEFAULT_BAND)
        self.fingerprint = data["fingerprint"]
        self.git_sha = data.get("git_sha")
        # linear fit us_per_step ~= a + b * flows over the measured points
        pts = sorted(((int(p["flows"]), float(p["us_per_step"]))
                      for p in data["step_kernel"].get("points", [])))
        if len(pts) >= 2:
            xs = [p[0] for p in pts]
            ys = [p[1] for p in pts]
            n = len(pts)
            mx, my = sum(xs) / n, sum(ys) / n
            den = sum((x - mx) ** 2 for x in xs) or 1.0
            self._step_b = sum((x - mx) * (y - my)
                               for x, y in pts) / den
            self._step_a = my - self._step_b * mx
        elif pts:
            self._step_a, self._step_b = pts[0][1], 0.0
        else:
            self._step_a = self._step_b = 0.0
        # the smallest measured flow count: predictions BELOW (half) this
        # are extrapolations the model never measured — the device plane
        # skips launch attribution there rather than raise false stale
        # flags on toy tables (tests craft models with tiny points)
        self.min_flows = pts[0][0] if pts else 0
        # ... and the largest: predictions far ABOVE it are equally
        # unmeasured (ISSUE 16: a flagship-scale table judged by pure
        # upward extrapolation would mis-tune the dispatch loop the same
        # way it would mis-flag prof.model_stale)
        self.max_flows = pts[-1][0] if pts else 0

    # -- raw tables --------------------------------------------------------
    def collective_us(self, kind: str, n_dev: int, width: int) -> float:
        """Launch cost of one ``kind`` collective on a ``n_dev`` mesh at
        ``width`` total slots: exact key, else linear interpolation in
        width (clamped) within the nearest measured device count."""
        table = self.data["collectives"].get(kind) or {}
        if not table:
            return 0.0
        entries: Dict[int, Dict[int, float]] = {}
        for key, us in table.items():
            d_s, w_s = str(key).split("x")
            entries.setdefault(int(d_s), {})[int(w_s)] = float(us)
        d = min(entries, key=lambda k: abs(k - n_dev))
        widths = sorted(entries[d])
        w = max(min(width, widths[-1]), widths[0])
        lo = max(x for x in widths if x <= w)
        hi = min(x for x in widths if x >= w)
        if lo == hi:
            return entries[d][lo]
        frac = (w - lo) / (hi - lo)
        return entries[d][lo] + frac * (entries[d][hi] - entries[d][lo])

    def step_us(self, flows: int) -> float:
        """Step-kernel cost of ONE tick at ``flows`` table rows."""
        return max(self._step_a + self._step_b * max(int(flows), 0), 0.0)

    def covers(self, flows: int) -> bool:
        """True when ``flows`` sits inside the calibrated step-kernel
        range (with 2x slack each way) — the no-extrapolation guard both
        launch attribution AND the dispatch auto-tuner sit behind: a
        prediction outside the measured points is a guess, and guesses
        neither raise stale flags nor reshape the dispatch loop."""
        if not self.max_flows:
            return False
        f = int(flows)
        return f * 2 >= self.min_flows and f <= 2 * self.max_flows

    def transfer_us(self) -> float:
        tr = self.data["transfer"]
        return float(tr.get("dispatch_us", 0.0)) \
            + float(tr.get("flush_us", 0.0))

    def flush_us_per_mb(self) -> float:
        """Marginal flush readback cost per MiB of buffer (the measured
        size slope, ISSUE 16); 0.0 on a pre-16 model that only measured
        one flush size — delta-compaction then has no measured savings
        to justify itself and stays off."""
        return float(self.data["transfer"].get("flush_us_per_mb", 0.0))

    def flush_savings_us(self, bytes_saved: int) -> float:
        """Predicted per-launch readback saving of shrinking the flush
        buffer by ``bytes_saved`` bytes."""
        return self.flush_us_per_mb() * max(int(bytes_saved), 0) / 2 ** 20

    # -- scheduler/attribution queries ------------------------------------
    def exchange_tick_us(self, n_dev: int, mode: str, pair_width: int,
                         leg_widths: List[int]) -> float:
        """Per-tick collective cost of one exchange mode: the fused
        all_to_all over the superposed [D, D*pair_width] slots, or one
        ppermute per rotation leg; both pay the fused stats psum the
        mesh kernel always issues."""
        psum = self.collective_us("psum", n_dev, 2)
        if mode == "fused":
            return psum + self.collective_us(
                "all_to_all", n_dev, n_dev * max(pair_width, 1))
        if mode == "ppermute":
            return psum + sum(
                self.collective_us("ppermute", n_dev, max(w, 1))
                for w in leg_widths)
        return psum if mode == "none" else 0.0

    def predict_window_us(self, steps: int, flows: int,
                          exchange_tick_us: float = 0.0) -> float:
        """Predicted device cost of one window launch: per-tick step
        kernel + per-tick exchange collectives, plus the fixed
        dispatch/flush transfer cost."""
        return max(int(steps), 0) * (self.step_us(flows)
                                     + max(exchange_tick_us, 0.0)) \
            + self.transfer_us()
