"""``simprof`` — the device cost observatory CLI.

Subcommands:

* ``simprof calibrate [--out PATH] [--quick] [--wall-cap-sec N]
  [--devices 2,3,4,8] [--batched]`` — microbenchmark this box into a
  stamped ``COSTMODEL.json`` (bounded subprocess; see calibrate.py).
  ``--batched`` additionally sweeps the vmapped fleet kernel at widths
  1/2/4/8, reported in the status row only.  The hidden ``--child``
  form is the in-subprocess half.
* ``simprof check [PATH]`` — validate a checked-in model: schema,
  digest currency, and the REFUSAL drills (a fingerprint-mutated and a
  measurement-tampered copy must both refuse to load) — the CI gate
  (``make profile-smoke``) that keeps the refusal path honest.
* ``simprof show [PATH]`` — human summary: fingerprint, measurement
  table shape, the launch-cost matrix, and what the exchange scheduler
  would pick at a few example schedule shapes.

Every subcommand prints ONE JSON line (CI-parseable) and exits 0/1.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import tempfile
from typing import List, Optional

from . import COSTMODEL_BASENAME
from . import model as _model


def _default_path() -> str:
    return _model.default_model_path()


def cmd_calibrate(args) -> int:
    from .calibrate import calibrate_child, run_calibration

    if args.child:
        return calibrate_child(args.child, args.quick, args.wall_cap_sec,
                               _parse_devices(args.devices),
                               batched=args.batched)
    out = args.out or _default_path()
    row = run_calibration(out, quick=args.quick,
                          wall_cap_sec=args.wall_cap_sec,
                          devices=_parse_devices(args.devices),
                          batched=args.batched)
    print(json.dumps({"simprof_calibrate": row}), flush=True)
    return 0 if row.get("ok") else 1


def _parse_devices(spec: Optional[str]) -> Optional[List[int]]:
    if not spec:
        return None
    return [int(x) for x in spec.split(",") if x.strip()]


def check_model(path: str) -> dict:
    """The ``simprof check`` core, importable by tests and the bench:
    schema + digest validation of the model at ``path``, plus the two
    refusal drills run against mutated copies in a temp dir."""
    row: dict = {"path": path, "ok": False, "problems": []}
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        row["problems"].append(f"unreadable: {e}")
        return row
    problems = _model.validate_schema(data)
    if not problems and _model.payload_digest(data) != data.get("digest"):
        problems.append("digest mismatch (payload edited after stamping)")
    row["problems"] = problems
    if problems:
        return row
    # informational: would THIS box load it?  (a foreign model correctly
    # refusing here is still a PASSING check — refusal is the contract)
    try:
        _model.load_model(path)
        row["loads_on_this_box"] = True
    except _model.CostModelError as e:
        row["loads_on_this_box"] = False
        row["refusal"] = str(e)[:200]
    # refusal drills: a fingerprint-mutated copy and a tampered
    # measurement copy must BOTH refuse to load
    with tempfile.TemporaryDirectory(prefix="simprof-check-") as td:
        drifted = copy.deepcopy(data)
        drifted["fingerprint"] = dict(
            drifted["fingerprint"],
            node=str(drifted["fingerprint"].get("node")) + "-elsewhere")
        drifted["digest"] = _model.payload_digest(drifted)
        p1 = os.path.join(td, "drifted.json")
        _model.save_model(p1, drifted)
        try:
            # the drill pins the drifted model against THIS box's
            # fingerprint... unless this box's node already mismatches
            # (foreign model), in which case pin against the model's own
            # pre-drift fingerprint so the drill tests the right edge
            _model.load_model(p1, fingerprint=data["fingerprint"])
            row["problems"].append(
                "stale-fingerprint model LOADED (refusal path broken)")
        except _model.CostModelError:
            row["stale_fingerprint_refused"] = True
        tampered = copy.deepcopy(data)
        tampered["collectives"].setdefault("ppermute", {})["2x8"] = 1e-9
        p2 = os.path.join(td, "tampered.json")
        with open(p2, "w") as f:
            json.dump(tampered, f)       # digest left stale on purpose
        try:
            _model.load_model(p2, fingerprint=data["fingerprint"])
            row["problems"].append(
                "digest-tampered model LOADED (digest path broken)")
        except _model.CostModelError:
            row["tampered_digest_refused"] = True
    row["fingerprint"] = data["fingerprint"]
    row["git_sha"] = data.get("git_sha")
    row["truncated"] = data.get("truncated")
    row["collective_points"] = sum(
        len(t) for t in data["collectives"].values())
    row["step_points"] = len(data["step_kernel"].get("points", []))
    row["ok"] = not row["problems"]
    return row


def cmd_check(args) -> int:
    path = args.path or _default_path()
    row = check_model(path)
    print(json.dumps({"simprof_check": row}), flush=True)
    return 0 if row["ok"] else 1


def cmd_show(args) -> int:
    path = args.path or _default_path()
    try:
        model = _model.load_model(path)
        loaded = True
        refusal = None
    except _model.CostModelError as e:
        loaded = False
        refusal = str(e)
        try:
            with open(path) as f:
                model = _model.CostModel(json.load(f), path=path)
        except Exception:
            print(json.dumps({"simprof_show": {
                "path": path, "error": refusal}}), flush=True)
            return 1
    # what the data-driven scheduler would pick at a few shapes
    choices = {}
    for d, legs, pair_w, leg_w in ((8, 4, 16, 16), (8, 1, 64, 64),
                                   (4, 3, 8, 8), (2, 1, 128, 128)):
        fused = model.exchange_tick_us(d, "fused", pair_w, [leg_w] * legs)
        pperm = model.exchange_tick_us(d, "ppermute", pair_w,
                                       [leg_w] * legs)
        choices[f"D={d},legs={legs}"] = {
            "fused_us": round(fused, 1), "ppermute_us": round(pperm, 1),
            "pick": "fused" if fused <= pperm else "ppermute"}
    row = {
        "path": path,
        "loads_on_this_box": loaded,
        **({"refusal": refusal} if refusal else {}),
        "fingerprint": model.fingerprint,
        "git_sha": model.git_sha,
        "band": model.band,
        "collectives": model.data["collectives"],
        "step_us_at_1k_flows": round(model.step_us(1000), 1),
        "transfer_us": round(model.transfer_us(), 1),
        "example_choices": choices,
    }
    print(json.dumps({"simprof_show": row}, indent=2), flush=True)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="simprof",
        description="shadow-tpu device cost observatory: calibrate / "
                    "check / show the per-box measured cost model "
                    f"({COSTMODEL_BASENAME})")
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("calibrate",
                       help="microbenchmark this box into a stamped "
                            "cost model (bounded subprocess)")
    c.add_argument("--out", default=None,
                   help=f"output path (default: the repo-root "
                        f"{COSTMODEL_BASENAME} / $SHADOW_COSTMODEL)")
    c.add_argument("--quick", action="store_true",
                   help="endpoint probe grid only (the CI smoke)")
    c.add_argument("--wall-cap-sec", type=float, default=600.0,
                   dest="wall_cap_sec")
    c.add_argument("--devices", default=None,
                   help="comma-separated mesh sizes (default 2,3,4,8)")
    c.add_argument("--batched", action="store_true",
                   help="also sweep the vmapped fleet kernel at widths "
                        "1/2/4/8 (ISSUE 18) — reported in the status "
                        "row only, never stamped into the COSTMODEL")
    c.add_argument("--child", default=None, metavar="OUT",
                   help=argparse.SUPPRESS)   # in-subprocess half
    c.set_defaults(fn=cmd_calibrate)
    k = sub.add_parser("check",
                       help="validate a model: schema + digest + the "
                            "stale-fingerprint/tamper refusal drills")
    k.add_argument("path", nargs="?", default=None)
    k.set_defaults(fn=cmd_check)
    s = sub.add_parser("show", help="human summary of a model")
    s.add_argument("path", nargs="?", default=None)
    s.set_defaults(fn=cmd_show)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
