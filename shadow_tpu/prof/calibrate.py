"""``simprof calibrate``: microbenchmark the actual backend into a
digest-stamped per-box cost model.

Methodology (arXiv 1912.03413's IPU microbenchmarking, applied to this
engine's three device cost centers):

* **per-collective launch cost** — one jitted ``shard_map`` program per
  (kind, D, width) whose ``fori_loop`` issues N collectives back to
  back; per-launch cost is wall/N.  The loop body carries a data
  dependence through the collective result so XLA cannot DCE it (the
  PR-9 trap: multiplying a collective by 0 deletes it).  Kinds are
  exactly what the mesh kernel issues: ``ppermute``, tiled
  ``all_to_all``, and the fused stats ``psum``;
* **step-kernel cost vs flows** — the production superwindow flush
  kernel (ops/torcells_device) timed at measured flow counts, so the
  model predicts the per-tick cost of the table the engine actually
  dispatches;
* **dispatch/flush transfer cost** — host->device upload of an [F]
  inject vector plus device->host materialization of a flush-sized
  buffer, the fixed per-launch transfer the pipeline amortizes.

Execution is the bench-multichip pattern: the parent spawns ONE bounded
child with the virtual device mesh forced on CPU (a real accelerator
environment is left alone), kills it on overrun, and wraps the child's
measurements with fingerprint + git sha + digest (model.build_model)
into an atomically-written ``COSTMODEL.json``.  The child checks a wall
deadline between probes and marks the model ``truncated`` when it had
to stop early — a truncated model is still valid for the points it
measured.
"""

from __future__ import annotations

import json
import os
import time as _walltime
from typing import Dict, List, Optional, Tuple

# default probe grids (ISSUE 15: D in {2,3,4,8} and slot widths); quick
# mode trims to the endpoints for the wall-capped CI smoke
DEVICES = (2, 3, 4, 8)
WIDTHS = (24, 240, 4080)
QUICK_DEVICES = (2, 8)
QUICK_WIDTHS = (24, 960)
# step-kernel sweep in CIRCUITS (flow rows = 5x).  The top points exist
# so the calibrated range covers flagship-scale tables (ISSUE 16: tor10k
# dispatches ~100k flow rows; under the two-sided no-extrapolation guard
# an uncovered table gets neither launch attribution NOR auto-tuning) —
# 24k circuits = 120k flows, covering 240k under the 2x slack.  Large
# points run proportionally fewer steps (_steps_for) so the sweep's wall
# stays bounded.
FLOW_POINTS = (200, 1000, 4000, 12000, 24000)
QUICK_FLOW_POINTS = (200, 2000)


def _steps_for(n_circ: int, steps: int) -> int:
    """Scale the timed step count down for large tables (cost per step
    grows ~linearly with flows; the per-step quotient stays accurate with
    fewer, longer steps) — never below 60 steps so launch overhead stays
    amortized out of the quotient."""
    if n_circ <= 4000:
        return steps
    return max(60, steps * 4000 // n_circ)


def _deadline_left(deadline: Optional[float]) -> float:
    if deadline is None:
        return float("inf")
    return deadline - _walltime.monotonic()


def measure_collectives(devices, widths, iters: int,
                        deadline: Optional[float]) -> Tuple[Dict, bool]:
    """Per-launch cost tables {kind: {"DxW": us}}; bool = truncated."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import device_mesh

    out: Dict[str, Dict[str, float]] = {"ppermute": {},
                                        "all_to_all": {},
                                        "psum": {}}
    truncated = False
    n_avail = len(jax.devices())
    for d in devices:
        if d > n_avail:
            continue
        mesh = device_mesh(d, axis_names=("x",))
        for width in widths:
            # per-shard width; all_to_all tiles over it, so keep it a
            # multiple of d (floor d)
            w = max((int(width) // d) * d, d)
            for kind in ("ppermute", "all_to_all", "psum"):
                if _deadline_left(deadline) <= 0:
                    truncated = True
                    return out, truncated
                perm = [(s, (s + 1) % d) for s in range(d)]

                def body(i, x, kind=kind, perm=perm):
                    if kind == "ppermute":
                        y = jax.lax.ppermute(x, "x", perm=perm)
                    elif kind == "all_to_all":
                        y = jax.lax.all_to_all(x, "x", 0, 0, tiled=True)
                    else:
                        y = x + jax.lax.psum(x[0], "x")
                    # the +i data dependence keeps every iteration (and
                    # the collective inside it) live under XLA
                    return y + i

                @jax.jit
                @partial(shard_map, mesh=mesh, in_specs=P("x"),
                         out_specs=P("x"), check_rep=False)
                def run(x, body=body, iters=iters):
                    return jax.lax.fori_loop(0, iters, body, x)

                x = jnp.zeros(d * w, jnp.int64)
                jax.block_until_ready(run(x))          # compile
                t0 = _walltime.perf_counter()
                jax.block_until_ready(run(x))
                t1 = _walltime.perf_counter()
                out[kind][f"{d}x{w}"] = round(
                    (t1 - t0) / iters * 1e6, 2)
    return out, truncated


def measure_step_kernel(flow_points, steps: int,
                        deadline: Optional[float]) -> Tuple[Dict, bool]:
    """Per-tick cost of the production span-flush kernel at measured
    circuit counts (points carry the padded flow-row count the engine's
    predictor is keyed by)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.torcells_device import (
        RING_DTYPE, DeviceTorCells, torcells_step_window_flush_nodonate)

    points: List[Dict] = []
    truncated = False
    for n_circ in flow_points:
        if _deadline_left(deadline) <= 0:
            truncated = True
            break
        pt_steps = _steps_for(int(n_circ), steps)
        inst = DeviceTorCells(n_relays=max(8, n_circ // 10),
                              n_circuits=n_circ, seed=11,
                              relay_bw_kibps=4096, max_latency_ms=30)
        fl = inst.flows
        f = inst.n_flows
        h = len(inst.refill)
        last_flow = np.flatnonzero(fl["flow_succ"] < 0)
        queued0 = jnp.asarray(
            (fl["flow_stage"] == 0).astype("int64") * 50)
        target0 = jnp.asarray(
            (fl["flow_succ"] < 0).astype("int64") * 50)
        state = (jnp.int64(0), jnp.zeros(f, jnp.int64),
                 jnp.zeros((inst.ring_len, f), RING_DTYPE),
                 jnp.asarray(inst.capacity), jnp.zeros(f, jnp.int64),
                 jnp.zeros(f, jnp.int64), jnp.full(f, -1, jnp.int64),
                 jnp.zeros(h, jnp.int64))
        args = (jnp.asarray(fl["flow_node"]), jnp.asarray(fl["flow_lat"]),
                jnp.asarray(fl["flow_succ"]), jnp.asarray(fl["seg_start"]),
                jnp.asarray(inst.refill), jnp.asarray(inst.capacity),
                jnp.asarray(last_flow))
        targets = np.array([pt_steps], dtype=np.int64)
        out = torcells_step_window_flush_nodonate(
            *state, queued0, target0, targets, np.int64(0), *args,
            ring_len=inst.ring_len)
        jax.block_until_ready(out)                    # compile
        t0 = _walltime.perf_counter()
        out = torcells_step_window_flush_nodonate(
            *state, queued0, target0, targets, np.int64(0), *args,
            ring_len=inst.ring_len)
        jax.block_until_ready(out)
        t1 = _walltime.perf_counter()
        points.append({"flows": int(f),
                       "us_per_step": round((t1 - t0) / pt_steps * 1e6,
                                            3)})
    return {"points": points}, truncated


def measure_batched_step_kernel(widths=(1, 2, 4, 8), n_circ: int = 1000,
                                steps: int = 200,
                                deadline: Optional[float] = None
                                ) -> Tuple[Dict, bool]:
    """Fleet-plane width sweep (ISSUE 18): per-lane per-tick cost of the
    VMAPPED span-flush kernel at widths 1..W — the measured answer to
    "how many co-resident simulations does one ~320 us launch amortize
    over before the compute wall bites".  Reported in the calibrate
    status row ONLY; the stamped COSTMODEL stays the single-lane model
    every existing consumer (autotune, launch attribution) is keyed by."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.torcells_device import (
        RING_DTYPE, DeviceTorCells, torcells_step_span_flush_batched)

    inst = DeviceTorCells(n_relays=max(8, n_circ // 10),
                          n_circuits=n_circ, seed=11,
                          relay_bw_kibps=4096, max_latency_ms=30)
    fl = inst.flows
    f = inst.n_flows
    h = len(inst.refill)
    last_flow = np.flatnonzero(fl["flow_succ"] < 0)
    queued0 = (fl["flow_stage"] == 0).astype("int64") * 50
    target0 = (fl["flow_succ"] < 0).astype("int64") * 50
    lane_state = (np.int64(0), np.zeros(f, np.int64),
                  np.zeros((inst.ring_len, f), RING_DTYPE),
                  np.asarray(inst.capacity), np.zeros(f, np.int64),
                  np.zeros(f, np.int64), np.full(f, -1, np.int64),
                  np.zeros(h, np.int64))
    tables = (np.asarray(fl["flow_node"]), np.asarray(fl["flow_lat"]),
              np.asarray(fl["flow_succ"]), np.asarray(fl["seg_start"]),
              np.asarray(inst.refill), np.asarray(inst.capacity),
              np.asarray(last_flow))
    points: List[Dict] = []
    truncated = False
    base_us = None
    for w in widths:
        if _deadline_left(deadline) <= 0:
            truncated = True
            break
        lane = (*lane_state, queued0, target0,
                np.array([steps], dtype=np.int64), np.int64(0), *tables)
        batch = tuple(jnp.asarray(np.stack([np.asarray(a)] * w))
                      for a in lane)
        out = torcells_step_span_flush_batched(
            *batch, ring_len=inst.ring_len)
        jax.block_until_ready(out)                    # compile
        t0 = _walltime.perf_counter()
        out = torcells_step_span_flush_batched(
            *batch, ring_len=inst.ring_len)
        jax.block_until_ready(out)
        t1 = _walltime.perf_counter()
        lane_us = (t1 - t0) / steps / w * 1e6
        if base_us is None:
            base_us = lane_us
        points.append({"width": int(w), "flows": int(f),
                       "us_per_lane_step": round(lane_us, 3),
                       "speedup_vs_serial": round(base_us / lane_us, 2)
                       if lane_us > 0 else 0.0})
    return {"points": points}, truncated


def measure_transfer(reps: int = 30, flows: int = 4096,
                     big_flows: int = 65536) -> Dict:
    """Fixed per-launch transfer cost: inject upload + flush readback.
    The readback is measured at TWO buffer sizes; the slope
    (``flush_us_per_mb``) is what prices the delta-compacted flush
    (ISSUE 16, prof/autotune.py) — on a box where readback cost is
    size-independent the slope is ~0 and compaction stays off."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def readback_us(n: int) -> float:
        dev = jnp.arange(n, dtype=jnp.int64)
        np.asarray(dev)
        t0 = _walltime.perf_counter()
        for _ in range(reps):
            np.asarray(dev + 1)  # +1: a fresh buffer per materialization
        return (_walltime.perf_counter() - t0) / reps * 1e6

    host = np.zeros(flows, dtype=np.int64)
    jax.block_until_ready(jnp.asarray(host))          # warm the path
    t0 = _walltime.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(jnp.asarray(host))
    up_us = (_walltime.perf_counter() - t0) / reps * 1e6
    down_us = readback_us(flows)
    down_big_us = readback_us(big_flows)
    mb = (big_flows - flows) * 8 / 2 ** 20
    slope = max((down_big_us - down_us) / mb, 0.0) if mb > 0 else 0.0
    return {"dispatch_us": round(up_us, 2), "flush_us": round(down_us, 2),
            "flush_us_per_mb": round(slope, 2)}


def calibrate_child(out_path: str, quick: bool, wall_cap_sec: float,
                    devices: Optional[List[int]] = None,
                    batched: bool = False) -> int:
    """The in-subprocess half: run every probe under the wall deadline
    and write raw measurements (+ truncated flag + wall) as JSON."""
    t0 = _walltime.monotonic()
    deadline = t0 + wall_cap_sec if wall_cap_sec > 0 else None
    devs = tuple(devices) if devices else (
        QUICK_DEVICES if quick else DEVICES)
    widths = QUICK_WIDTHS if quick else WIDTHS
    flow_points = QUICK_FLOW_POINTS if quick else FLOW_POINTS
    iters = 200 if quick else 500
    steps = 200 if quick else 400
    coll, trunc_c = measure_collectives(devs, widths, iters, deadline)
    step, trunc_s = measure_step_kernel(flow_points, steps, deadline)
    transfer = measure_transfer()
    payload = {
        "collectives": coll,
        "step_kernel": step,
        "transfer": transfer,
        "truncated": bool(trunc_c or trunc_s),
        "wall_sec": round(_walltime.monotonic() - t0, 2),
    }
    if batched:
        fleet, trunc_b = measure_batched_step_kernel(
            n_circ=200 if quick else 1000,
            steps=100 if quick else 200, deadline=deadline)
        fleet["truncated"] = trunc_b
        payload["fleet_batched"] = fleet
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, out_path)
    return 0


def run_calibration(out_path: str, quick: bool = False,
                    wall_cap_sec: float = 600.0,
                    devices: Optional[List[int]] = None,
                    n_dev_env: int = 8, batched: bool = False) -> Dict:
    """Parent orchestration: spawn the bounded child with the virtual
    device mesh forced on CPU, wrap its measurements into the stamped
    model, write ``out_path`` atomically.  Returns a status row
    ({"ok": bool, ...}); a wedged child is killed and reported, never a
    hang."""
    import subprocess
    import sys
    import tempfile

    from . import model as _model
    from ..fuzz.runner import child_env

    t0 = _walltime.monotonic()
    with tempfile.TemporaryDirectory(prefix="simprof-") as td:
        mpath = os.path.join(td, "measurements.json")
        args = [sys.executable, "-m", "shadow_tpu.prof", "calibrate",
                "--child", mpath, "--wall-cap-sec", str(wall_cap_sec)]
        if quick:
            args.append("--quick")
        if batched:
            args.append("--batched")
        if devices:
            args += ["--devices", ",".join(str(d) for d in devices)]
        try:
            proc = subprocess.run(
                args, env=child_env(n_dev_env), capture_output=True,
                text=True, timeout=wall_cap_sec + 120)
        except subprocess.TimeoutExpired:
            return {"ok": False,
                    "reason": f"calibration child exceeded the "
                              f"{wall_cap_sec + 120:.0f}s bound and was "
                              "killed"}
        if proc.returncode != 0 or not os.path.exists(mpath):
            return {"ok": False, "rc": proc.returncode,
                    "reason": "calibration child failed",
                    "tail": (proc.stdout + proc.stderr)[-800:]}
        with open(mpath) as f:
            meas = json.load(f)
    # the fleet width sweep rides in the STATUS ROW only — popped before
    # build_model so the stamped COSTMODEL stays the single-lane model
    # (its digest/schema consumers are all keyed by one-lane costs)
    fleet_batched = meas.pop("fleet_batched", None)
    data = _model.build_model(
        meas, wall_sec=_walltime.monotonic() - t0,
        truncated=bool(meas.get("truncated")))
    save_dir = os.path.dirname(os.path.abspath(out_path))
    if save_dir and not os.path.isdir(save_dir):
        os.makedirs(save_dir, exist_ok=True)
    _model.save_model(out_path, data)
    n_coll = sum(len(t) for t in data["collectives"].values())
    return {"ok": True, "path": out_path,
            **({"fleet_batched": fleet_batched} if fleet_batched else {}),
            "wall_sec": round(_walltime.monotonic() - t0, 1),
            "collective_points": n_coll,
            "step_points": len(data["step_kernel"]["points"]),
            "truncated": data["truncated"],
            "fingerprint": data["fingerprint"],
            "git_sha": data["git_sha"]}
