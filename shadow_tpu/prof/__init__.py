"""simprof: the device cost observatory (ISSUE 15 / ROADMAP item 5).

After the host-plane cuts of PRs 7-12 the flagship wall is dominated by
XLA device kernel compute — the one plane the repo observed only as a
single ``flush_sec`` blob, and the one whose scheduling decision (fused
``all_to_all`` vs lone ``ppermute`` in the mesh exchange) was made by
heuristic, not data.  This package closes both gaps with the
microbenchmark-calibration methodology of *Dissecting the Graphcore IPU
Architecture via Microbenchmarking* (arXiv 1912.03413) and the
measured-schedule framing of *FAST* (arXiv 2505.09764):

* :mod:`calibrate` — ``simprof calibrate`` microbenchmarks the actual
  backend in a bounded subprocess (per-collective launch cost across
  mesh widths, step-kernel cost vs flow count, dispatch/flush transfer
  cost) and persists a digest-stamped per-box ``COSTMODEL.json``;
* :mod:`model` — the :class:`~shadow_tpu.prof.model.CostModel` the mesh
  exchange scheduler and the device plane consult at run time; a model
  whose backend fingerprint does not match this box REFUSES to load
  (loudly) and the consumers fall back to the pre-existing heuristics;
* :mod:`ledger` — the persistent perf-trend ledger
  (``BENCH_HISTORY.jsonl``): bench.py appends every flagship/sharded
  row keyed by box + git sha, and ``trace_report --trend`` renders the
  trajectory with regression flags, so the next perf regression is
  caught by the repo instead of a human rereading CHANGES.md;
* :mod:`cli` — the ``simprof`` console entry (calibrate / check / show).

Live attribution rides the existing observability plane: the device
plane publishes per-launch predicted-vs-measured histograms under
``prof.*`` and a sim-time-correlated ``device-sim`` track into the
Chrome trace; a drifting model (measured/predicted outside the band)
raises the loud ``prof.model_stale`` counter instead of silently
mis-scheduling.
"""

from __future__ import annotations

import os

COSTMODEL_BASENAME = "COSTMODEL.json"
HISTORY_BASENAME = "BENCH_HISTORY.jsonl"


def repo_root() -> str:
    """The repo checkout containing this package (where the per-box
    COSTMODEL.json and BENCH_HISTORY.jsonl live, next to bench.py) —
    the ONE definition every prof path default derives from."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
