"""COSTMODEL-driven dispatch auto-tuner (ISSUE 16 / ROADMAP item 2).

PR 14 taught the mesh layer to pick its exchange kernel from measured
per-box costs (``choose_exchange_mode``); this module generalizes that
pattern to the WHOLE dispatch loop.  Given the calibrated
:class:`~shadow_tpu.prof.model.CostModel`, :func:`plan_dispatch` picks:

* **effective superwindow depth K** — how many consecutive quiet rounds
  one kernel launch may merge.  Per-launch cost has a FIXED half (the
  dispatch upload + flush readback ``transfer_us``, plus the collective
  launch floor) that a deeper K amortizes; the tuner deepens K until
  that fixed half is a small fraction of the window's per-step compute,
  instead of trusting the hand default of 8 on every box;
* **delta-compacted flush** — whether the packed flush buffer should be
  capped to the few lanes a window actually touches (overflow falls
  back to the full buffer, ops/torcells_device.py).  ON only when the
  measured flush size slope (``flush_us_per_mb``) says the readback
  bytes saved beat the compaction's extra kernel cost — on a box where
  launches, not bytes, dominate the transfer, compaction is pure
  overhead and stays off.

What the tuner deliberately does NOT touch: **dispatch cadence**
(``--device-plane-batch-steps``) and **granule size**
(``--device-plane-granule-ms``).  Both are digest-BEARING — wake times
clamp to the consuming barrier and per-hop latency rounds up to the
granule, so changing either changes simulation RESULTS, not just wall
time.  The tuner's contract is the same as ``choose_exchange_mode``'s:
it may only ever choose between bit-identical executions (digest parity
tuned-vs-hand-defaults is by construction and pinned by
tests/test_autotune.py).  Cadence and granule are therefore reported at
their contract values with source ``contract``, and the launch
amortization they could have bought is converted into the
digest-NEUTRAL K instead.

Engagement rules (:func:`plan_dispatch` returns a :class:`TunePlan`
whose ``source`` records what decided):

* ``off``      — ``--device-autotune off``: the hand/CLI defaults run
  untouched (the escape hatch, and the parity oracle's other side);
* ``defaults`` — no calibration on this box, the model was refused, or
  the flow table sits outside the calibrated range (the
  no-extrapolation guard, ``CostModel.covers``): hand defaults, exactly
  the pre-16 behavior;
* ``model``    — the measured model shaped the plan; the predicted
  per-launch cost is recorded so obs/profiler.py's
  predicted-vs-measured band audits the decision live
  (``prof.model_stale`` fires when the tuned prediction misses).

A knob the user explicitly set (e.g. ``--superwindow-rounds 1`` in a
parity test) is ALWAYS honored — the tuner only moves knobs still at
their hand defaults.
"""

from __future__ import annotations

from typing import Optional

# hand defaults the tuner may move (must mirror core/options.py)
DEFAULT_K = 8
DEFAULT_CADENCE = 8

# ceiling on the tuned superwindow depth: the targets vector is padded
# to K (static kernel shape), and the negotiation loop is O(K) per
# round — past this the launch amortization has long flattened out
MAX_K = 64

# the fixed per-launch cost should be at most this fraction of the
# launch's per-step compute before deepening K stops paying
AMORTIZE_FRACTION = 8

# the compaction's extra kernel cost per launch (the capped pack is a
# couple of extra masked scatters): compaction must save at least this
# much predicted readback time to turn on
COMPACT_MIN_SAVINGS_US = 25.0


class TunePlan:
    """One box's tuned dispatch plan (immutable after plan_dispatch)."""

    __slots__ = ("source", "superwindow_rounds", "min_dispatch_steps",
                 "granule_source", "flush_compact", "flush_cap_chains",
                 "flush_cap_nodes", "predicted_step_us",
                 "predicted_fixed_us", "flush_bytes_cap_saved", "k_would")

    def __init__(self, source: str, superwindow_rounds: int,
                 min_dispatch_steps: int, flush_compact: bool = False,
                 flush_cap_chains: int = 0, flush_cap_nodes: int = 0,
                 predicted_step_us: float = 0.0,
                 predicted_fixed_us: float = 0.0,
                 flush_bytes_cap_saved: int = 0,
                 k_would: Optional[int] = None):
        self.source = source
        self.superwindow_rounds = superwindow_rounds
        self.min_dispatch_steps = min_dispatch_steps
        # what the model WOULD have chosen for K had nothing pinned it —
        # equals superwindow_rounds when the tuner actually decided (or
        # had no model to decide with); diverges when a user-set K or
        # ``--device-autotune off`` overrode a live model's preference
        self.k_would = superwindow_rounds if k_would is None else k_would
        # cadence + granule are digest-bearing: always contract values
        self.granule_source = "contract"
        self.flush_compact = flush_compact
        self.flush_cap_chains = flush_cap_chains
        self.flush_cap_nodes = flush_cap_nodes
        self.predicted_step_us = predicted_step_us
        self.predicted_fixed_us = predicted_fixed_us
        self.flush_bytes_cap_saved = flush_bytes_cap_saved

    def metrics(self) -> dict:
        """The decision's audit trail, published under ``prof.*`` (the
        same registry namespace launch attribution uses, so bench rows
        pick these up through the existing prefix copy)."""
        return {
            "prof.autotune_source": self.source,
            "prof.autotune_k": self.superwindow_rounds,
            "prof.autotune_k_would": self.k_would,
            "prof.autotune_cadence": self.min_dispatch_steps,
            "prof.autotune_granule": self.granule_source,
            "prof.autotune_flush_compact": int(self.flush_compact),
            "prof.autotune_predicted_us": round(
                self.predicted_step_us * self.min_dispatch_steps
                + self.predicted_fixed_us, 1),
        }


def _tuned_k(model, per_step_us: float, cadence: int) -> int:
    """Deepen K until the fixed per-launch transfer is <=
    1/AMORTIZE_FRACTION of the launch's per-step compute.  Never
    shallower than the hand default — a box where the fixed cost is
    already negligible keeps today's behavior bit for bit."""
    fixed = model.transfer_us()
    if per_step_us <= 0:
        return DEFAULT_K
    k = -(-(AMORTIZE_FRACTION * fixed) // (per_step_us * max(cadence, 1)))
    return max(DEFAULT_K, min(MAX_K, int(k)))


def flush_caps(n_chains: int, n_nodes: int) -> tuple:
    """The capped flush sections: generous enough that a typical window
    (a handful of completions, the active lanes' node deltas) fits, and
    an overflowing one is detected from the header's TRUE counts and
    re-read full-length (ops/torcells_device.parse_flush)."""
    cap_c = max(16, min(n_chains, -(-n_chains // 8)))
    cap_h = max(64, min(n_nodes, -(-n_nodes // 4)))
    return int(cap_c), int(cap_h)


def plan_dispatch(model, model_status: str, options,
                  n_flows: int, n_chains: int, n_nodes: int,
                  exchange_tick_us: float = 0.0) -> TunePlan:
    """Build the dispatch plan for one plane.

    ``model`` may be None (uncalibrated/refused box); ``n_flows`` is the
    kernel's flow-row count (the step-cost key), ``n_chains``/``n_nodes``
    size the flush buffer the compaction decision prices."""
    k_opt = max(1, int(getattr(options, "superwindow_rounds", DEFAULT_K)))
    cadence = max(1, int(getattr(options, "device_plane_batch_steps",
                                 DEFAULT_CADENCE)))
    autotune = str(getattr(options, "device_autotune", "on") or "on")
    usable = (model is not None and model_status == "loaded"
              and model.covers(n_flows))
    if autotune == "off":
        # still RECORD what the model would have chosen (ISSUE 18): a
        # pinned run's metrics carry the counterfactual K, so perf
        # triage can see how far the hand value sits from the tuned one
        k_would = None
        if usable:
            per_step = model.step_us(n_flows) + max(exchange_tick_us, 0.0)
            k_would = _tuned_k(model, per_step, cadence)
        return TunePlan("off", k_opt, cadence, k_would=k_would)
    if not usable:
        # no measured basis on this box (or the table is outside the
        # calibrated range): hand defaults, exactly the pre-16 loop
        return TunePlan("defaults", k_opt, cadence)
    per_step = model.step_us(n_flows) + max(exchange_tick_us, 0.0)
    # a knob the user moved off its hand default is theirs, not ours —
    # but the preference is computed regardless, so the audit trail
    # records the would-have-chosen K even when the knob is pinned
    k_model = _tuned_k(model, per_step, cadence)
    k = k_model if k_opt == DEFAULT_K else k_opt
    # delta-compacted flush: ON only when the measured size slope says
    # the readback bytes saved beat the compaction's extra kernel work
    from ..ops.torcells_device import flush_len
    cap_c, cap_h = flush_caps(n_chains, n_nodes)
    full = flush_len(n_chains, n_nodes)
    capped = flush_len(n_chains, n_nodes, cap_c, cap_h)
    bytes_saved = (full - capped) * 8
    compact = model.flush_savings_us(bytes_saved) > COMPACT_MIN_SAVINGS_US
    return TunePlan("model", k, cadence,
                    flush_compact=compact,
                    flush_cap_chains=cap_c if compact else 0,
                    flush_cap_nodes=cap_h if compact else 0,
                    predicted_step_us=per_step,
                    predicted_fixed_us=model.transfer_us(),
                    flush_bytes_cap_saved=bytes_saved if compact else 0,
                    k_would=k_model)
