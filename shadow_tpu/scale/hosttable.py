"""HostTable: struct-of-arrays host state for internet-scale runs.

The eager boot path (core/controller.py) materializes one ``Host`` — plus
two interfaces, a router, a tracker, an RNG stream, and its ``Process``
objects — per ``quantity`` expansion.  At 100k hosts that is gigabytes of
Python objects and minutes of boot before the first round runs, even
though in a device-plane workload ~all of those hosts never execute a
single host-side event (ROADMAP item 2; the batch-scheduling playbook of
arxiv 2002.07062: device-resident work needs array rows, not objects).

The table replaces that with numpy columns (ids, ips, topology rows,
resolved bandwidths, token-bucket remainders, tracker byte/packet
counters, per-host RNG key lanes) plus ONE ``_HostGroup`` record per
config entry.  Everything a quiet host contributes to the simulation —
its DNS entry, its topology attachment, its digest state, its next boot
event time — is derived arithmetically from those columns:

* **names** are ``f"{group.id}{q+1}"`` computed on demand, never stored;
* **IPs** are a contiguous DNS block (``DNS.reserve_block``), so
  name<->ip resolution is arithmetic; an ``Address`` object is built
  lazily on first resolve;
* **RNG keys** are the vectorized ``derive(root, "host", id)`` family
  (``rng.derive_np``) — one threefry call for a whole group, bitwise
  identical to the scalar chain each eager ``Host`` performs;
* **wake times** (the earliest boot event a host would have scheduled:
  first process start/stop, heartbeat) feed the engine's window
  computation through ``Scheduler.next_event_time``, so round boundaries
  are identical to the eager run's.

A full ``Host`` is *materialized* only when the simulation first needs
it: the round-top promotion sweep (``promote_due``) materializes rows
whose wake time falls inside the new window and replays the exact boot
sequence the eager path ran at t=0 (same event times, same per-host
sequence numbers, same RNG counters), and ``Engine.host_by_ip/name``
materialize on lookup when another host's traffic reaches a quiet row.
Digest parity table-on vs table-off is therefore by construction —
tests/test_scale.py pins it on tor200 + star across serial/tpu/procs.

Device-plane integration: rows referenced by plane nodes register their
node indices here; the plane's per-node byte deltas fold into the
table's tracker columns at observation points (digest, teardown) exactly
as ``Tracker.pull_device`` folds them for materialized hosts.
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import stime
from ..core.defs import (CONFIG_MTU, INTERFACE_CAPACITY_FACTOR,
                         INTERFACE_REFILL_INTERVAL_NS)
from ..core.logger import get_logger
from ..core.rng import derive_np
from ..routing.address import Address, ip_to_int

_MAX = stime.SIM_TIME_MAX


def bucket_capacity(rate_kibps: int) -> int:
    """A fresh TokenBucket's bytes_remaining for ``rate_kibps`` — the same
    arithmetic (and the same constants) as
    host.network_interface.TokenBucket.__init__, kept in sync by the
    table-vs-object digest parity gates."""
    time_factor = stime.SIM_TIME_SEC // INTERFACE_REFILL_INTERVAL_NS
    refill = (rate_kibps * 1024) // time_factor
    return refill * INTERFACE_CAPACITY_FACTOR + CONFIG_MTU


class _HostGroup:
    """One config entry (``HostConfig``) worth of table rows: everything
    that is identical across its quantity expansion lives here once."""

    __slots__ = ("hc", "params_kwargs", "first_row", "count", "first_id",
                 "ip_base", "per_row_ips", "process_specs", "wake",
                 "add_process", "heartbeat_sec", "n_boot_events")

    def __init__(self, hc, params_kwargs, first_row, count, first_id):
        self.hc = hc
        self.params_kwargs = params_kwargs
        self.first_row = first_row
        self.count = count
        self.first_id = first_id
        self.ip_base = 0            # block-reserved groups
        self.per_row_ips = None     # hint groups: explicit per-row ips
        self.process_specs = []     # (ProcessConfig, app_path, args)
        self.wake = _MAX
        self.add_process = None     # controller-provided (host, pc) adder
        self.heartbeat_sec = 0
        self.n_boot_events = 0      # boot events eager mode would schedule

    def name_of(self, q: int) -> str:
        return self.hc.id if self.hc.quantity == 1 else f"{self.hc.id}{q + 1}"

    def row_of_name(self, name: str) -> Optional[int]:
        hc = self.hc
        if hc.quantity == 1:
            return self.first_row if name == hc.id else None
        if not name.startswith(hc.id):
            return None
        suffix = name[len(hc.id):]
        if not suffix.isdigit():
            return None
        q = int(suffix) - 1
        if 0 <= q < self.count and suffix == str(q + 1):
            # the canonical spelling only: "client01" must NOT alias
            # client1 — eager boot would fail to resolve it, so the lazy
            # path must too
            return self.first_row + q
        return None


class HostTable:
    """The struct-of-arrays host plane.  Built by the Controller at setup
    (reserve_group per config entry, then freeze()), attached to the
    engine as ``engine.host_table``."""

    def __init__(self, engine, capacity: int):
        self.engine = engine
        self.capacity = capacity
        self.rows = 0
        self.groups: List[_HostGroup] = []
        self._lock = threading.RLock()
        # columns (int64 unless noted)
        self.ids = np.zeros(capacity, dtype=np.int64)
        self.ips = np.zeros(capacity, dtype=np.int64)
        self.topo_rows = np.zeros(capacity, dtype=np.int64)
        self.bw_down = np.zeros(capacity, dtype=np.int64)
        self.bw_up = np.zeros(capacity, dtype=np.int64)
        # iface token-bucket state (full buckets until first host-side use,
        # which requires materialization — kept as explicit columns so the
        # digest contract is visible, and so future vectorized planes can
        # spend from them directly)
        self.snd_remaining = np.zeros(capacity, dtype=np.int64)
        self.rcv_remaining = np.zeros(capacity, dtype=np.int64)
        # tracker counters (remote in/out; the device plane's per-node byte
        # deltas fold in here for table-resident hosts)
        self.rx_bytes = np.zeros(capacity, dtype=np.int64)
        self.rx_pkts = np.zeros(capacity, dtype=np.int64)
        self.tx_bytes = np.zeros(capacity, dtype=np.int64)
        self.tx_pkts = np.zeros(capacity, dtype=np.int64)
        # per-host RNG key lanes (derive(root, "host", id), vectorized)
        self.rng_keys = np.zeros(capacity, dtype=np.uint64)
        self.group_idx = np.zeros(capacity, dtype=np.int32)
        self.materialized = np.zeros(capacity, dtype=bool)
        self._grp_remaining: List[int] = []   # owned, unmaterialized rows
        self._wake_heap: List[Tuple[int, int]] = []
        # device-plane node registration: row -> node index list
        self._dev_nodes: Dict[int, List[int]] = {}
        self._dev_plane = None
        # flows (processless device-plane transfers): raw per-row tuples
        # (row, route_down, route_up, down_bytes, up_bytes, start_ns)
        self.flows: List[tuple] = []
        self.materialized_count = 0
        self._closed_counters = False

    # -- construction (Controller.setup) ----------------------------------
    def reserve_group(self, hc, params_kwargs: dict, add_process) -> None:
        """Register one config entry's rows: ids, DNS, topology placement,
        resolved bandwidths, RNG keys, wake time.  No Host objects."""
        engine = self.engine
        n = hc.quantity
        first_row = self.rows
        first_id = engine.next_host_id()
        for _ in range(n - 1):
            engine.next_host_id()
        grp = _HostGroup(hc, params_kwargs, first_row, n, first_id)
        grp.add_process = add_process
        # name-domain collision guard: eager boot would raise at
        # dns.register on a duplicate name; block-reserved groups resolve
        # names lazily, so prefix-related groups (id "client" x20 vs a
        # separate "client12") must be rejected here instead.  Only
        # prefix-related pairs can collide, and those are rare enough to
        # scan the smaller group's name domain outright.
        for other in self.groups:
            a, b = grp, other
            if not (a.hc.id.startswith(b.hc.id)
                    or b.hc.id.startswith(a.hc.id)):
                continue
            small = a if a.count <= b.count else b
            big = b if small is a else a
            for q in range(small.count):
                if big.row_of_name(small.name_of(q)) is not None:
                    raise ValueError(
                        f"hostname {small.name_of(q)!r} is claimed by both "
                        f"host groups {a.hc.id!r} and {b.hc.id!r}")
        gidx = len(self.groups)
        self.groups.append(grp)
        sl = slice(first_row, first_row + n)
        ids = np.arange(first_id, first_id + n, dtype=np.int64)
        self.ids[sl] = ids
        self.group_idx[sl] = gidx
        # RNG key lanes: one vectorized threefry call for the whole group
        self.rng_keys[sl] = derive_np(engine.root_key, "host", ids)
        # DNS: a contiguous block when one is cleanly available at the
        # counter (arithmetic name<->ip, lazy Addresses); per-row
        # registration otherwise — for ip-hint groups, and whenever the
        # candidate block would collide with a registered IP or a
        # restricted range (unique_ip skips only the colliding addresses,
        # so the assignment must too, or table-on/off IPs diverge)
        block = None if hc.ip_hint else engine.dns.try_reserve_block(n)
        if block is None:
            grp.per_row_ips = np.zeros(n, dtype=np.int64)
            req = ip_to_int(hc.ip_hint) if hc.ip_hint else None
            for q in range(n):
                addr = engine.dns.register(first_id + q, grp.name_of(q), req)
                grp.per_row_ips[q] = addr.ip
            self.ips[sl] = grp.per_row_ips
        else:
            grp.ip_base = block
            self.ips[sl] = np.arange(block, block + n, dtype=np.int64)
        # topology attachment: one call per row (memoized candidate lists
        # make it cheap), consuming each host stream's draw #0 exactly as
        # Host.setup would — the vectorized first-draw family
        from ..core.rng import bits64_keys_np
        draws = bits64_keys_np(self.rng_keys[sl], 0)
        topo = engine.topology
        bw_cache: Dict[int, Tuple[int, int]] = {}
        for q in range(n):
            row = first_row + q
            ip = int(self.ips[row])
            vidx = topo.attach_host(
                ip, ip_hint=hc.ip_hint, city_hint=hc.city_hint,
                country_hint=hc.country_hint, geocode_hint=hc.geocode_hint,
                type_hint=hc.type_hint, choice_rand=int(draws[q]))
            down, up = hc.bandwidth_down_kibps, hc.bandwidth_up_kibps
            if down <= 0 or up <= 0:
                vbw = bw_cache.get(vidx)
                if vbw is None:
                    vbw = bw_cache[vidx] = topo.vertex_bandwidth_kibps(vidx)
                if down <= 0:
                    down = vbw[0] or 102400
                if up <= 0:
                    up = vbw[1] or 102400
            self.bw_down[row] = down
            self.bw_up[row] = up
            self.topo_rows[row] = topo.row_for_ip(ip)
        self.snd_remaining[sl] = [bucket_capacity(int(b))
                                  for b in self.bw_up[sl]]
        self.rcv_remaining[sl] = [bucket_capacity(int(b))
                                  for b in self.bw_down[sl]]
        self.rows += n
        # wake: the earliest boot event the eager path would schedule
        # (events at or past end_time are dropped by schedule_task and
        # never pend, so they are excluded here too)
        cands = []
        # heartbeats are NOT boot candidates: the eager path no longer
        # schedules a per-host heartbeat event either — one engine-level
        # sweep per interval covers rows and Hosts alike (ISSUE 10), so a
        # quiet row is never materialized just to report its counters
        grp.heartbeat_sec = params_kwargs.get("heartbeat_interval_sec", 0)
        for pc in hc.processes:
            cands.append(stime.from_seconds(pc.start_time_sec))
            if pc.stop_time_sec:
                cands.append(stime.from_seconds(pc.stop_time_sec))
        cands = [c for c in cands if c < engine.end_time]
        grp.wake = min(cands) if cands else _MAX
        grp.n_boot_events = len(cands)
        owned = self._owned_count(grp)
        self._grp_remaining.append(owned)
        if grp.wake < _MAX and owned:
            heapq.heappush(self._wake_heap, (grp.wake, gidx))
        # flows: expanded to per-row route tuples (scale/genscen.py owns
        # the tor-shape path derivation)
        if hc.flows:
            from .genscen import expand_flows
            self.flows.extend(expand_flows(self, grp))

    def add_group_process_spec(self, grp: _HostGroup, pc, app_path: str,
                               args: List[str]) -> None:
        grp.process_specs.append((pc, app_path, args))

    def freeze(self) -> None:
        """End of reservation: install the lazy DNS resolver and log."""
        self.engine.dns.lazy_resolver = self._lazy_resolve
        get_logger().message(
            "scale",
            f"host table: {self.rows} rows in {len(self.groups)} groups, "
            f"{self.nbytes() // 1024} KiB of columns, "
            f"{len(self.flows)} device flows")

    def nbytes(self) -> int:
        """Total column bytes (the exact part of the bytes-per-host
        budget; scale/memprof.py adds the RSS view)."""
        cols = (self.ids, self.ips, self.topo_rows, self.bw_down, self.bw_up,
                self.snd_remaining, self.rcv_remaining, self.rx_bytes,
                self.rx_pkts, self.tx_bytes, self.tx_pkts, self.rng_keys,
                self.group_idx, self.materialized)
        return int(sum(c.nbytes for c in cols))

    # -- ownership / lookup ------------------------------------------------
    def _owns_id(self, hid: int) -> bool:
        eng = self.engine
        return eng.shard_count == 1 \
            or (hid - 1) % eng.shard_count == eng.shard_id

    def _owned_count(self, grp: _HostGroup) -> int:
        if self.engine.shard_count == 1:
            return grp.count
        return sum(1 for q in range(grp.count)
                   if self._owns_id(grp.first_id + q))

    def row_of_name(self, name: str) -> Optional[int]:
        for grp in self.groups:
            row = grp.row_of_name(name)
            if row is not None:
                return row
        return None

    def row_of_ip(self, ip: int) -> Optional[int]:
        for grp in self.groups:
            if grp.per_row_ips is not None:
                hits = np.flatnonzero(grp.per_row_ips == ip)
                if len(hits):
                    return grp.first_row + int(hits[0])
            elif grp.ip_base <= ip < grp.ip_base + grp.count:
                return grp.first_row + (ip - grp.ip_base)
        return None

    def row_of_id(self, hid: int) -> Optional[int]:
        for grp in self.groups:
            if grp.first_id <= hid < grp.first_id + grp.count:
                return grp.first_row + (hid - grp.first_id)
        return None

    def name_of(self, row: int) -> str:
        grp = self.groups[self.group_idx[row]]
        return grp.name_of(row - grp.first_row)

    def _lazy_resolve(self, name: Optional[str] = None,
                      ip: Optional[int] = None) -> Optional[Address]:
        """DNS fallback: build (and register) the Address for a table row
        on first resolution — quiet hosts that nobody ever names pay no
        Address object at all."""
        row = self.row_of_name(name) if name is not None else \
            self.row_of_ip(ip)
        if row is None:
            return None
        addr = Address(int(self.ids[row]), int(self.ips[row]),
                       self.name_of(row))
        self.engine.dns.adopt(addr)
        return addr

    def unmaterialized_count(self) -> int:
        return self.rows - self.materialized_count

    # -- window integration ------------------------------------------------
    def next_wake(self) -> int:
        """Earliest boot-event time over owned, unmaterialized rows —
        folded into Scheduler.next_event_time so windows land on the same
        boundaries as the eager run's."""
        heap = self._wake_heap
        while heap and self._grp_remaining[heap[0][1]] <= 0:
            heapq.heappop(heap)
        return heap[0][0] if heap else _MAX

    def pending_boot_events(self) -> int:
        """Deferred boot events for owned, unmaterialized rows — the
        events an eager boot would already have sitting in the queues
        (none executed: an unmaterialized row's wake is still in the
        future).  Folded into Scheduler.pending_count so MID-RUN state
        digests (checkpoints) carry the same pending_events either way."""
        return sum(self.groups[g].n_boot_events * rem
                   for g, rem in enumerate(self._grp_remaining) if rem > 0)

    def heartbeat_intervals(self) -> set:
        """Distinct nonzero heartbeat intervals across groups with owned
        rows — the engine's sweep scheduler unions these with the live
        hosts' intervals.  Groups fully owned by other shards contribute
        nothing (their owners sweep them)."""
        return {g.heartbeat_sec for g in self.groups
                if g.heartbeat_sec > 0 and self._owned_count(g) > 0}

    def heartbeat_rows(self, interval_sec: int):
        """The sweep tick's table leg, part 1: every owned UNMATERIALIZED
        row on this interval as sorted ``(host_id, row, level, emit)``
        tuples — the engine merges them with the live hosts by id so the
        heartbeat log keeps GLOBAL host-id order.  No Host is ever
        materialized to heartbeat (the eager path's per-host events used
        to force exactly that).  Same emit gating as Tracker.heartbeat:
        with the log level filtered and the registry off, 100k quiet rows
        cost one group scan."""
        from ..core.logger import get_logger
        from ..obs.metrics import get_metrics
        registry = getattr(self.engine, "metrics", None) or get_metrics()
        log = get_logger()
        out = []
        for grp in self.groups:
            if grp.heartbeat_sec != interval_sec:
                continue
            level = grp.params_kwargs.get("heartbeat_log_level") \
                or "message"
            emit = log.would_log(level)
            if not emit and not registry.enabled:
                continue
            for q in range(grp.count):
                row = grp.first_row + q
                hid = grp.first_id + q
                if not self.materialized[row] and self._owns_id(hid):
                    out.append((hid, row, level, emit))
        out.sort()
        return out

    def heartbeat_row(self, entry, now: int) -> None:
        """Part 2: report ONE quiet row from columns — registry record +
        the SAME legacy line Tracker.heartbeat emits (one shared
        formatter, so the two surfaces cannot drift)."""
        from ..core.logger import get_logger
        from ..host.tracker import format_heartbeat_line
        from ..obs.metrics import get_metrics
        hid, row, level, emit = entry
        self._fold_device_row(row)
        name = self.name_of(row)
        vals = {"rx": int(self.rx_bytes[row]),
                "tx": int(self.tx_bytes[row]),
                "rx_pkts": int(self.rx_pkts[row]),
                "tx_pkts": int(self.tx_pkts[row]),
                "retrans": 0, "drops": 0, "proc_ms": 0.0}
        registry = getattr(self.engine, "metrics", None) or get_metrics()
        registry.record_host_heartbeat(name, vals)
        if emit:
            get_logger().log(level, "tracker",
                             format_heartbeat_line(name, vals),
                             sim_time=now)

    def promote_due(self, window_end: int) -> None:
        """Round-top promotion sweep: materialize + boot every owned row
        whose first boot event falls inside the new window.  Runs on the
        engine main thread between rounds (workers parked)."""
        heap = self._wake_heap
        while heap:
            wake, gidx = heap[0]
            if self._grp_remaining[gidx] <= 0:
                heapq.heappop(heap)
                continue
            if wake >= window_end:
                return
            heapq.heappop(heap)
            grp = self.groups[gidx]
            for q in range(grp.count):
                row = grp.first_row + q
                if not self.materialized[row] \
                        and self._owns_id(grp.first_id + q):
                    self.materialize_row(row)

    # -- materialization ---------------------------------------------------
    def materialize_row(self, row: int):
        """Promote one table row to a full Host, replaying exactly what
        the eager path did at setup + boot: same HostParams, same derived
        RNG stream (counter advanced past the topology-attach draw), same
        process construction order, and — for owned rows after boot — the
        same boot events at their original times (a transient worker clock
        of 0 reproduces schedule_task's ``t = now + delay`` arithmetic)."""
        with self._lock:
            if self.materialized[row]:
                return self.engine.hosts.get(int(self.ids[row]))
            from ..host.host import Host, HostParams
            engine = self.engine
            grp = self.groups[self.group_idx[row]]
            q = row - grp.first_row
            hid = int(self.ids[row])
            params = HostParams(name=grp.name_of(q),
                                bw_down_kibps=int(self.bw_down[row]),
                                bw_up_kibps=int(self.bw_up[row]),
                                **grp.params_kwargs)
            host = Host(hid, params, engine.root_key)
            # the topology-attach draw was consumed (vectorized) at reserve
            host.random.counter = 1
            addr = engine.dns.resolve_name(params.name)
            host.topo_row = int(self.topo_rows[row])
            engine.adopt_host(host, addr, owned=self._owns_id(hid))
            # tracker seed: bytes the device plane already folded into the
            # table's columns while the host was a row
            t = host.tracker
            for ctr, nbytes, npkts in (
                    (t.in_remote, int(self.rx_bytes[row]),
                     int(self.rx_pkts[row])),
                    (t.out_remote, int(self.tx_bytes[row]),
                     int(self.tx_pkts[row]))):
                if nbytes or npkts:
                    ctr.bytes_total += nbytes
                    ctr.bytes_data += nbytes
                    ctr.packets_total += npkts
                    ctr.packets_data += npkts
            nodes = self._dev_nodes.get(row)
            if nodes is not None and self._dev_plane is not None:
                t._device_feed = (self._dev_plane, nodes)
            for pc, _path, _args in grp.process_specs:
                grp.add_process(host, pc)
            self.materialized[row] = True
            self.materialized_count += 1
            if self._owns_id(hid):
                self._grp_remaining[self.group_idx[row]] -= 1
                if getattr(engine, "_boot_done", False):
                    self._replay_boot(host)
            return host

    def _replay_boot(self, host) -> None:
        from ..core.worker import Worker, current_worker, set_current_worker
        w = current_worker()
        transient = w is None
        if transient:
            w = Worker(0, self.engine)
            set_current_worker(w)
        saved = (w.now, w.active_host)
        w.now = 0
        w.active_host = host
        try:
            host.boot()
            for proc in host.processes:
                proc.schedule_start(w)
        finally:
            w.now, w.active_host = saved
            if transient:
                set_current_worker(None)
                w.finish()

    def materialize_by_ip(self, ip: int):
        row = self.row_of_ip(ip)
        return self.materialize_row(row) if row is not None else None

    def materialize_by_id(self, hid: int):
        row = self.row_of_id(hid)
        return self.materialize_row(row) if row is not None else None

    def materialize_by_name(self, name: str):
        row = self.row_of_name(name)
        return self.materialize_row(row) if row is not None else None

    def materialize_all(self) -> None:
        for row in range(self.rows):
            if not self.materialized[row]:
                self.materialize_row(row)

    # -- device-plane integration -----------------------------------------
    def plane_host_info(self, name: str) -> Optional[Tuple[int, int, int]]:
        """(topo_row, bw_up, bw_down) for the device plane's node layout —
        reads columns, never materializes."""
        row = self.row_of_name(name)
        if row is None:
            return None
        return (int(self.topo_rows[row]), int(self.bw_up[row]),
                int(self.bw_down[row]))

    def set_device_nodes(self, name: str, nodes: List[int], plane) -> bool:
        """Register a table row's plane node indices.  Returns False when
        ``name`` is not a table row (caller wires the Host directly)."""
        row = self.row_of_name(name)
        if row is None:
            return False
        self._dev_nodes[row] = nodes
        self._dev_plane = plane
        return True

    def _fold_device_row(self, row: int) -> None:
        """The table-side twin of Tracker.pull_device: fold the plane's
        pending per-node byte deltas into this row's tracker columns."""
        plane = self._dev_plane
        nodes = self._dev_nodes.get(row)
        if plane is None or nodes is None or self.materialized[row]:
            return
        for i in nodes:
            ncells, nbytes = plane.take_node_delta(i)
            if not nbytes:
                continue
            if plane.node_kind[i] == "tx":
                self.tx_bytes[row] += nbytes
                self.tx_pkts[row] += ncells
            else:
                self.rx_bytes[row] += nbytes
                self.rx_pkts[row] += ncells

    def flush_device_nodes(self, plane) -> None:
        """Teardown/observation sweep over every row that contributes
        plane nodes (materialized rows pull through their Tracker)."""
        for row in sorted(self._dev_nodes):
            if self.materialized[row]:
                host = self.engine.hosts.get(int(self.ids[row]))
                if host is not None:
                    host.tracker.pull_device()
            else:
                self._fold_device_row(row)

    # -- process/flow spec iteration (device-plane build) ------------------
    def iter_process_specs(self):
        """(host_id, host_name, app_path, args) for every deferred process,
        in host-id order — what build_plane_from_engine scans in place of
        ``host.processes`` for table rows."""
        for grp in self.groups:
            if not grp.process_specs:
                continue
            for q in range(grp.count):
                if self.materialized[grp.first_row + q]:
                    continue        # scanned via the live Host instead
                name = grp.name_of(q)
                for _pc, app_path, args in grp.process_specs:
                    yield grp.first_id + q, name, app_path, args

    # -- digest state ------------------------------------------------------
    def host_state(self, row: int) -> Dict:
        """The ``checkpoint._host_state`` dict a quiet eager Host would
        produce, synthesized from columns (plain ints — the digest is
        canonical JSON and numpy scalars must not leak into it)."""
        from ..routing.address import LOCALHOST_IP
        self._fold_device_row(row)
        grp = self.groups[self.group_idx[row]]
        q = row - grp.first_row
        name = grp.name_of(q)
        lo_cap = bucket_capacity(0)
        return {
            "name": name,
            "descriptors": {},
            "tracker": (int(self.rx_bytes[row]), int(self.tx_bytes[row]),
                        int(self.rx_pkts[row]), int(self.tx_pkts[row]),
                        0, 0),
            "processes": [(f"{name}.{pc.plugin}", False, False, None)
                          for pc, _path, _args in grp.process_specs],
            "ifaces": {LOCALHOST_IP: (lo_cap, lo_cap),
                       int(self.ips[row]): (int(self.snd_remaining[row]),
                                            int(self.rcv_remaining[row]))},
        }

    def host_states(self) -> Dict[int, Dict]:
        """Digest states for every owned, unmaterialized row (materialized
        hosts are collected through engine.hosts as usual)."""
        out: Dict[int, Dict] = {}
        for grp in self.groups:
            for q in range(grp.count):
                row = grp.first_row + q
                hid = grp.first_id + q
                if not self.materialized[row] and self._owns_id(hid):
                    out[hid] = self.host_state(row)
        return out

    # -- teardown ----------------------------------------------------------
    def close_counters(self) -> None:
        """Balance the host ObjectCounter ledger for rows that never
        materialized (eager mode counts new at setup + free at teardown;
        table rows do both here, in bulk, so totals and the leak report
        match)."""
        if self._closed_counters:
            return
        self._closed_counters = True
        n = sum(1 for grp in self.groups
                for q in range(grp.count)
                if not self.materialized[grp.first_row + q]
                and self._owns_id(grp.first_id + q))
        if n:
            self.engine.counters.count_new("host", n)
            self.engine.counters.count_free("host", n)

    def stats(self) -> Dict[str, int]:
        return {
            "scale.table_rows": self.rows,
            "scale.materialized_hosts": self.materialized_count,
            "scale.table_bytes": self.nbytes(),
            "scale.device_flows": len(self.flows),
        }
