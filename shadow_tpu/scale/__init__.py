"""Internet-scale tier (ROADMAP item 2): struct-of-arrays host state,
lazy host materialization, generated 100k-host scenarios, and memory
accounting.

* :mod:`.hosttable` — the HostTable: every configured host boots as a few
  numpy column entries; a full ``Host`` object exists only once the host
  actually needs plugin execution or a host-side event.
* :mod:`.genscen` — deterministic parameterized scenario generators
  (star100k, phold100k, tor100k) emitting ``Configuration`` objects
  directly instead of multi-megabyte XML strings.
* :mod:`.memprof` — bytes-per-host and peak-RSS accounting published
  through the metrics registry, so bench and CI gate memory the way they
  gate digests.
"""
