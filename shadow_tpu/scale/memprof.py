"""Memory accounting for the scale tier: bytes-per-host and peak RSS,
published through the PR-3 metrics registry so bench and CI gate memory
the way they gate digests (``make bench-smoke`` reads these back from the
metrics JSONL via tools/trace_report.py --metrics).

Two views, both honest about what they measure:

* **RSS view** — resident-set deltas around Controller.setup() plus the
  process peak (``getrusage`` ru_maxrss).  Includes interpreter overhead,
  numpy pools, everything: the number an operator's OOM killer sees.
* **Table view** — the HostTable's exact column bytes per row: the
  marginal cost the struct-of-arrays design promises (~hundreds of bytes
  per quiet host vs ~10 KB per eager Host).
"""

from __future__ import annotations

import resource
from typing import Dict, Optional


def current_rss_bytes() -> int:
    """Resident set size from /proc (Linux); 0 when unreadable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def peak_rss_mb() -> float:
    """Process-lifetime peak RSS in MiB (ru_maxrss is KiB on Linux)."""
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024,
                 1)


class BootProfile:
    """Setup-phase memory/wall accounting: snapshot() before host
    registration, commit() after, then install() onto the engine's
    metrics registry as the 'scale' source."""

    def __init__(self):
        self.rss_before = 0
        self.rss_after = 0
        self.boot_sec = 0.0
        self.n_hosts = 0
        self._t0 = 0.0

    def snapshot(self) -> None:
        import time as _walltime
        self.rss_before = current_rss_bytes()
        self._t0 = _walltime.monotonic()

    def commit(self, n_hosts: int) -> None:
        import time as _walltime
        self.boot_sec = round(_walltime.monotonic() - self._t0, 3)
        self.rss_after = current_rss_bytes()
        self.n_hosts = max(1, n_hosts)

    def bytes_per_host(self) -> int:
        return max(0, self.rss_after - self.rss_before) // self.n_hosts

    def install(self, engine) -> None:
        engine.metrics.source("scale", lambda: scrape(engine, self))


def scrape(engine, profile: Optional[BootProfile]) -> Dict:
    """The 'scale' metrics source: boot cost + table occupancy.  Flat
    namespace, same registry bench.py reads flush/overlap numbers from."""
    out: Dict = {}
    if profile is not None:
        out["scale.boot_sec"] = profile.boot_sec
        out["scale.bytes_per_host"] = profile.bytes_per_host()
        out["scale.boot_rss_mb"] = round(profile.rss_after / (1024 * 1024),
                                         1)
    out["scale.peak_rss_mb"] = peak_rss_mb()
    table = getattr(engine, "host_table", None)
    if table is not None:
        out.update(table.stats())
        out["scale.table_bytes_per_host"] = \
            table.nbytes() // max(1, table.rows)
    return out
