"""Deterministic parameterized scenario generators for the scale tier.

Emits ``Configuration`` objects directly — a 100k-host scenario is a few
``HostConfig`` records with ``quantity`` + ``FlowConfig`` entries, not a
multi-megabyte XML string (the tor10k generator in tools/workloads.py
already spends seconds just formatting XML the parser then re-tokenizes).

Five families, mirroring the reference's experiment shapes plus the
production-traffic fleet (ROADMAP item 4):

* :func:`star`    — one fat server, N clients each pulling bulk bytes over
  the device-resident traffic plane (workload #2 scaled out; star10k /
  star100k).
* :func:`phold`   — the classic PDES scheduler benchmark (host-plane
  stress: every host runs a real plugin, so this measures materialization
  throughput rather than quiet-row capacity; phold100k).
* :func:`tor`     — the reference's Tor shape (~10% relays, ~1% servers,
  the rest clients on distinct seeded 3-hop circuits; tor100k) with all
  traffic as 5-hop device-plane chains.
* :func:`cdn`     — HTTP/1.1-shaped flash crowd: tens of thousands of
  clients hammering a few fat origins via seeded 2-hop chains (cdn20k);
  the contended resource is the origins' egress buckets.
* :func:`swarm`   — BitTorrent-style many-to-many piece exchange over a
  seeded uniform partner graph (swarm2k); the mesh partitioner's
  cut-fraction worst case.

All structure is seeded (numpy ``default_rng``) so a scenario built with
the same arguments is identical, and the per-client tor paths are derived
*vectorized* at table-reserve time (:func:`expand_flows`) — ONE
``FlowConfig`` describes 100k distinct circuits.

Usage: ``python -m shadow_tpu.tools.mkscenario`` (CLI) or
``genscen.build("star100k")`` programmatically; tests/test_scale.py pins
determinism and shape.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core import stime
from ..core.configuration import Configuration, FlowConfig, HostConfig, \
    ProcessConfig


def _distinct3(rng, n: int, upper: int):
    """n seeded triples of distinct ints in [0, upper), vectorized: draw
    from shrinking ranges and shift past earlier picks."""
    if upper < 3:
        raise ValueError(f"need >= 3 candidates, have {upper}")
    a = rng.integers(0, upper, n)
    b = rng.integers(0, upper - 1, n)
    b = b + (b >= a)
    c = rng.integers(0, upper - 2, n)
    lo = np.minimum(a, b)
    hi = np.maximum(a, b)
    c = c + (c >= lo)
    c = c + (c >= hi)
    return a, b, c


def expand_flows(table, grp) -> List[tuple]:
    """Expand a group's ``FlowConfig`` entries into per-row flow tuples
    ``(row, route_down, route_up, down_bytes, up_bytes, start_ns)`` for the
    device plane (scale/hosttable.py stores them; parallel/device_plane.py
    turns them into flow specs).  Routes are name tuples in chain order:
    star is the 2-hop pair (dest->client / client->dest), a ``path`` or
    tor-seeded spec is the 5-hop tor pair."""
    out: List[tuple] = []
    hc = grp.hc
    for fc in hc.flows:
        n = grp.count
        starts = np.full(n, stime.from_seconds(fc.start_time_sec),
                         dtype=np.int64)
        if fc.stagger_waves > 1 and fc.stagger_step_sec > 0:
            starts = starts + (np.arange(n) % fc.stagger_waves) \
                * stime.from_seconds(fc.stagger_step_sec)
        if fc.tor_path_seed is not None:
            rng = np.random.default_rng(fc.tor_path_seed)
            g, m, e = _distinct3(rng, n, fc.tor_relays)
            dests = rng.integers(0, max(fc.tor_servers, 1), n)
            rp, sp = fc.tor_relay_prefix, fc.tor_server_prefix
            for q in range(n):
                client = grp.name_of(q)
                guard = f"{rp}{int(g[q]) + 1}"
                middle = f"{rp}{int(m[q]) + 1}"
                exit_ = f"{rp}{int(e[q]) + 1}"
                # a quantity-1 group keeps its bare id as its host name
                # (the sub-100-host tor shape has ONE dest — fuzz-found)
                dest = sp if fc.tor_servers == 1 \
                    else f"{sp}{int(dests[q]) + 1}"
                out.append((grp.first_row + q,
                            (dest, exit_, middle, guard, client),
                            (client, guard, middle, exit_, dest),
                            fc.down_bytes, fc.up_bytes, int(starts[q])))
        elif fc.dest_seed is not None:
            # seeded 2-hop destination draw over <dest_prefix>1..dest_count
            # (cdn flash-crowd / swarm many-to-many): a draw landing on the
            # host itself shifts to the next name so a group can target its
            # own peers without ever flowing to itself
            if fc.dest_count < 1:
                raise ValueError(
                    f"flow on {hc.id!r}: dest_seed needs dest_count >= 1")
            rng = np.random.default_rng(fc.dest_seed)
            draws = rng.integers(0, fc.dest_count, n)
            for q in range(n):
                client = grp.name_of(q)
                d = int(draws[q])
                # quantity-1 dest groups keep their bare id as the name
                dest = fc.dest_prefix if fc.dest_count == 1 \
                    else f"{fc.dest_prefix}{d + 1}"
                if dest == client:
                    if fc.dest_count < 2:
                        raise ValueError(
                            f"flow on {hc.id!r}: dest_seed over a single-"
                            "host group cannot avoid self-flows")
                    dest = f"{fc.dest_prefix}{(d + 1) % fc.dest_count + 1}"
                out.append((grp.first_row + q,
                            (dest, client), (client, dest),
                            fc.down_bytes, fc.up_bytes, int(starts[q])))
        elif fc.path:
            hops = [h.strip() for h in fc.path.split(",") if h.strip()]
            if len(hops) != 3:
                raise ValueError(
                    f"flow path {fc.path!r}: tor-shaped flows need exactly "
                    "3 relays (guard,middle,exit)")
            guard, middle, exit_ = hops
            for q in range(n):
                client = grp.name_of(q)
                out.append((grp.first_row + q,
                            (fc.dest, exit_, middle, guard, client),
                            (client, guard, middle, exit_, fc.dest),
                            fc.down_bytes, fc.up_bytes, int(starts[q])))
        else:
            for q in range(n):
                client = grp.name_of(q)
                out.append((grp.first_row + q,
                            (fc.dest, client), (client, fc.dest),
                            fc.down_bytes, fc.up_bytes, int(starts[q])))
    return out


# ---------------------------------------------------------------------------
# scenario builders
# ---------------------------------------------------------------------------

def star(n_clients: int = 100_000, stoptime: int = 600,
         down_bytes: int = 64 * 1024, up_bytes: int = 0,
         start_sec: float = 2.0, stagger_waves: int = 8,
         stagger_step_sec: float = 1.0,
         server_bw_kibps: int = 4 * 1024 * 1024,
         client_down_kibps: int = 102400,
         client_up_kibps: int = 51200) -> Configuration:
    """star100k: one fat server, n processless clients each pulling
    ``down_bytes`` over the device plane.  Every client is a HostTable row
    for the whole run; the server's egress bucket is the contended
    resource (the torcells segment-cumsum's big segment)."""
    cfg = Configuration(stop_time_sec=stoptime)
    cfg.hosts.append(HostConfig(
        id="server", bandwidth_down_kibps=server_bw_kibps,
        bandwidth_up_kibps=server_bw_kibps))
    cfg.hosts.append(HostConfig(
        id="client", quantity=n_clients,
        bandwidth_down_kibps=client_down_kibps,
        bandwidth_up_kibps=client_up_kibps,
        flows=[FlowConfig(dest="server", start_time_sec=start_sec,
                          down_bytes=down_bytes, up_bytes=up_bytes,
                          stagger_waves=stagger_waves,
                          stagger_step_sec=stagger_step_sec)]))
    return cfg


def phold(n_hosts: int = 100_000, stoptime: int = 60,
          msgs_in_flight: int = 1, waves: int = 50,
          bw_kibps: int = 10240) -> Configuration:
    """phold100k: every host runs the real phold plugin (uniform
    all-to-all UDP).  A host-plane stress: hosts materialize in ``waves``
    staggered boot waves, measuring promotion throughput."""
    cfg = Configuration(stop_time_sec=stoptime)
    hc = HostConfig(id="phold", quantity=n_hosts,
                    bandwidth_down_kibps=bw_kibps,
                    bandwidth_up_kibps=bw_kibps)
    # one process config per boot wave would need per-row start times the
    # quantity expansion cannot express; a single start keeps the classic
    # phold shape (the reference's test_phold boots all hosts at once too)
    hc.processes.append(ProcessConfig(
        plugin="python:phold", start_time_sec=1.0,
        arguments=f"{n_hosts} {msgs_in_flight} 9000"))
    cfg.hosts.append(hc)
    return cfg


def tor(n_hosts: int = 100_000, stoptime: int = 600,
        down_bytes: int = 48 * 1024, up_bytes: int = 2 * 1024,
        start_sec: float = 2.0, stagger_waves: int = 16,
        stagger_step_sec: float = 1.0, seed: int = 42) -> Configuration:
    """tor100k on the reference's Tor shape: ~10% relays, ~1% fat servers,
    the rest clients — every client a distinct seeded 3-hop circuit, all
    traffic 5-hop device-plane chains, zero plugin processes."""
    n_relays = max(3, n_hosts // 10)
    n_servers = max(1, n_hosts // 100)
    n_clients = max(1, n_hosts - n_relays - n_servers)
    cfg = Configuration(stop_time_sec=stoptime)
    cfg.hosts.append(HostConfig(
        id="relay", quantity=n_relays,
        bandwidth_down_kibps=102400, bandwidth_up_kibps=102400))
    cfg.hosts.append(HostConfig(
        id="dest", quantity=n_servers,
        bandwidth_down_kibps=1048576, bandwidth_up_kibps=1048576))
    cfg.hosts.append(HostConfig(
        id="torclient", quantity=n_clients,
        bandwidth_down_kibps=51200, bandwidth_up_kibps=10240,
        flows=[FlowConfig(dest="", start_time_sec=start_sec,
                          down_bytes=down_bytes, up_bytes=up_bytes,
                          stagger_waves=stagger_waves,
                          stagger_step_sec=stagger_step_sec,
                          tor_path_seed=seed, tor_relays=n_relays,
                          tor_relay_prefix="relay",
                          tor_servers=n_servers,
                          tor_server_prefix="dest")]))
    return cfg


def cdn(n_clients: int = 20_000, n_origins: int = 4, stoptime: int = 120,
        down_bytes: int = 256 * 1024, up_bytes: int = 1024,
        start_sec: float = 2.0, stagger_waves: int = 2,
        stagger_step_sec: float = 1.0, seed: int = 1,
        origin_bw_kibps: int = 4 * 1024 * 1024,
        client_down_kibps: int = 102400,
        client_up_kibps: int = 20480) -> Configuration:
    """cdn20k: an HTTP/1.1-shaped flash crowd — tens of thousands of
    clients hammering a handful of fat origins at once.  Every client is a
    processless table row with ONE seeded 2-hop chain to a drawn origin
    (``dest_seed``), so the contended resource is the few origins' egress
    buckets (the segment-cumsum's few huge segments), the inverse of tor's
    many-small-segments shape."""
    if n_origins < 1:
        raise ValueError("cdn needs at least one origin")
    cfg = Configuration(stop_time_sec=stoptime)
    cfg.hosts.append(HostConfig(
        id="origin", quantity=n_origins,
        bandwidth_down_kibps=origin_bw_kibps,
        bandwidth_up_kibps=origin_bw_kibps))
    cfg.hosts.append(HostConfig(
        id="cdnclient", quantity=n_clients,
        bandwidth_down_kibps=client_down_kibps,
        bandwidth_up_kibps=client_up_kibps,
        flows=[FlowConfig(dest="", start_time_sec=start_sec,
                          down_bytes=down_bytes, up_bytes=up_bytes,
                          stagger_waves=stagger_waves,
                          stagger_step_sec=stagger_step_sec,
                          dest_seed=seed, dest_count=n_origins,
                          dest_prefix="origin")]))
    return cfg


def swarm(n_peers: int = 2_000, pieces: int = 4, stoptime: int = 120,
          piece_bytes: int = 64 * 1024, start_sec: float = 2.0,
          stagger_waves: int = 4, stagger_step_sec: float = 1.0,
          seed: int = 1, bw_down_kibps: int = 51200,
          bw_up_kibps: int = 25600) -> Configuration:
    """swarm2k: a BitTorrent-style many-to-many swarm — every peer
    exchanges ``pieces`` bidirectional transfers with seeded-drawn
    partners (self-draws shift to the next peer).  The uniform random
    partner graph is the mesh partitioner's worst case: cut fraction
    approaches (D-1)/D at D shards, so this is the cut-stress workload
    the cdn/star/tor shapes never produce."""
    if n_peers < 2:
        raise ValueError("swarm needs at least two peers")
    cfg = Configuration(stop_time_sec=stoptime)
    flows = [FlowConfig(dest="", start_time_sec=start_sec,
                        down_bytes=piece_bytes, up_bytes=piece_bytes,
                        stagger_waves=stagger_waves,
                        stagger_step_sec=stagger_step_sec,
                        dest_seed=seed * 7919 + k, dest_count=n_peers,
                        dest_prefix="peer")
             for k in range(pieces)]
    cfg.hosts.append(HostConfig(
        id="peer", quantity=n_peers, bandwidth_down_kibps=bw_down_kibps,
        bandwidth_up_kibps=bw_up_kibps, flows=flows))
    return cfg


def mixnet(n_hosts: int = 2_000, stoptime: int = 120,
           down_bytes: int = 16 * 1024, up_bytes: int = 2 * 1024,
           cover_cell_bytes: int = 512, cover_cells: int = 8,
           cover_interval_sec: float = 2.0, start_sec: float = 2.0,
           stagger_waves: int = 4, stagger_step_sec: float = 1.0,
           seed: int = 7) -> Configuration:
    """mixnet2k: an onion-route variant with constant-rate cover traffic
    (ROADMAP item 5's device-plane best case).  The tor shape — ~10%
    relays, ~1% fat exits, the rest clients on distinct seeded 3-hop
    circuits — plus, per client, ``cover_cells`` fixed-size cover cells
    fired at a constant ``cover_interval_sec`` cadence over distinct
    seeded circuits (a mixnet's loop cover: traffic flows whether or not
    payload does).  Every cell is a processless 5-hop device chain, so
    the plane carries cells-per-second x clients with zero host events —
    the highest chain-count-per-host shape in the family set."""
    if cover_cells < 1:
        raise ValueError("mixnet needs at least one cover cell")
    n_relays = max(3, n_hosts // 10)
    n_servers = max(1, n_hosts // 100)
    n_clients = max(1, n_hosts - n_relays - n_servers)
    cfg = Configuration(stop_time_sec=stoptime)
    cfg.hosts.append(HostConfig(
        id="mixrelay", quantity=n_relays,
        bandwidth_down_kibps=102400, bandwidth_up_kibps=102400))
    cfg.hosts.append(HostConfig(
        id="mixdest", quantity=n_servers,
        bandwidth_down_kibps=1048576, bandwidth_up_kibps=1048576))
    tor_kw = dict(tor_path_seed=seed, tor_relays=n_relays,
                  tor_relay_prefix="mixrelay", tor_servers=n_servers,
                  tor_server_prefix="mixdest")
    # the payload circuit, then the constant-rate cover cells — each cell
    # wave rides its own seeded circuit (route diversity is the point of
    # cover), launched at a fixed cadence with no stagger so the rate the
    # plane sees is genuinely constant per client
    flows = [FlowConfig(dest="", start_time_sec=start_sec,
                        down_bytes=down_bytes, up_bytes=up_bytes,
                        stagger_waves=stagger_waves,
                        stagger_step_sec=stagger_step_sec, **tor_kw)]
    for k in range(cover_cells):
        flows.append(FlowConfig(
            dest="", start_time_sec=start_sec + k * cover_interval_sec,
            down_bytes=cover_cell_bytes, up_bytes=cover_cell_bytes,
            **dict(tor_kw, tor_path_seed=seed * 8191 + k + 1)))
    cfg.hosts.append(HostConfig(
        id="mixclient", quantity=n_clients,
        bandwidth_down_kibps=51200, bandwidth_up_kibps=25600,
        flows=flows))
    return cfg


FAMILIES: Dict[str, object] = {
    "star": star, "phold": phold, "tor": tor, "cdn": cdn, "swarm": swarm,
    "mixnet": mixnet,
}

# name -> (family, preset kwargs).  build() MERGES overrides onto the
# preset (overrides win), so build("star10k", stoptime=5) is the 10k
# preset at stoptime 5, never the family default silently.
PRESETS: Dict[str, tuple] = {
    "star2k": ("star", dict(n_clients=2_000, stoptime=120,
                            stagger_waves=2)),
    "star10k": ("star", dict(n_clients=10_000, stoptime=300,
                             stagger_waves=4)),
    "star100k": ("star", dict(n_clients=100_000)),
    "phold10k": ("phold", dict(n_hosts=10_000)),
    "phold100k": ("phold", dict(n_hosts=100_000)),
    "tor10k": ("tor", dict(n_hosts=10_000, stoptime=300,
                           stagger_waves=8)),
    "tor100k": ("tor", dict(n_hosts=100_000)),
    "cdn2k": ("cdn", dict(n_clients=2_000, n_origins=3, stoptime=60)),
    "cdn20k": ("cdn", dict(n_clients=20_000, n_origins=4)),
    "swarm500": ("swarm", dict(n_peers=500, pieces=3, stoptime=60)),
    "swarm2k": ("swarm", dict(n_peers=2_000, pieces=4)),
    "mixnet500": ("mixnet", dict(n_hosts=500, stoptime=60,
                                 cover_cells=4)),
    "mixnet2k": ("mixnet", dict(n_hosts=2_000, cover_cells=8)),
}

# kept for callers that list/run the presets directly
NAMED: Dict[str, object] = {
    name: (lambda fam=fam, kw=kw: FAMILIES[fam](**kw))
    for name, (fam, kw) in PRESETS.items()
}


def _validate_overrides(fn, name: str, kw: Dict) -> None:
    """Reject unknown builder kwargs LOUDLY: a typo'd ``stoptme=`` must
    never silently build the default scenario — the fuzzer's repro files
    depend on override fidelity."""
    import inspect
    valid = set(inspect.signature(fn).parameters)
    unknown = sorted(set(kw) - valid)
    if unknown:
        raise ValueError(
            f"scenario {name!r}: unknown override(s) "
            f"{', '.join(unknown)}; valid: {', '.join(sorted(valid))}")


def family_fn(name: str):
    """The builder function behind a preset or family name."""
    if name in PRESETS:
        return FAMILIES[PRESETS[name][0]]
    for prefix in sorted(FAMILIES, key=len, reverse=True):
        if name.startswith(prefix):
            return FAMILIES[prefix]
    raise ValueError(f"unknown scenario {name!r}; "
                     f"known: {', '.join(sorted(PRESETS))}")


def build(name: str, **overrides) -> Configuration:
    """Build a named scenario.  A preset name (``star10k``) merges
    ``overrides`` onto the preset's kwargs; a family name (``star``) uses
    the overrides directly.  Unknown override names raise ValueError
    naming the valid set (never a silently-default scenario)."""
    fn = family_fn(name)
    kw = {**PRESETS[name][1], **overrides} if name in PRESETS \
        else dict(overrides)
    _validate_overrides(fn, name, kw)
    return fn(**kw)


def config_digest(cfg: Configuration) -> str:
    """Stable content digest of a Configuration (determinism gate for the
    generators: same arguments => same digest)."""
    import dataclasses
    import hashlib
    import json
    blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True,
                      separators=(",", ":"), default=str).encode()
    return hashlib.sha256(blob).hexdigest()
