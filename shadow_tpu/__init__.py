"""shadow-tpu: a TPU-native parallel discrete-event network simulator.

Capabilities of Shadow 1.14.0 (RWails/shadow), re-architected for JAX/XLA:
the per-round packet-propagation hot path (path latency, reliability draws,
bandwidth shaping, queue drains) runs as one batched device kernel, while the
CPU side keeps the deterministic event-order contract and runs protocol state
machines and virtual processes.

Three planes (see SURVEY.md §7):
  * control plane  — shadow_tpu.core      (config, hosts, rounds, policies)
  * data plane     — shadow_tpu.ops       (device-resident topology + packet
                      batches, jit/vmap round step, pjit sharding)
  * process plane  — shadow_tpu.process   (virtual processes / apps)
"""

__version__ = "0.1.0"

from .core import stime  # noqa: F401
