"""Deterministic circuit/flow partitioning for the mesh traffic plane.

The sharded kernel's exactness argument (see exchange.py) requires every
node's WHOLE flow segment to live on one shard: the per-tick greedy
bandwidth allocation is a cumsum within each node's segment, so splitting
a segment would change allocation order.  The unit of placement is
therefore the node segment, and the objective is to co-locate the nodes a
circuit's consecutive hops are paced by — every hop whose successor lives
on another shard costs one slot in the cross-shard exchange.

:func:`chain_partition` walks the chains (each flow has at most one
successor, so circuits are simple paths over node segments) in ascending
head order and assigns each first-seen node to the currently-filling
shard until its flow budget is reached — chain-adjacent nodes land
together, and shards stay balanced to within one node segment.  Pure
numpy + dict walking, runs once at plane build, deterministic for a given
flow table (pinned by tests/test_meshplane.py).

:func:`build_mesh_layout` turns an arbitrary segment-aligned node->shard
assignment into the padded sharded layout (the ``build_sharded_layout``
contract the single-device plane's sharding has used since PR 7: real
rows front-packed per shard, padding rows self-segmented on the shard's
last local node slot with queued pinned 0, uniform pad/h_pad across
shards).  This module is the ONE definition of that contract —
:func:`pad_state` is the only legal original->padded translation.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def chain_partition(flow_node: np.ndarray, flow_succ: np.ndarray,
                    n_shards: int) -> Tuple[np.ndarray, int]:
    """Assign nodes to shards, chains-first: walk every chain from its
    head flow (ascending), assigning each not-yet-placed node to the
    current shard until the per-shard flow budget fills.  Returns
    (shard_of_node [max_node+1], cross_edges) where cross_edges counts
    flow->successor hops whose nodes landed on different shards."""
    flow_node = np.asarray(flow_node, dtype=np.int64)
    flow_succ = np.asarray(flow_succ, dtype=np.int64)
    f = len(flow_node)
    n_nodes = int(flow_node.max()) + 1 if f else 1
    seg_size = np.bincount(flow_node, minlength=n_nodes).astype(np.int64)
    shard_of = np.full(n_nodes, -1, dtype=np.int64)
    budget = -(-f // n_shards)
    # chain heads: flows nobody forwards into
    has_pred = np.zeros(f, dtype=bool)
    valid = flow_succ >= 0
    has_pred[flow_succ[valid]] = True
    shard = 0
    fill = 0
    for head in np.flatnonzero(~has_pred).tolist():
        i = head
        while i >= 0:
            node = int(flow_node[i])
            if shard_of[node] < 0:
                size = int(seg_size[node])
                if fill and fill + size > budget and shard < n_shards - 1:
                    shard += 1
                    fill = 0
                shard_of[node] = shard
                fill += size
            i = int(flow_succ[i])
    # nodes with no flows (cannot occur for tables built from chains, but
    # keep the map total): park them on the last shard
    shard_of[shard_of < 0] = n_shards - 1
    src_shard = shard_of[flow_node[valid]]
    dst_shard = shard_of[flow_node[flow_succ[valid]]]
    cross = int(np.count_nonzero(src_shard != dst_shard))
    return shard_of, cross


def contiguous_partition(flow_node: np.ndarray,
                         n_shards: int) -> np.ndarray:
    """The pre-mesh placement rule (PR 7's partition_flows): contiguous
    node-sorted ranges balanced by flow count.  Kept as the partitioner's
    baseline/oracle — chain_partition must never do worse on cross-shard
    hops than this for the same table (tests pin it)."""
    flow_node = np.asarray(flow_node, dtype=np.int64)
    f = len(flow_node)
    n_nodes = int(flow_node.max()) + 1 if f else 1
    starts = np.flatnonzero(np.r_[True, flow_node[1:] != flow_node[:-1]])
    bounds = [0]
    for s in range(1, n_shards):
        target = round(f * s / n_shards)
        i = int(np.searchsorted(starts, target))
        b = int(starts[i]) if i < len(starts) else f
        bounds.append(max(b, bounds[-1]))
    bounds.append(f)
    shard_of = np.full(n_nodes, n_shards - 1, dtype=np.int64)
    for s in range(n_shards):
        lo, hi = bounds[s], bounds[s + 1]
        if hi > lo:
            shard_of[np.unique(flow_node[lo:hi])] = s
    return shard_of


def build_mesh_layout(flow_node, flow_lat, flow_succ, seg_start,
                      refill, capacity, n_shards: int,
                      shard_of_node: Optional[np.ndarray] = None) -> dict:
    """Pad + index-map the (node-sorted) flow tables for the sharded
    kernel, honoring an arbitrary segment-aligned node->shard assignment
    (default: :func:`chain_partition`).  Real rows occupy the front of
    each shard's slice in ascending (node, original-row) order — a node's
    segment is copied whole, so within-segment allocation order is
    untouched; padding rows are self-segmented with queued always 0, so
    they serve nothing and perturb nothing.  Returns the padded tables
    plus src/keep/inv mappings for translating state between the original
    and padded layouts, and the exchange schedule over the cross-shard
    successor edges (exchange.build_exchange)."""
    flow_node = np.asarray(flow_node, dtype=np.int64)
    flow_lat = np.asarray(flow_lat, dtype=np.int64)
    flow_succ = np.asarray(flow_succ, dtype=np.int64)
    f = len(flow_node)
    if shard_of_node is None:
        shard_of_node, _ = chain_partition(flow_node, flow_succ, n_shards)
    shard_of_node = np.asarray(shard_of_node, dtype=np.int64)
    # per-shard row lists: each shard's nodes ascending, each node's whole
    # segment in original order (the array is node-sorted, so a node's
    # rows are one contiguous slice)
    starts = np.flatnonzero(np.r_[True, flow_node[1:] != flow_node[:-1]])
    ends = np.r_[starts[1:], f]
    seg_nodes = flow_node[starts]
    rows_per_shard = [[] for _ in range(n_shards)]
    for k in range(len(starts)):
        s = int(shard_of_node[seg_nodes[k]])
        rows_per_shard[s].append((int(seg_nodes[k]),
                                  int(starts[k]), int(ends[k])))
    sizes = [sum(e - b for _n, b, e in segs) for segs in rows_per_shard]
    pad = max(sizes) if sizes and max(sizes) else 1
    fp_total = n_shards * pad
    keep = np.zeros(fp_total, dtype=bool)
    src = np.zeros(fp_total, dtype=np.int64)
    for s in range(n_shards):
        pos = s * pad
        for _node, b, e in sorted(rows_per_shard[s]):
            src[pos:pos + (e - b)] = np.arange(b, e)
            pos += e - b
        keep[s * pad:pos] = True
    inv = np.full(f, -1, dtype=np.int64)
    inv[src[keep]] = np.flatnonzero(keep)

    node_p = flow_node[src]
    lat_p = flow_lat[src]
    lat_p[~keep] = 0        # diagnostic copy only; the kernel reads arr_lat
    succ_orig = flow_succ[src]
    succ_p = np.where((succ_orig >= 0) & keep, inv[np.maximum(succ_orig, 0)],
                      -1)
    # per-shard local node renumbering + local segment starts; uniform
    # local node count across shards (padded)
    h_locals = []
    node_local = np.zeros(fp_total, dtype=np.int64)
    seg_local = np.zeros(fp_total, dtype=np.int64)
    for s in range(n_shards):
        lo, hi = s * pad, (s + 1) * pad
        k = keep[lo:hi]
        nodes = node_p[lo:hi][k]
        uniq, local_ids = np.unique(nodes, return_inverse=True)
        h_locals.append(len(uniq))
        node_local[lo:lo + len(nodes)] = local_ids
        if len(nodes):
            sstarts = np.flatnonzero(np.r_[True, nodes[1:] != nodes[:-1]])
            seg_id = np.cumsum(np.r_[0, (nodes[1:] != nodes[:-1])
                                     .astype(np.int64)])
            seg_local[lo:lo + len(nodes)] = sstarts[seg_id]
        # padding rows: own one-row segments on the last local node slot
        for j in range(lo + int(k.sum()), hi):
            seg_local[j] = j - lo
    h_pad = max(h_locals) if h_locals else 1
    refill_p = np.zeros(n_shards * h_pad, dtype=np.int64)
    capacity_p = np.zeros(n_shards * h_pad, dtype=np.int64)
    node_src = np.full(n_shards * h_pad, -1, dtype=np.int64)
    for s in range(n_shards):
        lo = s * pad
        k = keep[lo:lo + pad]
        nodes = node_p[lo:lo + pad][k]
        uniq = np.unique(nodes)
        refill_p[s * h_pad:s * h_pad + len(uniq)] = np.asarray(refill)[uniq]
        capacity_p[s * h_pad:s * h_pad + len(uniq)] = \
            np.asarray(capacity)[uniq]
        node_src[s * h_pad:s * h_pad + len(uniq)] = uniq
        # padding rows point at the shard's last local node; they never
        # serve (queued stays 0) so sharing a real bucket is harmless
        node_local[lo + int(k.sum()):lo + pad] = h_pad - 1
    # successor-space arrival latency: arr_lat[j] = lat of j's predecessor
    # (each shard reads its own slice — the kernel's ring is shard-local)
    arr_lat = np.zeros(fp_total, dtype=np.int64)
    senders = np.flatnonzero(succ_p >= 0)
    arr_lat[succ_p[senders]] = lat_p[senders]
    lay = {
        "pad": pad, "keep": keep, "src": src, "inv": inv,
        "flow_node_local": node_local, "flow_lat": lat_p,
        "succ_global": succ_p, "seg_start_local": seg_local,
        "refill": refill_p, "capacity": capacity_p, "h_pad": h_pad,
        "node_src": node_src,    # padded local-node slot -> global node
        "arr_lat": arr_lat,
        "shard_base": (np.arange(n_shards, dtype=np.int64) * pad),
        "n_shards": n_shards,
        "shard_of_node": shard_of_node,
        "shard_sizes": np.asarray(sizes, dtype=np.int64),
    }
    from .exchange import build_exchange
    lay["exchange"] = build_exchange(succ_p, pad, n_shards)
    return lay


def pad_state(layout: dict, a, fill: int = 0) -> np.ndarray:
    """Translate a per-flow array from the original layout into the padded
    sharded layout (ONE definition of the padding contract — callers must
    not hand-roll ``out[keep] = a[src[keep]]``)."""
    src, keep = layout["src"], layout["keep"]
    out = np.full(len(src), fill, dtype=np.int64)
    out[keep] = np.asarray(a)[src[keep]]
    return out
