"""Cross-shard forward exchange: precomputed BvN permutation legs executed
as on-device collectives inside the sharded superwindow kernel.

Exactness argument (inherited from the PR-7 sharded kernel): the per-tick
greedy bandwidth allocation is independent ACROSS nodes, so with every
node's whole flow segment on one shard, per-shard segment cumsums are
bit-identical to the global ones.  The only cross-shard dataflow is cell
forwarding, and every flow has exactly one predecessor (circuits are
chains), so the per-tick arrival vector in successor space has exactly one
writer per slot — addition order cannot matter, and any exchange that
delivers the same (src value -> dst slot) pairs is bitwise-equivalent.

The PREVIOUS sharded kernel exchanged by scattering into a full [F] vector
and psum-ing it over the mesh every tick, with the whole arrival ring
REPLICATED on every shard: collective bytes and ring memory were O(F)
regardless of how little traffic actually crossed shards.  This module
replaces that with a minimal-round schedule in the all-to-all scheduling
literature's shape (FAST, arxiv 2505.09764; hierarchical BvN
decomposition, arxiv 2602.22756):

* at build time the static shard-to-shard cell-EDGE matrix M[s, d] (how
  many flow->successor hops go from shard s to shard d) is decomposed
  into <= D-1 rotation permutation legs — offset r covers every (s,
  (s+r) % D) entry of M's support, so the set of offsets actually present
  IS a Birkhoff-von-Neumann decomposition of the support into permutation
  matrices, and only offsets carrying traffic become legs (the FAST
  minimal-round property: a workload whose partition keeps chains local
  pays for exactly as many legs as it has distinct cross-shard offsets);
* at run time the legs execute FUSED: collective LAUNCHES dominate the
  per-tick wall (~320 us each on the 8-virtual-device CPU mesh, nearly
  size-independent at these widths), so a multi-leg schedule runs as ONE
  ``jax.lax.all_to_all`` over the superposed [D, pair_width] slot layout
  and a single-leg schedule as the bytes-minimal lone ``ppermute``; the
  sending shard gathers its served cells into its slots, the collective
  delivers them, the receiving shard scatter-adds them into its
  SHARD-LOCAL arrival ring.  No host transfer, no [F]-sized collective —
  ``mesh.host_bounces`` stays 0 by construction and the tick's exchange
  bytes are the actual cross-shard cell slots.  With the forwards/halt
  reductions fused into one psum, a tick costs 2 collective launches
  against the PR-7 replicated-ring kernel's 3 (measured ~20%
  faster/tick on the virtual mesh).

The kernel below (:func:`make_mesh_span_flush`) is otherwise the
superwindow step + packed flush of ops/torcells_device.py, byte-for-byte:
same tick math, same halt-at-completion rule (the per-tick completion
flag is psum'd so every shard halts at the same sub-window boundary), and
the packed flush buffer grows ONE trailing slot carrying the window's
cross-shard cell count so the host learns it with zero extra reads.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ...ops.torcells_device import CELL_WIRE_BYTES, _pack_flush_jnp, flush_len


class ExchangeSchedule:
    """The precomputed cross-shard forward schedule.

    ``offsets[k]`` is leg k's rotation (shard s sends to (s+r) % D);
    ``send_src[k]`` is int64 [D * width_k]: for each sending shard, the
    shard-LOCAL rows whose served cells ride leg k (slot-padded with -1);
    ``recv_dst[k]`` is int64 [D * width_k]: for each RECEIVING shard, the
    shard-local successor rows the same slots scatter into (-1 = padding,
    dropped).  Slot order is ascending sender local row, so sender and
    receiver tables line up by construction.

    Execution fuses the legs: with more than one leg the per-tick
    collective is ONE ``all_to_all`` whose [D, W] slot layout
    (``pair_width``/``a2a_src``/``a2a_dst``) is the superposition of the
    rotation legs — same cells, same slots, one launch (collective-launch
    count is what the per-tick wall buys on any backend); a single-leg
    schedule keeps the bytes-minimal lone ``ppermute``."""

    __slots__ = ("n_shards", "offsets", "widths", "send_src", "recv_dst",
                 "cross_edges", "matrix", "pair_width", "a2a_src",
                 "a2a_dst")

    def __init__(self, n_shards: int, offsets: List[int],
                 widths: List[int], send_src: List[np.ndarray],
                 recv_dst: List[np.ndarray], cross_edges: int,
                 matrix: np.ndarray, pair_width: int,
                 a2a_src: np.ndarray, a2a_dst: np.ndarray):
        self.n_shards = n_shards
        self.offsets = offsets
        self.widths = widths
        self.send_src = send_src
        self.recv_dst = recv_dst
        self.cross_edges = cross_edges
        self.matrix = matrix
        self.pair_width = pair_width
        self.a2a_src = a2a_src
        self.a2a_dst = a2a_dst

    @property
    def legs(self) -> int:
        return len(self.offsets)


def shard_edge_matrix(succ_global: np.ndarray, pad: int,
                      n_shards: int) -> np.ndarray:
    """The static shard-to-shard cell-edge matrix M[s, d]: count of flow
    rows on shard s whose successor lives on shard d != s."""
    succ_global = np.asarray(succ_global, dtype=np.int64)
    rows = np.flatnonzero(succ_global >= 0)
    s_src = rows // pad
    s_dst = succ_global[rows] // pad
    m = np.zeros((n_shards, n_shards), dtype=np.int64)
    cross = s_src != s_dst
    np.add.at(m, (s_src[cross], s_dst[cross]), 1)
    return m


def build_exchange(succ_global: np.ndarray, pad: int,
                   n_shards: int) -> ExchangeSchedule:
    """Decompose the cross-shard successor edges into rotation legs.

    Every entry M[s, d] maps to offset r = (d - s) % D; the used offsets
    (sorted ascending, deterministic) are the legs, each leg's width the
    max edge count any shard contributes at that offset."""
    succ_global = np.asarray(succ_global, dtype=np.int64)
    m = shard_edge_matrix(succ_global, pad, n_shards)
    rows = np.flatnonzero(succ_global >= 0)
    s_src = rows // pad
    s_dst = succ_global[rows] // pad
    cross = rows[s_src != s_dst]
    # per (offset, sending shard): (local src row, receiver local dst row)
    # pairs in ascending src-row order — the slot order BOTH tables use
    by_leg: dict = {}
    for i in cross.tolist():
        s = i // pad
        d = int(succ_global[i]) // pad
        r = (d - s) % n_shards
        by_leg.setdefault(r, {}).setdefault(s, []).append(
            (i - s * pad, int(succ_global[i]) - d * pad))
    offsets = sorted(by_leg)
    widths, send_src, recv_dst = [], [], []
    for r in offsets:
        per_shard = by_leg[r]
        w = max(len(v) for v in per_shard.values())
        snd = np.full(n_shards * w, -1, dtype=np.int64)
        rcv = np.full(n_shards * w, -1, dtype=np.int64)
        for s, pairs in sorted(per_shard.items()):
            d = (s + r) % n_shards
            for k, (src_row, dst_row) in enumerate(pairs):
                snd[s * w + k] = src_row
                rcv[d * w + k] = dst_row
        widths.append(w)
        send_src.append(snd)
        recv_dst.append(rcv)
    # fused all_to_all layout: slot chunk d of sender s carries the
    # (s -> d) edges; receiver m's chunk s scatters sender s's slots.
    # pair_width is the max edge count over ordered shard pairs, so the
    # [D, W] buffer superposes every rotation leg into one collective.
    pair_width = max(1, int(m.max()) if m.size else 1)
    a2a_src = np.full((n_shards, n_shards * pair_width), -1, dtype=np.int64)
    a2a_dst = np.full((n_shards, n_shards * pair_width), -1, dtype=np.int64)
    for r in offsets:
        for s, pairs in sorted(by_leg[r].items()):
            d = (s + r) % n_shards
            for k, (src_row, dst_row) in enumerate(pairs):
                a2a_src[s, d * pair_width + k] = src_row
                a2a_dst[d, s * pair_width + k] = dst_row
    return ExchangeSchedule(n_shards, offsets, widths, send_src, recv_dst,
                            int(len(cross)), m, pair_width,
                            a2a_src.reshape(-1), a2a_dst.reshape(-1))


def leg_of_edges(succ_global: np.ndarray, pad: int,
                 schedule: ExchangeSchedule) -> np.ndarray:
    """Per PADDED flow row: the index of the exchange leg its successor
    edge rides, or -1 (no edge, intra-shard, or padding).  The quiet-tick
    leg mask is built from this: OR each chain's rows' legs into a bitmask
    and a span whose active chains touch only a subset of legs can compile
    the rest out (make_mesh_span_raw's ``leg_mask``)."""
    succ_global = np.asarray(succ_global, dtype=np.int64)
    n_shards = schedule.n_shards
    leg_of = np.full(len(succ_global), -1, dtype=np.int64)
    lut = np.full(n_shards, -1, dtype=np.int64)
    for k, r in enumerate(schedule.offsets):
        lut[r] = k
    rows = np.flatnonzero(succ_global >= 0)
    s_src = rows // pad
    s_dst = succ_global[rows] // pad
    cross = s_src != s_dst
    leg_of[rows[cross]] = lut[(s_dst[cross] - s_src[cross]) % n_shards]
    return leg_of


def choose_exchange_mode(schedule: ExchangeSchedule, model=None,
                         override: str = "auto"
                         ) -> Tuple[str, float, str]:
    """Pick the exchange execution mode for a schedule: ``fused`` (one
    all_to_all over the superposed [D, pair_width] slots), ``ppermute``
    (one collective per rotation leg — lone for a single leg, multi-leg
    otherwise), or ``none`` (no cross-shard edges).

    Returns ``(mode, predicted_tick_us, source)``.  ``source`` says what
    decided: ``static`` (no cross edges), ``forced`` (the
    ``--exchange-mode`` CLI override), ``model`` (the measured per-box
    cost model, ISSUE 15 — cheapest predicted per-tick collective cost
    wins), or ``heuristic`` (no calibration on this box: today's PR-9
    rule, fused when multi-leg, lone ppermute otherwise — exactly the
    pre-model behavior, so an uncalibrated box changes nothing).
    ``predicted_tick_us`` is the model's per-tick exchange cost for the
    CHOSEN mode (0.0 without a model) — recorded as
    ``mesh.predicted_us`` so the decision is auditable in every scrape.

    Every candidate delivers the identical (src value -> dst slot)
    pairs, so the choice can only ever change WHICH bit-identical kernel
    runs: digest parity across modes is by construction, and pinned by
    tests/test_simprof.py with the override forced each way."""
    d = schedule.n_shards

    def predicted(mode: str) -> float:
        if model is None:
            return 0.0
        return model.exchange_tick_us(d, mode, schedule.pair_width,
                                      schedule.widths)

    if schedule.legs == 0:
        # cross-free table: no exchange collective, but the mesh kernel
        # still issues the per-tick stats psum — predict THAT, so the
        # audit value (and the window predictor fed from it) is the
        # cost actually paid, not a flattering zero
        return "none", round(predicted("none"), 2), "static"

    if override in ("fused", "ppermute"):
        return override, round(predicted(override), 2), "forced"
    heuristic = "fused" if schedule.legs > 1 else "ppermute"
    if model is None:
        return heuristic, 0.0, "heuristic"
    cost_f, cost_p = predicted("fused"), predicted("ppermute")
    if cost_f == cost_p:
        mode = heuristic            # measured tie: keep the known shape
    else:
        mode = "fused" if cost_f < cost_p else "ppermute"
    return mode, round(min(cost_f, cost_p), 2), "model"


def make_mesh_span_raw(mesh, axis: str, ring_len: int, pad: int,
                       schedule: ExchangeSchedule,
                       mode: Optional[str] = None,
                       leg_mask: Optional[Tuple[bool, ...]] = None):
    """The shard_map-ed SUPERWINDOW step with device-side cross-shard
    exchange.  Same argument list as the engine-facing flush kernel minus
    the flush packing; the arrival ring and arr_lat are SHARD-LOCAL
    (sharded in_specs), unlike the PR-7 kernel's replicated ring.  Returns
    the usual 9-tuple plus [9] = cross-shard cells exchanged this window
    (psum'd, replicated).

    ``leg_mask`` (ISSUE 16 quiet-tick fusion) is a STATIC per-leg bool
    tuple: a False leg issues NO collective this variant.  Safe whenever
    the masked legs provably carry zeros — a chain whose specs are not yet
    injected has queued=0 and an empty ring everywhere, so fwd=0 on all
    its rows; meshplane tracks which legs the ACTIVE chains can touch and
    compiles a variant per distinct superset mask.  Any SUPERSET of the
    truly-needed legs is bit-identical (extra legs exchange zeros), so the
    mask can only ever trade launches, never results.  With ``ppermute``
    each masked leg is one launch saved per tick; with ``fused`` the
    exchange is one launch regardless, so only the all-False mask (which
    degrades to ``none``: zero exchange collectives, stats psum only)
    changes the launch count."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_shards = schedule.n_shards
    if leg_mask is None:
        leg_mask = tuple(True for _ in range(schedule.legs))
    assert len(leg_mask) == schedule.legs, (len(leg_mask), schedule.legs)
    active_legs = [k for k in range(schedule.legs) if leg_mask[k]]
    # exchange tables are closed over as constants (the per-shard slice
    # is taken with dynamic_slice on the shard id).  Execution strategy
    # (``mode``; decided by choose_exchange_mode — measured cost model
    # when this box is calibrated, the PR-9 heuristic otherwise):
    # "fused" runs every leg as ONE all_to_all over the superposed
    # [D, pair_width] slot layout (one launch per tick — launches, not
    # bytes, dominate the per-tick wall at these widths); "ppermute"
    # runs one rotation collective PER leg (bytes-minimal: lone for a
    # single-leg schedule, multi-leg when the model says L launches
    # beat one wide all_to_all); a cross-free table pays no exchange.
    # Every mode delivers the identical (src value -> dst slot) pairs —
    # each slot has exactly one writer — so the choice is between
    # bit-identical kernels and digest parity holds by construction.
    if mode is None:
        mode = "fused" if schedule.legs > 1 else (
            "ppermute" if schedule.legs == 1 else "none")
    if schedule.legs == 0 or not active_legs:
        # cross-free table OR every leg masked quiet this variant: the
        # tick pays zero exchange collectives (stats psum still issues —
        # it is the halt synchronizer, not exchange traffic)
        mode = "none"
    assert mode in ("fused", "ppermute", "none"), mode
    if mode == "fused":
        ex_mode = "a2a"
        pw = schedule.pair_width
        a2a_src_tbl = jnp.asarray(schedule.a2a_src)
        a2a_dst_tbl = jnp.asarray(schedule.a2a_dst)
        chunk = n_shards * pw
    elif mode == "ppermute":
        ex_mode = "ppermute"
        # masked (quiet) legs compile out entirely: each is one saved
        # collective launch per tick in this variant
        leg_tbls = [(schedule.offsets[k], schedule.widths[k],
                     jnp.asarray(schedule.send_src[k]),
                     jnp.asarray(schedule.recv_dst[k]))
                    for k in active_legs]
    else:
        ex_mode = "none"

    def step(t0, queued, ring, tokens, delivered, target, done_tick,
             node_sent, inject, inject_target, targets, idle_ticks,
             flow_node_local, succ_global, seg_start_local,
             refill, capacity, arr_lat, shard_base):
        """All [*] args sharded on ``axis`` (including ring columns and
        arr_lat) except targets/scalars (replicated).  succ_global is the
        successor's GLOBAL padded index (-1 = chain end); whether it is
        local is decided against the shard's own row range."""

        def shard_body(t0, queued, ring, tokens, delivered, target,
                       done_tick, node_sent, inject, inject_target,
                       targets, idle_ticks, flow_node_local,
                       succ_global, seg_start_local, refill, capacity,
                       arr_lat, shard_base):
            fp = queued.shape[0]
            h_local = refill.shape[0]
            p = targets.shape[0]
            queued = queued + inject
            target = target + inject_target
            tokens = jnp.minimum(capacity, tokens + refill * idle_ticks)
            # idle jump: the local send history is stale — clear only when
            # ticks were actually banked (same rule as the 1-chip kernel)
            ring = jax.lax.cond(idle_ticks > 0,
                                lambda hh: jnp.zeros_like(hh),
                                lambda hh: hh, ring)
            end = targets[p - 1]
            size = jnp.int64(CELL_WIRE_BYTES)
            is_last = succ_global < 0
            base = shard_base[0]
            # intra-shard successor rows (cross-shard rows ride the legs)
            local_succ = succ_global - base
            intra = (succ_global >= 0) & (local_succ >= 0) \
                & (local_succ < fp)
            oob = jnp.int64(fp)
            intra_dst = jnp.where(intra, local_succ, oob)
            cols = jnp.arange(fp)
            shard = base // pad
            if ex_mode == "a2a":
                my_src = jax.lax.dynamic_slice(a2a_src_tbl,
                                               (shard * chunk,), (chunk,))
                my_dst = jax.lax.dynamic_slice(a2a_dst_tbl,
                                               (shard * chunk,), (chunk,))
                my_dst_slots = jnp.where(my_dst >= 0, my_dst, oob)
            elif ex_mode == "ppermute":
                # per-leg shard-local slices, hoisted out of the tick
                # loop (one (src rows, dst slots) pair per rotation leg)
                my_legs = []
                for leg_r, leg_w, snd_tbl, rcv_tbl in leg_tbls:
                    l_src = jax.lax.dynamic_slice(
                        snd_tbl, (shard * leg_w,), (leg_w,))
                    l_dst = jax.lax.dynamic_slice(
                        rcv_tbl, (shard * leg_w,), (leg_w,))
                    my_legs.append(
                        (leg_r, l_src, l_dst,
                         jnp.where(l_dst >= 0, l_dst, oob)))

            def body(state):
                (t, idx, halt, span_done, queued, ring, tokens, delivered,
                 target, done_tick, node_sent, forwards, cross) = state
                # arrivals: my rows' sends from arr_lat steps ago, out of
                # MY ring slice (columns with no predecessor gather zeros)
                arr = ring[jnp.mod(t - arr_lat, ring_len), cols]
                queued = queued + arr
                tokens = jnp.minimum(capacity, tokens + refill)
                cap_cells = tokens[flow_node_local] // size
                csum = jnp.cumsum(queued)
                before = csum - queued - jnp.where(
                    seg_start_local > 0,
                    csum[jnp.maximum(seg_start_local - 1, 0)],
                    jnp.int64(0)) * (seg_start_local > 0)
                served = jnp.clip(cap_cells - before, 0, queued)
                queued = queued - served
                spent = jax.ops.segment_sum(served * size, flow_node_local,
                                            num_segments=h_local)
                tokens = tokens - spent
                node_sent = node_sent + spent
                delivered = delivered + jnp.where(is_last, served, 0)
                newly = (is_last & (target > 0) & (done_tick < 0)
                         & (delivered >= target))
                done_tick = jnp.where(newly, t, done_tick)
                fwd = jnp.where(is_last, jnp.int64(0), served)
                # successor-space send vector, SHARD-LOCAL: intra-shard
                # sends scatter directly; cross-shard sends ride the
                # precomputed exchange (one collective per tick)
                v = jnp.zeros(fp, jnp.int64).at[intra_dst].add(
                    jnp.where(intra, fwd, 0), mode="drop")
                if ex_mode == "a2a":
                    vals = jnp.where(my_src >= 0,
                                     fwd[jnp.clip(my_src, 0, fp - 1)],
                                     jnp.int64(0))
                    got = jax.lax.all_to_all(vals, axis, 0, 0, tiled=True)
                    v = v.at[my_dst_slots].add(got, mode="drop")
                    cross = cross + jnp.sum(
                        jnp.where(my_dst >= 0, got, jnp.int64(0)))
                elif ex_mode == "ppermute":
                    # one rotation collective per leg (L launches/tick;
                    # the cost model decided L beat one fused a2a here)
                    for leg_r, l_src, l_dst, l_dst_slots in my_legs:
                        vals = jnp.where(l_src >= 0,
                                         fwd[jnp.clip(l_src, 0, fp - 1)],
                                         jnp.int64(0))
                        got = jax.lax.ppermute(
                            vals, axis,
                            perm=[(s, (s + leg_r) % n_shards)
                                  for s in range(n_shards)])
                        v = v.at[l_dst_slots].add(got, mode="drop")
                        cross = cross + jnp.sum(
                            jnp.where(l_dst >= 0, got, jnp.int64(0)))
                ring = ring.at[jnp.mod(t, ring_len)].set(
                    v.astype(ring.dtype))
                # fused stats reduction: forwards + the global completion
                # flag (any shard's newly-done chain halts every shard at
                # the same sub-window boundary) ride ONE psum per tick
                stats = jax.lax.psum(
                    jnp.stack([jnp.sum(served),
                               jnp.sum(newly.astype(jnp.int64))]), axis)
                forwards = forwards + stats[0]
                span_done = span_done | (stats[1] > 0)
                boundary = (t + 1) == targets[jnp.minimum(idx, p - 1)]
                halt = boundary & span_done
                idx = jnp.where(boundary, idx + 1, idx)
                span_done = span_done & ~boundary
                return (t + 1, idx, halt, span_done, queued, ring, tokens,
                        delivered, target, done_tick, node_sent, forwards,
                        cross)

            def cond(state):
                return (state[0] < end) & ~state[2]

            state = (t0, jnp.int64(0), jnp.bool_(False), jnp.bool_(False),
                     queued, ring, tokens, delivered, target,
                     done_tick, node_sent, jnp.int64(0), jnp.int64(0))
            out = jax.lax.while_loop(cond, body, state)
            # every exchanged cell was counted once, at its receiver
            cross_total = jax.lax.psum(out[12], axis)
            return (out[0], *out[4:12], cross_total)

        sharded = P(axis)
        repl = P()
        return shard_map(
            shard_body, mesh=mesh,
            in_specs=(repl, sharded, P(None, axis), sharded, sharded,
                      sharded, sharded, sharded, sharded, sharded, repl,
                      repl, sharded, sharded, sharded, sharded, sharded,
                      sharded, sharded),
            out_specs=(repl, sharded, P(None, axis), sharded, sharded,
                       sharded, sharded, sharded, repl, repl),
            check_rep=False)(
            t0, queued, ring, tokens, delivered, target, done_tick,
            node_sent, inject, inject_target, targets, idle_ticks,
            flow_node_local, succ_global, seg_start_local,
            refill, capacity, arr_lat, shard_base)

    return step


def make_mesh_span_flush(mesh, axis: str, ring_len: int, layout: dict,
                         last_flow_pad: np.ndarray, node_src: np.ndarray,
                         n_nodes: int, mode: Optional[str] = None,
                         leg_mask: Optional[Tuple[bool, ...]] = None,
                         cap_chains: Optional[int] = None,
                         cap_nodes: Optional[int] = None):
    """Mesh superwindow step + packed flush in ONE dispatch: the engine's
    sharded kernel (DeviceTrafficPlane._sharded_step contract — same
    argument list as the PR-7 kernel, so advance()/warmup() are layout-
    agnostic).  ``mode`` picks the exchange execution strategy
    (choose_exchange_mode; None = the legacy heuristic); ``leg_mask``
    compiles quiet exchange legs out (make_mesh_span_raw); the caps pick
    the delta-compacted flush layout (ops/torcells_device._pack_flush_jnp).
    The flush buffer is the standard packed layout with ONE trailing slot
    appended: [flush_len(..., caps)] = cross-shard cells exchanged this
    window (consume() folds it into the mesh metrics with no extra device
    read)."""
    raw = make_mesh_span_raw(mesh, axis, ring_len, layout["pad"],
                             layout["exchange"], mode=mode,
                             leg_mask=leg_mask)
    lf = np.asarray(last_flow_pad, dtype=np.int64)
    nsrc = np.asarray(node_src, dtype=np.int64)

    def global_sent(ns_padded):
        # padding slots (node_src < 0) scatter out of range and drop
        idx = jnp.where(nsrc >= 0, nsrc, jnp.int64(n_nodes))
        return jnp.zeros(n_nodes, jnp.int64).at[idx].add(ns_padded,
                                                         mode="drop")

    def step_flush(t0, queued, ring, tokens, delivered, target, done_tick,
                   node_sent, inject, inject_target, targets, idle_ticks,
                   flow_node_local, succ_global, seg_start_local,
                   refill, capacity, arr_lat, shard_base):
        done_in_last = done_tick[lf]
        sent_in = global_sent(node_sent)
        out = raw(t0, queued, ring, tokens, delivered, target, done_tick,
                  node_sent, inject, inject_target, targets, idle_ticks,
                  flow_node_local, succ_global, seg_start_local,
                  refill, capacity, arr_lat, shard_base)
        done_last = out[6][lf]
        newly = (done_last >= 0) & (done_in_last < 0)
        flush = _pack_flush_jnp(out[8], jnp.sum(out[4][lf]), out[0], newly,
                                done_last, global_sent(out[7]) - sent_in,
                                cap_chains, cap_nodes)
        flush = jnp.concatenate([flush, out[9][None]])
        return (*out[:9], flush)

    return jax.jit(step_flush)


def mesh_flush_extra(flush: np.ndarray, n_chains: int, n_nodes: int,
                     cap_chains: Optional[int] = None,
                     cap_nodes: Optional[int] = None) -> int:
    """The mesh flush buffer's trailing cross-shard cell count, or 0 for a
    standard-length buffer (the numpy twin after a demotion).  Pass the
    caps the buffer was packed with — the trailing slot rides at the end
    of the CAPPED layout."""
    base = flush_len(n_chains, n_nodes, cap_chains, cap_nodes)
    return int(flush[base]) if len(flush) > base else 0
