"""meshplane: attach the multi-chip mesh layout to a DeviceTrafficPlane.

``attach_mesh`` is the traffic plane's ONE sharding entry point
(DeviceTrafficPlane._setup_sharding delegates here for --tpu-devices N):
it builds the device mesh, runs the chain partitioner, precomputes the
BvN exchange schedule, installs the sharded superwindow kernel, and
registers the ``mesh.*`` metrics source.  Everything engine-facing
(advance/consume/warmup, pipelined dispatch, superwindows, checkpoints,
the dispatch guard's numpy-twin demotion) is untouched — the mesh kernel
keeps the exact argument/return contract of the single-device path, so
the plane composes with all of it by construction and digest parity
sharded-vs-single-device-vs-serial is pinned by tests/test_meshplane.py.

Metrics (scraped into the same registry the bench reads):

* ``mesh.host_bounces``   — cross-shard forwards that transited the host.
  The exchange is entirely device-side, so this stays 0 on the
  steady-state path; the counter exists so the contract is ASSERTED, not
  assumed (the acceptance gate reads it).
* ``mesh.cross_shard_cells`` — cells exchanged over the permutation legs
  (accumulated from the flush buffer's trailing slot, zero extra reads).
* ``mesh.exchange_legs`` / ``mesh.cross_edges`` — schedule shape: BvN
  rotation legs in the static schedule and the flow->successor edges that
  cross shards.
* ``mesh.occupancy_min`` / ``mesh.occupancy_mean`` — per-device real-flow
  fraction of the padded slice (partition balance).
"""

from __future__ import annotations

import numpy as np

from ...core.logger import get_logger
from . import device_mesh
from .exchange import (choose_exchange_mode, leg_of_edges,
                       make_mesh_span_flush)
from .partition import build_mesh_layout, chain_partition


class MeshPlaneInfo:
    """Per-run mesh introspection: schedule shape + runtime counters."""

    __slots__ = ("n_devices", "legs", "cross_edges", "cut_fraction",
                 "occupancy", "cross_shard_cells", "host_bounces",
                 "flush_base", "exchange_mode", "predicted_us",
                 "exchange_source", "model_status", "legs_active")

    def __init__(self, n_devices: int, legs: int, cross_edges: int,
                 cut_fraction: float, occupancy: np.ndarray,
                 flush_base: int, exchange_mode: str = "none",
                 predicted_us: float = 0.0,
                 exchange_source: str = "heuristic",
                 model_status: str = "absent"):
        self.n_devices = n_devices
        self.legs = legs
        self.cross_edges = cross_edges
        self.cut_fraction = cut_fraction
        self.occupancy = occupancy
        self.flush_base = flush_base
        # the exchange scheduling decision and its audit trail (ISSUE 15):
        # which identical-result kernel runs, its model-predicted per-tick
        # collective cost, and WHAT decided (model/heuristic/forced)
        self.exchange_mode = exchange_mode
        self.predicted_us = predicted_us
        self.exchange_source = exchange_source
        self.model_status = model_status
        self.cross_shard_cells = 0
        # exchange legs the CURRENT kernel variant actually issues
        # (quiet-tick fusion, ISSUE 16): starts at the full static
        # schedule, drops to the active-chain superset once the plane
        # picks a masked variant
        self.legs_active = legs
        # dispatch windows whose cross-shard forwards were delivered
        # HOST-side.  No steady-state path does — the acceptance gate
        # asserts it stays 0 — and the counter is falsifiable: after a
        # dispatch failure demotes a sharded plane to the numpy twin,
        # every busy window's cross forwards run on the host and count
        # here (device_plane.consume; the fault drill pins it nonzero)
        self.host_bounces = 0

    def metrics(self, plane) -> dict:
        return {
            "mesh.devices": self.n_devices,
            "mesh.exchange_legs": self.legs,
            "mesh.cross_edges": self.cross_edges,
            "mesh.cut_fraction": round(self.cut_fraction, 4),
            "mesh.cross_shard_cells": self.cross_shard_cells,
            "mesh.host_bounces": self.host_bounces,
            "mesh.occupancy_min": round(float(self.occupancy.min()), 4),
            "mesh.occupancy_mean": round(float(self.occupancy.mean()), 4),
            "mesh.demoted": int(plane.demoted),
            # the exchange decision (ISSUE 15): chosen kernel, the cost
            # model's predicted per-tick collective cost (0.0 when no
            # calibration loaded), and the decision source — so every
            # scrape says WHICH kernel ran and WHY
            "mesh.exchange_mode": self.exchange_mode,
            "mesh.predicted_us": self.predicted_us,
            "mesh.exchange_source": self.exchange_source,
            "mesh.cost_model": self.model_status,
            "mesh.legs_active": self.legs_active,
        }


def attach_mesh(plane, n_dev: int) -> None:
    """Shard ``plane``'s flow table over an ``n_dev``-device mesh: chain
    partition -> padded layout -> BvN exchange schedule -> sharded
    superwindow kernel, installed under the plane's standard sharded-step
    contract."""
    from ...ops.torcells_device import flush_len

    mesh = device_mesh(n_dev, axis_names=("flows",))
    shard_of_node, cross_hops = chain_partition(
        plane.flow_node, plane.flow_succ, n_dev)
    lay = build_mesh_layout(
        plane.flow_node, plane.flow_lat_steps, plane.flow_succ,
        plane.seg_start, plane.refill_step, plane.capacity_step, n_dev,
        shard_of_node)
    sched = lay["exchange"]
    plane._mesh = mesh
    plane._shard = lay
    # the exchange scheduling decision (ISSUE 15): the measured per-box
    # cost model picks fused-all_to_all vs (multi-leg) ppermute from
    # data; --exchange-mode forces it; an uncalibrated box falls back to
    # the PR-9 heuristic.  Identical-result kernels, so digest parity
    # across choices is by construction (pinned by tests/test_simprof.py)
    override = getattr(plane.engine.options, "exchange_mode", "auto")
    ex_mode, predicted_us, source = choose_exchange_mode(
        sched, plane._costmodel, override)
    # quiet-tick fusion support (ISSUE 16): per-chain exchange-leg
    # bitmask, so a span whose ACTIVE chains touch only a subset of the
    # legs can run a variant kernel with the quiet legs compiled out.
    # Safe because an un-injected chain's rows forward zero cells — any
    # SUPERSET of the active chains' legs is bit-identical (see
    # make_mesh_span_raw).  >63 legs cannot happen (legs <= D-1 and the
    # mesh caps out far below), but guard with the always-full sentinel.
    leg_of = leg_of_edges(lay["succ_global"], lay["pad"], sched)
    chain_bits = np.zeros(plane.n_chains, dtype=np.int64)
    if sched.legs > 63:
        chain_bits[:] = -1
    else:
        rows = np.flatnonzero((leg_of >= 0) & (lay["src"] >= 0))
        if len(rows):
            np.bitwise_or.at(
                chain_bits, plane.flow_circ[lay["src"][rows]],
                np.int64(1) << leg_of[rows])
    plane._chain_leg_bits = chain_bits
    plane._full_leg_bits = -1 if sched.legs > 63 \
        else (1 << sched.legs) - 1
    caps = getattr(plane, "_flush_caps", None)
    cap_c, cap_h = caps if caps else (None, None)

    def make_step(leg_mask=None, capped=True):
        cc, hh = (cap_c, cap_h) if capped else (None, None)
        return make_mesh_span_flush(
            mesh, "flows", plane.ring_len, lay,
            lay["inv"][plane.last_flow], lay["node_src"], plane.n_nodes,
            mode=ex_mode, leg_mask=leg_mask,
            cap_chains=cc, cap_nodes=hh)

    plane._mesh_make_step = make_step
    plane._sharded_step = make_step()
    edges_total = max(int(np.count_nonzero(plane.flow_succ >= 0)), 1)
    occupancy = lay["shard_sizes"].astype(np.float64) / max(lay["pad"], 1)
    plane._meshinfo = MeshPlaneInfo(
        n_dev, sched.legs, sched.cross_edges,
        cross_hops / edges_total, occupancy,
        flush_len(plane.n_chains, plane.n_nodes),
        exchange_mode=ex_mode, predicted_us=predicted_us,
        exchange_source=source, model_status=plane._costmodel_status)
    plane.engine.metrics.source(
        "mesh", lambda: plane._meshinfo.metrics(plane))
    get_logger().message(
        "device-plane",
        f"mesh plane: flow table sharded over {n_dev} devices "
        f"(pad {lay['pad']} flows/shard, {lay['h_pad']} nodes/shard, "
        f"{sched.cross_edges}/{edges_total} cross-shard hops over "
        f"{sched.legs} exchange legs; exchange={ex_mode} "
        f"[{source}], predicted {predicted_us} us/tick)")
