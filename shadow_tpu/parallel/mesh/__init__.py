"""meshplane: the multi-chip sharded traffic plane (ROADMAP item 1).

Three cooperating modules turn the device-resident traffic plane
(parallel/device_plane.py) from a one-chip program into a D-chip one:

* :mod:`partition` — a deterministic chain/flow partitioner assigning
  whole node segments to shards while keeping each circuit's consecutive
  hops co-located (minimizing cross-shard forwards), plus the padded
  layout builder every sharded consumer goes through — the ONE definition
  of the shard placement contract;
* :mod:`exchange` — the precomputed cross-shard forward schedule: the
  static shard-to-shard cell-edge matrix decomposed BvN-style into <= D-1
  rotation permutation legs (FAST, arxiv 2505.09764; hierarchical BvN,
  arxiv 2602.22756), executed as on-device ``ppermute`` collectives inside
  the shard_map tick loop — cross-shard cells never transit the host;
* :mod:`meshplane` — the DeviceTrafficPlane attachment: builds the mesh,
  partition, and exchange, installs the sharded superwindow kernel, and
  publishes the ``mesh.*`` metrics (host_bounces, cross_shard_cells,
  exchange_legs, per-device occupancy).

This module also owns :func:`device_mesh`, the single definition of
device-pool selection shared by every sharded consumer (the traffic
plane here and ops/round_step.py's ShardedPacketHopKernel).
"""

from __future__ import annotations

import numpy as np


def device_mesh(n_devices: int, axis_names=("flows",), shape=None):
    """Build a 1-D (or, with ``shape``, reshaped) jax Mesh over the first
    ``n_devices`` devices.  Prefers the default pool; when a TPU plugin
    owns the default slot with fewer chips than requested, falls back to
    the CPU pool (the 8-virtual-device test mesh / dryrun path).  Raises
    RuntimeError when not enough devices exist anywhere — the ONE
    definition of pool selection for every sharded consumer."""
    import jax
    from jax.sharding import Mesh

    pool = jax.devices()
    if len(pool) < n_devices:
        try:
            cpu_pool = jax.devices("cpu")
        except RuntimeError:
            cpu_pool = []
        if len(cpu_pool) >= n_devices:
            pool = cpu_pool
    devices = pool[:n_devices]
    if len(devices) < n_devices:
        raise RuntimeError(
            f"--tpu-devices={n_devices} but only {len(pool)} present")
    arr = np.array(devices)
    if shape is not None:
        arr = arr.reshape(shape)
    return Mesh(arr, axis_names=axis_names)
