"""Device-resident traffic plane: bulk flows advance in HBM, Python keeps
only the control plane.

This is the execution-plane promotion of ops/torcells_device.py (r3's
VERDICT item #1): instead of every DATA cell crossing the Python TCP stack
as discrete events, a Tor client in device mode builds its circuit through
the REAL engine (TCP connects, CREATE/EXTEND cells through real relays —
the control plane stays fully simulated), then registers the bulk transfer
as a device flow.  From that point the cells live in device tensors:

* one [F] flow table (circuit stage -> paced node, onward latency ticks,
  successor), sorted by paced node so the per-tick bandwidth allocation is
  the torcells segment-cumsum (exact greedy in circuit order, no sorting on
  device);
* per-node token buckets (1 ms refill, byte capacities from the SAME
  bucket parameters the engine's NetworkInterfaces use — ops/bandwidth.py);
* a [ring_len, F] arrival ring indexed by tick (the device analog of the
  delivery event queue).

The device plane is a two-stage pipeline over the engine's round loop
(stage -> launch -> collect):

* **stage** — client activations buffer injections host-side
  (``activate``) during a round;
* **launch** — at the TOP of the next dispatching round (right after the
  engine computes the window), ONE windowed dispatch advances the plane
  to the round barrier (ops/torcells_device.torcells_step_window_flush;
  state donated, so it never leaves HBM).  The dispatch is asynchronous:
  it computes while the host drains the round's arrivals (plugin
  execution + the native C plane);
* **collect** — at the next loop iteration, before the next window is
  computed, the engine materializes the dispatch's ONE packed flush
  buffer (forwards + delivered cursor + newly-completed chains +
  per-node byte deltas, delta-compacted on device) and wakes completed
  flows.

Completed flows wake their client process through an ordinary scheduled
event, so determinism is exact: completion ticks are device-computed, wake
times are their tick times clamped to the launching round's barrier, and
digests are identical across scheduler policies, across the device/numpy
execution modes (--device-plane=numpy runs the bit-identical host twin;
tests/test_device_plane.py pins both), and across pipelined vs serial
(--device-plane-sync) execution — the engine commits round N's plane
state before round N+1's staged injections are folded in, so overlap
never reorders anything (tests/test_device_pipeline.py).

What is and is NOT modeled (honesty contract, same spirit as
ops/bandwidth.py's docstring): the plane models BOTH directions of each
stream as independent cell chains (download server->exit->middle->guard->
client and upload client->guard->middle->exit->server), store-and-forward
at relay granularity with per-direction bucket contention (each host
contributes an egress node on its up bucket for sending hops and an
ingress node on its down bucket for the delivering hop — the same
send/receive TokenBucket split the engine's interfaces use), and fixed
512B+header wire cells.  It does not model per-cell TCP control (windows,
retransmits) for the bulk phase — circuit setup DOES exercise the full
TCP stack.  Reference analog: the traffic pattern shadow-plugin-tor
measures (worker.c:243-304 + network_interface.c:421-579 per-cell work,
executed here as dense tensor ticks).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import stime
from ..core.event import Event
from ..core.task import Task
from ..core.logger import get_logger

TICK_NS = 1_000_000          # 1 ms, = the interface refill interval


class _PoisonedFlush:
    """Fault-harness stand-in for an in-flight flush handle: materializing
    it raises (``device-dispatch:N``) or stalls (``device-dispatch-hang:N``,
    bounded so the abandoned guard thread cannot linger forever) — the
    deterministic stand-ins for a dispatch that failed or wedged."""

    def __init__(self, handle, hang: bool = False):
        self._handle = handle
        self._hang = hang

    def __array__(self, dtype=None, copy=None):
        if self._hang:
            import time as _wt
            # simlint: disable=SIM005 -- fault harness: a deliberate stall
            _wt.sleep(30.0)
        raise RuntimeError("fault injection: poisoned device dispatch")


class _SuperPlan:
    """One negotiated superwindow: the K=1 round recurrence replayed
    host-side (negotiate_superwindow), executed as ONE kernel launch.

    ``bounds`` is every merged virtual round's (window_start, window_end);
    ``targets`` the absolute step boundary each dispatching round's window
    maps to (ascending); ``round_of`` the bounds index that launched each
    target.  consume() maps the kernel's reached boundary (flush t_stop)
    back through ``round_of`` to learn which virtual round the plane — and
    therefore the engine's round counter and window bookkeeping — actually
    advanced to."""

    __slots__ = ("base", "targets", "bounds", "round_of")

    def __init__(self, base, targets, bounds, round_of):
        self.base = base
        self.targets = targets
        self.bounds = bounds
        self.round_of = round_of


class _FlowSpec:
    """One device-mode client = TWO independent cell chains, e.g. a tor
    download (server -> exit -> middle -> guard -> client) and upload
    (client -> guard -> middle -> exit -> server), or a star-bulk pair
    (server -> client / client -> server).  Chains may have different hop
    counts per spec — the flow table is built from the actual routes.  The
    client's flow is complete when BOTH chains have delivered.

    ``route_down`` may be None for an auto: consensus client; the plane
    resolves it at startup by replaying the client's derived path draw over
    the config-predicted consensus (resolve_auto_routes)."""

    __slots__ = ("client_name", "route_down", "route_up", "cells_down",
                 "cells_up", "circuit", "dirspec", "dest", "auto_start_ns")

    def __init__(self, client_name: str, route_down: Optional[List[str]],
                 route_up: Optional[List[str]], cells_down: int,
                 cells_up: int, dirspec: Optional[str] = None,
                 dest: Optional[str] = None):
        self.client_name = client_name
        self.route_down = route_down
        self.route_up = route_up
        self.cells_down = cells_down
        self.cells_up = cells_up
        self.circuit = -1
        self.dirspec = dirspec
        self.dest = dest
        # processless flow (scale tier): the plane self-activates it at
        # this sim time and completion needs no wake event — no plugin
        # ever joins, so the quiet client host stays a table row
        self.auto_start_ns: Optional[int] = None


def _cells_for(nstreams: int, specs: List[str]):
    from ..apps.tor import PAYLOAD_MAX
    cells_down = cells_up = 0
    for i in range(nstreams):
        up, down = (int(x) for x in specs[i % len(specs)].split(":"))
        cells_down += max(1, math.ceil(down / PAYLOAD_MAX))
        cells_up += max(1, math.ceil(up / PAYLOAD_MAX))
    return cells_down, cells_up


def parse_device_client(host_name: str, args: List[str]) -> Optional[_FlowSpec]:
    """Recognize a tor client process configured for device-plane data
    ('device' flag in its args).  args layout (apps/tor.py client role):
    client <socksport> <path> <dest> <destport> <nstreams> <spec...> device
    <path> is a static 3-hop list or 'auto:<dirhost>[:<dirport>]' (the
    consensus route is predicted at startup — resolve_auto_routes)."""
    if not args or args[0] != "client" or "device" not in args:
        return None
    # strip the mode token BEFORE positional parsing (client_main does the
    # same), so "client 9050 <path> dest 80 device" with nstreams omitted
    # falls back to the defaults instead of int("device") crashing
    args = [a for a in args if a != "device"]
    path_s = args[2]
    dest = args[3]
    nstreams = int(args[5]) if len(args) > 5 else 1
    specs = args[6:] or ["100:10000"]
    cells_down, cells_up = _cells_for(nstreams, specs)
    if path_s.startswith("auto:"):
        return _FlowSpec(host_name, None, None, cells_down, cells_up,
                         dirspec=path_s[len("auto:"):], dest=dest)
    path = [h.partition(":")[0] for h in path_s.split(",")]
    if len(path) != 3:
        raise ValueError(f"{host_name}: device-plane needs a 3-hop path")
    guard, middle, exit_ = path[0], path[1], path[2]
    return _FlowSpec(host_name,
                     [dest, exit_, middle, guard, host_name],
                     [host_name, guard, middle, exit_, dest],
                     cells_down, cells_up, dest=dest)


def parse_device_tgen(host_name: str, args: List[str]) -> Optional[_FlowSpec]:
    """Recognize a tgen client configured for device-plane data (workload
    #2, star bulk): client <server> <port> <spec...> device.  The flow is a
    2-hop pair: server->client download and client->server upload, paced by
    the two hosts' own up/down buckets."""
    if not args or args[0] != "client" or "device" not in args:
        return None
    args = [a for a in args if a != "device"]
    server = args[1]
    specs = args[3:] if len(args) > 3 else ["1024:65536"]
    cells_down, cells_up = _cells_for(len(specs), specs)
    return _FlowSpec(host_name, [server, host_name], [host_name, server],
                     cells_down, cells_up, dest=server)


def resolve_auto_routes(engine, specs: List[_FlowSpec]) -> None:
    """Fill in auto: specs' routes at startup by replaying each client's
    path draw: the consensus is config-determined (every relay publishes
    its name/orport/bw from its own args, and the authority serves them
    sorted by name), and device-mode clients draw from the DERIVED
    per-host stream host.random.spawn('device-circuit') — independent of
    execution order, so the replay here is exact.  The runtime cross-check
    (DeviceTrafficPlane.check_route via api.device_flow_start) fails
    loudly if the fetched consensus ever diverges from this prediction."""
    autos = [s for s in specs if s.route_down is None]
    if not autos:
        return
    from ..apps.tor import pick_weighted
    from ..core.rng import RandomSource, derive
    relays = {}
    for _hid, host_name, app, a in engine.iter_process_specs():
        if not app.endswith("tor"):
            continue
        # relay <orport> <dirauth_host:port> <bw>: publishes into the
        # consensus (apps/tor.py relay role)
        if a and a[0] == "relay" and len(a) > 2 and a[2]:
            orport = int(a[1]) if len(a) > 1 else 9001
            bw = int(a[3]) if len(a) > 3 else 100
            relays[host_name] = (orport, bw)
    consensus = [(n, p, w) for n, (p, w) in sorted(relays.items())]
    if not consensus:
        raise ValueError(
            "device plane: auto: clients configured but no publishing "
            "relays found (no dirauth-registered relay processes)")
    for s in autos:
        # the client's derived path stream, computed arithmetically so a
        # table-resident client needs no Host object to predict its route
        key = engine.host_stream_key(s.client_name)
        if key is None:
            raise ValueError(f"device plane: unknown host "
                             f"{s.client_name!r}")
        rng = RandomSource(derive(key, "device-circuit"))
        path = [name for name, _port in pick_weighted(rng, consensus)]
        if len(path) != 3:
            raise ValueError(
                f"{s.client_name}: consensus has only {len(path)} usable "
                "relays; device-plane circuits need 3 hops")
        guard, middle, exit_ = path[0], path[1], path[2]
        s.route_down = [s.dest, exit_, middle, guard, s.client_name]
        s.route_up = [s.client_name, guard, middle, exit_, s.dest]


class DeviceTrafficPlane:
    """Owns the device-resident state for all registered bulk flows and the
    engine-side activation/wake bookkeeping."""

    # process-wide high-water mark of the quiet-tick sharded-variant
    # cache, reported by `simfleet smoke` against the checked-in
    # [tool.simjit.budget] "device_plane.sharded_variants" entry (the
    # runtime half of the SIM305 compile-budget cross-check; the static
    # half pins the literal cap in _pick_sharded_step to the same value)
    sharded_variants_high_water = 0

    def __init__(self, engine, specs: List[_FlowSpec], mode: str = "device"):
        if engine.shard_count > 1:
            raise RuntimeError(
                "--device-plane is global state; it does not compose with "
                "--processes sharding (run the device plane single-process)")
        assert mode in ("device", "numpy")
        self.engine = engine
        self.mode = mode
        # dispatch cadence: accumulate at least this many steps before
        # launching a kernel dispatch (injections wait with them).  One
        # dispatch per engine round would pay a full state round trip per
        # round on backends without buffer donation (jax CPU copies the
        # donated state every call — measured ~7 ms at 50k flows); batching
        # K rounds' ticks into one dispatch amortizes it K-fold.  Wake
        # times are observed at the consuming barrier either way, and both
        # execution modes follow the identical cadence, so digests stay
        # parity-comparable.
        self.min_dispatch_steps = max(
            1, int(getattr(engine.options, "device_plane_batch_steps", 8)))
        # superwindow depth: how many consecutive lookahead rounds one
        # kernel launch may cover when no host-side event falls inside
        # them (engine._advance_window negotiates per round; ISSUE 7).
        # Also the static pad length of the kernel's targets vector.
        self.superwindow_rounds = max(
            1, int(getattr(engine.options, "superwindow_rounds", 8)))
        self._pending_plan: Optional[_SuperPlan] = None
        self._active_plan: Optional[_SuperPlan] = None
        self.superwindows = 0
        self._rounds_launched = 0    # virtual rounds covered by launches
        self._mesh = None
        self._shard = None           # layout dict when sharded
        self._sharded_step = None
        self.specs = specs
        for i, s in enumerate(specs):
            s.circuit = i
        # activate/check_route/join are keyed by host name, so the
        # one-flow-per-host rule holds for PLUGIN-driven specs only; auto
        # (processless) flows self-stage and wake by circuit index, never
        # through this dict — a swarm peer may carry many chains
        plugin_specs = [s for s in specs if s.auto_start_ns is None]
        self._by_client = {s.client_name: s for s in plugin_specs}
        if len(self._by_client) != len(plugin_specs):
            # two device-mode clients on one host would silently share a
            # circuit (the second spec wins) and one client's
            # activate/join would target the wrong flow, blocking until
            # end_time with no error
            seen: set = set()
            dup = next(s.client_name for s in plugin_specs
                       if s.client_name in seen or seen.add(s.client_name))
            raise ValueError(
                f"device plane: host {dup!r} has multiple device-mode tor "
                "clients; run at most one per host (flows are keyed by "
                "host name)")
        self._meshinfo = None        # set by attach_mesh when sharded
        # the measured per-box cost model (ISSUE 15, shadow_tpu/prof/):
        # consulted by attach_mesh for the exchange-mode decision and by
        # advance() for per-launch predicted cost.  A missing or
        # fingerprint-mismatched COSTMODEL.json degrades (loudly) to
        # None — the pre-model heuristics — never a crash.
        if mode == "device":
            from ..prof.model import load_for_engine
            self._costmodel, self._costmodel_status = load_for_engine(
                engine.options)
        else:
            self._costmodel, self._costmodel_status = None, "off"
        self._build_layout(engine)
        # COSTMODEL auto-tuner (ISSUE 16, prof/autotune.py): with a
        # loaded model covering this flow table, pick the effective
        # superwindow depth and the delta-compacted flush from measured
        # costs.  Digest-NEUTRAL by construction: K only merges rounds
        # the halt rule maps back exactly, and the capped flush is a
        # transport encoding (overflow re-reads full-length).  Cadence
        # and granule are digest-BEARING and stay at contract values.
        from ..prof.autotune import plan_dispatch
        self._tune_plan = plan_dispatch(
            self._costmodel, self._costmodel_status, engine.options,
            self.n_flows, self.n_chains, self.n_nodes)
        self._flush_caps = None      # (cap_chains, cap_nodes) when engaged
        self._inflight_caps = None   # caps the IN-FLIGHT dispatch packed with
        self._inflight_args = None   # its inputs (overflow re-run, nodonate)
        self.flush_bytes_saved = 0
        self.flush_overflows = 0
        if self._tune_plan.source == "model":
            self.superwindow_rounds = self._tune_plan.superwindow_rounds
            if self.superwindow_rounds > getattr(engine, "_superwindow", 1):
                engine._superwindow = self.superwindow_rounds
            if self._tune_plan.flush_compact and mode == "device":
                import jax
                if jax.default_backend() == "cpu":
                    # overflow recovery re-runs the SAME inputs through
                    # the full-length kernel, which needs them alive
                    # after the launch — exactly the non-donating CPU
                    # dispatch path's property.  Donating backends keep
                    # the full flush.
                    self._flush_caps = (self._tune_plan.flush_cap_chains,
                                        self._tune_plan.flush_cap_nodes)
        engine.metrics.source("autotune", self._autotune_metrics)
        # quiet-tick exchange-leg fusion (ISSUE 16): set by attach_mesh —
        # per-chain leg bitmasks; dispatch picks a variant kernel with
        # the quiet legs compiled out (superset masks are bit-identical)
        self._chain_leg_bits = None
        self._full_leg_bits = 0
        self._active_leg_bits = 0
        self._sharded_variants: Dict[int, object] = {}
        # multi-chip: shard the flow table over a device mesh (same
        # --tpu-devices axis the scheduler policy scales on).  Exact — see
        # parallel/mesh/ (partition + BvN exchange); state/API stay in the
        # ORIGINAL flow space, translated at the dispatch boundary.
        if mode == "device":
            n_dev = int(getattr(engine.options, "tpu_devices", 1) or 0)
            if n_dev == 0:
                import jax
                n_dev = len(jax.devices())
            if n_dev > 1:
                # the mesh path's launch cut is the exchange-leg mask;
                # flush compaction stays single-device (the overflow
                # re-run would need a per-variant full kernel here)
                self._flush_caps = None
                self._setup_sharding(n_dev)
        self._state = None           # lazy: built at first activation
        # processless flows (scale tier): (start_ns, circuit) ascending;
        # the plane self-activates each at its start time — next_time()
        # keeps the engine's windows coming until the last one is staged
        self._auto = sorted(
            (s.auto_start_ns, i) for i, s in enumerate(specs)
            if s.auto_start_ns is not None)
        self._auto_pos = 0
        self._inflight = False
        self._flush_handle = None    # in-flight packed flush (1-deep slot)
        self._flush_step = None      # backend-selected flush kernel (lazy)
        self._ticks_synced = 0
        self._inject_buf: List[Tuple[int, int]] = []   # (circuit, cells)
        self._waiters: Dict[int, Tuple[object, object]] = {}
        self._done: Dict[int, int] = {}   # circuit -> wake sim time ns
        self._woken: set = set()
        self._chain_done: Optional[np.ndarray] = None  # [C] step or -1
        self._flow_args_cached = None
        self._zero_inject_cached = None   # device-resident, reused when the
                                          # staged inject buffer is empty
        self.total_forwards = 0
        self.total_injected_cells = 0
        self.dispatches = 0
        self.device_ns = 0
        self.host_ns = 0
        # pipeline introspection: actual host<->device interactions (kernel
        # dispatch + inject upload + flush read) and the wall the in-flight
        # dispatch had to compute behind host round work
        self.device_calls = 0
        self.pipeline_overlap_ns = 0
        self._launch_wall = 0
        self._launch_pred = None     # (per_step_us, fixed_us) model
        self._launch_base = 0        # kernel t at launch (steps = t_stop-)
        # --device-plane-sync: block on the dispatch at launch time (the
        # serial oracle the pipelined run is digest-compared against)
        self._sync = bool(getattr(engine.options, "device_plane_sync",
                                  False))
        # idle fast path: when the plane provably has no cells anywhere
        # (every dispatched cell delivered, nothing buffered), rounds only
        # bank refill ticks instead of spinning the kernel; the next real
        # dispatch folds them in exactly (capped refill is idempotent)
        self._cells_dispatched = 0
        self._cells_delivered_seen = 0
        self._idle_ticks_banked = 0
        self.idle_rounds_skipped = 0
        # Dispatch supervision (ISSUE 2): every dispatch window is logged as
        # (base_ticks, inject pairs, n, idle) — a few ints per window — so
        # that a FAILED in-flight dispatch (exception at materialization, or
        # collect timeout via --device-watchdog-sec) can be recovered by
        # replaying the whole window history on the bit-identical numpy
        # twin.  Full-history replay rather than one-window replay because
        # the carried device state is donated on accelerators: after the
        # failed dispatch there is no pre-state buffer left to restart from.
        # On recovery the backend is PERMANENTLY demoted to the numpy twin
        # (graceful degradation: digest parity preserved, device speed
        # forfeited), counted in engine.supervision.
        self._dispatch_log: List[tuple] = []
        # observability hooks (shadow_tpu/obs/): dispatch/collect latency
        # histograms, bytes per flush, pipeline-overlap efficiency — all
        # no-ops (one attribute check) when tracing/metrics are off
        from ..obs.profiler import DeviceProfiler
        self._profiler = DeviceProfiler()
        self._watchdog_sec = float(
            getattr(engine.options, "device_watchdog_sec", 0) or 0)
        self.demoted = False
        self.recoveries = 0
        from ..core.supervision import parse_fault_inject
        fault = parse_fault_inject(
            getattr(engine.options, "fault_inject", "") or "")
        self._fault_dispatch = 0
        self._fault_hang = False
        if fault and fault["kind"] in ("device-dispatch",
                                       "device-dispatch-hang"):
            self._fault_dispatch = fault["dispatch"]
            self._fault_hang = fault["kind"] == "device-dispatch-hang"
        # self-healing (ISSUE 17): an injected device loss re-shards the
        # mesh onto D-1 devices at the next quiesced round boundary; a
        # demote-repromote poison fails like device-dispatch:N but the
        # demotion serves a probation (--repromote-after clean collects)
        # and then climbs back to the device rung once, replay guard armed
        self._fault_device_lost = 0
        if fault and fault["kind"] == "device-lost":
            self._fault_device_lost = fault["round"]
        if fault and fault["kind"] == "demote-repromote":
            self._fault_dispatch = fault["dispatch"]
        self._repromote_after = int(
            getattr(engine.options, "repromote_after", 0) or 0)
        self._probation_clean = 0
        self._repromoted = False
        self._replay_base = None   # state stash at re-promotion: a second
                                   # failure replays base + log, then the
                                   # numpy demotion is permanent
        # fleet lane (ISSUE 18): an engine run as a fleet batch lane
        # carries a FleetLane on its options; this plane's device
        # dispatches then ride the shared vmapped program (lane.dispatch
        # pads to the shape class, the batched launch advances every
        # parked lane at once, the lane unpads this plane's row).  The
        # lane path is synchronous (the digest-pinned --device-plane-sync
        # shape) and single-device only — sharded meshes keep their own
        # program.  Flush caps stay off: the lane's flush section is
        # always full-length (repacked host-side), so the capped variant
        # would only add an overflow path the batch cannot re-run.
        self._lane = None
        lane = getattr(engine.options, "_fleet_lane", None)
        if lane is not None and mode == "device" and self._shard is None:
            self._flush_caps = None
            self._lane = lane
            lane.attach_plane(self)
            from ..obs.metrics import fleet_source
            engine.metrics.source("fleet", fleet_source(lane.plane))

    # -- static layout ----------------------------------------------------
    def _build_layout(self, engine) -> None:
        """Flow table from the static specs: the torcells layout (sorted by
        paced node, segment cumsum offsets) with per-flow onward latencies
        gathered from the engine's real topology rows — no [H, H] local
        matrix is ever materialized (10k-host graphs would not fit)."""
        topo = engine.topology
        # Every host contributes up to TWO plane nodes: its EGRESS node
        # (up-bandwidth bucket — paces stages 0..3, the sending hops) and
        # its INGRESS node (down-bandwidth bucket — paces stage 4, the
        # delivering hop).  Distinct buckets per direction mirror the
        # engine's send/receive TokenBuckets; a client uploading and
        # downloading concurrently contends on the right one each way.
        names: List[Tuple[str, str]] = []      # (host, "tx"|"rx")
        name_idx: Dict[Tuple[str, str], int] = {}

        def node_of(nm: str, kind: str) -> int:
            key = (nm, kind)
            if key not in name_idx:
                name_idx[key] = len(names)
                names.append(key)
            return name_idx[key]

        # chains: 2 per spec (download then upload), VARIABLE hop counts —
        # a tor circuit is 5 stages, a star-bulk pair is 2 (the flow table
        # is built from the actual routes, not a fixed grid)
        chains: List[List[int]] = []
        for s in self.specs:
            for rt in (s.route_down, s.route_up):
                chains.append([node_of(nm, "tx") for nm in rt[:-1]] +
                              [node_of(rt[-1], "rx")])
        self.node_names = names
        self.node_hosts = []
        self.node_kind = [k for (_nm, k) in names]
        self._has_upload = np.array([s.cells_up > 0 for s in self.specs],
                                    dtype=bool)
        rows = np.empty(len(names), dtype=np.int64)
        rates = np.empty(len(names), dtype=np.int64)
        table = getattr(engine, "host_table", None)
        for i, (nm, kind) in enumerate(names):
            # deliberately NOT engine.host_by_name: that would materialize
            # every table row the flow table references — the whole point
            # is that quiet hosts contribute array rows, so read the
            # table's columns instead
            host = engine.hosts_by_name.get(nm)
            if host is not None:
                self.node_hosts.append(host)
                rows[i] = host.topo_row
                rates[i] = (host.params.bw_up_kibps if kind == "tx"
                            else host.params.bw_down_kibps)
                continue
            info = table.plane_host_info(nm) if table is not None else None
            if info is None:
                raise ValueError(f"device plane: unknown host {nm!r}")
            self.node_hosts.append(None)
            topo_row, bw_up, bw_down = info
            rows[i] = topo_row
            rates[i] = bw_up if kind == "tx" else bw_down
        from ..ops.bandwidth import bucket_params
        refill, capacity = bucket_params(rates)
        self.refill = refill.astype(np.int64)
        self.capacity = capacity.astype(np.int64)
        # flatten chains into pre-sort flow arrays (chain-contiguous)
        c = len(chains)
        chain_len = np.array([len(rt) for rt in chains], dtype=np.int64)
        n_flows = int(chain_len.sum())
        flow_chain = np.repeat(np.arange(c, dtype=np.int64), chain_len)
        flow_stage = np.concatenate(
            [np.arange(m, dtype=np.int64) for m in chain_len])
        flow_node = np.concatenate(
            [np.asarray(rt, dtype=np.int64) for rt in chains])
        is_last_pre = flow_stage == chain_len[flow_chain] - 1
        nxt = np.where(is_last_pre, flow_node,
                       np.roll(flow_node, -1))       # next stage, same chain
        pre_succ = np.where(is_last_pre, -1,
                            np.arange(n_flows, dtype=np.int64) + 1)
        lat_ns = np.asarray(topo.latency_ns)[rows[flow_node], rows[nxt]]
        lat_pre = np.where(is_last_pre, 0,
                           np.maximum(lat_ns // TICK_NS, 1))
        # sort by (paced node, chain, stage): the per-tick allocation is a
        # segment cumsum in this order (exact greedy per node)
        order = np.lexsort((flow_stage, flow_chain, flow_node))
        pos_of = np.empty(n_flows, dtype=np.int64)
        pos_of[order] = np.arange(n_flows)
        flow_node = flow_node[order]
        lat = lat_pre[order]
        succ = np.where(pre_succ[order] >= 0,
                        pos_of[np.maximum(pre_succ[order], 0)], -1)
        starts = np.flatnonzero(np.r_[True, flow_node[1:] != flow_node[:-1]])
        seg_id = np.cumsum(np.r_[0, (flow_node[1:] != flow_node[:-1])
                                 .astype(np.int64)])
        self.flow_node = flow_node
        self.flow_lat = lat.astype(np.int64)
        self.flow_succ = succ
        self.seg_start = starts[seg_id]
        self.flow_circ = flow_chain[order]
        self.flow_stage = flow_stage[order]
        # per-chain entry (stage 0) and exit (last stage) flow positions
        chain_base = np.r_[0, np.cumsum(chain_len)[:-1]]
        self.first_flow = pos_of[chain_base]
        self.last_flow = pos_of[chain_base + chain_len - 1]
        self.n_chains = len(chains)
        # Step granulation: the kernel's loop iteration covers ``granule``
        # milliseconds.  Chosen so the arrival ring stays <= ~64 slots even
        # on multi-second-latency topologies (the reference GraphML has
        # 2.3 s paths; a 1 ms-exact ring would be [2300, F] ~ 1 GB at 10k
        # circuits) AND the sequential step count stays low (state bytes x
        # steps is the device cost).  Bandwidth is exact at every granule
        # (refill and burst capacity scale with the step); per-hop latency
        # rounds UP to the next granule multiple — <= granule-1 ms late per
        # hop, never early — identically in both execution modes.
        max_lat = int(self.flow_lat.max()) if len(lat) else 1
        g = max(1, -(-(max_lat + 1) // 64))
        override = getattr(engine.options, "device_plane_granule_ms", 0)
        if override:
            g = int(override)
        self.granule = g
        lat_steps = -(-self.flow_lat // g)
        self.flow_lat_steps = np.where(self.flow_lat > 0,
                                       np.maximum(lat_steps, 1),
                                       0).astype(np.int64)
        self.ring_len = int(self.flow_lat_steps.max()) + 2
        self.refill_step = self.refill * g
        # rate preservation: a backlogged node must be able to spend a full
        # step's refill; burst capacity otherwise keeps the 1 ms bucket's
        self.capacity_step = np.maximum(self.capacity, self.refill_step)
        from ..ops.torcells_device import CELL_WIRE_BYTES
        if int(self.capacity_step.max()) // CELL_WIRE_BYTES >= 2 ** 31:
            # the int32 arrival ring (ops/torcells_device.RING_DTYPE) holds
            # per-step cell counts bounded by capacity/cell-size; a config
            # that could overflow it must fail loudly, not wrap
            raise ValueError(
                "device plane: a node's per-step burst capacity exceeds "
                "2**31 cells — the int32 arrival ring would overflow "
                "(lower --device-plane-granule-ms or the host bandwidth)")
        self.n_flows = n_flows
        self.n_nodes = len(names)
        # Vectorized tracker feed (ISSUE 7 control-plane cut): collects
        # fold each flush's per-node byte deltas into ONE numpy
        # scatter-add here; the per-host split into Tracker counter
        # objects happens lazily, only when something actually reads a
        # tracker (heartbeat, digest, teardown) — Tracker.pull_device().
        # 10k quiet hosts pay one np.add.at per collect instead of a
        # Python loop over every touched node.
        self._node_pending = np.zeros(self.n_nodes, dtype=np.int64)
        self._table = table
        name_nodes: Dict[str, List[int]] = {}
        for i, (nm, _kind) in enumerate(names):
            name_nodes.setdefault(nm, []).append(i)
        for nm, nodes in name_nodes.items():
            host = engine.hosts_by_name.get(nm)
            if host is not None:
                host.tracker._device_feed = (self, nodes)
            else:
                # table row: the table folds these nodes' deltas into its
                # tracker columns, and wires the feed at materialization
                table.set_device_nodes(nm, nodes, self)

    # -- state ------------------------------------------------------------
    def _init_state(self):
        if self._shard is not None:
            f = len(self._shard["src"])
            h = len(self._shard["refill"])
            tokens0 = self._shard["capacity"]
        else:
            f, h = self.n_flows, self.n_nodes
            tokens0 = self.capacity_step
        from ..ops.torcells_device import RING_DTYPE
        zeros_f = np.zeros(f, dtype=np.int64)
        state = (np.int64(self._ticks_synced),
                 zeros_f.copy(),                                   # queued
                 np.zeros((self.ring_len, f), dtype=RING_DTYPE),   # ring
                 tokens0.copy(),                                   # tokens
                 zeros_f.copy(),                                   # delivered
                 zeros_f.copy(),                                   # target
                 np.full(f, -1, dtype=np.int64),                   # done_tick
                 np.zeros(h, dtype=np.int64))                      # node_sent
        if self.mode == "device":
            import jax.numpy as jnp
            state = tuple(jnp.asarray(a) for a in state)
        self._state = state
        self._flow_args_cached = None
        self._zero_inject_cached = None
        self._chain_done = np.full(self.n_chains, -1, dtype=np.int64)

    def _setup_sharding(self, n_dev: int) -> None:
        """The ONE sharding entry point: the mesh plane (parallel/mesh/)
        owns partition, exchange schedule, kernel, and metrics."""
        from .mesh.meshplane import attach_mesh
        attach_mesh(self, n_dev)

    def _unshard_state(self, lay) -> tuple:
        """Translate the live padded state back to the ORIGINAL flow/node
        space under layout ``lay`` — the inverse of the pad_state
        translation: flow arrays gather through ``inv``, node arrays
        scatter through ``node_src`` (each global node lives on exactly
        one shard, so the scatter is an assignment)."""
        t, queued, ring, tokens, delivered, target, done_tick, node_sent = \
            (np.asarray(a) for a in self._state)
        inv = lay["inv"]
        node_src = lay["node_src"]
        valid = node_src >= 0
        tok = np.zeros(self.n_nodes, dtype=np.int64)
        sent = np.zeros(self.n_nodes, dtype=np.int64)
        tok[node_src[valid]] = tokens[valid]
        sent[node_src[valid]] = node_sent[valid]
        return (np.int64(t), queued[inv], np.ascontiguousarray(ring[:, inv]),
                tok, delivered[inv], target[inv], done_tick[inv], sent)

    @staticmethod
    def _state_digest(state) -> str:
        """Canonical digest of an original-space state tuple (dtype, shape,
        bytes per tensor) — the re-layout pin: translating state between
        device layouts must be the identity in the original space."""
        import hashlib
        h = hashlib.sha256()
        for a in state:
            arr = np.asarray(a)
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()

    def _reshard(self, engine) -> None:
        """Mid-run device loss on the sharded mesh (ROADMAP 4(b)): at a
        quiesced round boundary (no dispatch in flight), translate the
        live padded state back to the original flow space, re-run the
        chain partitioner and BvN exchange schedule for the surviving
        D-1 devices, translate the state into the new layout, and PIN the
        round trip — the original-space digest before the re-layout must
        equal the digest read back through the new layout, or the run
        aborts loudly.  The plane's mode, pipeline, superwindow and
        checkpoint contracts are untouched; only the layout moved.
        D=2 loses the mesh entirely and continues on the single-device
        kernel (same digest pin, identity translation)."""
        import time as _wt
        t0 = _wt.perf_counter_ns()
        old = self._shard
        n_old = int(old["n_shards"])
        n_new = n_old - 1
        old_info = self._meshinfo
        orig = self._unshard_state(old)
        digest_before = self._state_digest(orig)
        # old-layout kernels and caches die with the lost device
        self._sharded_variants.clear()
        self._flow_args_cached = None
        self._zero_inject_cached = None
        if n_new < 2:
            self._mesh = None
            self._shard = None
            self._sharded_step = None
            self._mesh_make_step = None
            self._chain_leg_bits = None
            self._full_leg_bits = 0
            self._active_leg_bits = 0
            state = orig
            if old_info is not None:
                old_info.n_devices = 1
                old_info.exchange_mode = "single"
            digest_after = self._state_digest(state)
        else:
            self._setup_sharding(n_new)
            # the new schedule's leg numbering shares nothing with the old
            # mask bookkeeping: run the always-correct full kernel from
            # here on (-1 is the full-kernel sentinel; future activations
            # OR into it harmlessly)
            self._active_leg_bits = -1
            lay = self._shard
            from .mesh.partition import pad_state
            keep, src = lay["keep"], lay["src"]
            ring_o = orig[2]
            ring_p = np.zeros((self.ring_len, len(src)), dtype=ring_o.dtype)
            ring_p[:, keep] = ring_o[:, src[keep]]
            node_src = lay["node_src"]
            valid = node_src >= 0
            tok_p = np.zeros(len(node_src), dtype=np.int64)
            sent_p = np.zeros(len(node_src), dtype=np.int64)
            tok_p[valid] = orig[3][node_src[valid]]
            sent_p[valid] = orig[7][node_src[valid]]
            state = (orig[0], pad_state(lay, orig[1]), ring_p, tok_p,
                     pad_state(lay, orig[4]), pad_state(lay, orig[5]),
                     pad_state(lay, orig[6], fill=-1), sent_p)
            self._state = state
            digest_after = self._state_digest(self._unshard_state(lay))
            # runtime counters survive the re-layout (the schedule-shape
            # fields are the NEW mesh's, by design)
            self._meshinfo.cross_shard_cells += old_info.cross_shard_cells
            self._meshinfo.host_bounces += old_info.host_bounces
        if digest_after != digest_before:
            raise RuntimeError(
                f"device plane re-shard {n_old}->{n_new}: state digest "
                f"changed across the re-layout ({digest_before[:12]} != "
                f"{digest_after[:12]}) — the translation is not the "
                "identity; aborting rather than continuing on corrupt "
                "state")
        if self.mode == "device":
            import jax.numpy as jnp
            state = tuple(jnp.asarray(a) for a in state)
        self._state = state
        engine.supervision.count_reshard(
            n_old, n_new, mttr_ns=_wt.perf_counter_ns() - t0)

    def _read_summaries(self):
        """(delivered, done_tick, node_sent) in the ORIGINAL flow/node
        space, whatever the execution layout.  Final-state reader for
        tests/tooling (e.g. the conservation gate) — the engine hot path
        never calls this; consume() reads the packed flush buffer, and
        materializing full state tensors here would forfeit the pipeline
        if it ever crept into a per-round path."""
        delivered = np.asarray(self._state[4])
        done_tick = np.asarray(self._state[6])
        node_sent = np.asarray(self._state[7])
        if self._shard is None:
            return delivered, done_tick, node_sent
        inv = self._shard["inv"]
        node_src = self._shard["node_src"]
        global_sent = np.zeros(self.n_nodes, dtype=np.int64)
        valid = node_src >= 0
        np.add.at(global_sent, node_src[valid], node_sent[valid])
        return delivered[inv], done_tick[inv], global_sent

    def _flow_args(self):
        """The static flow tables, resident where the kernel runs: committed
        device buffers in device mode (uploaded ONCE — re-sending ~2 MB of
        int64 tables per dispatch at 10k circuits would waste host link
        bandwidth every round), plain numpy for the twin."""
        if self._flow_args_cached is None:
            args = (self.flow_node, self.flow_lat_steps, self.flow_succ,
                    self.seg_start, self.refill_step, self.capacity_step,
                    self.last_flow)
            if self.mode == "device":
                import jax.numpy as jnp
                args = tuple(jnp.asarray(a) for a in args)
            self._flow_args_cached = args
        return self._flow_args_cached

    def _zero_inject(self):
        """A reusable (device-resident in device mode) zero inject vector in
        the execution layout — most dispatches carry no injections, and
        re-uploading two [F] int64 zero vectors per dispatch is exactly the
        per-round transfer chatter the pipeline exists to cut."""
        if self._zero_inject_cached is None:
            f = len(self._shard["src"]) if self._shard is not None \
                else self.n_flows
            z = np.zeros(f, dtype=np.int64)
            if self.mode == "device":
                import jax.numpy as jnp
                z = jnp.asarray(z)
            self._zero_inject_cached = z
        return self._zero_inject_cached

    # -- app-facing -------------------------------------------------------
    def activate(self, client_name: str, cells: Optional[int] = None) -> int:
        """Called by the client app once its circuit is built: inject both
        directions' cells (download at the server's chain head, upload at
        the client's) on the next dispatch."""
        spec = self._by_client.get(client_name)
        if spec is None:
            raise ValueError(f"{client_name} has no device flow spec")
        if cells is not None and cells < 1:
            # a zero-cell chain's completion (target > 0) can never fire, so
            # the joining client would block until end_time — reject loudly
            raise ValueError(
                f"{client_name}: activate(cells={cells}) — device flows "
                "need at least 1 cell")
        return self._activate_spec(spec, cells)

    def _activate_spec(self, spec, cells: Optional[int] = None) -> int:
        """Inject a spec's cells (shared by name-keyed plugin activation
        and circuit-indexed auto staging — auto flows are not in
        ``_by_client``, a host may carry many of them)."""
        # an explicit cells argument overrides the DOWNLOAD size; the
        # configured upload still runs (completion requires both chains)
        down = spec.cells_down if cells is None else cells
        up = spec.cells_up
        self._inject_buf.append((2 * spec.circuit, down))
        if up:
            self._inject_buf.append((2 * spec.circuit + 1, up))
        if self._chain_leg_bits is not None:
            # quiet-tick fusion bookkeeping: the chains this injection
            # activates may now carry cells over their exchange legs —
            # the active-leg superset only ever GROWS (in-flight cells
            # never migrate legs), which is what keeps every cached
            # masked variant digest-identical to the full kernel
            self._active_leg_bits |= int(self._chain_leg_bits[
                2 * spec.circuit])
            if up:
                self._active_leg_bits |= int(self._chain_leg_bits[
                    2 * spec.circuit + 1])
        self.total_injected_cells += down + up
        return spec.circuit

    def check_route(self, client_name: str, hops: List[str]) -> None:
        """Cross-check the client's RUNTIME route (hop host names in
        client-side order, e.g. [guard, middle, exit] for tor or [server]
        for star bulk) against the spec the flow table was built from.  A
        mismatch means an auto: client's fetched consensus diverged from
        the startup prediction — the flows would silently ride the wrong
        links, so fail loudly instead."""
        spec = self._by_client.get(client_name)
        if spec is None:
            raise ValueError(f"{client_name} has no device flow spec")
        expect = spec.route_up[1:-1] if len(spec.route_up) > 2 \
            else [spec.route_up[-1]]
        if list(hops) != expect:
            raise RuntimeError(
                f"device plane: {client_name}'s runtime route {hops} != "
                f"predicted route {expect} (the consensus diverged from "
                "the startup prediction — e.g. a relay published late)")

    def is_done(self, circuit: int) -> bool:
        return circuit in self._done

    def result(self, circuit: int) -> int:
        return self._done[circuit]

    def register_waiter(self, circuit: int, process, thread) -> None:
        self._waiters[circuit] = (process, thread)

    def warmup(self) -> None:
        """Pre-compile the windowed kernel for this plane's exact shapes
        using throwaway state (XLA compiles are 20-40s on a real TPU; the
        bench excludes them from timed walls).  No plane state is touched."""
        if self.mode != "device":
            return
        if self._lane is not None:
            # fleet lanes share the batched program, compiled once per
            # (shape class, width) at the first launch — a per-lane
            # warmup would compile the UNBATCHED kernel nobody calls
            return
        import jax
        import jax.numpy as jnp
        from ..ops.torcells_device import (RING_DTYPE,
                                           step_window_flush_for_backend)
        if self._flush_step is None:
            self._flush_step = step_window_flush_for_backend()
        if self._shard is not None:
            lay = self._shard
            fp, hp = len(lay["src"]), len(lay["refill"])
            zp = np.zeros(fp, dtype=np.int64)
            state = (np.int64(0), jnp.zeros(fp, jnp.int64),
                     jnp.zeros((self.ring_len, fp), RING_DTYPE),
                     jnp.asarray(lay["capacity"]),
                     jnp.zeros(fp, jnp.int64), jnp.zeros(fp, jnp.int64),
                     jnp.full(fp, -1, jnp.int64), jnp.zeros(hp, jnp.int64))
            out = self._sharded_step(
                *state, zp, zp, self._pad_targets([1]), np.int64(0),
                lay["flow_node_local"], lay["succ_global"],
                lay["seg_start_local"], lay["refill"], lay["capacity"],
                lay["arr_lat"], lay["shard_base"])
            jax.block_until_ready(out)
            return
        f, h = self.n_flows, self.n_nodes
        z = np.zeros(f, dtype=np.int64)
        state = (np.int64(0), jnp.zeros(f, jnp.int64),
                 jnp.zeros((self.ring_len, f), RING_DTYPE),
                 jnp.asarray(self.capacity_step),
                 jnp.zeros(f, jnp.int64), jnp.zeros(f, jnp.int64),
                 jnp.full(f, -1, jnp.int64), jnp.zeros(h, jnp.int64))
        out = self._flush_step(
            *state, z, z, self._pad_targets([1]), np.int64(0),
            self.flow_node, self.flow_lat_steps, self.flow_succ,
            self.seg_start, self.refill_step, self.capacity_step,
            self.last_flow, ring_len=self.ring_len)
        jax.block_until_ready(out)
        if self._flush_caps is not None:
            # the tuned dispatch runs the CAPPED flush kernel — compile
            # it here too so the first timed dispatch pays no XLA wall
            from ..ops.torcells_device import torcells_step_window_flush_capped
            cc, hh = self._flush_caps
            out = torcells_step_window_flush_capped(
                *state, z, z, self._pad_targets([1]), np.int64(0),
                self.flow_node, self.flow_lat_steps, self.flow_succ,
                self.seg_start, self.refill_step, self.capacity_step,
                self.last_flow, ring_len=self.ring_len,
                cap_chains=cc, cap_nodes=hh)
            jax.block_until_ready(out)

    def _pad_targets(self, targets: List[int]) -> np.ndarray:
        """Pad a superwindow's boundary list to the static kernel shape by
        repeating the final boundary (repeats are never reached: the loop
        ends at targets[-1])."""
        pad = self.superwindow_rounds
        out = np.full(pad, int(targets[-1]), dtype=np.int64)
        out[:len(targets)] = np.asarray(targets, dtype=np.int64)
        return out

    # -- engine-facing ----------------------------------------------------
    def negotiate_superwindow(self, nxt: int, lookahead: int, host_next: int,
                              end_time: int, cap_time: Optional[int],
                              max_rounds: int) -> Optional[int]:
        """Replay the K=1 round recurrence forward from the window the
        engine just computed ([nxt, nxt+lookahead)) and merge up to
        ``max_rounds`` consecutive rounds into ONE superwindow, stopping
        before the first round that would contain a host-side event
        (``host_next``: the earliest Python-queue or native-C-heap event) —
        or a checkpoint/resume boundary (``cap_time``).  Returns the merged
        span's end (the engine's new window_end) and stages a _SuperPlan
        for advance(), or None when no extension applies.

        The plan replicates advance()'s own cadence decisions exactly, so
        a K-round launch produces the same dispatch bases/targets — and,
        with the kernel's halt-at-completion rule, the same wake barriers —
        as K separate rounds: digest parity K=1-vs-K is by construction
        (tests/test_superwindow.py pins it).  That construction is why the
        auto-tuner (prof/autotune.py) may deepen K freely from measured
        launch costs: quiet rounds — including the quiet ticks between
        cross-shard exchange activity on a masked mesh variant — merge
        into one span launch with bit-identical results at any depth."""
        if (max_rounds <= 1 or self._state is None or self._inflight
                or self.superwindow_rounds <= 1):
            return None
        if (not self._inject_buf
                and self._cells_delivered_seen >= self._cells_dispatched):
            # empty plane: not driving windows; nothing to merge
            return None
        grid = TICK_NS * self.granule
        q = self.min_dispatch_steps
        synced = self._ticks_synced
        bounds: List[tuple] = []
        targets: List[int] = []
        round_of: List[int] = []
        ws = nxt
        for i in range(min(max_rounds, self.superwindow_rounds)):
            we = min(ws + lookahead, end_time)
            if i > 0 and cap_time is not None \
                    and (ws >= cap_time or we > cap_time):
                # a checkpoint/resume boundary at cap_time: the round
                # containing (or starting at) it must run K=1 so the
                # snapshot digest lands on an exact visited round boundary
                break
            if host_next < we:
                break               # a host event falls inside this round
            t_i = we // grid
            if t_i - synced >= q:   # advance()'s cadence rule, replayed
                targets.append(int(t_i))
                round_of.append(i)
                synced = t_i
            bounds.append((ws, we))
            nxt_dev = (synced + q) * grid
            if nxt_dev >= host_next or nxt_dev >= end_time:
                break               # next round would be host-driven
            ws = nxt_dev
        if len(bounds) < 2 or not targets:
            return None
        self._pending_plan = _SuperPlan(int(self._ticks_synced), targets,
                                        bounds, round_of)
        return bounds[-1][1]

    def advance(self, engine) -> None:
        """LAUNCH: dispatch the window step advancing the plane to the
        current round's barrier — or, when a superwindow was negotiated,
        through the whole merged span in ONE kernel launch.  Called at the
        TOP of the round (right after the engine computes the window), so
        the dispatch computes while the host drains the round's arrivals;
        consume() collects at the next loop iteration, always before the
        next window.  Staged injections (activations from earlier rounds)
        are folded in at the dispatch's base step — the engine has already
        committed the previous dispatch, so the one-deep in-flight slot is
        free here."""
        import time as _wt
        t0 = _wt.perf_counter_ns()
        assert not self._inflight, \
            "device plane: launch with an uncollected dispatch in flight"
        if self._fault_device_lost and self._shard is not None \
                and self._state is not None \
                and engine.rounds_executed + 1 >= self._fault_device_lost:
            # injected device loss: the plane is quiesced here (no dispatch
            # in flight — the assert above IS the boundary condition), so
            # re-partition onto the survivors before this round's launch
            self._fault_device_lost = 0
            self._reshard(engine)
        if self._auto_pos < len(self._auto):
            ws = engine.scheduler.window_start
            if self._state is None and not self._inject_buf \
                    and self.total_injected_cells == 0:
                # nothing has ever dispatched: re-base the step counter to
                # the window so the first dispatch does not grind through
                # the pre-traffic idle gap tick by tick
                self._ticks_synced = max(self._ticks_synced,
                                         ws // (TICK_NS * self.granule))
            self._stage_autos(ws)
        plan, self._pending_plan = self._pending_plan, None
        if plan is None:
            target_ticks = engine.scheduler.window_end // (TICK_NS
                                                           * self.granule)
            n = target_ticks - self._ticks_synced
            if n <= 0 and not self._inject_buf:
                return
            n = max(n, 0)
            if self._state is None:
                if not self._inject_buf and self.total_injected_cells == 0:
                    # nothing has ever activated: don't spin the kernel
                    self._ticks_synced = target_ticks
                    return
                self._init_state()
            elif (not self._inject_buf
                  and self._cells_delivered_seen >= self._cells_dispatched):
                # plane is empty: bank the ticks, skip the dispatch
                self._idle_ticks_banked += n
                self._ticks_synced = target_ticks
                self.idle_rounds_skipped += 1
                return
            if n < self.min_dispatch_steps:
                # cadence batching: let ticks (and injections) accumulate a
                # few rounds before paying a dispatch; next_time() keeps the
                # engine window loop coming back even when the Python plane
                # idles
                return
            targets = [int(target_ticks)]
        else:
            # superwindow: the plan's targets ARE the K=1 dispatch targets;
            # ticks_synced advances at consume, from the flush's t_stop
            # (the kernel may halt at an earlier boundary on a completion)
            targets = plan.targets
            n = targets[-1] - self._ticks_synced
        inject_pairs = list(self._inject_buf)
        if self._inject_buf:
            f = self.n_flows
            inject = np.zeros(f, dtype=np.int64)
            inject_target = np.zeros(f, dtype=np.int64)
            for circ, cells in self._inject_buf:
                inject[self.first_flow[circ]] += cells
                inject_target[self.last_flow[circ]] += cells
                self._cells_dispatched += cells
            self._inject_buf.clear()
            if self._shard is not None:
                from .mesh.partition import pad_state
                inject = pad_state(self._shard, inject)
                inject_target = pad_state(self._shard, inject_target)
            if self.mode == "device":
                self.device_calls += 1          # inject upload
        else:
            inject = inject_target = self._zero_inject()
        idle = self._idle_ticks_banked
        self._idle_ticks_banked = 0
        # Step continuity: the kernel's carried t equals the last dispatch's
        # end step; _ticks_synced (pre-update here) additionally counts any
        # banked idle steps, so re-basing to it jumps t exactly over the
        # idle gap — legal because idle banking requires an empty ring — and
        # is the identity when nothing was banked.  (Re-basing to anything
        # else desynchronizes the arrival ring's absolute slots: cells would
        # be skipped or re-read — caught by an adversarial review repro and
        # now pinned by test_varying_dispatch_sizes_preserve_arrivals.)
        if self.mode == "device":
            # the log exists solely to recover a FAILED device dispatch;
            # the numpy twin executes synchronously and cannot leave a
            # failed in-flight slot, so logging there (or after demotion)
            # would only accumulate memory it can never use
            self._dispatch_log.append((int(self._ticks_synced),
                                       inject_pairs, list(targets),
                                       int(idle)))
        state = (np.int64(self._ticks_synced), *self._state[1:])
        tvec = self._pad_targets(targets)
        if self._shard is not None:
            lay = self._shard
            out = self._pick_sharded_step()(
                *state, inject, inject_target,
                tvec, np.int64(idle), lay["flow_node_local"],
                lay["succ_global"], lay["seg_start_local"],
                lay["refill"], lay["capacity"], lay["arr_lat"],
                lay["shard_base"])
        elif self.mode == "device" and self._lane is not None:
            # fleet lane (ISSUE 18): the dispatch parks at the shared
            # plane's barrier and returns this lane's row of the vmapped
            # launch — a real-shaped, already-materialized numpy
            # 10-tuple, so consume() runs unchanged (the collect is a
            # no-op np.asarray).  Synchronous by construction: the
            # digest-pinned --device-plane-sync shape.
            out = self._lane.dispatch(state, np.asarray(inject),
                                      np.asarray(inject_target), tvec,
                                      int(idle))
        elif self.mode == "device":
            if self._flush_step is None:
                from ..ops.torcells_device import (
                    step_window_flush_for_backend)
                self._flush_step = step_window_flush_for_backend()
            if self._flush_caps is not None:
                # delta-compacted flush (tuner decision): pack only the
                # capped lane counts; stash the inputs so an overflowing
                # window (true counts in the header exceed the caps) can
                # re-run full-length at consume — legal because this
                # path is non-donating, so the inputs stay alive
                from ..ops.torcells_device import (
                    torcells_step_window_flush_capped)
                cc, hh = self._flush_caps
                out = torcells_step_window_flush_capped(
                    *state, inject, inject_target, tvec, np.int64(idle),
                    *self._flow_args(), ring_len=self.ring_len,
                    cap_chains=cc, cap_nodes=hh)
                self._inflight_caps = (cc, hh)
                self._inflight_args = (state, inject, inject_target,
                                       tvec, np.int64(idle))
            else:
                out = self._flush_step(*state, inject, inject_target,
                                       tvec, np.int64(idle),
                                       *self._flow_args(),
                                       ring_len=self.ring_len)
        else:
            from ..ops.torcells_device import torcells_step_window_numpy_flush
            out = torcells_step_window_numpy_flush(*state, inject,
                                                   inject_target, tvec, idle,
                                                   *self._flow_args(),
                                                   self.ring_len)
        self._state = out[:8]
        self._flush_handle = out[9]
        if plan is None:
            # single-target dispatch: the kernel cannot halt before its one
            # boundary, so the reached step is known without the flush
            self._ticks_synced = targets[-1]
        else:
            self._active_plan = plan
        self._inflight = True
        self.dispatches += 1
        if self.mode == "device":
            self.device_calls += 1              # the dispatch itself
            if self._sync:
                # serial oracle: idle through the kernel instead of
                # overlapping — everything else is identical, so digests
                # must match the pipelined run bit for bit
                import jax
                jax.block_until_ready(self._flush_handle)
        if self._fault_dispatch and self.dispatches == self._fault_dispatch \
                and self.mode == "device":
            # fault harness: this dispatch's collect raises (or hangs) —
            # consume() must recover via the numpy-twin replay (device-only:
            # the twin has no asynchronous slot to poison)
            self._flush_handle = _PoisonedFlush(self._flush_handle,
                                                hang=self._fault_hang)
            self._fault_dispatch = 0
        # per-launch predicted device cost (ISSUE 15): per-tick step
        # kernel + exchange collectives, plus the fixed transfer, from
        # the measured model.  Stored as (per-step, fixed) — a
        # superwindow kernel may HALT at an earlier negotiated boundary
        # on a completion, so consume() scales the per-step half by the
        # steps actually reached (flush t_stop) before judging the
        # band; predicting the full plan span would flag early-halted
        # windows as model-stale on a perfectly calibrated model.
        self._launch_pred = None       # (per_step_us, fixed_us)
        # the kernel's carried t runs from this base to the reached
        # boundary: steps executed = t_stop - base (idle-banked ticks
        # are a re-base jump, not loop iterations, so they don't count)
        self._launch_base = int(targets[-1]) - int(n)
        if self._costmodel is not None and self.mode == "device":
            if self._shard is not None:
                kernel_flows = len(self._shard["src"])
                ex_us = self._meshinfo.predicted_us
            else:
                kernel_flows = self.n_flows
                ex_us = 0.0
            # only predict INSIDE the model's measured range (the
            # two-sided CostModel.covers guard): a table far below the
            # smallest — or above the largest — calibrated flow count
            # would be judged by pure extrapolation and flood
            # prof.model_stale with false positives
            if self._costmodel.covers(kernel_flows):
                self._launch_pred = (
                    self._costmodel.step_us(kernel_flows)
                    + max(ex_us, 0.0),
                    self._costmodel.transfer_us())
        self._launch_wall = _wt.perf_counter_ns()
        self.host_ns += self._launch_wall - t0
        self._profiler.on_dispatch(t0, self._launch_wall, int(n),
                                   len(inject_pairs), self.dispatches,
                                   engine.scheduler.window_end)

    def consume(self, engine) -> None:
        """COLLECT: materialize the in-flight dispatch's packed flush
        buffer (ONE device->host transfer), wake completed flows, and feed
        the per-node byte deltas to the trackers.  Runs before the engine
        computes the next window (same contract as the tpu policy's
        consume_flush).  An exception raised inside the in-flight dispatch
        surfaces HERE, at materialization — nothing is caught."""
        if not self._inflight:
            return
        import time as _wt
        t0 = _wt.perf_counter_ns()
        self.pipeline_overlap_ns += t0 - self._launch_wall
        # the slot is released up front so state stays consistent whether
        # the collect succeeds, raises, or is recovered
        handle, self._flush_handle = self._flush_handle, None
        self._inflight = False
        with self._profiler.tracer.span(
                "device.collect", "device",
                sim_ns=engine.scheduler.window_start,
                args={"dispatch": self.dispatches}):
            try:
                # blocks iff still computing; a failure inside the
                # in-flight dispatch RAISES here (guarded by
                # --device-watchdog-sec), and the dispatch guard recovers
                # it on the numpy twin
                flush = self._collect_flush(engine, handle)
            except Exception as e:  # noqa: BLE001 - any dispatch failure
                flush = self._recover_dispatch(engine, e)
        t1 = _wt.perf_counter_ns()
        self.device_ns += t1 - t0
        self._profiler.on_collect(self._launch_wall, t0, t1 - t0,
                                  int(getattr(flush, "nbytes", 0)),
                                  self.dispatches,
                                  engine.scheduler.window_start)
        if self.mode == "device":
            self.device_calls += 1              # the flush read
        from ..ops.torcells_device import (flush_len, flush_overflowed,
                                           parse_flush)
        caps, self._inflight_caps = self._inflight_caps, None
        args, self._inflight_args = self._inflight_args, None
        if caps is not None and self.mode != "device":
            caps = None     # recovered on the twin: flush is full-length
        if caps is not None:
            if flush_overflowed(flush, *caps):
                # a busy window outran the tuned caps: re-run the SAME
                # inputs through the full-length kernel (bit-identical
                # state math — only the flush encoding differs) and read
                # the complete buffer.  Persistent overflow means the
                # caps are mis-sized for this phase: stop paying the
                # re-runs and revert to full flushes for the rest of
                # the run.
                flush = self._rerun_full_flush(args)
                self.flush_overflows += 1
                caps = None
                if self.flush_overflows >= 8:
                    self._flush_caps = None
            else:
                self.flush_bytes_saved += 8 * (
                    flush_len(self.n_chains, self.n_nodes)
                    - flush_len(self.n_chains, self.n_nodes, *caps))
        (forwards, delivered_sum, t_stop, done_chains, done_steps, node_idx,
         node_delta) = parse_flush(flush, self.n_chains, self.n_nodes,
                                   *(caps or (None, None)))
        # launch attribution (ISSUE 15): predicted-vs-measured per-launch
        # gauges, the model-stale band check, and the sim-correlated
        # device track span — one call per collect, ~free when no model
        # is loaded and observability is off.  Placed AFTER parse_flush
        # so the prediction covers the steps the kernel actually REACHED
        # (t_stop): a superwindow halting early on a completion is
        # judged on its real span, never flagged stale for not running
        # the merged rounds it skipped.  Device mode only — the numpy
        # twin's host-side walls must not pollute the launch gauges.
        if self.mode == "device":
            steps_done = max(int(t_stop) - self._launch_base, 0)
            pred_us = None
            if self._launch_pred is not None:
                per_step, fixed = self._launch_pred
                pred_us = steps_done * per_step + fixed
            self._profiler.on_window(
                self._launch_wall, t1, t1 - t0, steps_done,
                self.granule, pred_us,
                self._costmodel.band if self._costmodel is not None
                else 0.0,
                engine.scheduler.window_start,
                self._meshinfo.exchange_mode if self._meshinfo is not None
                else "single")
        if self._meshinfo is not None:
            # mesh flush: ONE trailing slot carries the window's
            # cross-shard cell count (zero extra device reads; a
            # standard-length buffer — the numpy twin after a demotion —
            # contributes 0)
            from .mesh.exchange import mesh_flush_extra
            self._meshinfo.cross_shard_cells += mesh_flush_extra(
                flush, self.n_chains, self.n_nodes)
            if self.mode == "numpy" and forwards > 0 \
                    and self._meshinfo.cross_edges > 0:
                # demoted sharded plane: this window's cross-shard
                # forwards executed HOST-side on the twin — counted so
                # the mesh.host_bounces == 0 steady-state gate is
                # falsifiable, not a tautology (the fault drill pins it
                # going nonzero after a demotion)
                self._meshinfo.host_bounces += 1
        self.total_forwards += forwards
        self._cells_delivered_seen = delivered_sum
        plan, self._active_plan = self._active_plan, None
        if plan is not None:
            # superwindow collect: the kernel reached t_stop — the plan's
            # final boundary, or an earlier one when a completion halted
            # it.  Rewind the engine's bookkeeping to the virtual round
            # that launched the reached span: the window bounds become that
            # round's (so completion wakes clamp to ITS barrier, exactly
            # as K=1 would), and the round counter advances by the merged
            # rounds actually covered (state digests carry it).
            try:
                j = plan.targets.index(t_stop)
            except ValueError:
                raise AssertionError(
                    f"device plane: superwindow stopped at step {t_stop}, "
                    f"not one of its negotiated boundaries {plan.targets}")
            r = plan.round_of[j]
            ws, we = plan.bounds[r]
            engine.scheduler.set_window(ws, we)
            engine.rounds_executed += r
            self._ticks_synced = t_stop
            self.superwindows += 1
            self._rounds_launched += r + 1
        else:
            self._rounds_launched += 1

        # trackers: per-node spent-byte deltas, delta-compacted on device,
        # folded with ONE numpy scatter-add; the per-host split into
        # Tracker counters happens on read (Tracker.pull_device) — the
        # vectorized control-plane cut (ISSUE 7)
        if len(node_idx):
            np.add.at(self._node_pending, node_idx, node_delta)

        # wake completed clients: BOTH chains (download 2c, upload 2c+1)
        # must have delivered; wake at the later completion step
        # (deterministic: ticks from the kernel, clamped to the barrier —
        # under a superwindow the halt rule guarantees every completion
        # here belongs to the span whose barrier the window now carries).
        # Only the chains that newly completed THIS dispatch arrive in the
        # flush buffer — O(completions), not O(circuits), per collect.
        # The batched wake fold (ISSUE 10): wake times are computed in one
        # vectorized pass and the events land in the scheduler through ONE
        # push_batch call instead of a per-circuit push chain; the wake
        # event itself then resumes the client directly (the wake IS the
        # continue — _device_wake_task), so a completed flow costs one
        # scheduler round-trip, not two.
        if len(done_chains):
            barrier = engine.scheduler.window_end
            self._chain_done[done_chains] = done_steps
            circs = np.unique(np.asarray(done_chains) >> 1)
            d = self._chain_done[2 * circs]
            u = self._chain_done[2 * circs + 1]
            ready = (d >= 0) & ((u >= 0) | ~self._has_upload[circs])
            steps = np.maximum(d, u)
            wakes = np.maximum((steps + 1) * TICK_NS * self.granule,
                               barrier)
            # ONE fold loop for both delivery sinks, so the done-guard /
            # decline rules can never desync between the planes: under the
            # native plane the wakes land as C-heap continuation events in
            # ONE push_cont_batch extension call (ISSUE 12 — same per-host
            # sequence claims, same wake times, no Python Task/Event per
            # flow); otherwise as Events through one push_batch call
            native = getattr(engine, "native_plane", None)
            make = self._make_wake_item if native is not None \
                else self._make_wake_event
            items = []
            for circ, wake in zip(circs[ready].tolist(),
                                  wakes[ready].tolist()):
                if circ in self._done:
                    continue
                self._done[circ] = wake
                item = make(engine, circ, wake)
                if item is not None:
                    items.append(item)
            if items:
                if native is not None:
                    native.push_device_wakes(items)
                else:
                    engine.counters.count_new("event", len(items))
                    engine.scheduler.policy.push_batch(
                        items, 0, engine.scheduler.window_end)
        # probation clock (ISSUE 17): each clean collect on the demoted
        # twin counts toward re-promotion; the threshold re-attempts the
        # device rung once (permanent-on-repeat preserved via _repromoted)
        if (self.demoted and self.mode == "numpy"
                and self._repromote_after > 0 and not self._repromoted):
            self._probation_clean += 1
            if self._probation_clean >= self._repromote_after:
                self._repromote(engine)
        self.host_ns += _wt.perf_counter_ns() - t1

    def _collect_flush(self, engine, handle) -> np.ndarray:
        """Materialize the in-flight dispatch's flush buffer, bounded by
        ``--device-watchdog-sec`` in device mode: the blocking read runs on
        a helper thread so a dispatch that never completes (wedged runtime,
        dead device tunnel) raises TimeoutError here instead of freezing
        the round loop forever.  Only the guard's bookkeeping (thread spawn
        + join return) is charged to supervision overhead — the wait for
        the result is the dispatch's own cost, watchdog or not."""
        if self.mode != "device" or self._watchdog_sec <= 0:
            return np.asarray(handle)
        import threading
        import time as _wt
        t_g = _wt.perf_counter_ns()
        # the result box is written by the helper thread and read by the
        # dispatcher: one lock covers both sides (simrace SIM102 — a
        # timed-out join() returning does NOT order the abandoned
        # helper's late write against the dispatcher's read, so the
        # dict-sharing idiom was a real, if narrow, race window)
        box: Dict[str, object] = {}
        box_lock = threading.Lock()

        def _work() -> None:
            try:
                out = np.asarray(handle)
            except BaseException as e:  # noqa: BLE001 - forwarded below
                with box_lock:
                    box["err"] = e
            else:
                with box_lock:
                    box["out"] = out

        th = threading.Thread(target=_work, daemon=True,
                              name="device-dispatch-collect")
        th.start()
        engine.supervision.overhead_ns += _wt.perf_counter_ns() - t_g
        th.join(self._watchdog_sec)
        if th.is_alive():
            # the helper thread is abandoned with the handle (it cannot be
            # interrupted mid-XLA-call); the numpy replay takes over
            raise TimeoutError(
                f"device dispatch did not complete within "
                f"{self._watchdog_sec:.0f}s (--device-watchdog-sec)")
        t_g = _wt.perf_counter_ns()
        with box_lock:
            err = box.get("err")
            out = box.get("out")
        if err is not None:
            raise err
        engine.supervision.overhead_ns += _wt.perf_counter_ns() - t_g
        return out

    def _rerun_full_flush(self, args) -> np.ndarray:
        """Overflow recovery for the delta-compacted flush: the capped
        buffer's TRUE header counts exceeded its caps, so some
        completions/node deltas were dropped from the ENCODING (never from
        the state — the capped and full kernels run byte-identical tick
        math).  Re-run the stashed inputs through the full-length kernel
        and read its complete flush.  Only reachable on the non-donating
        path, where the inputs survived the capped launch."""
        assert args is not None, "flush overflow with no stashed inputs"
        state, inject, inject_target, tvec, idle = args
        if self._flush_step is None:
            from ..ops.torcells_device import step_window_flush_for_backend
            self._flush_step = step_window_flush_for_backend()
        out = self._flush_step(*state, inject, inject_target, tvec, idle,
                               *self._flow_args(), ring_len=self.ring_len)
        self.device_calls += 1          # the recovery dispatch + read
        # simjit: disable=SIM302 -- designed collect: overflow recovery exists to READ the complete flush; the window is already lost
        return np.asarray(out[9])

    def _pick_sharded_step(self):
        """The sharded kernel variant for this dispatch (quiet-tick
        exchange-leg fusion): when the active chains touch only a subset
        of the schedule's legs, run a variant with the quiet legs
        compiled out — each masked ppermute leg is one collective launch
        saved per tick, and an all-masked span issues zero exchange
        collectives.  The active-leg set only grows, every variant is a
        superset of the cells actually in flight, and a full compile
        cache falls back to the always-correct full kernel."""
        if self._chain_leg_bits is None or self._full_leg_bits == 0:
            return self._sharded_step
        bits = self._active_leg_bits
        full = self._full_leg_bits
        if bits < 0 or full < 0 or bits == full:
            if self._meshinfo is not None:
                self._meshinfo.legs_active = full.bit_length() \
                    if full >= 0 else self._meshinfo.legs
            return self._sharded_step
        step = self._sharded_variants.get(bits)
        if step is None:
            if len(self._sharded_variants) >= 4:
                # compile budget spent: the full kernel is always right
                if self._meshinfo is not None:
                    self._meshinfo.legs_active = full.bit_length()
                return self._sharded_step
            n_legs = full.bit_length()
            mask = tuple(bool(bits >> k & 1) for k in range(n_legs))
            step = self._mesh_make_step(mask)
            self._sharded_variants[bits] = step
            DeviceTrafficPlane.sharded_variants_high_water = max(
                DeviceTrafficPlane.sharded_variants_high_water,
                len(self._sharded_variants))
        if self._meshinfo is not None:
            self._meshinfo.legs_active = bin(bits).count("1")
        return step

    def _autotune_metrics(self) -> Dict[str, object]:
        """The ``prof.autotune_*`` registry source: the tuner's decision
        plus its runtime outcomes.  flush_compact reports the caps
        actually ENGAGED (the plan's choice can be overridden by the
        backend gate, the mesh path, or the persistent-overflow
        revert)."""
        m = self._tune_plan.metrics()
        m["prof.autotune_flush_compact"] = int(self._flush_caps is not None)
        m["prof.flush_bytes_saved"] = self.flush_bytes_saved
        m["prof.flush_overflows"] = self.flush_overflows
        return m

    def _recover_dispatch(self, engine, exc: BaseException) -> np.ndarray:
        """Graceful device-plane degradation: the in-flight dispatch failed
        (exception or watchdog timeout), so rebuild the plane's state by
        replaying the FULL logged window history on the bit-identical numpy
        twin — the carried device state is donated on accelerators, so
        there is no pre-state buffer to restart from — and PERMANENTLY
        demote the backend to the twin.  Digest parity is preserved (the
        twin is the parity oracle the tests pin); device speed is
        forfeited.  Returns the failed window's flush buffer, which the
        caller consumes exactly as if the device had produced it."""
        get_logger().warning(
            "device-plane",
            f"in-flight dispatch failed ({exc!r}); replaying "
            f"{len(self._dispatch_log)} windows on the numpy twin and "
            "permanently demoting the backend to numpy")
        self.mode = "numpy"
        self.demoted = True
        self.recoveries += 1
        engine.supervision.count_dispatch_recovery(
            f"device dispatch recovered on the numpy twin ({exc!r}); "
            "backend demoted for the rest of the run")
        self._mesh = None
        self._shard = None
        self._sharded_step = None
        self._sharded_variants.clear()
        self._chain_leg_bits = None
        self._flush_step = None
        # the twin packs full-length flushes only; drop the capped-path
        # bookkeeping with the device backend
        self._flush_caps = None
        self._inflight_caps = None
        self._inflight_args = None
        # predictions are calibrated for the DEVICE kernels; the numpy
        # twin must not be judged (or scheduled) by them
        self._costmodel = None
        self._costmodel_status = "demoted"
        self._launch_pred = None
        self._flow_args_cached = None
        self._zero_inject_cached = None
        from ..ops.torcells_device import (RING_DTYPE,
                                           torcells_step_window_numpy_flush)
        f, h = self.n_flows, self.n_nodes
        if self._replay_base is not None:
            # the window-replay guard armed at re-promotion: this is the
            # re-promoted rung failing AGAIN — replay from the stashed
            # probation-exit state plus the log since, then the demotion
            # is permanent (self._repromoted blocks another probation)
            state = tuple(np.asarray(a).copy() for a in self._replay_base[1])
        else:
            state = (np.int64(0), np.zeros(f, dtype=np.int64),
                     np.zeros((self.ring_len, f), dtype=RING_DTYPE),
                     self.capacity_step.copy(),
                     np.zeros(f, dtype=np.int64), np.zeros(f, dtype=np.int64),
                     np.full(f, -1, dtype=np.int64),
                     np.zeros(h, dtype=np.int64))
        args = self._flow_args()        # plain numpy now that mode flipped
        flush = None
        for base, pairs, targets, idle in self._dispatch_log:
            inject = np.zeros(f, dtype=np.int64)
            inject_target = np.zeros(f, dtype=np.int64)
            for circ, cells in pairs:
                inject[self.first_flow[circ]] += cells
                inject_target[self.last_flow[circ]] += cells
            out = torcells_step_window_numpy_flush(
                np.int64(base), *state[1:], inject, inject_target,
                self._pad_targets(targets), np.int64(idle), *args,
                self.ring_len)
            state = out[:8]
            flush = out[9]
        self._state = state
        assert flush is not None, "recovery with an empty dispatch log"
        self._dispatch_log.clear()      # demoted: the log has no future use
        self._replay_base = None
        # arm the probation clock (ISSUE 17): after --repromote-after
        # clean collects on the twin, consume() re-attempts the device
        # rung once.  A rung that already climbed back stays down for good.
        self._probation_clean = 0
        return flush

    def _repromote(self, engine) -> None:
        """Climb back up the recovery ladder (ISSUE 17): the numpy
        demotion served its probation, so re-attempt the device rung ONCE
        with the window-replay guard re-armed — the current twin state is
        stashed as the replay base, so a second dispatch failure rebuilds
        from it (base + log replay) and re-demotes permanently.  Single-
        device rung only: a mesh lost to a real fault re-enters through
        the re-shard path, not here."""
        import jax.numpy as jnp
        self._replay_base = (int(self._ticks_synced),
                             tuple(np.asarray(a).copy()
                                   for a in self._state))
        self._dispatch_log.clear()
        self.mode = "device"
        self.demoted = False
        self._repromoted = True
        self._flush_step = None
        self._flow_args_cached = None
        self._zero_inject_cached = None
        self._state = tuple(jnp.asarray(a) for a in self._state)
        engine.supervision.count_repromotion("device plane backend",
                                             self._probation_clean)

    def _make_wake_event(self, engine, circuit: int,
                         when: int) -> Optional[Event]:
        """Build (not push) one completion-wake event; consume() lands the
        whole collect's wakes in one push_batch call."""
        if when >= engine.end_time:
            return None
        if self.specs[circuit].auto_start_ns is not None:
            # processless flow: no client will ever join — a wake event
            # would only materialize a quiet table row for nothing
            return None
        waiter = self._waiters.pop(circuit, None)
        host = self.engine.host_by_name(self.specs[circuit].client_name)
        task = Task(_device_wake_task, (self, circuit, waiter), None,
                    name="device_flow_done")
        return Event(task, when, host, host, host.next_event_sequence())

    def _make_wake_item(self, engine, circuit: int, when: int):
        """The _make_wake_event twin for the native continuation plane:
        (when, host, plane, circuit, waiter) for push_device_wakes —
        identical decline rules, the sequence claim deferred to the ONE
        push_cont_batch extension call (same per-host counter, same
        order)."""
        if when >= engine.end_time:
            return None
        if self.specs[circuit].auto_start_ns is not None:
            return None
        waiter = self._waiters.pop(circuit, None)
        host = self.engine.host_by_name(self.specs[circuit].client_name)
        return (when, host, self, circuit, waiter)

    def _stage_autos(self, now_ns: int) -> None:
        """Activate every processless flow whose start time has been
        reached (injections enter at the next dispatch base, like an app
        activation staged last round)."""
        while self._auto_pos < len(self._auto) \
                and self._auto[self._auto_pos][0] <= now_ns:
            _t, circ = self._auto[self._auto_pos]
            self._auto_pos += 1
            self._activate_spec(self.specs[circ])

    def busy(self) -> bool:
        """True while the plane still has work the engine must keep
        windows advancing for (undelivered cells, buffered injections, an
        unconsumed dispatch, or un-started processless flows)."""
        return (bool(self._inject_buf) or self._inflight
                or self._cells_delivered_seen < self._cells_dispatched
                or self._auto_pos < len(self._auto))

    def next_time(self) -> int:
        """The next sim time the plane needs a window at — its dispatch
        cadence point, or the next processless flow's start.  Folded into
        the engine's next-window computation so a quiet Python plane
        cannot strand in-flight device traffic (the plane's flows would
        otherwise only progress while unrelated Python events kept the
        round loop alive)."""
        t = stime.SIM_TIME_MAX
        if self._auto_pos < len(self._auto):
            t = self._auto[self._auto_pos][0]
        if (bool(self._inject_buf) or self._inflight
                or self._cells_delivered_seen < self._cells_dispatched):
            t = min(t, (self._ticks_synced + self.min_dispatch_steps)
                    * self.granule * TICK_NS)
        return t

    def take_node_delta(self, i: int) -> Tuple[int, int]:
        """Consume node ``i``'s pending byte delta as (cells, bytes) —
        shared by the Tracker fold below and the host table's column fold
        (scale/hosttable.py), so both account identically."""
        from ..ops.torcells_device import CELL_WIRE_BYTES
        nbytes = int(self._node_pending[i])
        if not nbytes:
            return 0, 0
        self._node_pending[i] = 0
        return nbytes // CELL_WIRE_BYTES, nbytes

    def pull_tracker_nodes(self, tracker, nodes: List[int]) -> None:
        """Fold a host's pending device-plane byte deltas (accumulated by
        consume()'s single scatter-add) into its Tracker counters: an
        egress node's spend is the host's tx, an ingress (delivering hop)
        node's spend is its rx.  Called from Tracker.pull_device at
        observation points (heartbeat, digest, teardown) only — never on
        the round path."""
        for i in nodes:
            ncells, nbytes = self.take_node_delta(i)
            if not nbytes:
                continue
            c = tracker.out_remote if self.node_kind[i] == "tx" \
                else tracker.in_remote
            c.packets_total += ncells
            c.bytes_total += nbytes
            c.packets_data += ncells
            c.bytes_data += nbytes

    def flush_all_trackers(self) -> None:
        """Teardown sweep: fold every pending node delta so post-run
        readers (tests, digests, tools) see final tracker totals.  Table
        rows fold into the table's columns (or through their materialized
        Host's tracker) via the table's own sweep."""
        for host in dict.fromkeys(h for h in self.node_hosts
                                  if h is not None):
            host.tracker.pull_device()
        if self._table is not None:
            self._table.flush_device_nodes(self)

    def stats(self) -> Dict[str, int]:
        # mesh introspection is NOT mirrored here: the mesh.* registry
        # source (mesh/meshplane.py) is the one spelling of those
        # counters — readers scrape the registry like every other source
        return {
            "circuits": len(self.specs),
            "injected_cells": self.total_injected_cells,
            "forwards": self.total_forwards,
            "completed": len(self._done),
            "dispatches": self.dispatches,
            "idle_rounds_skipped": self.idle_rounds_skipped,
            # superwindow introspection (ISSUE 7): merged multi-round
            # launches, and how many virtual engine rounds each kernel
            # launch covered on average — the dispatch-amortization number
            # the tor10k host wall is attacked with
            "superwindows": self.superwindows,
            "rounds_per_launch": round(
                self._rounds_launched / max(self.dispatches, 1), 2),
            # delta-compacted flush outcomes (ISSUE 16): readback bytes
            # the capped encoding saved, and windows that outran the
            # caps (each paid one full-length re-run; persistent
            # overflow reverts the caps entirely)
            "flush_bytes_saved": self.flush_bytes_saved,
            "flush_overflows": self.flush_overflows,
            "mode": self.mode,
            # dispatch-guard outcomes: >0 recoveries means a dispatch
            # failed, the window history replayed on the numpy twin, and
            # the backend was demoted for the rest of the run
            "recoveries": self.recoveries,
            "demoted": self.demoted,
            # recovery-ladder introspection (ISSUE 17): whether the rung
            # climbed back after its probation (one shot; a repeat fault
            # re-demotes for good)
            "repromoted": self._repromoted,
            # the plane's own wall split (VERDICT r4 weak #2: this was
            # tracked but never exported, hiding ~half the flagship wall):
            # host_sec = advance() dispatch prep + wake bookkeeping;
            # device_sec = blocking materialization of dispatch summaries
            "plane_host_sec": round(self.host_ns / 1e9, 3),
            "plane_device_sec": round(self.device_ns / 1e9, 3),
            # pipeline introspection: host<->device interactions (dispatch +
            # inject upload + flush read; <= 3 per dispatch) and the wall
            # the in-flight dispatch computed behind host round work
            "device_calls": self.device_calls,
            "pipeline_overlap_sec": round(self.pipeline_overlap_ns / 1e9, 3),
            # fraction of device compute hidden behind host round work:
            # overlap / (overlap + blocked collect); 1.0 = the collect
            # never blocked (obs/profiler.py reads the same definition)
            "overlap_efficiency": round(
                self.pipeline_overlap_ns
                / max(self.pipeline_overlap_ns + self.device_ns, 1), 4),
        }


def _device_wake_task(args, _unused) -> None:
    plane, circuit, waiter = args
    if waiter is None:
        waiter = plane._waiters.pop(circuit, None)
    if waiter is None:
        return                       # client not waiting yet; wait() will
    process, thread = waiter         # see _done and return immediately
    if circuit in plane._woken:
        return
    plane._woken.add(circuit)
    thread.wake_value = plane._done[circuit]
    # the wake IS the continue (the fold _thread_wake_task already uses
    # for sleep wakes): this event executes in the client host's context
    # at the wake time — exactly where the continue event it used to
    # schedule would run — so resuming directly saves one scheduler
    # round-trip per completed flow (ISSUE 10 batched wake path)
    from ..process.process import BLOCKED, RUNNABLE
    if thread.state == BLOCKED:
        thread.state = RUNNABLE
        thread._unblock_cb = None
        # the wake IS the continue: resume directly; any separately
        # scheduled continue event keeps its own (no-op) delivery and
        # clears the coalescing flag itself (ISSUE 12 satellite)
        process.continue_()


def build_plane_from_engine(engine, mode: str = "device"):
    """Scan the engine's processes for device-mode clients (tor circuits
    AND tgen star-bulk flows) plus the host table's processless flow
    configs (scale tier); returns a DeviceTrafficPlane or None if the
    workload has none.  The scan goes through engine.iter_process_specs so
    deferred table rows contribute identical specs to live Hosts."""
    specs = []
    for _hid, host_name, app, args in engine.iter_process_specs():
        spec = None
        if app.endswith("tor"):
            spec = parse_device_client(host_name, args)
        elif app.endswith("tgen"):
            spec = parse_device_tgen(host_name, args)
        if spec is not None:
            specs.append(spec)
    table = getattr(engine, "host_table", None)
    if table is not None and table.flows:
        from ..apps.tor import PAYLOAD_MAX
        for (_row, route_down, route_up, down_bytes, up_bytes,
             start_ns) in table.flows:
            client = route_down[-1]
            s = _FlowSpec(client, list(route_down), list(route_up),
                          max(1, math.ceil(down_bytes / PAYLOAD_MAX)),
                          math.ceil(up_bytes / PAYLOAD_MAX) if up_bytes
                          else 0, dest=route_down[0])
            s.auto_start_ns = int(start_ns)
            specs.append(s)
    if not specs:
        return None
    resolve_auto_routes(engine, specs)
    plane = DeviceTrafficPlane(engine, specs, mode=mode)
    get_logger().message(
        "device-plane",
        f"device traffic plane: {len(specs)} circuits, "
        f"{plane.n_flows} flows, {plane.n_nodes} nodes, "
        f"ring_len={plane.ring_len}, granule={plane.granule} ms, "
        f"mode={mode}")
    return plane
