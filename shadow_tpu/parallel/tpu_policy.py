"""The ``tpu`` scheduler policy: per-host event queues + device-batched hops.

This is the seventh scheduler policy (SURVEY.md §2.2; the reference's six
live in core/scheduler.py).  Event storage and popping are identical to the
``host`` policy; what changes is the inter-host packet hop
(worker.c:243-304): instead of a per-packet reliability draw + latency
lookup on the CPU, packets sent during a round are appended to a batch, and
at the round barrier ONE jitted device step (ops/round_step.py) computes
every drop decision and delivery time at once.  CPU<->TPU exchange happens
only at round boundaries — the conservative lookahead window guarantees no
intra-round causality violation, the same argument the reference's
host-steal policy uses for its cross-host barrier clamp
(scheduler_policy_host_steal.c:229-242).

The batch is structure-of-arrays from the moment of capture: offer_packet
appends into parallel columns (row indices come from the per-host cached
topology row, so there is no per-packet dict lookup), and flush_round turns
them into numpy arrays with one bulk conversion each before the device step.
Survivor delivery events are then pushed with the per-host queue locks taken
once per destination host, not once per packet.

Parity: drops are keyed by packet uid through the same threefry cipher the
CPU policies use, so a simulation under ``tpu`` delivers/drops exactly the
same packets at exactly the same times as under ``global``/``steal``
(asserted by tests/test_tpu_policy.py).
"""

from __future__ import annotations

import threading
import time as _walltime
from typing import List, Optional, Tuple

import numpy as np

from ..core.scheduler import HostQueuesPolicy
from ..core.event import Event
from ..core.task import Task
from ..core.worker import _deliver_packet_task


class TPUPolicy(HostQueuesPolicy):
    def __init__(self):
        super().__init__()
        self._batch_lock = threading.Lock()
        # SoA pending batch (parallel columns, one row per offered packet)
        self._p_pkts: List = []
        self._p_src_hosts: List = []
        self._p_dst_hosts: List = []
        self._p_seqs: List[int] = []
        self._p_src_rows: List[int] = []
        self._p_dst_rows: List[int] = []
        self._p_uids: List[int] = []
        self._p_times: List[int] = []
        self._kernel = None
        self.packets_batched = 0
        self.packets_dropped = 0
        # per-round introspection (read by the engine heartbeat)
        self.last_batch = 0
        self.device_ns = 0          # cumulative wall ns inside kernel.step
        self.host_flush_ns = 0      # cumulative wall ns in flush outside step

    # -- worker-facing batching -------------------------------------------
    def offer_packet(self, packet, worker) -> bool:
        """Append a packet hop to the round batch (called from
        Worker.send_packet in place of the scalar CPU path).  The source-host
        event sequence id is claimed NOW so the deterministic order tuple
        (time, dst, src, seq) reflects send order, as on the CPU path."""
        engine = worker.engine
        dst_host = engine.host_by_ip(packet.dst_ip)
        if dst_host is None:
            packet.add_status("INET_DROPPED")
            return True
        src_host = worker.active_host
        seq_owner = src_host if src_host is not None else dst_host
        seq = seq_owner.next_event_sequence()
        with self._batch_lock:
            self._p_pkts.append(packet)
            self._p_src_hosts.append(src_host)
            self._p_dst_hosts.append(dst_host)
            self._p_seqs.append(seq)
            self._p_src_rows.append(src_host.topo_row if src_host is not None
                                    else dst_host.topo_row)
            self._p_dst_rows.append(dst_host.topo_row)
            self._p_uids.append(packet.uid)
            self._p_times.append(worker.now)
        self.packets_batched += 1
        return True

    # -- round-boundary flush ---------------------------------------------
    def _ensure_kernel(self, engine):
        if self._kernel is None:
            from ..ops.round_step import (PacketHopKernel,
                                          ShardedPacketHopKernel)
            topo = engine.topology
            n_dev = getattr(engine.options, "tpu_devices", 0)
            if n_dev == 0:
                # 0 = all local devices (options.py); sharding only engages
                # when that is actually more than one chip
                import jax
                n_dev = len(jax.devices())
            if n_dev > 1:
                # scale-out: the round batch is sharded across a 1-D mesh
                # (ICI collectives combine the min-next-time reduction)
                self._kernel = ShardedPacketHopKernel(
                    topo, engine._drop_key, engine.bootstrap_end, n_dev,
                    shard_matrix=getattr(engine.options,
                                         "tpu_shard_matrix", False))
            else:
                self._kernel = PacketHopKernel(
                    topo, engine._drop_key, engine.bootstrap_end)
        return self._kernel

    def flush_round(self, engine) -> int:
        """Run the device step for the round's batch and push the surviving
        delivery events.  Called by the engine once per round, after workers
        drain and before the next window is computed."""
        t0 = _walltime.perf_counter_ns()
        with self._batch_lock:
            n = len(self._p_pkts)
            if n == 0:
                self.last_batch = 0
                return 0
            pkts = self._p_pkts;      self._p_pkts = []
            src_hosts = self._p_src_hosts;  self._p_src_hosts = []
            dst_hosts = self._p_dst_hosts;  self._p_dst_hosts = []
            seqs = self._p_seqs;      self._p_seqs = []
            src_rows = self._p_src_rows;    self._p_src_rows = []
            dst_rows = self._p_dst_rows;    self._p_dst_rows = []
            uids = self._p_uids;      self._p_uids = []
            times = self._p_times;    self._p_times = []
        self.last_batch = n
        kernel = self._ensure_kernel(engine)
        topo = engine.topology

        src_arr = np.array(src_rows, dtype=np.int32)
        dst_arr = np.array(dst_rows, dtype=np.int32)
        uid_arr = np.array(uids, dtype=np.uint64)
        time_arr = np.array(times, dtype=np.int64)

        barrier = engine.scheduler.window_end
        t1 = _walltime.perf_counter_ns()
        # --tpu-max-inflight bounds one device step's padded batch (HBM
        # safety valve for enormous rounds); lanes are independent, so
        # chunked steps are exact
        cap = max(1, getattr(engine.options, "tpu_max_inflight", 0) or n)
        if n <= cap:
            deliver, keep = kernel.step(src_arr, dst_arr, uid_arr, time_arr,
                                        barrier)
        else:
            parts = [kernel.step(src_arr[i:i + cap], dst_arr[i:i + cap],
                                 uid_arr[i:i + cap], time_arr[i:i + cap],
                                 barrier)
                     for i in range(0, n, cap)]
            deliver = np.concatenate([p[0] for p in parts])
            keep = np.concatenate([p[1] for p in parts])
        t2 = _walltime.perf_counter_ns()

        # per-path packet accounting for the kept lanes, vectorized
        # (the CPU latency lookup path counts per call)
        np.add.at(topo.path_packet_counts, (src_arr[keep], dst_arr[keep]),
                  1)
        deliver_list = deliver.tolist()
        keep_list = keep.tolist()

        delivered = 0
        dropped = 0
        end_time = engine.end_time
        count_drop = engine.count_packet_drop
        push = super().push
        counters = engine.counters
        sharded = engine.shard_count > 1
        owns = engine.owns_host
        outboxes = engine.shard_outboxes
        shard_of = engine.shard_of
        for i in range(n):
            pkt = pkts[i]
            if not keep_list[i]:
                pkt.add_status("INET_DROPPED")
                count_drop(pkt)
                dropped += 1
                continue
            t = deliver_list[i]
            if t >= end_time:
                continue
            pkt.add_status("INET_SENT")
            dst = dst_hosts[i]
            if sharded and not owns(dst):
                # --processes: hand the finished hop to the owner shard (the
                # seq was claimed at offer time, so the event tuple matches)
                outboxes[shard_of(dst)].append(
                    (t, dst.id, src_hosts[i].id, seqs[i], pkt.to_wire()))
                delivered += 1
                continue
            task = Task(_deliver_packet_task, dst, pkt,
                        name="deliver_packet")
            ev = Event(task, t, dst, src_hosts[i], seqs[i])
            push(ev, 0, barrier)
            delivered += 1
        counters.count_new("event", delivered)
        self.packets_dropped += dropped
        t3 = _walltime.perf_counter_ns()
        self.device_ns += t2 - t1
        self.host_flush_ns += (t1 - t0) + (t3 - t2)
        return delivered

    def pending_count(self) -> int:
        return super().pending_count() + len(self._p_pkts)

    def next_time(self) -> int:
        # A non-empty batch means there are future deliveries not yet pushed;
        # flush_round always runs before next_time in the engine loop, so the
        # base implementation is correct — assert the contract in debug runs.
        assert not self._p_pkts, "flush_round must run before next_time"
        return super().next_time()
