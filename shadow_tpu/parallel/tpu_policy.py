"""The ``tpu`` scheduler policy: per-host event queues + device-batched hops.

This is the seventh scheduler policy (SURVEY.md §2.2; the reference's six
live in core/scheduler.py).  Event storage and popping are identical to the
``host`` policy; what changes is the inter-host packet hop
(worker.c:243-304): instead of a per-packet reliability draw + latency
lookup on the CPU, packets sent during a round are appended to a batch, and
at the round barrier ONE jitted device step (ops/round_step.py) computes
every drop decision and delivery time at once.  CPU<->TPU exchange happens
only at round boundaries — the conservative lookahead window guarantees no
intra-round causality violation, the same argument the reference's
host-steal policy uses for its cross-host barrier clamp
(scheduler_policy_host_steal.c:229-242).

Capture is one tuple append per packet (row indices come from the per-host
cached topology row, so there is no per-packet dict lookup); flush_round
unzips the rows into numpy columns, packs them into ONE [1+B, 3] int64
device upload (header row = batch count + barrier, so no per-call scalar
transfers), and LAUNCHES the jitted step without materializing.  The engine
consumes the results at the top of the next loop iteration — always before
the next window is computed, so causality and determinism are exact — which
overlaps device compute with the barrier bookkeeping (and, on a real
accelerator, hides the device round trip behind host-side work).

Parity: drops are keyed by packet uid through the same threefry cipher the
CPU policies use, so a simulation under ``tpu`` delivers/drops exactly the
same packets at exactly the same times as under ``global``/``steal``
(asserted by tests/test_tpu_policy.py).
"""

from __future__ import annotations

import threading
import time as _walltime
from typing import List, Optional, Tuple

import numpy as np

from ..core.scheduler import GlobalSinglePolicy, HostQueuesPolicy
from ..core.event import Event
from ..core.task import Task
from ..core.worker import _deliver_packet_task


class _TPUBatchMixin:
    """The device-batching behavior (offer/launch/consume/warmup), layered
    over an event-storage policy.  Two concrete layouts:

    * TPUSerialPolicy — over the single global queue (workers == 0).  The
      per-host-queue layout costs a measured ~1.5 s extra on tor200's pops
      alone (min-scan across 305 queues vs one pop_before), which was the
      bulk of the r3 tpu-vs-serial regression — batching never needed it.
    * TPUPolicy — over the per-host locked queues (threaded runs, where
      per-host ownership is what makes parallel pops safe).
    """

    def _init_batch(self):
        self._batch_lock = threading.Lock()
        # pending batch: one row tuple per offered packet (pkt, src_host,
        # dst_host, seq, src_row, dst_row, uid, time); a single append per
        # offer keeps the capture hot path minimal — the flush unzips into
        # SoA columns with one zip(*) pass
        self._p_rows: List[Tuple] = []
        self._kernel = None
        self.packets_batched = 0
        self.packets_dropped = 0
        # launched-but-unconsumed chunks: (pkts, src_hosts, dst_hosts, seqs,
        # src_rows, dst_rows, deliver, keep) where deliver/keep may still be
        # computing on the device.  consume_flush materializes them at the
        # NEXT round boundary, so device compute overlaps host round work.
        self._pending: List[Tuple] = []
        # mid-round chunk size: once this many offers accumulate, a chunk is
        # launched immediately so the device works while the round is still
        # executing (0 = launch only at the barrier; None = read the option
        # on first offer — lazily, because the engine isn't known yet)
        self._chunk: Optional[int] = None
        # serializes _launch (worker threads may chunk-launch concurrently;
        # distinct from _batch_lock, which _drain_batch takes)
        self._launch_lock = threading.Lock()
        self._sync = False          # --processes shards need same-round results
        # per-round introspection (read by the engine heartbeat)
        self.last_batch = 0
        self.device_ns = 0          # cumulative wall ns blocked on the device
        self.host_flush_ns = 0      # cumulative wall ns in flush outside step

    # -- worker-facing batching -------------------------------------------
    def offer_packet(self, packet, worker) -> bool:
        """Append a packet hop to the round batch (called from
        Worker.send_packet in place of the scalar CPU path).  The source-host
        event sequence id is claimed NOW so the deterministic order tuple
        (time, dst, src, seq) reflects send order, as on the CPU path."""
        engine = worker.engine
        dst_host = engine.host_by_ip(packet.dst_ip)
        if dst_host is None:
            packet.add_status("INET_DROPPED")
            return True
        src_host = worker.active_host
        seq_owner = src_host if src_host is not None else dst_host
        seq = seq_owner.next_event_sequence()
        row = (packet, src_host, dst_host, seq,
               src_host.topo_row if src_host is not None
               else dst_host.topo_row,
               dst_host.topo_row, packet.uid, worker.now)
        if self.serial:
            # workers == 0: the lock is pure overhead on the hottest
            # capture path (the CPU-time gate's margin lives here)
            self._p_rows.append(row)
            n = len(self._p_rows)
        else:
            with self._batch_lock:
                self._p_rows.append(row)
                n = len(self._p_rows)
        self.packets_batched += 1
        if self._chunk is None:
            self._chunk = getattr(engine.options, "tpu_chunk", 0)
        if self._chunk and n >= self._chunk:
            # mid-round launch: ship the accumulated chunk now so the device
            # computes while the host executes the rest of the round
            self._launch(engine, self._drain_batch())
        return True

    def _drain_batch(self) -> Optional[Tuple]:
        with self._batch_lock:
            if not self._p_rows:
                return None
            rows = self._p_rows
            self._p_rows = []
        return tuple(zip(*rows))

    # -- round-boundary flush ---------------------------------------------
    def _ensure_kernel(self, engine):
        if self._kernel is None:
            from ..ops.round_step import (PacketHopKernel,
                                          ShardedPacketHopKernel)
            topo = engine.topology
            opts = engine.options
            n_dev = getattr(opts, "tpu_devices", 0)
            if n_dev == 0:
                # 0 = all local devices (options.py); sharding only engages
                # when that is actually more than one chip
                import jax
                n_dev = len(jax.devices())
            threshold = getattr(opts, "tpu_device_threshold", 0)
            if n_dev > 1:
                # scale-out: the round batch is sharded across a 1-D mesh
                # (ICI collectives combine the min-next-time reduction)
                self._kernel = ShardedPacketHopKernel(
                    topo, engine._drop_key, engine.bootstrap_end, n_dev,
                    shard_matrix=getattr(opts, "tpu_shard_matrix", False))
                self._kernel.DEVICE_THRESHOLD = threshold
            else:
                self._kernel = PacketHopKernel(
                    topo, engine._drop_key, engine.bootstrap_end,
                    device_threshold=threshold)
            if self._chunk is None:
                self._chunk = getattr(opts, "tpu_chunk", 0)
            # --processes shards hand cross-shard hops to their owner at the
            # SAME round's barrier (procs.py outbox drain), so they cannot
            # defer materialization; checkpointing snapshots round state, so
            # it needs everything pushed too (the engine consumes before
            # writing regardless — this just keeps flush's return count
            # meaningful there).
            self._sync = engine.shard_count > 1
        return self._kernel

    def _launch(self, engine, cols) -> None:
        """Dispatch one chunk's device step asynchronously and queue it for
        consume_flush.  (pkts, ..., times) columns -> pending tuple.
        Serialized: worker threads may chunk-launch concurrently and the
        kernel/perf counters are shared state."""
        if cols is None:
            return
        with self._launch_lock:
            self._launch_locked(engine, cols)

    def _launch_locked(self, engine, cols) -> None:
        t0 = _walltime.perf_counter_ns()
        (pkts, src_hosts, dst_hosts, seqs, src_rows, dst_rows,
         uids, times) = cols
        n = len(pkts)
        self.last_batch = n
        kernel = self._ensure_kernel(engine)
        src_arr = np.array(src_rows, dtype=np.int32)
        dst_arr = np.array(dst_rows, dtype=np.int32)
        uid_arr = np.array(uids, dtype=np.uint64)
        time_arr = np.array(times, dtype=np.int64)
        barrier = engine.scheduler.window_end
        # --tpu-max-inflight bounds one device step's padded batch (HBM
        # safety valve for enormous rounds); lanes are independent, so
        # chunked steps are exact
        cap = max(1, getattr(engine.options, "tpu_max_inflight", 0) or n)
        for i in range(0, n, cap):
            j = min(i + cap, n)
            deliver, keep = kernel.launch(src_arr[i:j], dst_arr[i:j],
                                          uid_arr[i:j], time_arr[i:j],
                                          barrier)
            self._pending.append((pkts[i:j], src_hosts[i:j], dst_hosts[i:j],
                                  seqs[i:j], src_arr[i:j], dst_arr[i:j],
                                  deliver, keep, barrier))
        self.host_flush_ns += _walltime.perf_counter_ns() - t0

    def warmup(self, engine, max_batch: int = 8192) -> None:
        """Pre-compile the hop kernel for every bucket size up to
        ``max_batch`` (one dummy launch per power-of-two shape).  XLA
        compiles are 20-40s each on a real TPU; benches and long runs warm
        them up front so compile time isn't charged to the measured loop."""
        from ..ops.round_step import MIN_BUCKET, bucket_size
        kernel = self._ensure_kernel(engine)
        if kernel.DEVICE_THRESHOLD and max_batch < kernel.DEVICE_THRESHOLD:
            return
        b = MIN_BUCKET
        while b <= bucket_size(max_batch):
            # smallest batch that maps to bucket b AND clears the bypass; a
            # bucket whose whole (b/2, b] range is below the threshold can
            # never reach the device, so skip it instead of re-warming the
            # threshold's own bucket shape repeatedly
            n = max(b // 2 + 1, kernel.DEVICE_THRESHOLD, 1)
            if n > b:
                b <<= 1
                continue
            dummy_rows = np.zeros(n, dtype=np.int32)
            d, k = kernel.launch(dummy_rows, dummy_rows,
                                 np.zeros(n, dtype=np.uint64),
                                 np.zeros(n, dtype=np.int64), 0)
            np.asarray(d); np.asarray(k)
            b <<= 1
        kernel.device_calls = 0
        kernel.host_calls = 0
        kernel.buckets_seen.clear()

    def flush_round(self, engine) -> int:
        """Launch the device step for the round's remaining batch.  Called by
        the engine once per round after workers drain.  In async mode (the
        default) the results are NOT materialized here — the engine calls
        consume_flush at the top of the next iteration, before the next
        window is computed, so the device works through the barrier
        bookkeeping.  Sharded runs consume immediately (same-round outbox
        contract).

        Quiet rounds (no offers — every superwindow-merged span, and most
        rounds of a device-plane run whose traffic lives in HBM) return
        after the one empty-batch check: the kernel is built lazily by the
        first real launch (_launch_locked), and consume_flush with nothing
        pending is the _sync path's own no-op."""
        cols = self._drain_batch()
        if cols is None:
            self.last_batch = 0
        else:
            self._launch(engine, cols)
        if self._sync:
            return self.consume_flush(engine) or (cols is not None)
        # truthy iff a launch happened: the engine's quiet-round
        # dirty-tracking (ISSUE 10) counts rounds whose flush did nothing
        return cols is not None

    def consume_flush(self, engine) -> int:
        """Materialize every launched chunk and push the surviving delivery
        events.  MUST run before the engine computes the next window (the
        engine loop guarantees it); the time blocked here is the exposed
        device wait the async split is minimizing."""
        if not self._pending:
            return 0
        t0 = _walltime.perf_counter_ns()
        pending = self._pending
        self._pending = []
        topo = engine.topology
        delivered = 0
        dropped = 0
        end_time = engine.end_time
        count_drop = engine.count_packet_drop
        push = super().push
        counters = engine.counters
        sharded = engine.shard_count > 1
        owns = engine.owns_host
        outboxes = engine.shard_outboxes
        shard_of = engine.shard_of
        t_dev = 0
        for (pkts, src_hosts, dst_hosts, seqs, src_arr, dst_arr,
             deliver, keep, barrier) in pending:
            td0 = _walltime.perf_counter_ns()
            m = len(pkts)
            # blocks iff the device isn't done; device results are padded to
            # the bucket size (slicing on host is one memcpy, not a dispatch)
            deliver = np.asarray(deliver)[:m]
            keep = np.asarray(keep)[:m]
            t_dev += _walltime.perf_counter_ns() - td0
            # per-path packet accounting for the kept lanes, vectorized
            # (the CPU latency lookup path counts per call)
            np.add.at(topo.path_packet_counts,
                      (src_arr[keep], dst_arr[keep]), 1)
            deliver_list = deliver.tolist()
            keep_list = keep.tolist()
            for i in range(len(pkts)):
                pkt = pkts[i]
                if not keep_list[i]:
                    pkt.add_status("INET_DROPPED")
                    count_drop(pkt)
                    dropped += 1
                    continue
                t = deliver_list[i]
                if t >= end_time:
                    continue
                pkt.add_status("INET_SENT")
                dst = dst_hosts[i]
                if sharded and not owns(dst):
                    # --processes: hand the finished hop to the owner shard
                    # (the seq was claimed at offer time, so the event tuple
                    # matches)
                    outboxes[shard_of(dst)].append(
                        (t, dst.id, src_hosts[i].id, seqs[i], pkt.to_wire()))
                    delivered += 1
                    continue
                task = Task(_deliver_packet_task, dst, pkt,
                            name="deliver_packet")
                ev = Event(task, t, dst, src_hosts[i], seqs[i])
                push(ev, 0, barrier)
                delivered += 1
        counters.count_new("event", delivered)
        self.packets_dropped += dropped
        t1 = _walltime.perf_counter_ns()
        self.device_ns += t_dev
        self.host_flush_ns += (t1 - t0) - t_dev
        return delivered

    def pending_count(self) -> int:
        return (super().pending_count() + len(self._p_rows)
                + sum(len(p[0]) for p in self._pending))

    def next_time(self) -> int:
        # Unlaunched offers or unconsumed chunks here would mean the engine
        # computed a window while deliveries were still in flight; the loop
        # contract (consume_flush -> next_time -> run -> flush_round) makes
        # that impossible — assert it.
        assert not self._p_rows and not self._pending, \
            "consume_flush must run before next_time"
        return super().next_time()


class TPUSerialPolicy(_TPUBatchMixin, GlobalSinglePolicy):
    """tpu policy over the single global event queue (workers == 0)."""

    def __init__(self):
        GlobalSinglePolicy.__init__(self)
        self._init_batch()


class TPUPolicy(_TPUBatchMixin, HostQueuesPolicy):
    """tpu policy over per-host locked queues (threaded runs)."""

    def __init__(self):
        HostQueuesPolicy.__init__(self)
        self._init_batch()
