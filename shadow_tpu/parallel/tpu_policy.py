"""The ``tpu`` scheduler policy: per-host event queues + device-batched hops.

This is the seventh scheduler policy (SURVEY.md §2.2; the reference's six
live in core/scheduler.py).  Event storage and popping are identical to the
``host`` policy; what changes is the inter-host packet hop
(worker.c:243-304): instead of a per-packet reliability draw + latency
lookup on the CPU, packets sent during a round are appended to a batch, and
at the round barrier ONE jitted device step (ops/round_step.py) computes
every drop decision and delivery time at once.  CPU<->TPU exchange happens
only at round boundaries — the conservative lookahead window guarantees no
intra-round causality violation, the same argument the reference's
host-steal policy uses for its cross-host barrier clamp
(scheduler_policy_host_steal.c:229-242).

Parity: drops are keyed by packet uid through the same threefry cipher the
CPU policies use, so a simulation under ``tpu`` delivers/drops exactly the
same packets at exactly the same times as under ``global``/``steal``
(asserted by tests/test_tpu_policy.py).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from ..core.scheduler import HostQueuesPolicy
from ..core.event import Event
from ..core.task import Task
from ..core.worker import _deliver_packet_task


class TPUPolicy(HostQueuesPolicy):
    def __init__(self):
        super().__init__()
        self._batch_lock = threading.Lock()
        # pending hop: (packet, src_host, dst_host, seq, send_time)
        self._pending: List[Tuple] = []
        self._kernel = None
        self._rows_by_ip = {}
        self.packets_batched = 0
        self.packets_dropped = 0

    # -- worker-facing batching -------------------------------------------
    def offer_packet(self, packet, worker) -> bool:
        """Append a packet hop to the round batch (called from
        Worker.send_packet in place of the scalar CPU path).  The source-host
        event sequence id is claimed NOW so the deterministic order tuple
        (time, dst, src, seq) reflects send order, as on the CPU path."""
        engine = worker.engine
        dst_host = engine.host_by_ip(packet.dst_ip)
        if dst_host is None:
            packet.add_status("INET_DROPPED")
            return True
        src_host = worker.active_host
        seq_owner = src_host if src_host is not None else dst_host
        seq = seq_owner.next_event_sequence()
        with self._batch_lock:
            self._pending.append(
                (packet, src_host, dst_host, seq, worker.now))
        self.packets_batched += 1
        return True

    # -- round-boundary flush ---------------------------------------------
    def _ensure_kernel(self, engine):
        if self._kernel is None:
            from ..ops.round_step import (PacketHopKernel,
                                          ShardedPacketHopKernel)
            topo = engine.topology
            n_dev = getattr(engine.options, "tpu_devices", 0)
            if n_dev == 0:
                # 0 = all local devices (options.py); sharding only engages
                # when that is actually more than one chip
                import jax
                n_dev = len(jax.devices())
            if n_dev > 1:
                # scale-out: the round batch is sharded across a 1-D mesh
                # (ICI collectives combine the min-next-time reduction)
                self._kernel = ShardedPacketHopKernel(
                    topo, engine._drop_key, engine.bootstrap_end, n_dev,
                    shard_matrix=getattr(engine.options,
                                         "tpu_shard_matrix", False))
            else:
                self._kernel = PacketHopKernel(
                    topo, engine._drop_key, engine.bootstrap_end)
            self._rows = topo  # row lookups go through topology
        return self._kernel

    def flush_round(self, engine) -> int:
        """Run the device step for the round's batch and push the surviving
        delivery events.  Called by the engine once per round, after workers
        drain and before the next window is computed."""
        with self._batch_lock:
            pending, self._pending = self._pending, []
        if not pending:
            return 0
        kernel = self._ensure_kernel(engine)
        topo = engine.topology
        n = len(pending)
        src_rows = np.empty(n, dtype=np.int32)
        dst_rows = np.empty(n, dtype=np.int32)
        uids = np.empty(n, dtype=np.uint64)
        send_times = np.empty(n, dtype=np.int64)
        for i, (pkt, _s, _d, _q, t) in enumerate(pending):
            src_rows[i] = topo.row_for_ip(pkt.src_ip)
            dst_rows[i] = topo.row_for_ip(pkt.dst_ip)
            uids[i] = pkt.uid
            send_times[i] = t

        barrier = engine.scheduler.window_end
        deliver, keep = kernel.step(src_rows, dst_rows, uids, send_times, barrier)

        delivered = 0
        end_time = engine.end_time
        for i, (pkt, src_host, dst_host, seq, _t) in enumerate(pending):
            if not keep[i]:
                pkt.add_status("INET_DROPPED")
                engine.count_packet_drop(pkt)
                self.packets_dropped += 1
                continue
            # per-path packet accounting, as the CPU latency lookup does
            topo.path_packet_counts[src_rows[i], dst_rows[i]] += 1
            t = int(deliver[i])
            if t >= end_time:
                continue
            pkt.add_status("INET_SENT")
            task = Task(_deliver_packet_task, dst_host, pkt,
                        name="deliver_packet")
            ev = Event(task, t, dst_host, src_host, seq)
            engine.counters.count_new("event")
            super().push(ev, 0, barrier)
            delivered += 1
        return delivered

    def pending_count(self) -> int:
        return super().pending_count() + len(self._pending)

    def next_time(self) -> int:
        # A non-empty batch means there are future deliveries not yet pushed;
        # flush_round always runs before next_time in the engine loop, so the
        # base implementation is correct — assert the contract in debug runs.
        assert not self._pending, "flush_round must run before next_time"
        return super().next_time()
