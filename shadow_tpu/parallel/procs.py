"""Process-parallel scale-out: shard engines + a conservative round barrier.

``--processes N`` partitions the hosts round-robin across N OS processes.
Each child builds the COMPLETE simulation skeleton (hosts, DNS, topology —
so addressing, bandwidth resolution, and RNG derivations are bitwise
identical to a single-process run) but boots and executes events only for
its owned partition.  The only cross-host coupling in the whole simulator is
the packet hop (core/worker.py ``send_packet``), so the shard boundary is a
packet boundary: hops whose destination lives on another shard are finished
locally (reliability draw + latency lookup — both keyed by packet uid /
topology, identical everywhere) and shipped to the owner at the round
barrier, which pushes the delivery event with the identical
(time, dst, src, seq) order tuple.

Why this is exact, not approximate: every scheduler policy already clamps
cross-host deliveries to the current window end (core/scheduler.py ``push``),
and the window size never exceeds the minimum topology latency — so no
packet sent during round R can be delivered inside round R.  Exchanging
packets at the barrier therefore reproduces the serial event timeline
bit-for-bit; the parity tests assert equal state digests against a
single-process run.

This is the analog of the reference's master/slave split taken across
process boundaries (the reference kept all workers in one process and
scaled with pthreads, core/scheduler.c:266-333; a C simulator can — for
CPython the GIL makes threads useless for compute, so real multicore
scaling needs processes).  The round protocol is the classic conservative
PDES exchange (null-message-free, barrier-synchronized), the same shape an
MPI/NCCL allreduce-per-round backend would have on a multi-host deployment:
``out``-boxes are the all-to-all, the min-next-time gather is the allreduce.

Per round, parent <-> children exchange:

    parent -> all : ("run", window_start, window_end)
    child  -> par : ("out", [outbox per shard])      after draining the round
    parent -> all : ("in", inbox)                     routed all-to-all
    child  -> par : ("min", next_event_time, pending) after ingesting inbox

plus ("collect" -> "hosts") for assembled checkpoints and
("stop" -> "final") at the end.  Checkpoints taken by the parent merge the
shards' per-host states through the same ``assemble_state`` the serial
writer uses, so snapshot digests are comparable across process counts.

Self-healing (ISSUE 17): a shard that dies mid-protocol (SIGKILL, OOM,
``os._exit``) no longer ends the run.  The surviving shards are already
quiesced at the round barrier (they park in ``conn.recv`` until the parent
routes their inbox — the barrier IS the checkpoint boundary), so the parent
respawns the dead shard and drives it through a deterministic replay of the
recorded protocol history: the identical ("run", ws, we) windows and
("in", inbox) payloads, with every replayed round's outbox signature and
min-report cross-checked against the first life, and the shard's host-state
digest verified at the newest recorded snapshot boundary (the join-boundary
digest check; pure round-zero replay when no checkpoint was written).  Any
divergence aborts loudly — a resurrection may never silently simulate
something else.  Bounded by ``--max-resurrections`` with exponential
backoff; each detour is counted in ``SupervisionStats`` with its MTTR.
The replay history (window list + per-shard inboxes) is retained in the
parent for the life of the run — the price of being able to rebuild any
shard from round zero, same as the determinism-kernel resume contract.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time as _walltime
from typing import Dict, List, Optional

from ..core import stime
from ..core.logger import SimLogger, get_logger, set_logger


# ---------------------------------------------------------------------------
# child (shard) side
# ---------------------------------------------------------------------------

def _shard_main(conn, options, config) -> None:
    """Entry point of one shard process (spawned; top-level for pickling)."""
    try:
        set_logger(SimLogger(level=options.log_level))
        _shard_body(conn, options, config)
    except BaseException as e:  # noqa: BLE001 - surfaced to the parent
        import traceback
        try:
            conn.send(("error", f"{e!r}\n{traceback.format_exc()}"))
        except Exception:
            pass
        raise


def _shard_body(conn, options, config) -> None:
    from ..core.checkpoint import collect_host_states
    from ..core.controller import Controller
    from ..core.event import Event
    from ..core.task import Task
    from ..core.worker import Worker, set_current_worker, \
        _deliver_packet_task
    from ..routing.packet import Packet

    ctrl = Controller(options, config)
    ctrl.setup()
    engine = ctrl.engine
    log = get_logger()

    # fault harness (shard-exit:SID:ROUND): this shard hard-exits at the
    # start of round ROUND — os._exit skips the ("error", ...) report, so
    # the parent sees exactly what a SIGKILL/OOM kill looks like and must
    # recover via dead-shard detection, never a hang
    from ..core.supervision import parse_fault_inject
    fault = parse_fault_inject(getattr(options, "fault_inject", "") or "")
    fault_exit_round = 0
    if fault and fault["kind"] in ("shard-exit", "shard-exit-resurrect") \
            and fault["shard"] == engine.shard_id:
        fault_exit_round = fault["round"]

    engine.sim_start_wall = _walltime.monotonic()
    engine.schedule_boot()
    worker = Worker(0, engine)
    set_current_worker(worker)
    tracer = engine.tracer

    import gc
    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.collect()
        gc.freeze()
        gc.disable()

    hosts_by_id = engine.hosts
    scheduler = engine.scheduler
    try:
        conn.send(("ready", engine.lookahead_ns, engine.end_time,
                   len(engine.hosts)))
        conn.send(("min", scheduler.next_event_time(),
                   scheduler.pending_count()))
        while True:
            msg = conn.recv()
            kind = msg[0]
            if kind == "stop":
                break
            if kind == "collect":
                conn.send(("hosts", collect_host_states(engine)))
                continue
            ws, we = msg[1], msg[2]
            if fault_exit_round and \
                    engine.rounds_executed + 1 >= fault_exit_round:
                os._exit(3)
            scheduler.window_start = ws
            scheduler.window_end = we
            worker.round_end = we
            if engine.native_plane is not None:
                engine.native_plane.set_window(we)
            if engine.host_table is not None:
                # same round-top promotion sweep the serial loop runs
                engine.host_table.promote_due(we)
            with tracer.span("round", "engine", sim_ns=ws,
                             args={"round": engine.rounds_executed,
                                   "shard": engine.shard_id}):
                worker.run_round()
            with tracer.span("flush", "engine", sim_ns=ws):
                engine._flush_round()
            conn.send(("out", engine.drain_outboxes()))
            with tracer.span("exchange", "engine", sim_ns=ws):
                inbox = conn.recv()[1]
            for t, dst_id, src_id, seq, wire in inbox:
                if engine.native_plane is not None:
                    # C-plane shard: the hop lands straight in the C event
                    # heap (all TCP/UDP sockets live there); same clamp,
                    # same sender-claimed identity
                    engine.native_plane.c.push_deliver(int(t), int(dst_id),
                                                       int(src_id),
                                                       int(seq), wire)
                    continue
                # table rows materialize on first delivery, exactly like
                # the in-process host_by_ip path (the owner side boots the
                # row; the replica side exists for identity only)
                dst_host = engine.host_by_id(dst_id)
                src_host = engine.host_by_id(src_id)
                pkt = Packet.from_wire(wire)
                ev = Event(Task(_deliver_packet_task, dst_host, pkt,
                                name="deliver_packet"),
                           t, dst_host, src_host, seq)
                # the push clamp (still at this round's window end) matches
                # what the serial run applied when the hop was scheduled
                scheduler.push(ev, worker)
            engine.rounds_executed += 1
            engine._heartbeat()
            log.flush()
            conn.send(("min", scheduler.next_event_time(),
                       scheduler.pending_count()))
    finally:
        if gc_was_enabled:
            gc.enable()
            gc.unfreeze()
            gc.collect()
        set_current_worker(None)

    events = worker.counters._free.get("event", 0)
    if engine.native_plane is not None:
        # fold the C plane's event lifecycle into this shard's totals
        # (mirrors Engine._run_serial's accounting)
        sched, execd, drops, _last = engine.native_plane.counters()
        events += execd
        worker.counters.count_new("event", sched)
        worker.counters.count_free("event", execd)
        if drops:
            worker.counters.count_new("packet_drop", drops)
        # the shard teardown sweep reads every host's C counters from ONE
        # bulk snapshot, exactly like the serial/threaded final sweeps
        # (ISSUE 10 satellite; this used to pay a C round-trip per host)
        with engine.native_plane.bulk_sync():
            for host in engine.hosts.values():
                engine.native_plane.sync_tracker(host.id, host.tracker)
    worker.finish()
    host_states = collect_host_states(engine)
    for host in engine.hosts.values():
        # dict.fromkeys: deterministic dedupe (set order varies — SIM003)
        for iface in dict.fromkeys(host.interfaces.values()):
            if iface.pcap is not None:
                iface.pcap.close()
        if engine.owns_host(host):
            engine.counters.count_free("host")
    if engine.host_table is not None:
        engine.host_table.close_counters()
    log.flush()
    # observability merge (ISSUE 3): the shard's flight-recorder ring and
    # metrics scrape ride the final message; the parent merges traces onto
    # per-shard tracks (Chrome pid = shard id) and folds the scrapes into
    # its summary.  Shard engines never export/write files themselves.
    from ..obs.metrics import get_metrics
    from ..obs.trace import get_tracer
    if get_metrics().enabled:
        # closing tracker sweep (same as Engine._obs_finish): the shard's
        # scrape ships end-of-run tracker totals to the parent summary,
        # and the heartbeat lines it logs need one more flush to reach
        # the shard's log (the earlier flush predates the sweep).  Under
        # the native plane the counter reads come from ONE bulk snapshot
        # (ISSUE 10 satellite — the serial sweep already did).
        from contextlib import nullcontext
        ctx = engine.native_plane.bulk_sync() \
            if engine.native_plane is not None else nullcontext()
        with ctx:
            for host in engine.hosts.values():
                if engine.owns_host(host):
                    host.tracker.heartbeat(engine.scheduler.window_start)
        log.flush()
    conn.send(("final", {
        "events": events,
        "rounds": engine.rounds_executed,
        "plugin_errors": engine.plugin_errors,
        "pending": scheduler.pending_count(),
        "host_states": host_states,
        "counters_new": dict(engine.counters._new),
        "counters_free": dict(engine.counters._free),
        "wall": _walltime.monotonic() - engine.sim_start_wall,
        "trace_events": get_tracer().drain(),
        "trace_epoch": get_tracer().epoch,
        "trace_dropped": get_tracer().dropped,
        "metrics": get_metrics().scrape(),
        "supervision": engine.supervision.summary(),
    }))


# ---------------------------------------------------------------------------
# parent (coordinator) side
# ---------------------------------------------------------------------------

class ShardDeadError(RuntimeError):
    """A shard process died (or went watchdog-silent) mid-protocol — the
    distinguished failure the supervision ledger counts, as opposed to a
    shard that REPORTED an error before exiting.

    ``sid`` names the dead shard; ``resurrectable`` is False for the
    live-but-silent watchdog case (killing and replaying a shard that may
    still be computing is not a recovery, it is a race — that path stays a
    diagnostic abort)."""

    sid: int = -1
    resurrectable: bool = True


def _recv_supervised(conn, proc, sid: int, watchdog_sec: float):
    """Shard supervision: a ``recv`` that polls in short slices and checks
    the shard process between them.  A shard that died without reporting
    (SIGKILL, OOM, os._exit) surfaces as a diagnostic ShardDeadError within
    ~a poll slice instead of parking the parent in ``Connection.recv``
    forever — the parent decides whether to resurrect or abort;
    ``watchdog_sec > 0`` additionally bounds how long a LIVE but silent
    shard may stall a round barrier."""
    waited = 0.0
    while True:
        if conn.poll(0.5):
            try:
                msg = conn.recv()
            except EOFError:
                raise ShardDeadError(
                    f"shard {sid} closed its pipe mid-message "
                    f"(exit code {proc.exitcode})")
            if msg[0] == "error":
                raise RuntimeError(f"shard failed:\n{msg[1]}")
            return msg
        if not proc.is_alive():
            if conn.poll(0):
                continue        # final message raced the death check
            raise ShardDeadError(
                f"shard {sid} died (exit code {proc.exitcode}) without "
                "reporting an error (dead-shard detection)")
        waited += 0.5
        if watchdog_sec > 0 and waited >= watchdog_sec:
            err = ShardDeadError(
                f"shard {sid} alive but silent for {waited:.0f}s "
                "(--shard-watchdog-sec) — aborting with diagnostics")
            err.resurrectable = False
            raise err


class ProcsController:
    """Coordinator for ``--processes N``: spawns the shard engines, drives
    the window/exchange protocol, assembles checkpoints and the final state
    digest.  Mirrors the reference Master's role (core/master.c) across
    process boundaries."""

    def __init__(self, options, config):
        if options.processes < 2:
            raise ValueError("--processes needs N >= 2 (use the regular "
                             "engine for a single process)")
        self.options = options
        self.config = config
        self.n_shards = int(options.processes)
        self.rounds_executed = 0
        self.events_executed = 0
        self.final_state: Optional[Dict] = None
        self.digest: Optional[str] = None
        self.checkpoints: List[str] = []
        self.resume_verified = False
        from ..core.supervision import SupervisionStats, parse_fault_inject
        self.supervision = SupervisionStats()
        # self-healing state (ISSUE 17): the recorded protocol history a
        # resurrected shard replays, per-shard snapshot-boundary digests
        # for the join verification, and the respawn budget.  The legacy
        # ``shard-exit`` drill keeps PR-2 abort semantics (it exists to
        # drill dead-shard DETECTION); real deaths and the
        # ``shard-exit-resurrect`` drill take the resurrection path.
        fault = parse_fault_inject(getattr(options, "fault_inject", "")
                                   or "")
        self._legacy_abort = bool(fault and fault["kind"] == "shard-exit")
        self.max_resurrections = int(
            getattr(options, "max_resurrections", 3))
        self._history: List[tuple] = []       # (ws, we, inboxes, out_sigs,
                                              #  mins) per completed round
        self._ck_verify: Dict[int, List[str]] = {}   # rounds -> per-sid
                                                     # host-state digests
        self._initial: Optional[tuple] = None  # (readies, first mins)
        self._resurrections_used = 0
        self._death_wall = 0.0
        self._last_collect_sid_digests: List[str] = []
        self._shard_wd = float(getattr(options, "shard_watchdog_sec", 0)
                               or 0)
        self._ctx = None
        self.conns: List = []
        self.procs: List = []
        # parent-side observability: the parent owns the merged trace file
        # (per-shard tracks) and the metrics summary; its own track is
        # labeled 'parent' on a pid past the shard range
        from ..obs import configure_observability
        self.tracer, self.metrics, self._metrics_writer = \
            configure_observability(options, shard_id=self.n_shards,
                                    label="parent")

    def _child_options(self, shard_id: int):
        import dataclasses
        opt = dataclasses.replace(self.options)
        opt.processes = 0
        opt.shard_id = shard_id
        opt.shard_count = self.n_shards
        # each shard drains its partition with the single serial worker; a
        # threaded scheduler inside a shard would strand events on worker>0
        # heaps that _shard_body's lone Worker(0) never pops
        opt.workers = 0
        # checkpoints are assembled by the parent from shard host-states;
        # per-shard snapshot files would be partial and misleading — and
        # the parent likewise owns resume verification over the ASSEMBLED
        # state, so shards never verify partial digests
        opt.checkpoint_interval_sec = 0
        opt.checkpoint_every_rounds = 0
        opt.resume_path = None
        # the parent seeds the data directory from the template ONCE before
        # spawning (N children racing shutil.copytree would collide)
        opt.data_template = None
        return opt

    # -- self-healing plumbing (ISSUE 17) ----------------------------------

    def _spawn(self, sid: int, clear_fault: bool = False) -> None:
        """Spawn (or respawn) shard ``sid``.  A resurrection spawns with
        the shard-exit fault harness CLEARED: the drill simulates ONE
        SIGKILL, and a replacement that re-dies at the same round would
        only drain the budget without testing anything new.  Every other
        fault kind is kept — the replacement must replay its first life
        exactly, demotions included."""
        opt = self._child_options(sid)
        if clear_fault and (opt.fault_inject or "").startswith("shard-exit"):
            opt.fault_inject = ""
        parent_conn, child_conn = self._ctx.Pipe()
        p = self._ctx.Process(target=_shard_main,
                              args=(child_conn, opt, self.config),
                              daemon=True, name=f"shard-{sid}")
        p.start()
        child_conn.close()
        if sid < len(self.conns):
            self.conns[sid] = parent_conn
            self.procs[sid] = p
        else:
            self.conns.append(parent_conn)
            self.procs.append(p)

    def _recv(self, sid: int):
        try:
            return _recv_supervised(self.conns[sid], self.procs[sid], sid,
                                    self._shard_wd)
        except ShardDeadError as e:
            # the ledger records the detection regardless of what the
            # parent does next (resurrect or abort), and the timeline
            # rides along like every other recovery seam
            self.supervision.shard_deaths_detected += 1
            self.supervision._dump_flight_recorder(
                f"shard {sid} death detected")
            self._death_wall = _walltime.monotonic()
            e.sid = sid
            raise

    def _send(self, sid: int, msg) -> None:
        try:
            self.conns[sid].send(msg)
        except (BrokenPipeError, OSError):
            self.supervision.shard_deaths_detected += 1
            self.supervision._dump_flight_recorder(
                f"shard {sid} death detected (send)")
            self._death_wall = _walltime.monotonic()
            e = ShardDeadError(
                f"shard {sid} pipe closed on send "
                f"(exit code {self.procs[sid].exitcode})")
            e.sid = sid
            raise e

    def _heal_or_raise(self, e: ShardDeadError) -> int:
        """Decide a dead shard's fate: resurrect within budget, or abort
        loudly.  Returns the shard id after a successful resurrection."""
        if self._legacy_abort or not getattr(e, "resurrectable", True):
            raise e
        if self._resurrections_used >= self.max_resurrections:
            raise RuntimeError(
                f"resurrection budget exhausted (--max-resurrections "
                f"{self.max_resurrections}, used "
                f"{self._resurrections_used}): {e} — aborting")
        self._resurrect(e.sid)
        return e.sid

    def _resurrect(self, sid: int) -> None:
        """Respawn shard ``sid`` and replay it to the current round
        barrier.  The surviving shards are quiesced (parked in their
        ``conn.recv`` at the barrier) for the duration; they never see the
        detour.  Replay is the determinism-kernel resume contract applied
        to one shard: identical windows + identical inboxes => identical
        state, cross-checked per round (outbox signature, min report) and
        digest-verified at the newest recorded snapshot boundary.  Any
        mismatch aborts loudly — a genuinely corrupt or divergent replay
        may never rejoin the barrier."""
        import hashlib

        from ..core.checkpoint import digest_of_state
        log = get_logger()
        self._resurrections_used += 1
        attempt = self._resurrections_used
        backoff = 0.05 * (2 ** (attempt - 1))
        log.warning(
            "procs",
            f"shard {sid} died mid-protocol; resurrecting (attempt "
            f"{attempt}/{self.max_resurrections}) after {backoff:.2f}s "
            "backoff — survivors stay quiesced at the round barrier")
        # real wall-clock backoff by design: the corpse's OS resources
        # (pipes, memory) need releasing before the respawn, and repeated
        # crash loops must decelerate — nothing here advances virtual time
        _walltime.sleep(backoff)  # simlint: disable=SIM005 -- supervision backoff is wall time by definition
        old = self.procs[sid]
        try:
            self.conns[sid].close()
        except Exception:
            pass
        old.join(timeout=5)
        if old.is_alive():
            old.terminate()
            old.join(timeout=5)
        if old.is_alive():
            old.kill()
            old.join(timeout=5)
        self._spawn(sid, clear_fault=True)
        ready = self._recv(sid)
        m0 = self._recv(sid)
        if self._initial is not None:
            exp_ready, exp_min = self._initial
            if tuple(ready[1:]) != tuple(exp_ready[sid][1:]) or \
                    (m0[1], m0[2]) != (exp_min[sid][1], exp_min[sid][2]):
                raise RuntimeError(
                    f"shard {sid} resurrection diverged at boot: the "
                    "replacement's ready/min report does not match its "
                    "first life — config/seed drifted; aborting")
        for r, (ws, we, inboxes, out_sigs, mins_r) in \
                enumerate(self._history):
            self._send(sid, ("run", ws, we))
            out = self._recv(sid)[1]
            sig = hashlib.sha256(repr(out).encode()).hexdigest()
            if sig != out_sigs[sid]:
                raise RuntimeError(
                    f"shard {sid} resurrection diverged at round {r}: "
                    "replayed outbox does not match the recorded one — "
                    "aborting (a resurrection may never silently simulate "
                    "something else)")
            self._send(sid, ("in", inboxes[sid]))
            m = self._recv(sid)
            if (m[1], m[2]) != (mins_r[sid][1], mins_r[sid][2]):
                raise RuntimeError(
                    f"shard {sid} resurrection diverged at round {r}: "
                    "replayed min report does not match the recorded one "
                    "— aborting")
            if r + 1 in self._ck_verify:
                # the join-boundary digest gate: at every boundary the
                # parent snapshotted, the replayed shard's own host states
                # must digest to exactly what it contributed then
                self._send(sid, ("collect",))
                states = self._recv(sid)[1]
                if digest_of_state(states) != self._ck_verify[r + 1][sid]:
                    raise RuntimeError(
                        f"shard {sid} resurrection diverged at the round-"
                        f"{r + 1} snapshot boundary: replayed host-state "
                        "digest does not match the checkpointed one — "
                        "aborting")
        mttr = int((_walltime.monotonic() - self._death_wall) * 1e9)
        self.supervision.count_shard_resurrection(sid, attempt, mttr)

    def _drive_round(self, ws: int, we: int) -> List[tuple]:
        """One conservative round with self-healing: run -> out gather ->
        inbox route -> min gather, any phase surviving a shard death by
        resurrecting and re-driving that shard through the round.  A shard
        whose outbox was already received before it died must reproduce it
        bit-identically after resurrection (the determinism pin).  Records
        the round in the replay history on success."""
        import hashlib
        n = self.n_shards
        run_sent = [False] * n
        outs: Dict[int, list] = {}
        expect_outs: Dict[int, list] = {}
        inboxes: Optional[List[list]] = None
        in_sent = [False] * n
        mins: Dict[int, tuple] = {}
        while True:
            try:
                for sid in range(n):
                    if not run_sent[sid]:
                        self._send(sid, ("run", ws, we))
                        run_sent[sid] = True
                for sid in range(n):
                    if sid not in outs:
                        outs[sid] = self._recv(sid)[1]
                        if sid in expect_outs \
                                and outs[sid] != expect_outs[sid]:
                            raise RuntimeError(
                                f"shard {sid} resurrection diverged: the "
                                "re-driven round's outbox does not match "
                                "what the first life sent — aborting")
                if inboxes is None:
                    inboxes = [[] for _ in range(n)]
                    for s in range(n):
                        for d in range(n):
                            inboxes[d].extend(outs[s][d])
                with self.tracer.span("exchange", "procs", sim_ns=ws):
                    for sid in range(n):
                        if not in_sent[sid]:
                            self._send(sid, ("in", inboxes[sid]))
                            in_sent[sid] = True
                    for sid in range(n):
                        if sid not in mins:
                            mins[sid] = self._recv(sid)
                break
            except ShardDeadError as e:
                sid = self._heal_or_raise(e)
                # re-drive the resurrected shard through THIS round from
                # the top; everything it already delivered is cross-checked
                run_sent[sid] = False
                if sid in outs:
                    expect_outs[sid] = outs.pop(sid)
                in_sent[sid] = False
                mins.pop(sid, None)
        out_sigs = [hashlib.sha256(repr(outs[s]).encode()).hexdigest()
                    for s in range(n)]
        mins_list = [mins[s] for s in range(n)]
        self._history.append((ws, we, inboxes, out_sigs, mins_list))
        return mins_list

    def run(self) -> int:
        from ..core.checkpoint import assemble_state, digest_of_state

        log = get_logger()
        n = self.n_shards
        template = getattr(self.options, "data_template", None)
        if template and not os.path.exists(self.options.data_directory):
            import shutil
            shutil.copytree(template, self.options.data_directory)
        self._ctx = mp.get_context("spawn")
        t_start = _walltime.monotonic()
        for sid in range(n):
            self._spawn(sid)
        conns, procs = self.conns, self.procs

        try:
            # boot-phase deaths stay aborts: a shard that cannot even
            # reach its first barrier would die again on respawn
            readies = [self._recv(sid) for sid in range(n)]
            lookahead = readies[0][1]
            end_time = readies[0][2]
            assert all(r[1] == lookahead and r[2] == end_time
                       for r in readies), "shards disagree on lookahead/end"
            mins = [self._recv(sid) for sid in range(n)]
            self._initial = (readies, mins)
            log.message(
                "procs",
                f"starting sharded simulation: {readies[0][3]} hosts over "
                f"{n} processes, lookahead={lookahead / 1e6:.3f} ms, "
                f"end={end_time / 1e9:.1f} s")

            writer = None
            if self.options.checkpoint_interval_sec > 0 \
                    or getattr(self.options,
                               "checkpoint_every_rounds", 0) > 0:
                from ..core.checkpoint import CheckpointWriter
                writer = CheckpointWriter(
                    self.options.checkpoint_interval_sec,
                    self.options.checkpoint_dir,
                    getattr(self.options, "checkpoint_every_rounds", 0))
            resume_snap = None
            if getattr(self.options, "resume_path", None):
                from ..core.checkpoint import find_last_good_snapshot
                resume_snap, resolved = find_last_good_snapshot(
                    self.options.resume_path)
                log.message(
                    "procs",
                    f"resuming from {resolved} "
                    f"(t={resume_snap['sim_time_ns'] / 1e9:.3f}s): "
                    "replaying to the snapshot boundary, digest-verified "
                    "there")
            self.metrics.source(
                "procs", lambda: {"procs.rounds": self.rounds_executed,
                                  "procs.shards": n})
            self.metrics.source(
                "supervision",
                lambda: {f"supervision.{k}": v
                         for k, v in self.supervision.summary().items()})
            last_ws = 0
            while True:
                nxt = min(m[1] for m in mins)
                if nxt >= end_time or nxt >= stime.SIM_TIME_MAX:
                    break
                ws, we = nxt, min(nxt + lookahead, end_time)
                with self.tracer.span("round", "procs", sim_ns=ws,
                                      args={"round": self.rounds_executed}):
                    mins = self._drive_round(ws, we)
                last_ws = ws
                if resume_snap is not None \
                        and ws >= resume_snap["sim_time_ns"]:
                    self._verify_resume(ws, resume_snap,
                                        sum(m[2] for m in mins))
                    resume_snap = None
                # parent-assembled checkpoint at the same boundaries the
                # serial CheckpointWriter uses (shared due()/path_for
                # cadence, BEFORE the round counter increments — so
                # snapshot names and digests line up with a serial run)
                if writer is not None \
                        and writer.due(ws, self.rounds_executed):
                    with self.tracer.span("checkpoint.write", "procs",
                                          sim_ns=ws):
                        self._write_checkpoint(ws, sum(m[2] for m in mins),
                                               writer)
                self.rounds_executed += 1
                if self._metrics_writer is not None:
                    self._metrics_writer.maybe_write(
                        self.metrics, self.rounds_executed, ws)

            if resume_snap is not None:
                from ..core.checkpoint import warn_resume_unreached
                warn_resume_unreached(resume_snap, "procs")
            finals = self._gather_finals()
        except BaseException:
            # abnormal termination (shard death, protocol error): export
            # the parent's own flight-recorder events best-effort so the
            # abort keeps its timeline; shard rings die with their
            # processes — the log dump in the recv handler is their trace
            try:
                if self.tracer.enabled:
                    self.tracer.export()
                if self._metrics_writer is not None:
                    self._metrics_writer.write_summary(
                        self.metrics, self.rounds_executed, 0)
            except Exception:
                pass
            raise
        finally:
            # closing the pipes first unblocks any shard still parked in
            # conn.recv() (EOFError -> exit), so a mid-run failure tears
            # down immediately instead of waiting out join timeouts
            for c in conns:
                try:
                    c.close()
                except Exception:
                    pass
            # straggler sweep: escalate terminate -> grace -> kill and
            # REAP after each step, so a shard that died during quiesce
            # (or wedged ignoring SIGTERM) cannot leave a zombie racing
            # the checkpoint barrier of a subsequent run
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=10)
                if p.is_alive():
                    p.kill()
                    p.join(timeout=10)

        host_states: Dict = {}
        for f in finals:
            host_states.update(f["host_states"])
        self.events_executed = sum(f["events"] for f in finals)
        assert all(f["rounds"] == self.rounds_executed for f in finals)
        state = assemble_state(last_ws, self.rounds_executed, host_states,
                               sum(f["pending"] for f in finals))
        self.final_state = state
        self.digest = digest_of_state(state)
        plugin_errors = sum(f["plugin_errors"] for f in finals)

        from ..core.counters import ObjectCounter
        totals = ObjectCounter()
        for f in finals:
            for k, v in f["counters_new"].items():
                totals.count_new(k, v)
            for k, v in f["counters_free"].items():
                totals.count_free(k, v)
        log.message(
            "procs",
            f"sharded simulation finished: {self.rounds_executed} rounds, "
            f"{self.events_executed} events, {n} processes, "
            f"{_walltime.monotonic() - t_start:.3f}s wall")
        if totals.leaks():
            log.message("procs", totals.report())
        self._obs_finish(finals, totals, last_ws)
        log.flush()
        return 1 if plugin_errors else 0

    def _obs_finish(self, finals, totals, last_ws: int) -> None:
        """Merge the shards' observability payloads: flight-recorder rings
        land on per-shard tracks in ONE trace file; metrics scrapes and the
        assembled leak report land in the parent's summary record."""
        if self.tracer.enabled:
            for f in finals:
                self.tracer.ingest(f.get("trace_events") or [],
                                   f.get("trace_epoch"))
                # the merged file's drop count must cover the SHARDS' ring
                # evictions, not just the parent's (no silent truncation)
                self.tracer.dropped += int(f.get("trace_dropped") or 0)
            path = self.tracer.export()
            if path:
                get_logger().message("procs", f"trace written: {path}")
        if self._metrics_writer is not None:
            for key, val in totals.summary().items():
                self.metrics.set_summary_info(key, val)
            self.metrics.set_summary_info(
                "shards", [f.get("metrics", {}) for f in finals])
            self.metrics.set_summary_info(
                "shard_supervision", [f.get("supervision", {})
                                      for f in finals])
            self._metrics_writer.write_summary(self.metrics,
                                               self.rounds_executed,
                                               last_ws)
            get_logger().message(
                "procs",
                f"metrics written: {self._metrics_writer.path} "
                f"({self._metrics_writer.records_written} records)")

    def _collect_assembled(self, ws: int, pending: int) -> Dict:
        """Gather every shard's host states and assemble the canonical
        digestible state (shared by checkpoint writes and resume verify).
        Heal-aware: a shard dying mid-collect is resurrected and re-asked
        (collect is state-neutral, so a re-ask is exact).  Records each
        shard's own host-state digest so a later resurrection replay can
        be digest-verified at this exact boundary."""
        from ..core.checkpoint import assemble_state, digest_of_state
        n = self.n_shards
        sent = [False] * n
        by_sid: Dict[int, Dict] = {}
        while True:
            try:
                for sid in range(n):
                    if not sent[sid]:
                        self._send(sid, ("collect",))
                        sent[sid] = True
                for sid in range(n):
                    if sid not in by_sid:
                        by_sid[sid] = self._recv(sid)[1]
                break
            except ShardDeadError as e:
                sid = self._heal_or_raise(e)
                sent[sid] = False
                by_sid.pop(sid, None)
        self._last_collect_sid_digests = [digest_of_state(by_sid[s])
                                          for s in range(n)]
        host_states: Dict = {}
        for s in range(n):
            host_states.update(by_sid[s])
        return assemble_state(ws, self.rounds_executed, host_states, pending)

    def _gather_finals(self) -> List[Dict]:
        """Heal-aware stop/final gather: a shard dying at the very last
        barrier is resurrected (full-history replay) and re-stopped — its
        final payload is deterministic, so the run still ends digest-clean
        (wall-clock fields differ but are never digested)."""
        n = self.n_shards
        sent = [False] * n
        by_sid: Dict[int, Dict] = {}
        while True:
            try:
                for sid in range(n):
                    if not sent[sid]:
                        self._send(sid, ("stop",))
                        sent[sid] = True
                for sid in range(n):
                    if sid not in by_sid:
                        by_sid[sid] = self._recv(sid)[1]
                break
            except ShardDeadError as e:
                sid = self._heal_or_raise(e)
                sent[sid] = False
                by_sid.pop(sid, None)
        return [by_sid[s] for s in range(n)]

    def _verify_resume(self, ws: int, snap: Dict, pending: int) -> None:
        """--resume under --processes: the shared boundary gate computed
        over the parent-assembled state."""
        from ..core.checkpoint import digest_of_state, verify_resume_boundary
        verify_resume_boundary(
            snap, ws,
            digest_of_state(self._collect_assembled(ws, pending)),
            "procs")
        self.resume_verified = True
        self.supervision.resume_verified = True

    def _write_checkpoint(self, ws: int, pending: int, writer) -> None:
        from ..core.checkpoint import save_state
        state = self._collect_assembled(ws, pending)
        # arm the join-boundary gate: len(self._history) rounds are
        # complete at this barrier; a future resurrection replaying past
        # it must reproduce each shard's digest recorded here
        self._ck_verify[len(self._history)] = \
            list(self._last_collect_sid_digests)
        os.makedirs(self.options.checkpoint_dir, exist_ok=True)
        path = writer.path_for(ws, self.rounds_executed)
        save_state(state, path, {
            "seed": self.options.seed,
            "scheduler_policy": self.options.scheduler_policy,
            # record the EFFECTIVE worker count: every shard runs with
            # workers=0 (see _child_options), whatever the user passed.
            "workers": 0,
            "stop_time_sec": self.options.stop_time_sec,
            "processes": self.n_shards,
        })
        writer.mark_written(ws, self.rounds_executed, path)
        self.checkpoints.append(path)
        get_logger().message("procs", f"checkpoint written: {path}")


def run_sharded(options, config) -> int:
    return ProcsController(options, config).run()
