"""Process-parallel scale-out: shard engines + a conservative round barrier.

``--processes N`` partitions the hosts round-robin across N OS processes.
Each child builds the COMPLETE simulation skeleton (hosts, DNS, topology —
so addressing, bandwidth resolution, and RNG derivations are bitwise
identical to a single-process run) but boots and executes events only for
its owned partition.  The only cross-host coupling in the whole simulator is
the packet hop (core/worker.py ``send_packet``), so the shard boundary is a
packet boundary: hops whose destination lives on another shard are finished
locally (reliability draw + latency lookup — both keyed by packet uid /
topology, identical everywhere) and shipped to the owner at the round
barrier, which pushes the delivery event with the identical
(time, dst, src, seq) order tuple.

Why this is exact, not approximate: every scheduler policy already clamps
cross-host deliveries to the current window end (core/scheduler.py ``push``),
and the window size never exceeds the minimum topology latency — so no
packet sent during round R can be delivered inside round R.  Exchanging
packets at the barrier therefore reproduces the serial event timeline
bit-for-bit; the parity tests assert equal state digests against a
single-process run.

This is the analog of the reference's master/slave split taken across
process boundaries (the reference kept all workers in one process and
scaled with pthreads, core/scheduler.c:266-333; a C simulator can — for
CPython the GIL makes threads useless for compute, so real multicore
scaling needs processes).  The round protocol is the classic conservative
PDES exchange (null-message-free, barrier-synchronized), the same shape an
MPI/NCCL allreduce-per-round backend would have on a multi-host deployment:
``out``-boxes are the all-to-all, the min-next-time gather is the allreduce.

Per round, parent <-> children exchange:

    parent -> all : ("run", window_start, window_end)
    child  -> par : ("out", [outbox per shard])      after draining the round
    parent -> all : ("in", inbox)                     routed all-to-all
    child  -> par : ("min", next_event_time, pending) after ingesting inbox

plus ("collect" -> "hosts") for assembled checkpoints and
("stop" -> "final") at the end.  Checkpoints taken by the parent merge the
shards' per-host states through the same ``assemble_state`` the serial
writer uses, so snapshot digests are comparable across process counts.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time as _walltime
from typing import Dict, List, Optional

from ..core import stime
from ..core.logger import SimLogger, get_logger, set_logger


# ---------------------------------------------------------------------------
# child (shard) side
# ---------------------------------------------------------------------------

def _shard_main(conn, options, config) -> None:
    """Entry point of one shard process (spawned; top-level for pickling)."""
    try:
        set_logger(SimLogger(level=options.log_level))
        _shard_body(conn, options, config)
    except BaseException as e:  # noqa: BLE001 - surfaced to the parent
        import traceback
        try:
            conn.send(("error", f"{e!r}\n{traceback.format_exc()}"))
        except Exception:
            pass
        raise


def _shard_body(conn, options, config) -> None:
    from ..core.checkpoint import collect_host_states
    from ..core.controller import Controller
    from ..core.event import Event
    from ..core.task import Task
    from ..core.worker import Worker, set_current_worker, \
        _deliver_packet_task
    from ..routing.packet import Packet

    ctrl = Controller(options, config)
    ctrl.setup()
    engine = ctrl.engine
    log = get_logger()

    # fault harness (shard-exit:SID:ROUND): this shard hard-exits at the
    # start of round ROUND — os._exit skips the ("error", ...) report, so
    # the parent sees exactly what a SIGKILL/OOM kill looks like and must
    # recover via dead-shard detection, never a hang
    from ..core.supervision import parse_fault_inject
    fault = parse_fault_inject(getattr(options, "fault_inject", "") or "")
    fault_exit_round = 0
    if fault and fault["kind"] == "shard-exit" \
            and fault["shard"] == engine.shard_id:
        fault_exit_round = fault["round"]

    engine.sim_start_wall = _walltime.monotonic()
    engine.schedule_boot()
    worker = Worker(0, engine)
    set_current_worker(worker)
    tracer = engine.tracer

    import gc
    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.collect()
        gc.freeze()
        gc.disable()

    hosts_by_id = engine.hosts
    scheduler = engine.scheduler
    try:
        conn.send(("ready", engine.lookahead_ns, engine.end_time,
                   len(engine.hosts)))
        conn.send(("min", scheduler.next_event_time(),
                   scheduler.pending_count()))
        while True:
            msg = conn.recv()
            kind = msg[0]
            if kind == "stop":
                break
            if kind == "collect":
                conn.send(("hosts", collect_host_states(engine)))
                continue
            ws, we = msg[1], msg[2]
            if fault_exit_round and \
                    engine.rounds_executed + 1 >= fault_exit_round:
                os._exit(3)
            scheduler.window_start = ws
            scheduler.window_end = we
            worker.round_end = we
            if engine.native_plane is not None:
                engine.native_plane.set_window(we)
            if engine.host_table is not None:
                # same round-top promotion sweep the serial loop runs
                engine.host_table.promote_due(we)
            with tracer.span("round", "engine", sim_ns=ws,
                             args={"round": engine.rounds_executed,
                                   "shard": engine.shard_id}):
                worker.run_round()
            with tracer.span("flush", "engine", sim_ns=ws):
                engine._flush_round()
            conn.send(("out", engine.drain_outboxes()))
            with tracer.span("exchange", "engine", sim_ns=ws):
                inbox = conn.recv()[1]
            for t, dst_id, src_id, seq, wire in inbox:
                if engine.native_plane is not None:
                    # C-plane shard: the hop lands straight in the C event
                    # heap (all TCP/UDP sockets live there); same clamp,
                    # same sender-claimed identity
                    engine.native_plane.c.push_deliver(int(t), int(dst_id),
                                                       int(src_id),
                                                       int(seq), wire)
                    continue
                # table rows materialize on first delivery, exactly like
                # the in-process host_by_ip path (the owner side boots the
                # row; the replica side exists for identity only)
                dst_host = engine.host_by_id(dst_id)
                src_host = engine.host_by_id(src_id)
                pkt = Packet.from_wire(wire)
                ev = Event(Task(_deliver_packet_task, dst_host, pkt,
                                name="deliver_packet"),
                           t, dst_host, src_host, seq)
                # the push clamp (still at this round's window end) matches
                # what the serial run applied when the hop was scheduled
                scheduler.push(ev, worker)
            engine.rounds_executed += 1
            engine._heartbeat()
            log.flush()
            conn.send(("min", scheduler.next_event_time(),
                       scheduler.pending_count()))
    finally:
        if gc_was_enabled:
            gc.enable()
            gc.unfreeze()
            gc.collect()
        set_current_worker(None)

    events = worker.counters._free.get("event", 0)
    if engine.native_plane is not None:
        # fold the C plane's event lifecycle into this shard's totals
        # (mirrors Engine._run_serial's accounting)
        sched, execd, drops, _last = engine.native_plane.counters()
        events += execd
        worker.counters.count_new("event", sched)
        worker.counters.count_free("event", execd)
        if drops:
            worker.counters.count_new("packet_drop", drops)
        # the shard teardown sweep reads every host's C counters from ONE
        # bulk snapshot, exactly like the serial/threaded final sweeps
        # (ISSUE 10 satellite; this used to pay a C round-trip per host)
        with engine.native_plane.bulk_sync():
            for host in engine.hosts.values():
                engine.native_plane.sync_tracker(host.id, host.tracker)
    worker.finish()
    host_states = collect_host_states(engine)
    for host in engine.hosts.values():
        # dict.fromkeys: deterministic dedupe (set order varies — SIM003)
        for iface in dict.fromkeys(host.interfaces.values()):
            if iface.pcap is not None:
                iface.pcap.close()
        if engine.owns_host(host):
            engine.counters.count_free("host")
    if engine.host_table is not None:
        engine.host_table.close_counters()
    log.flush()
    # observability merge (ISSUE 3): the shard's flight-recorder ring and
    # metrics scrape ride the final message; the parent merges traces onto
    # per-shard tracks (Chrome pid = shard id) and folds the scrapes into
    # its summary.  Shard engines never export/write files themselves.
    from ..obs.metrics import get_metrics
    from ..obs.trace import get_tracer
    if get_metrics().enabled:
        # closing tracker sweep (same as Engine._obs_finish): the shard's
        # scrape ships end-of-run tracker totals to the parent summary,
        # and the heartbeat lines it logs need one more flush to reach
        # the shard's log (the earlier flush predates the sweep).  Under
        # the native plane the counter reads come from ONE bulk snapshot
        # (ISSUE 10 satellite — the serial sweep already did).
        from contextlib import nullcontext
        ctx = engine.native_plane.bulk_sync() \
            if engine.native_plane is not None else nullcontext()
        with ctx:
            for host in engine.hosts.values():
                if engine.owns_host(host):
                    host.tracker.heartbeat(engine.scheduler.window_start)
        log.flush()
    conn.send(("final", {
        "events": events,
        "rounds": engine.rounds_executed,
        "plugin_errors": engine.plugin_errors,
        "pending": scheduler.pending_count(),
        "host_states": host_states,
        "counters_new": dict(engine.counters._new),
        "counters_free": dict(engine.counters._free),
        "wall": _walltime.monotonic() - engine.sim_start_wall,
        "trace_events": get_tracer().drain(),
        "trace_epoch": get_tracer().epoch,
        "trace_dropped": get_tracer().dropped,
        "metrics": get_metrics().scrape(),
        "supervision": engine.supervision.summary(),
    }))


# ---------------------------------------------------------------------------
# parent (coordinator) side
# ---------------------------------------------------------------------------

class ShardDeadError(RuntimeError):
    """A shard process died (or went watchdog-silent) mid-protocol — the
    distinguished failure the supervision ledger counts, as opposed to a
    shard that REPORTED an error before exiting."""


def _recv_supervised(conn, proc, sid: int, watchdog_sec: float):
    """Shard supervision: a ``recv`` that polls in short slices and checks
    the shard process between them.  A shard that died without reporting
    (SIGKILL, OOM, os._exit) surfaces as a diagnostic RuntimeError within
    ~a poll slice instead of parking the parent in ``Connection.recv``
    forever; ``watchdog_sec > 0`` additionally bounds how long a LIVE but
    silent shard may stall a round barrier."""
    waited = 0.0
    while True:
        if conn.poll(0.5):
            try:
                msg = conn.recv()
            except EOFError:
                raise ShardDeadError(
                    f"shard {sid} closed its pipe mid-message "
                    f"(exit code {proc.exitcode}) — aborting cleanly")
            if msg[0] == "error":
                raise RuntimeError(f"shard failed:\n{msg[1]}")
            return msg
        if not proc.is_alive():
            if conn.poll(0):
                continue        # final message raced the death check
            raise ShardDeadError(
                f"shard {sid} died (exit code {proc.exitcode}) without "
                "reporting an error — aborting cleanly (dead-shard "
                "detection)")
        waited += 0.5
        if watchdog_sec > 0 and waited >= watchdog_sec:
            raise ShardDeadError(
                f"shard {sid} alive but silent for {waited:.0f}s "
                "(--shard-watchdog-sec) — aborting with diagnostics")


class ProcsController:
    """Coordinator for ``--processes N``: spawns the shard engines, drives
    the window/exchange protocol, assembles checkpoints and the final state
    digest.  Mirrors the reference Master's role (core/master.c) across
    process boundaries."""

    def __init__(self, options, config):
        if options.processes < 2:
            raise ValueError("--processes needs N >= 2 (use the regular "
                             "engine for a single process)")
        self.options = options
        self.config = config
        self.n_shards = int(options.processes)
        self.rounds_executed = 0
        self.events_executed = 0
        self.final_state: Optional[Dict] = None
        self.digest: Optional[str] = None
        self.checkpoints: List[str] = []
        self.resume_verified = False
        from ..core.supervision import SupervisionStats
        self.supervision = SupervisionStats()
        # parent-side observability: the parent owns the merged trace file
        # (per-shard tracks) and the metrics summary; its own track is
        # labeled 'parent' on a pid past the shard range
        from ..obs import configure_observability
        self.tracer, self.metrics, self._metrics_writer = \
            configure_observability(options, shard_id=self.n_shards,
                                    label="parent")

    def _child_options(self, shard_id: int):
        import dataclasses
        opt = dataclasses.replace(self.options)
        opt.processes = 0
        opt.shard_id = shard_id
        opt.shard_count = self.n_shards
        # each shard drains its partition with the single serial worker; a
        # threaded scheduler inside a shard would strand events on worker>0
        # heaps that _shard_body's lone Worker(0) never pops
        opt.workers = 0
        # checkpoints are assembled by the parent from shard host-states;
        # per-shard snapshot files would be partial and misleading — and
        # the parent likewise owns resume verification over the ASSEMBLED
        # state, so shards never verify partial digests
        opt.checkpoint_interval_sec = 0
        opt.checkpoint_every_rounds = 0
        opt.resume_path = None
        # the parent seeds the data directory from the template ONCE before
        # spawning (N children racing shutil.copytree would collide)
        opt.data_template = None
        return opt

    def run(self) -> int:
        from ..core.checkpoint import assemble_state, digest_of_state

        log = get_logger()
        n = self.n_shards
        template = getattr(self.options, "data_template", None)
        if template and not os.path.exists(self.options.data_directory):
            import shutil
            shutil.copytree(template, self.options.data_directory)
        ctx = mp.get_context("spawn")
        conns = []
        procs = []
        t_start = _walltime.monotonic()
        for sid in range(n):
            parent_conn, child_conn = ctx.Pipe()
            p = ctx.Process(target=_shard_main,
                            args=(child_conn, self._child_options(sid),
                                  self.config),
                            daemon=True, name=f"shard-{sid}")
            p.start()
            child_conn.close()
            conns.append(parent_conn)
            procs.append(p)

        sid_of = {id(c): i for i, c in enumerate(conns)}
        shard_wd = float(getattr(self.options, "shard_watchdog_sec", 0) or 0)

        def recv(c):
            sid = sid_of[id(c)]
            try:
                return _recv_supervised(c, procs[sid], sid, shard_wd)
            except ShardDeadError:
                # the ledger records the detection (it aborts the run, but
                # distinguishes 'we caught a dead shard cleanly' from 'a
                # shard reported its own error'); the abort carries the
                # parent's recent timeline, like every other recovery seam
                self.supervision.shard_deaths_detected += 1
                self.supervision._dump_flight_recorder(
                    f"shard {sid} death detected")
                raise

        try:
            readies = [recv(c) for c in conns]
            lookahead = readies[0][1]
            end_time = readies[0][2]
            assert all(r[1] == lookahead and r[2] == end_time
                       for r in readies), "shards disagree on lookahead/end"
            mins = [recv(c) for c in conns]
            log.message(
                "procs",
                f"starting sharded simulation: {readies[0][3]} hosts over "
                f"{n} processes, lookahead={lookahead / 1e6:.3f} ms, "
                f"end={end_time / 1e9:.1f} s")

            writer = None
            if self.options.checkpoint_interval_sec > 0 \
                    or getattr(self.options,
                               "checkpoint_every_rounds", 0) > 0:
                from ..core.checkpoint import CheckpointWriter
                writer = CheckpointWriter(
                    self.options.checkpoint_interval_sec,
                    self.options.checkpoint_dir,
                    getattr(self.options, "checkpoint_every_rounds", 0))
            resume_snap = None
            if getattr(self.options, "resume_path", None):
                from ..core.checkpoint import find_last_good_snapshot
                resume_snap, resolved = find_last_good_snapshot(
                    self.options.resume_path)
                log.message(
                    "procs",
                    f"resuming from {resolved} "
                    f"(t={resume_snap['sim_time_ns'] / 1e9:.3f}s): "
                    "replaying to the snapshot boundary, digest-verified "
                    "there")
            self.metrics.source(
                "procs", lambda: {"procs.rounds": self.rounds_executed,
                                  "procs.shards": n})
            self.metrics.source(
                "supervision",
                lambda: {f"supervision.{k}": v
                         for k, v in self.supervision.summary().items()})
            last_ws = 0
            while True:
                nxt = min(m[1] for m in mins)
                if nxt >= end_time or nxt >= stime.SIM_TIME_MAX:
                    break
                ws, we = nxt, min(nxt + lookahead, end_time)
                with self.tracer.span("round", "procs", sim_ns=ws,
                                      args={"round": self.rounds_executed}):
                    for c in conns:
                        c.send(("run", ws, we))
                    outs = [recv(c)[1] for c in conns]
                    with self.tracer.span("exchange", "procs", sim_ns=ws):
                        for sid, c in enumerate(conns):
                            inbox = []
                            for o in outs:
                                inbox.extend(o[sid])
                            c.send(("in", inbox))
                        mins = [recv(c) for c in conns]
                last_ws = ws
                if resume_snap is not None \
                        and ws >= resume_snap["sim_time_ns"]:
                    self._verify_resume(conns, recv, ws, resume_snap,
                                        sum(m[2] for m in mins))
                    resume_snap = None
                # parent-assembled checkpoint at the same boundaries the
                # serial CheckpointWriter uses (shared due()/path_for
                # cadence, BEFORE the round counter increments — so
                # snapshot names and digests line up with a serial run)
                if writer is not None \
                        and writer.due(ws, self.rounds_executed):
                    with self.tracer.span("checkpoint.write", "procs",
                                          sim_ns=ws):
                        self._write_checkpoint(conns, recv, ws,
                                               sum(m[2] for m in mins),
                                               writer)
                self.rounds_executed += 1
                if self._metrics_writer is not None:
                    self._metrics_writer.maybe_write(
                        self.metrics, self.rounds_executed, ws)

            if resume_snap is not None:
                from ..core.checkpoint import warn_resume_unreached
                warn_resume_unreached(resume_snap, "procs")
            for c in conns:
                c.send(("stop",))
            finals = [recv(c)[1] for c in conns]
        except BaseException:
            # abnormal termination (shard death, protocol error): export
            # the parent's own flight-recorder events best-effort so the
            # abort keeps its timeline; shard rings die with their
            # processes — the log dump in the recv handler is their trace
            try:
                if self.tracer.enabled:
                    self.tracer.export()
                if self._metrics_writer is not None:
                    self._metrics_writer.write_summary(
                        self.metrics, self.rounds_executed, 0)
            except Exception:
                pass
            raise
        finally:
            # closing the pipes first unblocks any shard still parked in
            # conn.recv() (EOFError -> exit), so a mid-run failure tears
            # down immediately instead of waiting out join timeouts
            for c in conns:
                c.close()
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()

        host_states: Dict = {}
        for f in finals:
            host_states.update(f["host_states"])
        self.events_executed = sum(f["events"] for f in finals)
        assert all(f["rounds"] == self.rounds_executed for f in finals)
        state = assemble_state(last_ws, self.rounds_executed, host_states,
                               sum(f["pending"] for f in finals))
        self.final_state = state
        self.digest = digest_of_state(state)
        plugin_errors = sum(f["plugin_errors"] for f in finals)

        from ..core.counters import ObjectCounter
        totals = ObjectCounter()
        for f in finals:
            for k, v in f["counters_new"].items():
                totals.count_new(k, v)
            for k, v in f["counters_free"].items():
                totals.count_free(k, v)
        log.message(
            "procs",
            f"sharded simulation finished: {self.rounds_executed} rounds, "
            f"{self.events_executed} events, {n} processes, "
            f"{_walltime.monotonic() - t_start:.3f}s wall")
        if totals.leaks():
            log.message("procs", totals.report())
        self._obs_finish(finals, totals, last_ws)
        log.flush()
        return 1 if plugin_errors else 0

    def _obs_finish(self, finals, totals, last_ws: int) -> None:
        """Merge the shards' observability payloads: flight-recorder rings
        land on per-shard tracks in ONE trace file; metrics scrapes and the
        assembled leak report land in the parent's summary record."""
        if self.tracer.enabled:
            for f in finals:
                self.tracer.ingest(f.get("trace_events") or [],
                                   f.get("trace_epoch"))
                # the merged file's drop count must cover the SHARDS' ring
                # evictions, not just the parent's (no silent truncation)
                self.tracer.dropped += int(f.get("trace_dropped") or 0)
            path = self.tracer.export()
            if path:
                get_logger().message("procs", f"trace written: {path}")
        if self._metrics_writer is not None:
            for key, val in totals.summary().items():
                self.metrics.set_summary_info(key, val)
            self.metrics.set_summary_info(
                "shards", [f.get("metrics", {}) for f in finals])
            self.metrics.set_summary_info(
                "shard_supervision", [f.get("supervision", {})
                                      for f in finals])
            self._metrics_writer.write_summary(self.metrics,
                                               self.rounds_executed,
                                               last_ws)
            get_logger().message(
                "procs",
                f"metrics written: {self._metrics_writer.path} "
                f"({self._metrics_writer.records_written} records)")

    def _collect_assembled(self, conns, recv, ws: int, pending: int) -> Dict:
        """Gather every shard's host states and assemble the canonical
        digestible state (shared by checkpoint writes and resume verify)."""
        from ..core.checkpoint import assemble_state
        for c in conns:
            c.send(("collect",))
        host_states: Dict = {}
        for c in conns:
            host_states.update(recv(c)[1])
        return assemble_state(ws, self.rounds_executed, host_states, pending)

    def _verify_resume(self, conns, recv, ws: int, snap: Dict,
                       pending: int) -> None:
        """--resume under --processes: the shared boundary gate computed
        over the parent-assembled state."""
        from ..core.checkpoint import digest_of_state, verify_resume_boundary
        verify_resume_boundary(
            snap, ws,
            digest_of_state(self._collect_assembled(conns, recv, ws,
                                                    pending)),
            "procs")
        self.resume_verified = True
        self.supervision.resume_verified = True

    def _write_checkpoint(self, conns, recv, ws: int, pending: int,
                          writer) -> None:
        from ..core.checkpoint import save_state
        state = self._collect_assembled(conns, recv, ws, pending)
        os.makedirs(self.options.checkpoint_dir, exist_ok=True)
        path = writer.path_for(ws, self.rounds_executed)
        save_state(state, path, {
            "seed": self.options.seed,
            "scheduler_policy": self.options.scheduler_policy,
            # record the EFFECTIVE worker count: every shard runs with
            # workers=0 (see _child_options), whatever the user passed.
            "workers": 0,
            "stop_time_sec": self.options.stop_time_sec,
            "processes": self.n_shards,
        })
        writer.mark_written(ws, self.rounds_executed, path)
        self.checkpoints.append(path)
        get_logger().message("procs", f"checkpoint written: {path}")


def run_sharded(options, config) -> int:
    return ProcsController(options, config).run()
