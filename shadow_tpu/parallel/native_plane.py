"""Native (C) data plane: glue between the engine and _shadow_dataplane.so.

The C extension (native/dataplane.cc) owns the per-event hot path — TCP/UDP
protocol pipeline, interface token buckets + qdisc, router AQM, protocol
timers, and the inter-host hop — as a faithful C re-expression of this
repo's own Python modules, so a native run produces bit-identical state
digests to a Python-plane run (tests/test_native_dataplane.py pins this).

This module provides:

* :class:`NativeSocket` — the Python descriptor wrapper apps/epoll/process
  blocking interact with; every data operation is one C call.
* :class:`NativePlane` — engine-side owner: host registration, the status
  callback shim (fires Python descriptor listeners at the exact points the
  Python plane fires them, with the worker clock/active-host mirrored so
  wakeup events draw the same sequence ids), digest/tracker access.
* :class:`NativeGlobalPolicy` — the serial scheduler policy that merges the
  C event heap with the Python event queue into one total order: runs of
  consecutive C events execute in a single ``plane.run`` call (no Python
  dispatch per protocol event — the 3x+ events/s lever, VERDICT r4 next
  #1); a Python callback that schedules an earlier Python event shrinks the
  active run's horizon through ``lower_limit``, keeping the merge exact.

Reference analog: the reference runs this loop in C end-to-end
(worker.c:149-216, tcp.c:1121-1278, network_interface.c:421-579); here the
control plane stays Python and only the data plane is native.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import time as _walltime
from contextlib import contextmanager
from typing import List, Optional

from ..core import stime
from ..core.logger import get_logger
from ..core.scheduler import GlobalSinglePolicy
from ..core.worker import current_worker

CB_STATUS, CB_CHILD, CB_CLOSED, CB_EPOLL = 0, 1, 2, 3
K_TCP, K_UDP = 0, 1
_SENT_D = -(2 ** 31)
_SENT_Q = -(2 ** 63)

_MOD = None
_MOD_TRIED = False


def _load_module():
    """Import the extension from shadow_tpu/native/, building on demand.

    A committed-but-stale .so is rebuilt, not silently loaded: when
    native/dataplane.cc is newer than the extension, ``make`` runs (a no-op
    when the artifact is actually current) so a source edit can never be
    masked by an old binary.  If the rebuild fails while a stale .so
    exists, loading it would silently execute outdated code — refuse.

    ``SHADOW_SANITIZE=address,undefined`` (any -fsanitize= spec) switches
    to a sanitizer-instrumented twin, ``_shadow_dataplane_san.so``, built
    via ``make SANITIZE=...`` with ``-fno-omit-frame-pointer`` — a
    separate artifact so the hardened test run (tests/test_native_sanitize
    .py) never clobbers the production extension.  ``SHADOW_SANITIZE=
    thread`` selects the ThreadSanitizer twin ``_shadow_dataplane_tsan
    .so`` instead (its own artifact: TSan cannot link with ASan, and the
    matrix run builds both).  Loading a sanitized build into a stock
    interpreter additionally needs the runtime preloaded
    (LD_PRELOAD=libasan.so / libtsan.so); the sanitize tests arrange
    that."""
    global _MOD, _MOD_TRIED
    if _MOD_TRIED:
        return _MOD
    _MOD_TRIED = True
    san = os.environ.get("SHADOW_SANITIZE", "").strip()
    if san == "thread":
        artifact = "_shadow_dataplane_tsan.so"
    elif san:
        artifact = "_shadow_dataplane_san.so"
    else:
        artifact = "_shadow_dataplane.so"
    make_args = [f"SANITIZE={san}"] if san else []
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "native", artifact)
    src = os.path.join(here, "..", "native", "dataplane.cc")
    stale = (os.path.exists(path) and os.path.exists(src)
             and os.path.getmtime(src) > os.path.getmtime(path))
    if not os.path.exists(path) or stale:
        try:
            subprocess.run(["make", "-s"] + make_args +
                           [os.path.join("..", "shadow_tpu", "native",
                                         artifact)],
                           cwd=os.path.join(here, "..", "native"),
                           check=True, timeout=120)
        except Exception:
            if not os.path.exists(path):
                return None
            # staleness is LOUD but not fatal when the rebuild is
            # impossible (no toolchain / read-only checkout): git does not
            # preserve mtimes, so a fresh clone can look "stale" while the
            # committed extension is perfectly good — losing the native
            # plane over that would be worse than warning
            get_logger().warning(
                "native-plane",
                "_shadow_dataplane.so is older than dataplane.cc and the "
                "rebuild failed; loading the existing extension anyway "
                "(run `make -C native` to be sure it is current)")
    _MOD = _try_import(path)
    if _MOD is None:
        # a committed .so built on another box may not load here (e.g. a
        # newer libstdc++ than this container ships): force-rebuild from
        # source (make -B: mtimes say "current" but the binary is unusable)
        # and retry — same never-trust-a-stale-binary rule as above.  The
        # existing file is only replaced if the build succeeds, so a box
        # without a toolchain keeps its checkout intact.
        try:
            subprocess.run(["make", "-s", "-B"] + make_args +
                           [os.path.join("..", "shadow_tpu", "native",
                                         artifact)],
                           cwd=os.path.join(here, "..", "native"),
                           check=True, timeout=120)
        except Exception:
            return None
        _MOD = _try_import(path)
    return _MOD


def _try_import(path: str):
    try:
        spec = importlib.util.spec_from_file_location("_shadow_dataplane",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


def native_available() -> bool:
    return _load_module() is not None


def _cc_kinds() -> dict:
    """config-token -> C-plane CcKind id, from the authoritative spec so
    a spec-defined family (cubicx, bbrx) is selectable here with no hand
    edit.  Read as JSON — this module must not import ops.protocol_tables
    (jax import side effect; see tests/test_simgen.py)."""
    import json
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(pkg, "..", "spec", "protocol_spec.json")
    try:
        with open(path, encoding="utf-8") as f:
            return dict(json.load(f)["congestion"]["kinds"])
    except (OSError, KeyError, ValueError):
        return {"reno": 0, "aimd": 1, "cubic": 2, "cubicx": 3, "bbrx": 4}


_CC_KINDS = _cc_kinds()
_RQ_KINDS = {"codel": 0, "single": 1, "static": 2}


class NativeSocket:
    """Descriptor-API wrapper over one C-plane socket.

    Mirrors the surface SyscallAPI / epoll / the process block-dispatch use
    on TCPSocket/UDPSocket.  Status bits live in C; listener registration
    toggles the C-side ``watched`` flag so unwatched sockets never pay a
    callback."""

    __slots__ = ("plane", "sid", "handle", "host", "kind", "closed",
                 "_listeners", "_nonblock", "unix_path")

    def __init__(self, plane: "NativePlane", sid: int, handle: int, host,
                 kind: str):
        self.plane = plane
        self.sid = sid
        self.handle = handle
        self.host = host
        self.kind = kind
        self.closed = False
        self._listeners: List = []
        self._nonblock = False      # set by the shim's fcntl(O_NONBLOCK)
        self.unix_path = None

    # -- status / listeners (descriptor/base.py) --------------------------
    @property
    def status(self) -> int:
        return self.plane.c.status(self.sid)

    def has_status(self, bits: int) -> bool:
        return (self.plane.c.status(self.sid) & bits) == bits

    def add_listener(self, cb) -> None:
        if cb not in self._listeners:
            self._listeners.append(cb)
            if len(self._listeners) == 1:
                self.plane.c.watch(self.sid, 1)

    def remove_listener(self, cb) -> None:
        if cb in self._listeners:
            self._listeners.remove(cb)
            if not self._listeners:
                self.plane.c.watch(self.sid, 0)

    def _notify(self, changed: int) -> None:
        for cb in list(self._listeners):
            cb(self, changed)

    # -- naming -----------------------------------------------------------
    def _fields(self):
        return self.plane.c.sock_fields(self.sid)

    @property
    def bound_ip(self):
        return self._fields()[3]

    @property
    def bound_port(self):
        return self._fields()[4]

    @property
    def peer_ip(self):
        return self._fields()[5]

    @property
    def peer_port(self):
        return self._fields()[6]

    @property
    def state(self):
        return self._fields()[7]

    @property
    def is_bound(self) -> bool:
        return self._fields()[4] is not None

    @property
    def in_bytes(self) -> int:
        """FIONREAD surface (RPC shim ioctl): buffered input bytes.  The C
        plane tracks the same quantity the Python sockets do (UDP: queued
        datagram bytes incl. headers; TCP: 0 — tcp.py never maintains
        in_bytes, read_bytes is its measure), so parity holds exactly."""
        return self.plane.c.sock_state(self.sid)[6]

    # -- buffer sizes (RPC shim setsockopt/getsockopt) --------------------
    @property
    def send_buf_size(self) -> int:
        return self.plane.c.buf_sizes(self.sid)[0]

    @send_buf_size.setter
    def send_buf_size(self, v: int) -> None:
        self.plane.c.set_buf_size(self.sid, 0, int(v))

    @property
    def recv_buf_size(self) -> int:
        return self.plane.c.buf_sizes(self.sid)[1]

    @recv_buf_size.setter
    def recv_buf_size(self, v: int) -> None:
        self.plane.c.set_buf_size(self.sid, 1, int(v))

    # -- data/user API (SyscallAPI surface) -------------------------------
    def bind_native(self, ip: int, port: int, wildcard: bool) -> int:
        return self.plane.c.bind(self.sid, ip, port, 1 if wildcard else 0)

    def connect_to(self, dst_ip: int, dst_port: int) -> bool:
        return self.plane.c.connect(self.sid, dst_ip, dst_port,
                                    self.host.now)

    def take_socket_error(self) -> Optional[str]:
        return self.plane.c.take_error(self.sid)

    def listen(self, backlog: int = 128) -> None:
        self.plane.c.listen(self.sid, backlog)

    def accept_child(self) -> Optional["NativeSocket"]:
        r = self.plane.c.accept(self.sid, self.host.now)
        if r is None:
            return None
        cid = r[0]
        return self.plane.wrappers[cid]

    def send_user_data(self, data, dst_ip: int = 0, dst_port: int = 0) -> int:
        return self.plane.c.send(self.sid, data, dst_ip, dst_port,
                                 self.host.now)

    def receive_user_data(self, nbytes: int):
        return self.plane.c.recv(self.sid, nbytes, self.host.now)

    def peek_user_data(self, nbytes: int):
        return self.plane.c.peek(self.sid, nbytes)

    def shutdown(self, how: int) -> None:
        self.plane.c.shutdown(self.sid, how, self.host.now)

    def close(self) -> None:
        self.plane.c.close(self.sid, self.host.now)

    # -- digest (core/checkpoint.py _socket_state) ------------------------
    def digest_tuple(self) -> tuple:
        return self.plane.c.sock_state(self.sid)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"NativeSocket(fd={self.handle}, kind={self.kind})"


class ContinuationLedger:
    """Green-thread continuation ledger (ISSUE 12): the Python side of the
    batched continuation plane.

    Every suspended-plugin wake — sleep expiry, descriptor-block
    satisfaction/timeout, device-flow completion, coalesced process
    continue — lives as ONE C-heap event (``EV_PY_CONT``) carrying an index
    into this table, instead of a Python Task+Event through the scheduler
    queue.  The C round executor delivers *runs* of consecutive
    continuations through one ``py_exec_batch`` callback (``pop_cont``
    re-checks the total order every step, so the run is exactly as long as
    the per-event order allows); the per-event path (`cont_cb`, used by the
    demoted pop loop) delivers the same entries one callback each.  Wakes
    the C plane decides itself (socket-block waiters) arrive through
    ``take_fired`` and are applied before any resume, preserving the
    fire-before-continue ordering of the retired Python listener closures.

    Delivery order is the event total order: at equal times that is
    (host id, per-host sequence) — i.e. host-id order across processes and
    wake order within one, with each process's threads resumed in creation
    order by ``continue_`` — the deterministic drain the batched plane
    pins against the per-event path."""

    __slots__ = ("plane", "entries", "_free")

    def __init__(self, plane: "NativePlane"):
        self.plane = plane
        self.entries: List = []
        self._free: List[int] = []

    def add(self, entry) -> int:
        if self._free:
            cid = self._free.pop()
            self.entries[cid] = entry
        else:
            cid = len(self.entries)
            self.entries.append(entry)
        return cid

    def free(self, cid: int) -> None:
        self.entries[cid] = None
        self._free.append(cid)

    def apply_fired(self) -> None:
        """Apply every C-decided block wake (sock waiters satisfied at
        status-change time): set the woken thread's resume value + state.
        The owning process's coalesced continue event was pushed by C at
        fire time, so application is pure bookkeeping — it must happen
        before ANY continuation resumes (a timeout event ordered before
        the continue must observe the disarm)."""
        fired = self.plane.c.take_fired()
        if fired is None:
            return
        from ..process.process import BLOCKED, RUNNABLE
        for cid in fired:
            e = self.entries[cid]
            self.free(cid)
            if e is None:
                continue
            _kind, _host, _process, thread, box = e
            if not box[0]:
                continue
            box[0] = False
            if thread.state == BLOCKED:
                thread.wake_value = True
                thread.state = RUNNABLE
                thread._unblock_cb = None

    def deliver(self, cid: int, t: int) -> None:
        """Execute one continuation event: mirror the worker/host context
        exactly as ``Event.execute`` would, then resume.  Simulation-side
        exceptions are marked (plane.sim_exc) so the round executor's
        demotion guard re-raises them untouched."""
        self.apply_fired()
        e = self.entries[cid]
        kind = e[0]
        host = e[1]
        w = current_worker()
        if w is not None:
            w.now = t
            w.active_host = host
        host.now = t
        try:
            if kind == "continue":
                # persistent per-process entry (never freed); C cleared the
                # coalescing flag before delivery
                e[2]._continue_now()
                return
            self.free(cid)
            from ..process.process import BLOCKED, RUNNABLE
            if kind == "wake":
                # sleep expiry: the wake IS the continue
                _k, _h, process, thread = e
                if thread.state == BLOCKED:
                    thread.state = RUNNABLE
                    thread._unblock_cb = None
                process._continue_now()
            elif kind == "timeout":
                # block timeout: lost the race iff the box was disarmed
                _k, _h, process, thread, box, sid, block_cid, cancel = e
                if not box[0]:
                    return
                box[0] = False
                if sid is not None:
                    self.plane.c.sock_unblock(sid, block_cid)
                    self.free(block_cid)
                elif cancel is not None:
                    cancel()
                if thread.state == BLOCKED:
                    thread.wake_value = False
                    process._wake_thread(thread)
            elif kind == "device":
                # device-flow completion (device_plane._device_wake_task
                # semantics): resume the joining client directly
                _k, _h, dplane, circuit, waiter = e
                if waiter is None:
                    waiter = dplane._waiters.pop(circuit, None)
                if waiter is None or circuit in dplane._woken:
                    return
                dplane._woken.add(circuit)
                process, thread = waiter
                thread.wake_value = dplane._done[circuit]
                if thread.state == BLOCKED:
                    thread.state = RUNNABLE
                    thread._unblock_cb = None
                    process._continue_now()
            else:  # pragma: no cover - ledger corruption is a plane bug
                raise RuntimeError(f"unknown continuation kind {kind!r}")
        except BaseException as exc:
            self.plane.sim_exc = exc
            raise
        finally:
            if w is not None:
                w.active_host = None


class NativeGlobalPolicy(GlobalSinglePolicy):
    """Serial global policy merging the C event heap into the total order.

    Two dispatch paths over the SAME total order:

    * the **C round executor** (``run_window``, ISSUE 10): one extension
      call drives the whole window — C events execute natively, Python
      events through one ``py_exec`` callback each.  The default.
    * the **per-event pop loop** (``pop``): the pre-executor merge, kept
      as the permanent demotion target — a round-executor failure finishes
      its window here (events are atomic and both paths execute the
      identical order, so the hand-off is exact) and stays here.
    """

    def __init__(self, plane: "NativePlane"):
        super().__init__()
        self._plane = plane
        self.serial = True
        # native-plane call spans (ISSUE 3): bound ONCE at construction —
        # the traced wrapper only exists when the run is traced, so the
        # untraced hot path pays nothing (c.run is called per pop-loop
        # leg, far too hot for a per-call enabled check)
        from ..obs.trace import get_tracer
        self._tracer = get_tracer()
        self._run_c = self._run_c_traced if self._tracer.enabled \
            else plane.c.run
        # round-executor state (ISSUE 10): window count for metrics, the
        # demotion latch, and the deterministic fault countdown
        # (--fault-inject native-round:N)
        self.round_windows = 0
        self.round_demoted = False
        # recovery-ladder re-promotion (ISSUE 17): after --repromote-after
        # clean per-event windows the executor is re-attempted ONCE; a
        # second failure re-demotes permanently (the one-shot latch)
        self._repromote_after = int(
            getattr(plane.engine.options, "repromote_after", 0) or 0)
        self._probation_clean = 0
        self.round_repromoted = False
        self._py_exc = None
        from ..core.supervision import parse_fault_inject
        fault = parse_fault_inject(
            getattr(plane.engine.options, "fault_inject", "") or "")
        self._fault_countdown = fault["window"] \
            if fault and fault["kind"] == "native-round" else 0
        # --fault-inject continuation-batch:N — the Nth py_exec_batch call
        # raises, drilling demotion to the per-event pop loop (where
        # continuations deliver one cont_cb each)
        self._cont_fault_countdown = fault["batch"] \
            if fault and fault["kind"] == "continuation-batch" else 0

    def _run_c_traced(self, t, d, s, q) -> None:
        with self._tracer.span("native.run", "native", sim_ns=int(t)):
            self._plane.c.run(t, d, s, q)

    def _batch_drilled(self) -> int:
        """drain_cont_batch wrapped in the continuation-batch:N countdown
        (--fault-inject): the Nth batch delivery raises, and the window
        finishes on the per-event pop loop — the drilled demotion target."""
        self._cont_fault_countdown -= 1
        if self._cont_fault_countdown == 0:
            raise RuntimeError("fault injection: continuation batch")
        return self._plane.drain_cont_batch()

    def run_window(self, worker, window_end) -> bool:
        """Execute the whole window via the C round executor.  Returns
        False when demoted (caller falls back to the per-event loop, which
        also FINISHES a window the executor failed partway through)."""
        if worker.id != 0:
            return False
        if self.round_demoted:
            # probation clock (ISSUE 17): each window the per-event loop
            # completes cleanly counts; at the threshold the executor is
            # re-attempted once — the hand-off is exact in both
            # directions (both paths execute the identical total order),
            # so the climb back is as safe as the demotion was
            if self._repromote_after > 0 and not self.round_repromoted \
                    and self._probation_clean >= self._repromote_after:
                self.round_demoted = False
                self.round_repromoted = True
                self._plane.engine.supervision.count_repromotion(
                    "native round executor", self._probation_clean)
            else:
                self._probation_clean += 1
                return False
        q = self.queue
        we = int(window_end)
        counters = worker.counters
        self._py_exc = None

        def py_exec():
            # invoked by C exactly when the Python top precedes the C heap
            # top: pop THE earliest Python event, execute it, and return
            # the queue's new top key so the C-side mirror stays exact
            ev = q.pop_before(we)
            if ev is None:      # pragma: no cover - mirror guarantees one
                return None
            worker.now = ev.time
            try:
                if ev.execute(worker):
                    worker.last_event_time = ev.time
                    counters.count_free("event")
            except BaseException as e:
                # mark app/event errors so the guard below re-raises them
                # instead of demoting the executor over someone else's bug
                self._py_exc = e
                raise
            return q.peek_key()

        batch = self._batch_drilled if self._cont_fault_countdown > 0 \
            else self._plane.drain_cont_batch
        try:
            if self._fault_countdown > 0:
                self._fault_countdown -= 1
                if self._fault_countdown == 0:
                    raise RuntimeError(
                        "fault injection: native round executor")
            if self._tracer.enabled:
                with self._tracer.span("native.round", "native",
                                       sim_ns=we):
                    self._plane.c.run_window(we, q.peek_key(), py_exec,
                                             batch)
            else:
                self._plane.c.run_window(we, q.peek_key(), py_exec, batch)
        except BaseException as e:
            if e is self._py_exc or e is self._plane.sim_exc \
                    or not isinstance(e, Exception):
                # simulated-app failures propagate exactly as on the
                # per-event path, and KeyboardInterrupt/SystemExit are
                # never the executor's fault — demoting would swallow a
                # Ctrl-C and run the simulation to completion (the device
                # dispatch guard catches Exception only for the same
                # reason)
                raise
            self.round_demoted = True
            self._plane.engine.supervision.count_native_round_demotion(
                repr(e))
            return False        # per-event loop completes this window
        self.round_windows += 1
        return True

    def push(self, event, worker_id: int, barrier: int) -> None:
        if event.dst_host is not event.src_host and event.time < barrier:
            event.time = barrier
        self.queue.push(event)
        # a callback-scheduled Python event may precede the C heap's next
        # event: shrink the active C run's horizon (no-op outside run)
        self._plane.c.lower_limit(*event.order_key())

    def pop(self, worker_id: int, window_end: int):
        if worker_id != 0:
            return None
        c = self._plane.c
        q = self.queue
        while True:
            pk = q.peek_key()
            ck = c.next_key()
            py_ok = pk is not None and pk[0] < window_end
            c_ok = ck is not None and ck[0] < window_end
            if c_ok and (not py_ok or ck < pk):
                # execute the C run up to the next Python event (or the
                # window end); callbacks may add Python events and shrink
                # the horizon, so re-evaluate afterwards
                if py_ok:
                    self._run_c(pk[0], pk[1], pk[2], pk[3])
                else:
                    # int(): window_end inherits float-ness from fractional
                    # <shadow stoptime> configs
                    self._run_c(int(window_end), _SENT_D, _SENT_D, _SENT_Q)
                continue
            if not py_ok:
                return None
            return q.pop_before(window_end)

    def next_time(self) -> int:
        t = super().next_time()
        ck = self._plane.c.next_key()
        if ck is not None and ck[0] < t:
            t = ck[0]
        return t

    def pending_count(self) -> int:
        return len(self.queue) + self._plane.c.pending()


class NativePlane:
    """Engine-side owner of the C data plane."""

    def __init__(self, engine):
        mod = _load_module()
        if mod is None:
            raise RuntimeError("native dataplane extension unavailable "
                               "(make -C native)")
        self.engine = engine
        self.c = mod.Plane()
        self.wrappers: List[Optional[NativeSocket]] = []
        self._synced = {}           # hid -> last-synced C tracker tuple
        self._bulk_rows = None      # hid -> row, inside bulk_sync() only
        self.sim_exc = None         # last simulation-code exception (the
                                    # round-executor guard re-raises these)
        # batched continuation plane (ISSUE 12)
        self.ledger = ContinuationLedger(self)
        self.eps: List = []         # epoll token -> Epoll (readiness cache)
        self.py_exec_batch_calls = 0
        self.continuations_fused = 0    # delivered through py_exec_batch
        self.continuations_single = 0   # delivered per-event (demoted path)
        topo = engine.topology
        opts = engine.options
        lat = topo.latency_ns
        rel = topo.reliability
        cnt = topo.path_packet_counts
        self.c.configure(
            lat.ctypes.data, rel.ctypes.data, cnt.ctypes.data,
            int(lat.shape[0]), int(engine._drop_key),
            int(engine.bootstrap_end), int(engine.end_time),
            _CC_KINDS[getattr(opts, "tcp_congestion_control", "reno")],
            int(getattr(opts, "tcp_ssthresh", 0)),
            int(getattr(opts, "tcp_windows", 10)),
            lat, rel, cnt)
        self.c.set_callback(self._callback)
        self.c.set_cont_callback(self._deliver_cont)
        if engine.shard_count > 1:
            # --processes: finished cross-shard hops land in the engine's
            # outboxes exactly where the Python plane appends them
            # (core/worker.py:129-141); the unused slot keeps the C
            # signature uniform
            def _xshard(t, dst_hid, src_hid, _unused, seq, wire,
                        _eng=engine):
                try:
                    dst = _eng.hosts[dst_hid]
                    _eng.shard_outboxes[_eng.shard_of(dst)].append(
                        (t, dst_hid, src_hid, seq, wire))
                except BaseException as e:
                    # simulation-side failure: the round executor's guard
                    # must PROPAGATE it (same marking as _callback), not
                    # demote-and-continue past a half-executed event
                    self.sim_exc = e
                    raise
            self.c.set_xshard_callback(_xshard)
        self._attach_hosts()

    # -- host registration + counter proxying -----------------------------
    def _attach_hosts(self) -> None:
        from ..routing.address import LOCALHOST_IP
        eng = self.engine
        for hid in sorted(eng.hosts):
            host = eng.hosts[hid]
            p = host.params
            self.c.add_host(
                int(hid), int(host.ip), int(LOCALHOST_IP),
                int(host.topo_row), int(p.bw_down_kibps), int(p.bw_up_kibps),
                1 if p.qdisc == "rr" else 0, _RQ_KINDS[p.router_queue],
                int(p.recv_buf_size), int(p.send_buf_size),
                1 if p.autotune_recv else 0, 1 if p.autotune_send else 0,
                int(host._next_handle), int(host._next_port),
                int(host._event_seq), int(host._packet_counter),
                int(host._packet_priority),
                1 if eng.owns_host(host) else 0,
                _CC_KINDS[p.tcp_cc] if getattr(p, "tcp_cc", None)
                else -1)
            # the per-host deterministic counters move into C so both
            # planes draw from the same sequence space, interleaved exactly
            host.native_plane = self
            host.next_event_sequence = \
                (lambda c=self.c, h=hid: lambda: c.next_seq(h))()
            host.allocate_handle = \
                (lambda c=self.c, h=hid: lambda: c.alloc_handle(h))()
            host.next_packet_uid = \
                (lambda c=self.c, h=hid: lambda: c.next_packet_uid(h))()
            host.next_packet_priority = \
                (lambda c=self.c, h=hid: lambda: c.next_packet_priority(h))()
            host.tracker._native = (self, hid)

    # -- socket creation ---------------------------------------------------
    def create_socket(self, host, kind: str) -> NativeSocket:
        sid, handle = self.c.socket(host.id, K_TCP if kind == "tcp"
                                    else K_UDP)
        w = NativeSocket(self, sid, handle, host, kind)
        while len(self.wrappers) <= sid:
            self.wrappers.append(None)
        self.wrappers[sid] = w
        host.register_descriptor(w)
        return w

    # -- continuation plane (ISSUE 12) -------------------------------------
    def token_for(self, process) -> int:
        """The process's C-side coalescing token (lazily registered with a
        persistent 'continue' ledger entry)."""
        tok = process._cont_token
        if tok is None:
            host = process.host
            cid = self.ledger.add(("continue", host, process))
            tok = self.c.register_proc(host.id, cid)
            process._cont_token = tok
        return tok

    def sched_continue(self, process, now: int) -> None:
        """Coalesced process-continue: ONE EV_PY_CONT in flight per process
        (the C-side mirror of Process._continue_scheduled, shared with the
        C-decided socket-block wakes)."""
        self.c.sched_continue(now, self.token_for(process))

    def push_sleep(self, process, thread, now: int, delay_ns: int) -> None:
        host = process.host
        cid = self.ledger.add(("wake", host, process, thread))
        if self.c.push_cont(now, host.id, delay_ns, cid) is None:
            self.ledger.free(cid)    # past end time: never wakes (parity
                                     # with schedule_task's decline)

    def block_native(self, process, thread, desc, bits: int,
                     timeout_ns: int, now: int) -> bool:
        """Register a C-side socket-block waiter: the wake condition
        (status & (bits|S_CLOSED)) is decided IN C at status-change time,
        with no per-change Python callback.  Returns False when the
        condition already holds (caller resumes synchronously)."""
        host = process.host
        box = [True]
        cid = self.ledger.add(("block", host, process, thread, box))
        tok = self.token_for(process)
        if not self.c.sock_block(desc.sid, bits, cid, tok):
            self.ledger.free(cid)
            return False
        if timeout_ns >= 0:
            tid = self.ledger.add(("timeout", host, process, thread, box,
                                   desc.sid, cid, None))
            if self.c.push_cont(now, host.id, timeout_ns, tid) is None:
                self.ledger.free(tid)
        return True

    def push_block_timeout(self, process, thread, box, now: int,
                           timeout_ns: int, cancel) -> None:
        """Timeout leg for a block on a PYTHON descriptor under the native
        plane: the wake detection stays a Python listener, but the timeout
        event lives in the C heap like every other continuation."""
        host = process.host
        cid = self.ledger.add(("timeout", host, process, thread, box,
                               None, None, cancel))
        if self.c.push_cont(now, host.id, timeout_ns, cid) is None:
            self.ledger.free(cid)

    def push_device_wakes(self, items) -> None:
        """Land a collect's completion wakes in ONE extension call:
        ``items`` = [(when, host, dplane, circuit, waiter), ...] in the
        per-event fold's order, so the C-side per-host sequence claims are
        identical to the retired push_batch Event chain."""
        batch = []
        for when, host, dplane, circuit, waiter in items:
            cid = self.ledger.add(("device", host, dplane, circuit, waiter))
            batch.append((when, host.id, 0, cid))
        self.c.push_cont_batch(batch)

    def ep_token(self, ep) -> int:
        tok = getattr(ep, "_native_tok", None)
        if tok is None:
            tok = len(self.eps)
            self.eps.append(ep)
            ep._native_tok = tok
        return tok

    def _deliver_cont(self, cid: int, t: int) -> None:
        """Per-event continuation delivery (the demoted pop loop / a lone
        continuation executed by plane_exec)."""
        self.continuations_single += 1
        t0 = _walltime.perf_counter_ns()
        try:
            self.ledger.deliver(cid, t)
        finally:
            self.engine.add_plugin_exec_ns(
                _walltime.perf_counter_ns() - t0)

    def drain_cont_batch(self) -> int:
        """The py_exec_batch callback: drain the maximal run of consecutive
        continuations in one C->Python round trip.  ``pop_cont`` re-checks
        the merged total order each step (window horizon, the Python-top
        mirror, AND any C event a resume just scheduled), so the batch ends
        exactly where per-event dispatch would interleave something else.
        Plugin wall is attributed once per batch, not per resume."""
        n = 0
        pop = self.c.pop_cont
        deliver = self.ledger.deliver
        t0 = _walltime.perf_counter_ns()
        try:
            e = pop()
            while e is not None:
                n += 1
                deliver(e[0], e[1])
                e = pop()
        finally:
            self.py_exec_batch_calls += 1
            self.continuations_fused += n
            self.engine.add_plugin_exec_ns(
                _walltime.perf_counter_ns() - t0)
        return n

    # -- callback shim -----------------------------------------------------
    def _callback(self, kind: int, hid: int, t: int, a: int, b: int) -> None:
        """Invoked by C at listener/lifecycle points.  Mirrors the clock and
        active host the way event.execute does, so any task a listener
        schedules gets the same (time, dst, src, seq) tuple as on the
        Python plane."""
        eng = self.engine
        host = eng.hosts[hid]
        w = current_worker()
        prev = (w.now, w.active_host, host.now) if w is not None else None
        if w is not None:
            w.now = t
            w.active_host = host
        host.now = t
        try:
            if kind == CB_STATUS:
                wrap = self.wrappers[a]
                if wrap is not None:
                    wrap._notify(b)
            elif kind == CB_CHILD:
                # a LISTEN socket spawned a child (C allocated its handle):
                # register the wrapper so accept()/digests see it
                child = NativeSocket(self, a, b, host, "tcp")
                while len(self.wrappers) <= a:
                    self.wrappers.append(None)
                self.wrappers[a] = child
                host.register_descriptor(child)
            elif kind == CB_CLOSED:
                wrap = self.wrappers[a]
                if wrap is not None:
                    wrap.closed = True
                    host.descriptor_table_remove(wrap.handle)
            elif kind == CB_EPOLL:
                # C readiness cache delivery: b = (ep_tok << 16) | revents,
                # fired only when the epoll-visible outcome changed
                ep = self.eps[b >> 16]
                wrap = self.wrappers[a]
                if wrap is not None:
                    ep._apply_native_revents(wrap.handle, b & 0xFFFF)
        except BaseException as e:
            # mark simulation-side failures so the round executor's guard
            # PROPAGATES them (a listener/app bug is not the executor's
            # fault and must surface exactly as on the per-event path)
            self.sim_exc = e
            raise
        finally:
            if prev is not None:
                w.now, w.active_host, host.now = prev

    # -- engine integration ------------------------------------------------
    def set_window(self, window_end: int) -> None:
        # window_end inherits float-ness from a fractional <shadow stoptime>
        self.c.set_window(int(window_end))

    def counters(self):
        """(events_scheduled, events_executed, packet_drops, last_time)."""
        return self.c.counters()

    @contextmanager
    def bulk_sync(self):
        """Snapshot EVERY host's C tracker counters in one extension call;
        ``sync_tracker`` calls inside the block read rows from the
        snapshot instead of paying a per-host C round-trip (the ISSUE 7
        vectorized control-plane cut: a 10k-host end-of-run sweep is one
        C call + one numpy reshape, not 10k `c.tracker()` trips)."""
        import numpy as np
        rows = np.frombuffer(self.c.tracker_all(),
                             dtype=np.int64).reshape(-1, 34)
        self._bulk_rows = {int(r[0]): r for r in rows}
        try:
            yield
        finally:
            self._bulk_rows = None

    def sync_tracker(self, hid: int, tracker) -> None:
        """Fold the C plane's counter DELTAS since the last sync into the
        Python tracker.  Additive, not overwriting: other engine components
        (the device-resident traffic plane's per-node byte feed) also add
        into the same Python counters, exactly as on the Python plane."""
        if self._bulk_rows is not None:
            v = tuple(int(x) for x in self._bulk_rows[hid][1:])
        else:
            v = self.c.tracker(hid)
        prev = self._synced.get(hid)
        if prev == v:
            return                  # quiet host: nothing moved since
        self._synced[hid] = v
        names = ("packets_total", "bytes_total", "packets_control",
                 "bytes_control", "packets_data", "bytes_data",
                 "packets_retrans", "bytes_retrans")
        k = 0
        for ctr in (tracker.in_local, tracker.in_remote, tracker.out_local,
                    tracker.out_remote):
            for n in names:
                delta = v[k] - (prev[k] if prev else 0)
                if delta:
                    setattr(ctr, n, getattr(ctr, n) + delta)
                k += 1
        drop_delta = v[k] - (prev[k] if prev else 0)
        if drop_delta:
            tracker.drops += drop_delta

    def iface_digest(self, hid: int) -> dict:
        """{ip: (send_remaining, recv_remaining)} for checkpoint.

        The C plane models exactly two interfaces per host (lo + eth, the
        reference's layout); if the Python host ever grows more, this digest
        would silently omit them and diverge from the Python plane's — fail
        loudly instead."""
        from ..routing.address import LOCALHOST_IP
        host = self.engine.hosts[hid]
        if len(host.interfaces) != 2:
            raise RuntimeError(
                f"native plane: host {host.name!r} has "
                f"{len(host.interfaces)} interfaces; the C plane digests "
                "exactly two (lo + eth) — a topology change here needs a "
                "matching dataplane.cc iface_state extension")
        lo_s, lo_r, eth_s, eth_r = self.c.iface_state(hid)
        return {LOCALHOST_IP: (lo_s, lo_r), host.ip: (eth_s, eth_r)}


def eligible(engine, log_reason: bool = False) -> Optional[str]:
    """None when the native plane can engage; otherwise the blocking reason
    (auto mode logs and falls back; --dataplane=native raises it)."""
    opts = engine.options
    if opts.workers != 0:
        return "threaded run (native plane is serial-only)"
    table = getattr(engine, "host_table", None)
    if table is not None and table.unmaterialized_count() > 0:
        # the C plane registers every host at attach; lazily-materialized
        # table rows would be invisible to it.  Digest parity Python-vs-C
        # is pinned, so the fallback costs speed only.
        return "host table active (lazy hosts; C plane needs all hosts " \
               "at attach)"
    if engine.scheduler.policy_name != "global":
        return (f"policy {engine.scheduler.policy_name!r} "
                "(native plane backs the serial global policy)")
    for host in engine.hosts.values():
        if host.params.log_pcap:
            return "pcap capture enabled"
        if host.cpu is not None and host.cpu.enabled:
            return "host CPU delay model enabled"
    log = get_logger()
    if log.would_log("debug"):
        return "debug logging (per-packet audit trails are Python-plane)"
    if not native_available():
        return "extension not built (make -C native)"
    return None


def attach(engine) -> Optional[NativePlane]:
    """Build the plane, swap in the merging policy, and mark the engine.
    Returns the plane (None when ineligible in auto mode)."""
    mode = getattr(engine.options, "dataplane", "auto")
    if mode == "python":
        return None
    reason = eligible(engine)
    if reason is not None:
        if mode == "native":
            raise RuntimeError(f"--dataplane=native unavailable: {reason}")
        get_logger().message("engine",
                             f"native dataplane off: {reason}")
        return None
    plane = NativePlane(engine)
    policy = NativeGlobalPolicy(plane)
    policy.hosts = engine.scheduler.policy.hosts
    engine.scheduler.policy = policy
    engine.native_plane = plane
    get_logger().message(
        "engine",
        f"native C dataplane engaged: {len(engine.hosts)} hosts "
        "(TCP/UDP pipeline + interface + router + hop in C)")
    return plane
