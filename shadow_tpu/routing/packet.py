"""Packet and Payload.

Capability of the reference's Packet/Payload (routing/packet.c, payload.c):

* protocol header union (local pipe / UDP / TCP) — here small per-protocol
  header objects;
* payload bytes shared on copy (payload.c refcount; Python bytes are
  immutable so sharing is free);
* per-packet priority used by the FIFO qdisc tiebreak (packet.c:52-57,
  assigned from the host's monotonically increasing counter);
* a delivery-status audit trail (packet_addDeliveryStatus, 20+ PDS_* flags)
  used for debugging and by tests to assert a packet's life cycle;
* a globally unique ``uid`` that keys the order-independent reliability draw
  (replaces the reference's execution-order-coupled rand_r draw).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core import defs

# Delivery-status flags (subset of the reference's PDS_* covering every
# transition our pipeline makes; extend freely).
STATUSES = (
    "CREATED", "SND_CREATED", "SND_TCP_ENQUEUE_THROTTLED", "SND_TCP_ENQUEUE_RETRANSMIT",
    "SND_SOCKET_BUFFERED", "SND_INTERFACE_SENT", "INET_SENT", "INET_DROPPED",
    "ROUTER_ENQUEUED", "ROUTER_DROPPED", "ROUTER_DEQUEUED",
    "RCV_INTERFACE_BUFFERED", "RCV_INTERFACE_RECEIVED", "RCV_INTERFACE_DROPPED",
    "RCV_SOCKET_PROCESSED", "RCV_SOCKET_DROPPED", "RCV_SOCKET_BUFFERED",
    "RCV_SOCKET_DELIVERED", "DESTROYED",
)


class UDPHeader:
    __slots__ = ("src_ip", "src_port", "dst_ip", "dst_port")

    def __init__(self, src_ip, src_port, dst_ip, dst_port):
        self.src_ip = src_ip
        self.src_port = src_port
        self.dst_ip = dst_ip
        self.dst_port = dst_port


class TCPHeader:
    __slots__ = ("src_ip", "src_port", "dst_ip", "dst_port", "flags",
                 "sequence", "acknowledgment", "window", "sel_acks", "timestamp",
                 "timestamp_echo")

    def __init__(self, src_ip, src_port, dst_ip, dst_port, flags=0,
                 sequence=0, acknowledgment=0, window=0,
                 sel_acks: Optional[List[Tuple[int, int]]] = None,
                 timestamp: int = 0, timestamp_echo: int = 0):
        self.src_ip = src_ip
        self.src_port = src_port
        self.dst_ip = dst_ip
        self.dst_port = dst_port
        self.flags = flags
        self.sequence = sequence
        self.acknowledgment = acknowledgment
        self.window = window
        self.sel_acks = sel_acks or []
        self.timestamp = timestamp
        self.timestamp_echo = timestamp_echo


# >>> simgen:begin region=tcp-flags spec=293c930bb679 body=5c389b66fae3
# TCP header flag bits (reference tcp.c enum ProtocolTCPFlags).
TCP_NONE = 0
TCP_RST = 2
TCP_SYN = 4
TCP_ACK = 8
TCP_FIN = 16
# <<< simgen:end region=tcp-flags


# Full per-packet delivery-status audit trails (the reference's PDS_* flags,
# packet.c:59-60) cost real time at millions of packets; they are recorded
# only when the log level includes debug.  The retransmit marker the Tracker
# needs survives as a dedicated flag either way.
AUDIT_STATUSES = False


class Packet:
    """A simulated network packet."""

    __slots__ = ("uid", "header", "payload", "priority", "statuses",
                 "header_size", "arrival_time", "total_size", "retransmit",
                 "src_ip", "dst_ip", "src_port", "dst_port", "payload_size")

    _uid_counter = 0

    def __init__(self, uid: int, header, payload: bytes, priority: int,
                 header_size: int):
        self.uid = uid                  # global, keys the reliability draw
        self.header = header
        self.payload = payload or b""
        self.priority = priority        # FIFO qdisc tiebreak
        self.header_size = header_size
        self.statuses: List[str] = ["CREATED"] if AUDIT_STATUSES else []
        self.arrival_time = -1
        # bytes charged to token buckets; header and payload are immutable,
        # so sizes and addresses are flattened to plain attributes (these are
        # the hottest reads in the whole pipeline)
        self.payload_size = len(self.payload)
        self.total_size = header_size + self.payload_size
        self.retransmit = False
        self.src_ip = header.src_ip
        self.dst_ip = header.dst_ip
        self.src_port = header.src_port
        self.dst_port = header.dst_port

    # -- constructors ------------------------------------------------------
    @classmethod
    def new_udp(cls, uid: int, priority: int, src_ip, src_port, dst_ip,
                dst_port, payload: bytes) -> "Packet":
        assert len(payload) <= defs.CONFIG_DATAGRAM_MAX_SIZE
        return cls(uid, UDPHeader(src_ip, src_port, dst_ip, dst_port), payload,
                   priority, defs.CONFIG_HEADER_SIZE_UDPIPETH)

    @classmethod
    def new_tcp(cls, uid: int, priority: int, header: TCPHeader,
                payload: bytes) -> "Packet":
        return cls(uid, header, payload, priority, defs.CONFIG_HEADER_SIZE_TCPIPETH)

    def copy(self, new_uid: int) -> "Packet":
        """Header deep copy, payload shared (reference packet_copy :100).
        Retransmitted TCP packets get fresh uids so their drop draws are
        independent, like fresh rand draws in the reference."""
        import copy as _copy
        p = Packet(new_uid, _copy.copy(self.header), self.payload,
                   self.priority, self.header_size)
        p.statuses = list(self.statuses)
        p.retransmit = self.retransmit
        return p

    # -- cross-process wire format (parallel/procs.py) ---------------------
    def to_wire(self) -> tuple:
        """Flatten to plain ints/bytes for shipping to another shard engine
        (the procs scale-out exchanges packets at round barriers the way the
        reference's master/slave split would over MPI).  Exact round-trip:
        ``from_wire(p.to_wire())`` reconstructs every field the receiving
        host's protocol stack and the state digest can observe."""
        h = self.header
        if isinstance(h, TCPHeader):
            hdr = ("t", h.src_ip, h.src_port, h.dst_ip, h.dst_port, h.flags,
                   h.sequence, h.acknowledgment, h.window,
                   tuple(h.sel_acks), h.timestamp, h.timestamp_echo)
        else:
            hdr = ("u", h.src_ip, h.src_port, h.dst_ip, h.dst_port)
        return (self.uid, self.priority, hdr, self.payload, self.retransmit,
                tuple(self.statuses))

    @classmethod
    def from_wire(cls, wire: tuple) -> "Packet":
        uid, priority, hdr, payload, retransmit, statuses = wire
        if hdr[0] == "t":
            header = TCPHeader(hdr[1], hdr[2], hdr[3], hdr[4], hdr[5], hdr[6],
                               hdr[7], hdr[8], [tuple(b) for b in hdr[9]],
                               hdr[10], hdr[11])
            hsize = defs.CONFIG_HEADER_SIZE_TCPIPETH
        else:
            header = UDPHeader(hdr[1], hdr[2], hdr[3], hdr[4])
            hsize = defs.CONFIG_HEADER_SIZE_UDPIPETH
        p = cls(uid, header, payload, priority, hsize)
        p.retransmit = retransmit
        p.statuses = list(statuses)
        return p

    # -- accessors ---------------------------------------------------------
    def is_tcp(self) -> bool:
        return isinstance(self.header, TCPHeader)

    def add_status(self, status: str) -> None:
        if status == "SND_TCP_ENQUEUE_RETRANSMIT":
            self.retransmit = True
        if AUDIT_STATUSES:
            self.statuses.append(status)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        kind = "tcp" if self.is_tcp() else "udp"
        return (f"Packet#{self.uid}({kind} {self.src_ip}:{self.src_port}->"
                f"{self.dst_ip}:{self.dst_port} len={self.payload_size})")
