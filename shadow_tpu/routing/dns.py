"""DNS: the global name <-> IP registry.

Capability of the reference's DNS (routing/dns.c): assigns unique IPs from a
counter while skipping restricted CIDR ranges (dns.c:30-66), registers
(name, ip) pairs, resolves both directions; backs getaddrinfo emulation.
Assignment order is deterministic (registration order), which matters for the
determinism gate.
"""

from __future__ import annotations

from typing import Dict, Optional

from .address import Address, ip_to_int, int_to_ip


def _in_range(ip: int, base: str, prefix: int) -> bool:
    b = ip_to_int(base)
    mask = ((1 << prefix) - 1) << (32 - prefix)
    return (ip & mask) == (b & mask)


def _is_restricted(ip: int) -> bool:
    # Same ranges the reference refuses to hand out (dns.c:30-66):
    # loopback, link-local, multicast/reserved, zero-net, broadcast.
    return (
        _in_range(ip, "127.0.0.0", 8)
        or _in_range(ip, "0.0.0.0", 8)
        or _in_range(ip, "169.254.0.0", 16)
        or _in_range(ip, "224.0.0.0", 4)
        or _in_range(ip, "240.0.0.0", 4)
        or ip == ip_to_int("255.255.255.255")
    )


class DNS:
    def __init__(self):
        self._ip_counter = ip_to_int("11.0.0.1")
        self._by_name: Dict[str, Address] = {}
        self._by_ip: Dict[int, Address] = {}

    def unique_ip(self) -> int:
        ip = self._ip_counter
        while _is_restricted(ip) or ip in self._by_ip:
            ip += 1
        self._ip_counter = ip + 1
        return ip

    def register(self, host_id: int, name: str, requested_ip: Optional[int] = None,
                 mac: int = 0) -> Address:
        if name in self._by_name:
            raise ValueError(f"hostname {name!r} is already registered")
        if requested_ip is not None and not _is_restricted(requested_ip) \
                and requested_ip not in self._by_ip:
            ip = requested_ip
        else:
            ip = self.unique_ip()
        addr = Address(host_id, ip, name, mac=mac)
        self._by_name[name] = addr
        self._by_ip[ip] = addr
        return addr

    def deregister(self, addr: Address) -> None:
        self._by_name.pop(addr.name, None)
        self._by_ip.pop(addr.ip, None)

    def resolve_name(self, name: str) -> Optional[Address]:
        return self._by_name.get(name)

    def resolve_ip(self, ip: int) -> Optional[Address]:
        return self._by_ip.get(ip)

    def __len__(self) -> int:
        return len(self._by_ip)
