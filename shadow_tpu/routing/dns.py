"""DNS: the global name <-> IP registry.

Capability of the reference's DNS (routing/dns.c): assigns unique IPs from a
counter while skipping restricted CIDR ranges (dns.c:30-66), registers
(name, ip) pairs, resolves both directions; backs getaddrinfo emulation.
Assignment order is deterministic (registration order), which matters for the
determinism gate.
"""

from __future__ import annotations

from typing import Dict, Optional

from .address import Address, ip_to_int, int_to_ip


def _in_range(ip: int, base: str, prefix: int) -> bool:
    b = ip_to_int(base)
    mask = ((1 << prefix) - 1) << (32 - prefix)
    return (ip & mask) == (b & mask)


# The ranges the reference refuses to hand out (dns.c:30-66): loopback,
# zero-net, link-local, multicast/reserved, broadcast.  ONE definition:
# both the per-IP test and the block reservation derive from it.
_RESTRICTED_CIDRS = (("127.0.0.0", 8), ("0.0.0.0", 8), ("169.254.0.0", 16),
                     ("224.0.0.0", 4), ("240.0.0.0", 4),
                     ("255.255.255.255", 32))

_RESTRICTED = None


def _restricted_intervals():
    """_RESTRICTED_CIDRS as sorted [lo, hi) int intervals, computed once."""
    global _RESTRICTED
    if _RESTRICTED is None:
        ivals = []
        for base, prefix in _RESTRICTED_CIDRS:
            lo = ip_to_int(base) & ((((1 << prefix) - 1)
                                     << (32 - prefix)) & 0xFFFFFFFF)
            ivals.append((lo, lo + (1 << (32 - prefix))))
        _RESTRICTED = sorted(ivals)
    return _RESTRICTED


def _is_restricted(ip: int) -> bool:
    return any(_in_range(ip, base, prefix)
               for base, prefix in _RESTRICTED_CIDRS)


class DNS:
    def __init__(self):
        self._ip_counter = ip_to_int("11.0.0.1")
        self._by_name: Dict[str, Address] = {}
        self._by_ip: Dict[int, Address] = {}
        # lazy resolver (scale/hosttable.py): consulted on a miss so
        # table-resident hosts resolve without ever materializing an
        # Address per quiet host up front.  Returns an Address (which the
        # hook itself registers) or None.
        self.lazy_resolver = None
        # block reservations ([lo, hi) intervals): their IPs are assigned
        # but deliberately NOT in _by_ip — collision checks must consult
        # this list too, or an ip_hint could duplicate a reserved row's IP
        self._blocks: list = []

    def _in_reserved_block(self, ip: int) -> bool:
        return any(lo <= ip < hi for lo, hi in self._blocks)

    def unique_ip(self) -> int:
        ip = self._ip_counter
        while _is_restricted(ip) or ip in self._by_ip \
                or self._in_reserved_block(ip):
            ip += 1
        self._ip_counter = ip + 1
        return ip

    def try_reserve_block(self, count: int) -> Optional[int]:
        """Claim ``count`` consecutive IPs starting at the counter and
        return the base — or None when the candidate range is not clean
        (it crosses a restricted CIDR or an already-registered IP).  The
        caller then falls back to per-IP :meth:`register`, because
        :meth:`unique_ip` skips ONLY the colliding addresses and a block
        that jumped the whole range would assign different IPs than an
        eager per-host registration — breaking table-on vs table-off
        digest parity.  A clean block is arithmetic (base + i), which is
        what lets a 100k-row host table resolve name<->ip without a dict
        entry per host.  Interval checks, not per-IP scans."""
        base = self._ip_counter
        for lo, hi in _restricted_intervals():  # hi exclusive
            if base < hi and base + count > lo:
                return None
        for ip in self._by_ip:
            if base <= ip < base + count:
                return None
        self._ip_counter = base + count
        self._blocks.append((base, base + count))
        return base

    def adopt(self, addr: Address) -> None:
        """Register a lazily-built Address (a table row's, resolved for the
        first time) under the block reservation that already owns its IP."""
        self._by_name[addr.name] = addr
        self._by_ip[addr.ip] = addr

    def register(self, host_id: int, name: str, requested_ip: Optional[int] = None,
                 mac: int = 0) -> Address:
        if name in self._by_name:
            raise ValueError(f"hostname {name!r} is already registered")
        if requested_ip is not None and not _is_restricted(requested_ip) \
                and requested_ip not in self._by_ip \
                and not self._in_reserved_block(requested_ip):
            # a hint inside a reserved block would silently duplicate a
            # table row's IP (block IPs are assigned but not in _by_ip)
            ip = requested_ip
        else:
            ip = self.unique_ip()
        addr = Address(host_id, ip, name, mac=mac)
        self._by_name[name] = addr
        self._by_ip[ip] = addr
        return addr

    def deregister(self, addr: Address) -> None:
        self._by_name.pop(addr.name, None)
        self._by_ip.pop(addr.ip, None)

    def resolve_name(self, name: str) -> Optional[Address]:
        addr = self._by_name.get(name)
        if addr is None and self.lazy_resolver is not None:
            addr = self.lazy_resolver(name=name)
        return addr

    def resolve_ip(self, ip: int) -> Optional[Address]:
        addr = self._by_ip.get(ip)
        if addr is None and self.lazy_resolver is not None:
            addr = self.lazy_resolver(ip=ip)
        return addr

    def __len__(self) -> int:
        return len(self._by_ip)
