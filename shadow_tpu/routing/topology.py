"""Topology as tensors: GraphML network graph → device-resident matrices.

The reference (src/main/routing/topology.c) imports an igraph GraphML file
and answers per-packet latency/reliability queries with *lazy* one-to-all
Dijkstra plus a path cache (topology.c:1655 `_topology_computeSourcePaths`,
:1284 cache probe).  On TPU the right shape is the opposite: compute the
whole attached-pair matrix **eagerly at load** (like the reference, only for
vertices that actually have hosts attached — topology.c:1681) and keep it
device-resident as

    latency_ns     int64  [A, A]   (A = attached vertices)
    reliability    float32[A, A]

so the per-round packet kernel is a pure gather.  The CPU scheduler policies
query the same numpy matrices, guaranteeing CPU/TPU parity.

Semantics matched to the reference (behavior, not code):
  * edge attribute ``latency`` is milliseconds; path latency = sum of edge
    latencies along the latency-shortest path (topology.c:1476-1502).
  * path reliability = (1-src vertex loss) * prod(1-edge loss) * (1-dst
    vertex loss) (topology.c:1427-1463).
  * zero-latency shortest paths are clamped to 1 ms (topology.c:1848-1852).
  * self-paths (src and dst on the same vertex) use the cheapest incident
    edge twice: latency = 2*min, reliability = r_min**2 (topology.c:1640-1650).
  * complete graphs (or ``preferdirectpaths`` + adjacent) use the direct edge
    instead of Dijkstra (topology.c:1877-1928, :2019).
  * packet delay in sim-time = ceil(latency_ms -> ns) (worker.c:276).
  * host attachment picks a vertex by ip/city/country/geocode/type hints with
    longest-IP-prefix tiebreak (topology.c:2094-2371).
"""

from __future__ import annotations

import math
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import stime
from ..core.logger import get_logger
from .address import ip_to_int


class GraphVertex:
    __slots__ = ("index", "gid", "attrs")

    def __init__(self, index: int, gid: str, attrs: Dict[str, str]):
        self.index = index
        self.gid = gid
        self.attrs = attrs

    def get_float(self, name: str) -> Optional[float]:
        v = self.attrs.get(name)
        return float(v) if v not in (None, "") else None

    def get_int(self, name: str) -> Optional[int]:
        v = self.get_float(name)
        return int(v) if v is not None else None


class GraphEdge:
    __slots__ = ("src", "dst", "latency_ms", "jitter_ms", "packetloss")

    def __init__(self, src: int, dst: int, latency_ms: float, jitter_ms: float,
                 packetloss: float):
        self.src = src
        self.dst = dst
        self.latency_ms = latency_ms
        self.jitter_ms = jitter_ms
        self.packetloss = packetloss


def parse_graphml(text: str) -> Tuple[List[GraphVertex], List[GraphEdge], bool, Dict[str, str]]:
    """Minimal GraphML reader covering the reference's schema: typed <key>
    declarations, <node>/<edge> with <data> children, directedness."""
    ns = {"g": "http://graphml.graphdrawing.org/xmlns"}
    root = ET.fromstring(text)

    def findall(el, tag):
        out = el.findall(f"g:{tag}", ns)
        return out if out else el.findall(tag)

    keys = {}  # key id -> attr name
    for k in findall(root, "key"):
        keys[k.get("id")] = k.get("attr.name", k.get("id"))

    graphs = findall(root, "graph")
    if not graphs:
        raise ValueError("GraphML contains no <graph>")
    graph = graphs[0]
    directed = graph.get("edgedefault", "undirected") == "directed"

    def data_of(el) -> Dict[str, str]:
        d = {}
        for c in findall(el, "data"):
            name = keys.get(c.get("key"), c.get("key"))
            d[name] = (c.text or "").strip()
        return d

    graph_attrs = data_of(graph)
    vertices: List[GraphVertex] = []
    vid_to_index: Dict[str, int] = {}
    for n in findall(graph, "node"):
        gid = n.get("id")
        attrs = data_of(n)
        attrs.setdefault("id", gid)
        v = GraphVertex(len(vertices), gid, attrs)
        vid_to_index[gid] = v.index
        vertices.append(v)

    edges: List[GraphEdge] = []
    for e in findall(graph, "edge"):
        d = data_of(e)
        edges.append(GraphEdge(
            vid_to_index[e.get("source")], vid_to_index[e.get("target")],
            latency_ms=float(d.get("latency", 0.0) or 0.0),
            jitter_ms=float(d.get("jitter", 0.0) or 0.0),
            packetloss=float(d.get("packetloss", 0.0) or 0.0)))
    return vertices, edges, directed, graph_attrs


class Topology:
    """The network graph with eagerly computed attached-pair path tensors."""

    def __init__(self, vertices: List[GraphVertex], edges: List[GraphEdge],
                 directed: bool, graph_attrs: Dict[str, str]):
        self.vertices = vertices
        self.edges = edges
        self.directed = directed
        self.graph_attrs = graph_attrs
        self.prefer_direct_paths = graph_attrs.get(
            "preferdirectpaths", "").lower() in ("1", "true", "yes")

        n = len(vertices)
        # Dense would explode for big sparse graphs; keep edges in CSR.
        import scipy.sparse as sp
        rows, cols, lat, rel = [], [], [], []
        for e in edges:
            rows.append(e.src); cols.append(e.dst)
            lat.append(max(e.latency_ms, 0.0)); rel.append(1.0 - e.packetloss)
            if not directed and e.src != e.dst:
                rows.append(e.dst); cols.append(e.src)
                lat.append(max(e.latency_ms, 0.0)); rel.append(1.0 - e.packetloss)
        # Parallel edges: keep the minimum-latency one (deterministic;
        # matches the reference's single igraph_get_eid edge resolution).
        best: Dict[Tuple[int, int], Tuple[float, float]] = {}
        for r, c, l, rr in zip(rows, cols, lat, rel):
            k = (r, c)
            if k not in best or l < best[k][0]:
                best[k] = (l, rr)
        self._edge_lat: Dict[Tuple[int, int], float] = {k: v[0] for k, v in best.items()}
        self._edge_rel: Dict[Tuple[int, int], float] = {k: v[1] for k, v in best.items()}
        # Integer-ns edge weights (ceil per edge, like the reference's final
        # ms->ns ceil) keep all path sums exact: ns values < 2**53 are exact
        # in the float64 scipy works in.  +1 per edge keeps zero-latency
        # edges visible to CSR (scipy drops explicit zeros); the +hop_count
        # bias is subtracted exactly in finalize()'s integer DP.
        self._edge_ns: Dict[Tuple[int, int], int] = {
            k: int(math.ceil(v[0] * stime.SIM_TIME_MS)) for k, v in best.items()}
        if best:
            keys = list(best)       # the dict itself: insertion-ordered
            rr = [k[0] for k in keys]
            cc = [k[1] for k in keys]
            ww = [self._edge_ns[k] + 1 for k in keys]
            self._csr = sp.csr_matrix((np.array(ww, dtype=np.float64), (rr, cc)),
                                      shape=(n, n))
        else:
            self._csr = sp.csr_matrix((n, n))

        self.is_complete = self._check_complete()
        self._vloss = np.array([v.get_float("packetloss") or 0.0 for v in vertices],
                               dtype=np.float64)

        # Attachment state
        self.attached_index: Dict[int, int] = {}   # vertex index -> row in matrices
        self.attached_vertices: List[int] = []     # row -> vertex index
        self._ip_to_row: Dict[int, int] = {}       # host IP -> matrix row
        self.latency_ns: Optional[np.ndarray] = None
        self.reliability: Optional[np.ndarray] = None
        self.min_latency_ns: int = stime.SIM_TIME_MAX
        self.path_packet_counts: Optional[np.ndarray] = None
        self._finalized = False
        self._device_cache = None
        self._attach_cands_cache: Dict[tuple, list] = {}

    # -- construction ------------------------------------------------------
    @classmethod
    def from_graphml(cls, text: str) -> "Topology":
        return cls(*parse_graphml(text))

    @classmethod
    def from_file(cls, path: str) -> "Topology":
        if path.endswith(".xz"):
            import lzma
            with lzma.open(path, "rt") as f:
                return cls.from_graphml(f.read())
        with open(path, "r") as f:
            return cls.from_graphml(f.read())

    def _check_complete(self) -> bool:
        """Complete = every ordered vertex pair (incl. self loops on multi-
        vertex graphs? reference checks all pairs have an edge) is adjacent.
        Single-vertex graphs with a self-loop count as complete."""
        n = len(self.vertices)
        if n == 0:
            return False
        if n == 1:
            return (0, 0) in self._edge_lat
        # _edge_lat is deduplicated and holds both directions for undirected
        # graphs, so completeness is a simple count check.
        non_self = sum(1 for (i, j) in self._edge_lat if i != j)
        return non_self == n * (n - 1)

    # -- host attachment ---------------------------------------------------
    def attach_host(self, ip: int, ip_hint: Optional[str] = None,
                    city_hint: Optional[str] = None, country_hint: Optional[str] = None,
                    geocode_hint: Optional[str] = None, type_hint: Optional[str] = None,
                    choice_rand: Optional[int] = None) -> int:
        """Pick an attachment vertex for a host (reference topology_attach
        :2371 / _topology_findAttachmentVertex :2248).  Returns vertex index.

        Filtering: exact-IP match wins outright; otherwise candidates are
        filtered by each provided hint in turn (ignoring hints that would
        empty the set); the longest-common-IP-prefix with ip_hint breaks
        ties; any remainder is broken deterministically with ``choice_rand``.
        """
        if self._finalized:
            raise RuntimeError("cannot attach hosts after finalize()")

        if ip_hint:
            exact = [v for v in self.vertices if v.attrs.get("ip") == ip_hint]
            if exact:
                return self._record_attachment(exact[0].index, ip)

        # hint filtering is identical for every host with the same hints
        # (the common case: none) — memoize the candidate list so 10k-host
        # boots don't rescan the vertex set per host
        hint_key = (type_hint, city_hint, country_hint, geocode_hint)
        cached = self._attach_cands_cache.get(hint_key)
        if cached is None:
            cands = list(self.vertices)

            def filt(key: str, want: Optional[str]):
                nonlocal cands
                if not want:
                    return
                kept = [v for v in cands
                        if v.attrs.get(key, "").lower() == want.lower()]
                if kept:
                    cands = kept

            filt("type", type_hint)
            filt("citycode", city_hint)
            filt("countrycode", country_hint)
            filt("geocode", geocode_hint)
            self._attach_cands_cache[hint_key] = cands
        else:
            cands = cached

        if ip_hint and len(cands) > 1:
            want = ip_to_int(ip_hint)
            def prefix_len(v: GraphVertex) -> int:
                vip = v.attrs.get("ip")
                if not vip:
                    return -1
                try:
                    x = ip_to_int(vip) ^ want
                except Exception:
                    return -1
                return 32 if x == 0 else 32 - x.bit_length()
            best_len = max(prefix_len(v) for v in cands)
            cands = [v for v in cands if prefix_len(v) == best_len]

        idx = cands[(choice_rand or 0) % len(cands)].index
        return self._record_attachment(idx, ip)

    def _record_attachment(self, vertex_index: int, ip: int) -> int:
        if vertex_index not in self.attached_index:
            self.attached_index[vertex_index] = len(self.attached_vertices)
            self.attached_vertices.append(vertex_index)
        self._ip_to_row[ip] = self.attached_index[vertex_index]
        return vertex_index

    def vertex_bandwidth_kibps(self, vertex_index: int) -> Tuple[int, int]:
        """(down, up) KiB/s defaults for hosts attached here."""
        v = self.vertices[vertex_index]
        down = v.get_int("bandwidthdown") or 0
        up = v.get_int("bandwidthup") or 0
        return down, up

    # -- path matrix computation ------------------------------------------
    def finalize(self) -> None:
        """Compute the [A, A] latency/reliability matrices for all attached
        vertex pairs.  Eager equivalent of the reference's lazy per-source
        Dijkstra + cache."""
        if self._finalized:
            return
        A = len(self.attached_vertices)
        n = len(self.vertices)
        lat_ns = np.zeros((A, A), dtype=np.int64)
        rel = np.ones((A, A), dtype=np.float64)

        if A > 0 and self.is_complete:
            for i, si in enumerate(self.attached_vertices):
                for j, dj in enumerate(self.attached_vertices):
                    if si == dj:
                        l, r = self._self_path(si)
                    else:
                        l = self._edge_ns[(si, dj)]
                        r = (self._edge_rel[(si, dj)]
                             * (1.0 - self._vloss[si]) * (1.0 - self._vloss[dj]))
                    lat_ns[i, j] = l
                    rel[i, j] = r
        elif A > 0:
            from scipy.sparse.csgraph import dijkstra
            srcs = np.array(self.attached_vertices, dtype=np.int64)
            # _csr already contains both arc directions for undirected
            # graphs, so always treat it as directed here.  Weights are
            # integer ns + 1 per edge (see __init__); ns-scale values are
            # exact in float64 and the hop bias is removed exactly below.
            dist, pred = dijkstra(self._csr, directed=True,
                                  indices=srcs, return_predecessors=True)
            for i, si in enumerate(self.attached_vertices):
                order = np.argsort(dist[i], kind="stable")
                # DP along each predecessor chain in distance order:
                # reliability product and exact hop count.
                relpath = np.full(n, np.nan)
                hops = np.zeros(n, dtype=np.int64)
                relpath[si] = 1.0
                for v in order:
                    if not np.isfinite(dist[i][v]) or v == si:
                        continue
                    p = pred[i][v]
                    if p < 0 or np.isnan(relpath[p]):
                        continue
                    relpath[v] = relpath[p] * self._edge_rel.get((p, v),
                                    self._edge_rel.get((v, p), 1.0))
                    hops[v] = hops[p] + 1
                for j, dj in enumerate(self.attached_vertices):
                    if si == dj:
                        l, r = self._self_path(si)
                        lat_ns[i, j] = l
                        rel[i, j] = r
                        continue
                    if self.prefer_direct_paths and (si, dj) in self._edge_ns:
                        # preferdirectpaths graphs use the direct edge for
                        # adjacent pairs even when a multi-hop path is
                        # shorter (reference topology.c:2019, :1877-1928).
                        lat_ns[i, j] = self._edge_ns[(si, dj)]
                        rel[i, j] = (self._edge_rel[(si, dj)]
                                     * (1.0 - self._vloss[si]) * (1.0 - self._vloss[dj]))
                        continue
                    d = dist[i][dj]
                    if not np.isfinite(d):
                        raise ValueError(
                            f"no path between attached vertices "
                            f"{self.vertices[si].gid} and {self.vertices[dj].gid}")
                    lat_ns[i, j] = int(d) - int(hops[dj])  # exact integer ns
                    rel[i, j] = (relpath[dj] * (1.0 - self._vloss[si])
                                 * (1.0 - self._vloss[dj]))

        # 0 -> 1ms clamp (reference topology.c:1848-1852 clamps zero-latency
        # shortest paths to 1 ms).
        self.latency_ns = np.where(lat_ns <= 0, stime.SIM_TIME_MS, lat_ns).astype(np.int64)
        self.reliability = np.clip(rel, 0.0, 1.0).astype(np.float32)
        self.path_packet_counts = np.zeros((A, A), dtype=np.int64)
        if A > 0:
            self.min_latency_ns = int(self.latency_ns.min())
        self._finalized = True
        get_logger().message(
            "topology",
            f"finalized path matrices: {A} attached vertices of {n}, "
            f"min latency {self.min_latency_ns / 1e6:.3f} ms, "
            f"{'complete' if self.is_complete else 'sparse'} graph")

    def _self_path(self, vertex_index: int) -> Tuple[int, float]:
        """Cheapest incident edge used twice (topology.c:1545-1653).
        Returns (latency_ns, reliability)."""
        best_lat, best_rel = None, 1.0
        for (u, w), l in self._edge_ns.items():
            if u == vertex_index or w == vertex_index:
                if best_lat is None or l < best_lat:
                    best_lat = l
                    best_rel = self._edge_rel[(u, w)]
        if best_lat is None:
            return stime.SIM_TIME_MS, 1.0  # isolated vertex: 1ms self path
        return 2 * best_lat, best_rel * best_rel

    # -- queries (CPU side) ------------------------------------------------
    def row_for_ip(self, ip: int) -> Optional[int]:
        return self._ip_to_row.get(ip)

    def latency_ns_ip(self, src_ip: int, dst_ip: int) -> int:
        i = self._ip_to_row[src_ip]
        j = self._ip_to_row[dst_ip]
        self.path_packet_counts[i, j] += 1
        return int(self.latency_ns[i, j])

    def reliability_ip(self, src_ip: int, dst_ip: int) -> float:
        return float(self.reliability[self._ip_to_row[src_ip], self._ip_to_row[dst_ip]])

    # -- device view -------------------------------------------------------
    def device_tensors(self):
        """(latency_ns int64[A,A], reliability f32[A,A]) as jax arrays."""
        if self._device_cache is None:
            from .. import ops  # noqa: F401  (enables x64 so int64 survives)
            import jax.numpy as jnp
            lat = jnp.asarray(self.latency_ns)
            assert lat.dtype == jnp.int64, "device latency must be int64 ns"
            self._device_cache = (lat, jnp.asarray(self.reliability))
        return self._device_cache

    def ip_row_array(self, ips: List[int]) -> np.ndarray:
        """Map a list of host IPs to matrix rows (for building the host →
        attached-vertex index used by the device kernel)."""
        return np.array([self._ip_to_row[ip] for ip in ips], dtype=np.int32)


def single_vertex_topology(bandwidth_down_kibps: int = 102400,
                           bandwidth_up_kibps: int = 102400,
                           latency_ms: float = 10.0,
                           packetloss: float = 0.0) -> Topology:
    """The built-in one-vertex + self-loop graph used by ``--test`` (reference
    core/support/examples.c)."""
    v = GraphVertex(0, "poi-1", {
        "id": "poi-1", "ip": "0.0.0.0", "citycode": "0", "countrycode": "US",
        "asn": "0", "type": "net",
        "bandwidthdown": str(bandwidth_down_kibps),
        "bandwidthup": str(bandwidth_up_kibps), "packetloss": str(packetloss)})
    e = GraphEdge(0, 0, latency_ms=latency_ms, jitter_ms=0.0, packetloss=packetloss)
    return Topology([v], [e], directed=False, graph_attrs={})
