"""Address: a (host id, IP, name) identity with cached string forms.

Capability of the reference's refcounted Address (routing/address.c): each
network interface gets one; DNS hands them out and resolves between forms.
IPs are plain host-order uint32 ints internally.
"""

from __future__ import annotations

import ipaddress
from typing import Optional


def ip_to_int(dotted: str) -> int:
    # manual parse: ~10x faster than ipaddress.IPv4Address and this runs
    # several times per host during 10k-host boot; falls back for anything
    # that isn't plain dotted-quad
    parts = dotted.split(".")
    if len(parts) == 4:
        try:
            a, b, c, d = (int(p) for p in parts)
            if 0 <= a <= 255 and 0 <= b <= 255 and 0 <= c <= 255 \
                    and 0 <= d <= 255 \
                    and all(p == str(int(p)) for p in parts):
                return (a << 24) | (b << 16) | (c << 8) | d
        except ValueError:
            pass
    return int(ipaddress.IPv4Address(dotted))


def int_to_ip(v: int) -> str:
    return (f"{(v >> 24) & 0xFF}.{(v >> 16) & 0xFF}."
            f"{(v >> 8) & 0xFF}.{v & 0xFF}")


LOCALHOST_IP = ip_to_int("127.0.0.1")
BROADCAST_IP = ip_to_int("255.255.255.255")


class Address:
    __slots__ = ("host_id", "ip", "name", "mac", "is_local", "_ip_str")

    def __init__(self, host_id: int, ip: int, name: str, mac: int = 0,
                 is_local: bool = False):
        self.host_id = host_id
        self.ip = ip
        self.name = name
        self.mac = mac
        self.is_local = is_local
        self._ip_str: Optional[str] = None

    @property
    def ip_string(self) -> str:
        if self._ip_str is None:
            self._ip_str = int_to_ip(self.ip)
        return self._ip_str

    def __repr__(self) -> str:
        return f"Address({self.name}={self.ip_string})"
