"""Sim-time tracing: spans + instants into a bounded flight-recorder ring,
exported as Chrome trace-event JSON (Perfetto-loadable).

Every record carries BOTH clocks:

* **wall time** (``ts``/``dur``, microseconds since tracer start) — what
  Perfetto renders, and what profiling reads (dispatch latency, overlap);
* **sim time** (``args.sim_ns``) — the virtual clock, which is
  deterministic: two identically-seeded runs produce identical sim-time
  event streams (tests/test_obs.py mirrors the log-diff determinism gate
  over the trace stream, wall fields excluded).

Storage is a ring buffer per track (thread) — the flight-recorder
property: memory is bounded however long the run, and the recent past is
always available for a post-mortem.  Supervision watchdogs dump the last-N
spans on any recovery (``dump_recent``), so a fault arrives with its
timeline attached.  Sharded runs (parallel/procs.py) ``drain()`` each
shard's ring into the parent, which merges them onto per-shard tracks
(Chrome ``pid`` = shard id) and writes one file.

The disabled path returns a shared null span: one attribute check + one
no-op context manager per call site, pinned ~0 by bench.py's
``obs_overhead_sec`` column.
"""

from __future__ import annotations

import json
import threading
import time as _walltime
from collections import deque
from typing import Dict, List, Optional

DEFAULT_RING = 65536     # events kept per track (flight-recorder depth)


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records a Chrome 'X' (complete) event on exit."""

    __slots__ = ("_tracer", "name", "cat", "sim_ns", "args", "_t0")

    def __init__(self, tracer, name, cat, sim_ns, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.sim_ns = sim_ns
        self.args = args

    def __enter__(self):
        self._t0 = _walltime.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer.complete(self.name, self.cat, self._t0,
                              _walltime.perf_counter(), self.sim_ns,
                              self.args)
        return False


class Tracer:
    def __init__(self, enabled: bool = False, path: Optional[str] = None,
                 ring: Optional[int] = None, shard_id: int = 0,
                 label: Optional[str] = None):
        self.enabled = enabled
        self.path = path
        # a zero/negative depth would make deque(maxlen=...) raise at the
        # FIRST recorded span, deep into the run — fall back to the default
        self.ring = ring if (ring and ring > 0) else DEFAULT_RING
        self.shard_id = shard_id
        # Chrome pid -> display name; foreign pids (ingested shard events)
        # default to "shard N" at export
        self.pid_labels = {shard_id: label or f"shard {shard_id}"}
        self._t0 = _walltime.perf_counter()
        self._rings: Dict[str, deque] = {}
        self._foreign: List[dict] = []    # ingested (e.g. shard) events
        self._lock = threading.Lock()
        self.dropped = 0                  # events evicted by ring bounds

    # -- recording ---------------------------------------------------------
    def _sim_now(self) -> int:
        """Fallback sim clock when the call site didn't pass one: the
        active worker's virtual time (same source the logger uses)."""
        from ..core import worker as _worker_mod
        w = _worker_mod.current_worker()
        return w.now if w is not None else -1

    def _record(self, ev: dict) -> None:
        """Append one event to its track's ring.  The lock covers the
        append so readers (events/drain/recent — notably the flight-
        recorder dump inside a supervised recovery on ANOTHER thread)
        never iterate a deque mid-mutation."""
        with self._lock:
            ring = self._rings.get(ev["tid"])
            if ring is None:
                ring = self._rings.setdefault(ev["tid"],
                                              deque(maxlen=self.ring))
            if len(ring) == ring.maxlen:
                self.dropped += 1
            ring.append(ev)

    def complete(self, name: str, cat: str, t0: float, t1: float,
                 sim_ns: Optional[int], args: Optional[dict],
                 tid: Optional[str] = None) -> None:
        """Record a finished span [t0, t1] (perf_counter seconds).
        ``tid`` overrides the track — the device plane's sim-correlated
        ``device-sim`` track (obs/profiler.py) gets its own lane in the
        merged Chrome trace instead of interleaving with the engine
        thread's round spans."""
        if sim_ns is None:
            sim_ns = self._sim_now()
        self._record({"name": name, "cat": cat, "ph": "X",
                      "ts": round((t0 - self._t0) * 1e6, 3),
                      "dur": round((t1 - t0) * 1e6, 3),
                      "pid": self.shard_id,
                      "tid": tid or threading.current_thread().name,
                      "args": dict(args, sim_ns=sim_ns) if args
                      else {"sim_ns": sim_ns}})

    def span(self, name: str, cat: str = "sim",
             sim_ns: Optional[int] = None, args: Optional[dict] = None):
        """Context manager timing a span; a shared no-op when disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, cat, sim_ns, args)

    def instant(self, name: str, cat: str = "sim",
                sim_ns: Optional[int] = None,
                args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        if sim_ns is None:
            sim_ns = self._sim_now()
        self._record({"name": name, "cat": cat, "ph": "i", "s": "t",
                      "ts": round((_walltime.perf_counter() - self._t0)
                                  * 1e6, 3),
                      "pid": self.shard_id,
                      "tid": threading.current_thread().name,
                      "args": dict(args, sim_ns=sim_ns) if args
                      else {"sim_ns": sim_ns}})

    # -- reading / merging -------------------------------------------------
    def _collect_locked(self) -> List[dict]:
        out: List[dict] = []
        for ring in self._rings.values():
            out.extend(ring)
        out.extend(self._foreign)
        return out

    def events(self) -> List[dict]:
        """Every buffered event (local rings + ingested), unsorted."""
        with self._lock:
            return self._collect_locked()

    def drain(self) -> List[dict]:
        """Take + clear every buffered event — the shard side of the merge
        protocol (parallel/procs.py ships these in its 'final' message)."""
        with self._lock:
            out = self._collect_locked()
            self._rings.clear()
            self._foreign = []
        return out

    @property
    def epoch(self) -> float:
        """Absolute monotonic-clock seconds of this tracer's ts=0 origin
        (perf_counter at construction).  Shipped over the procs protocol so
        the parent can align each shard's events onto ITS timeline — on
        Linux CLOCK_MONOTONIC is shared across processes, so the shift is
        exact."""
        return self._t0

    def ingest(self, events: List[dict],
               epoch: Optional[float] = None) -> None:
        """Merge another tracer's drained events (parent side: each shard's
        events arrive with their own ``pid`` and land on per-shard tracks).
        ``epoch`` is the source tracer's :attr:`epoch`; when given, event
        timestamps are re-based onto THIS tracer's origin so the merged
        file's tracks share one wall timeline (without it, each shard's
        ts=0 would be its own construction instant — seconds of skew)."""
        shift_us = 0.0 if epoch is None else (epoch - self._t0) * 1e6
        if shift_us:
            events = [dict(e, ts=round(e["ts"] + shift_us, 3))
                      for e in events]
        with self._lock:
            self._foreign.extend(events)

    def recent(self, n: int = 30) -> List[dict]:
        """The flight recorder's last-``n`` events, oldest first."""
        evs = self.events()
        evs.sort(key=lambda e: e["ts"])
        return evs[-n:]

    def dump_recent(self, domain: str, reason: str, n: int = 30) -> int:
        """Log the flight recorder's recent spans — called by supervision
        watchdogs on any recovery so the fault carries its timeline.
        Returns the number of spans dumped."""
        from ..core.logger import get_logger
        log = get_logger()
        evs = self.recent(n)
        if not evs:
            log.warning(domain,
                        f"flight recorder: no spans buffered ({reason}; "
                        "run with --trace to record timelines)")
            return 0
        log.warning(domain,
                    f"flight recorder: last {len(evs)} spans before "
                    f"recovery ({reason}):")
        for ev in evs:
            sim = ev.get("args", {}).get("sim_ns", -1)
            dur = ev.get("dur", 0.0)
            log.warning(domain,
                        f"  [flight-recorder] +{ev['ts'] / 1e3:.3f}ms "
                        f"dur={dur / 1e3:.3f}ms sim={sim / 1e9:.6f}s "
                        f"{ev['cat']}:{ev['name']} "
                        f"(shard {ev['pid']}, {ev['tid']})")
        return len(evs)

    # -- export ------------------------------------------------------------
    def chrome_events(self) -> List[dict]:
        """Chrome trace-event list: metadata (process/thread names) +
        buffered events sorted by (pid, tid, ts) — monotonic timestamps
        per track, as Perfetto expects."""
        evs = sorted(self.events(),
                     key=lambda e: (e["pid"], e["tid"], e["ts"]))
        pids = sorted({e["pid"] for e in evs})
        tracks = sorted({(e["pid"], e["tid"]) for e in evs})
        meta: List[dict] = []
        for pid in pids:
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": "",
                         "args": {"name": self.pid_labels.get(
                             pid, f"shard {pid}")}})
        for pid, tid in tracks:
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tid}})
        return meta + evs

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace JSON; returns the path (None if tracing
        is disabled or no path was configured)."""
        path = path or self.path
        if not self.enabled or not path:
            return None
        blob = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "shadow-tpu flight recorder",
                "ring_per_track": self.ring,
                "events_dropped_by_ring": self.dropped,
            },
        }
        with open(path, "w") as f:
            json.dump(blob, f)
        return path


_default: Optional[Tracer] = None


def get_tracer() -> Tracer:
    global _default
    if _default is None:
        _default = Tracer(enabled=False)
    return _default


def set_tracer(tracer: Tracer) -> None:
    global _default
    _default = tracer
