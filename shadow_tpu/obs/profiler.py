"""Device-plane profiling: dispatch/collect latency histograms, bytes per
flush, and pipeline-overlap efficiency.

Hooked by parallel/device_plane.py at its three pipeline edges:

* **dispatch** (``advance``) — host-side launch cost (batch packing +
  kernel dispatch call), steps/injections per window;
* **in-flight** — the wall between launch and collect start: the time the
  device computed BEHIND host round work (the overlap the async pipeline
  exists to create);
* **collect** (``consume``) — blocking materialization of the packed flush
  buffer, and its size in bytes (the per-dispatch device->host transfer).

The latency *distributions* live here (per-phase visibility is what made
the IPU architecture legible by microbenchmarking, arXiv:1912.03413, and
what later dispatch-scheduling work optimizes, arXiv:2505.09764); the
overlap *totals* and ``overlap_efficiency`` are published ONCE, by
``DeviceTrafficPlane.stats()`` (the ``plane.*`` scrape namespace), so the
number cannot drift between two computations.

Everything feeds the metrics registry under ``device.*``; span emission
rides the tracer so a ``--trace`` run sees each dispatch's timeline in
Perfetto.  With observability disabled every hook is an attribute check.
"""

from __future__ import annotations

from .metrics import get_metrics
from .trace import get_tracer


class DeviceProfiler:
    """Per-plane profiling state; constructed by DeviceTrafficPlane."""

    def __init__(self):
        self.tracer = get_tracer()
        registry = get_metrics()
        self.enabled = registry.enabled or self.tracer.enabled
        self.dispatch_us = registry.histogram("device.dispatch_launch_us")
        self.collect_us = registry.histogram("device.collect_blocked_us")
        self.flush_bytes = registry.histogram("device.flush_bytes")
        # launch attribution (ISSUE 15, shadow_tpu/prof/): per-launch
        # predicted-vs-measured device cost from the calibrated model,
        # and the loud stale-model counter — populated only when a cost
        # model actually loaded (on_window's predicted is None otherwise)
        self.pred_us = registry.histogram("prof.launch_predicted_us")
        self.meas_us = registry.histogram("prof.launch_measured_us")
        self.model_stale = registry.counter("prof.model_stale")
        self.launches_checked = registry.counter("prof.launches_checked")

    # -- hooks (called from the device plane) ------------------------------
    def on_dispatch(self, t0_ns: int, t1_ns: int, steps: int,
                    injections: int, dispatch_idx: int,
                    sim_ns: int) -> None:
        """Host-side launch cost of one window dispatch ([t0, t1] are
        perf_counter_ns stamps around advance()'s dispatch section)."""
        if not self.enabled:
            return
        self.dispatch_us.observe((t1_ns - t0_ns) / 1e3)
        if self.tracer.enabled:
            self.tracer.complete(
                "device.dispatch", "device", t0_ns / 1e9, t1_ns / 1e9,
                sim_ns, {"dispatch": dispatch_idx, "steps": steps,
                         "injections": injections})

    def on_collect(self, launch_wall_ns: int, collect_start_ns: int,
                   blocked_ns: int, nbytes: int, dispatch_idx: int,
                   sim_ns: int) -> None:
        """``launch_wall_ns``/``collect_start_ns`` are perf_counter_ns
        stamps from the plane; their gap is the overlap the pipeline
        bought, rendered as the ``device.inflight`` span."""
        if not self.enabled:
            return
        self.collect_us.observe(blocked_ns / 1e3)
        self.flush_bytes.observe(nbytes)
        if self.tracer.enabled:
            self.tracer.complete("device.inflight", "device",
                                 launch_wall_ns / 1e9,
                                 collect_start_ns / 1e9, sim_ns,
                                 {"dispatch": dispatch_idx,
                                  "flush_bytes": nbytes,
                                  "blocked_us": round(blocked_ns / 1e3, 1)})

    def on_window(self, launch_ns: int, end_ns: int, blocked_ns: int,
                  steps: int, granule_ms: int,
                  predicted_us, band: float, sim_base_ns: int,
                  exchange_mode: str) -> None:
        """Per-launch attribution (ISSUE 15): pair the model's predicted
        device cost with the measured launch->collect-end wall, count
        band violations in ``prof.model_stale``, and emit the
        sim-correlated ``device.window`` span onto the dedicated
        ``device-sim`` Chrome-trace track.

        The measured span UPPER-bounds the kernel wall (the pipeline
        overlaps host work inside it), so the band check is one-sided
        by default: ``measured < predicted / band`` proves the model
        OVERpredicts (the kernel finished inside a span band-times
        shorter than predicted).  UNDERprediction is only judged when
        the collect blocked for most of the span — there the span IS
        the kernel wall — so host-heavy rounds cannot false-positive
        the counter."""
        if predicted_us is None and not self.enabled:
            return
        measured_us = (end_ns - launch_ns) / 1e3
        self.meas_us.observe(measured_us)
        if predicted_us is not None:
            self.pred_us.observe(predicted_us)
            self.launches_checked.inc()
            over = measured_us * band < predicted_us
            blocked_dominated = blocked_ns * 2 >= (end_ns - launch_ns)
            under = blocked_dominated and measured_us > predicted_us * band
            if over or under:
                self.model_stale.inc()
        if self.tracer.enabled:
            self.tracer.complete(
                "device.window", "device-sim", launch_ns / 1e9,
                end_ns / 1e9, sim_base_ns,
                {"steps": steps,
                 "sim_span_ms": steps * granule_ms,
                 "exchange_mode": exchange_mode,
                 "measured_us": round(measured_us, 1),
                 "predicted_us": round(predicted_us, 1)
                 if predicted_us is not None else None},
                tid="device-sim")
