"""Flight-recorder observability plane (ISSUE 3).

The reference scatters its visibility across per-host tracker heartbeats
(host/tracker.c), getrusage engine heartbeats (slave.c:390-411), and the
shutdown object-lifecycle leak report; our port additionally has a device
pipeline and supervision seams with timing worth keeping.  This package
gives all of them one structured home with three cooperating layers:

* :mod:`obs.trace`   — spans/instants carrying BOTH sim-time and wall-time,
  recorded into a bounded per-track ring buffer (a flight recorder: the
  recent past is always available, memory is always bounded), exported as
  Chrome trace-event JSON (``--trace PATH``, loadable in Perfetto);
* :mod:`obs.metrics` — a registry of counters/gauges/histograms/sources
  scraped on a round cadence to JSONL plus a final summary
  (``--metrics PATH --metrics-every N``), absorbing the ObjectCounter,
  SupervisionStats, tracker heartbeats, and device-plane stats as sources
  instead of leaving each its own ad-hoc format;
* :mod:`obs.profiler` — device-plane hooks (dispatch/collect latency
  histograms, bytes per flush, pipeline-overlap efficiency) feeding both.

Everything is OFF by default and the disabled path is a handful of
attribute checks per round (pinned by bench.py's ``obs_overhead_sec``
column); simulation state is never touched, so digests are identical with
observability on or off (tests/test_obs.py pins this).
"""

from __future__ import annotations

import time as _walltime


def configure_observability(options, shard_id=None, label=None):
    """Build + install the global tracer/registry from run options.

    Called by Engine.__init__ (and the procs parent, which passes an
    explicit ``shard_id`` past the shard range plus ``label='parent'``)
    the same way the CLI installs the logger: per run, module-global, so
    distant modules (tracker, device plane, native plugins) reach it
    without threading an engine reference through every signature.
    Returns ``(tracer, registry, metrics_writer_or_None)``.
    """
    from .metrics import MetricsRegistry, MetricsWriter, set_metrics
    from .trace import Tracer, set_tracer

    if shard_id is None:
        shard_id = int(getattr(options, "shard_id", 0) or 0)
    trace_path = getattr(options, "trace_path", None)
    tracer = Tracer(enabled=bool(trace_path), path=trace_path,
                    ring=int(getattr(options, "trace_ring", 0) or 0) or None,
                    shard_id=shard_id, label=label)
    set_tracer(tracer)
    metrics_path = getattr(options, "metrics_path", None)
    registry = MetricsRegistry(enabled=bool(metrics_path))
    set_metrics(registry)
    writer = None
    # shard engines record but never write files: their rings/scrapes ride
    # the procs final message and the parent owns the merged outputs (N
    # children appending to one path would interleave garbage)
    if metrics_path and int(getattr(options, "shard_count", 1) or 1) == 1:
        writer = MetricsWriter(
            metrics_path,
            int(getattr(options, "metrics_every_rounds", 0) or 0))
    return tracer, registry, writer


# measuring the disabled path must itself stay cheap: each hook form is
# timed over at most this many iterations and scaled linearly to the
# requested count (the loops are constant-cost, so the extrapolation is
# exact to measurement noise)
_CALIBRATION_CAP = 200_000


def disabled_overhead_sec(span_hooks: int, enabled_checks: int = 0) -> float:
    """Measure the DISABLED observability plane's cost in its two actual
    forms: ``span_hooks`` null-span enter/exits (the ~6 fixed engine hooks
    per round) plus ``enabled_checks`` bare ``get_tracer()``+``.enabled``
    probes (the per-process-resume / per-RPC guard form, which never
    constructs a span when off).  bench.py prices the engine hooks at the
    run's round count and the guard checks at the run's EVENT count — an
    upper bound on resumes, so ``obs_overhead_sec`` is a conservative
    measured pin that the disabled path rounds to zero."""
    from .trace import Tracer, get_tracer, set_tracer

    span_hooks = max(0, int(span_hooks))
    enabled_checks = max(0, int(enabled_checks))
    tracer = Tracer(enabled=False)
    total = 0.0
    n = min(span_hooks, _CALIBRATION_CAP)
    if n:
        t0 = _walltime.perf_counter()
        for _ in range(n):
            with tracer.span("obs.overhead", "bench"):
                pass
        total += (_walltime.perf_counter() - t0) * (span_hooks / n)
    n = min(enabled_checks, _CALIBRATION_CAP)
    if n:
        prev = get_tracer()
        set_tracer(tracer)
        try:
            t0 = _walltime.perf_counter()
            for _ in range(n):
                if get_tracer().enabled:
                    pass  # pragma: no cover - tracer is disabled
            total += (_walltime.perf_counter() - t0) * (enabled_checks / n)
        finally:
            set_tracer(prev)
    return total
