"""Unified metrics registry: counters/gauges/histograms/sources, scraped on
a round cadence to JSONL plus a final summary.

One registry per run (module-global, installed like the logger).  Existing
telemetry becomes *sources* instead of keeping its own format:

* ``core/counters.py`` ObjectCounter — per-type new/free tallies + the
  shutdown leak report land in the final summary (``object_leaks``);
* ``core/supervision.py`` SupervisionStats — watchdog fires/recoveries;
* ``host/tracker.py`` heartbeats — the SAME values the legacy
  ``[shadow-heartbeat]`` log line carries (the line keeps printing, and
  tools/plot_log.py keeps scraping it; the registry aggregates);
* ``core/engine.py`` ``[engine-heartbeat]`` getrusage lines — ditto;
* the device plane + tpu policy phase timings (``flush_sec``,
  ``device_wait_sec``, ``pipeline_overlap_sec``) — bench.py reads these
  from ``scrape()`` instead of re-deriving them with ad-hoc timers.

``enabled`` gates only the per-event recording paths (heartbeat capture,
profiler observes); registration and :meth:`scrape` always work, so tools
can read phase timings from a run that never wrote a metrics file.

Thread safety (simrace's first customer, ISSUE 5): the registry is
scraped from the engine loop but its instruments are incremented from
watchdog helper threads (the dispatch-collect guard), worker threads
(spans/heartbeats on threaded schedulers) and supervision recovery
paths.  ONE registry RLock covers instrument mutation, instrument
creation, heartbeat capture and the scrape snapshot, so a scrape never
reads a histogram mid-update and concurrent ``inc()`` never loses
counts (tests/test_concurrency_stress.py hammers exactly this).
Reentrant because a gauge/source callable read under the scrape lock
may itself touch the registry.
"""

from __future__ import annotations

import json
import threading
import time as _walltime
from typing import Callable, Dict, List, Optional


class Counter:
    """Monotonic count (thread-safe under the registry lock)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: Optional[threading.RLock] = None):
        self.name = name
        self.value = 0
        self._lock = lock if lock is not None else threading.RLock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Point-in-time value: either ``set()`` or a callable read at scrape."""

    __slots__ = ("name", "value", "fn", "_lock")

    def __init__(self, name: str, fn: Optional[Callable] = None,
                 lock: Optional[threading.RLock] = None):
        self.name = name
        self.value = 0
        self.fn = fn
        self._lock = lock if lock is not None else threading.RLock()

    def set(self, v) -> None:
        with self._lock:
            self.value = v

    def read(self):
        if self.fn is not None:
            return self.fn()
        with self._lock:
            return self.value


class Histogram:
    """Streaming distribution: count/sum/min/max + power-of-two buckets
    (bucket key k counts observations in [2^k, 2^(k+1)); everything below
    1 — sub-unit fractions, zero, negatives — lands in bucket key -1, so
    pick units that put interesting values above 1, e.g. microseconds).
    Enough to read latency tails without per-observation storage.

    The final snapshot additionally estimates p50/p95/p99 (ISSUE 15):
    linear interpolation inside the covering power-of-two bucket,
    clamped to the observed [min, max] — quantization error is bounded
    by the bucket width (a factor of two), which is exactly the
    resolution the tails are read at."""

    __slots__ = ("name", "count", "total", "min", "max", "buckets",
                 "_lock")

    def __init__(self, name: str, lock: Optional[threading.RLock] = None):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets: Dict[int, int] = {}
        self._lock = lock if lock is not None else threading.RLock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            k = -1 if v < 1 else int(v).bit_length() - 1
            self.buckets[k] = self.buckets.get(k, 0) + 1

    def _quantile_locked(self, q: float) -> float:
        """Estimate quantile ``q`` from the power-of-two buckets (lock
        held): walk the cumulative counts to the covering bucket,
        interpolate linearly inside it, clamp to observed [min, max]."""
        target = q * self.count
        run = 0
        for k, n in sorted(self.buckets.items()):
            run += n
            if run >= target:
                lo = 0.0 if k < 0 else float(2 ** k)
                hi = 1.0 if k < 0 else float(2 ** (k + 1))
                frac = 1.0 - (run - target) / n
                v = lo + (hi - lo) * frac
                return min(max(v, self.min), self.max)
        return self.max

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if not self.count:
                return {"count": 0}
            return {"count": self.count, "sum": self.total,
                    "min": self.min, "max": self.max,
                    "mean": self.total / self.count,
                    # percentile summaries (ISSUE 15): the tail columns
                    # trace_report --metrics prints; schema pinned by
                    # tests/test_simprof.py
                    "p50": round(self._quantile_locked(0.50), 3),
                    "p95": round(self._quantile_locked(0.95), 3),
                    "p99": round(self._quantile_locked(0.99), 3),
                    "buckets": {str(k): v
                                for k, v in sorted(self.buckets.items())}}


class MetricsRegistry:
    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        # ONE reentrant lock shared by every instrument (see the module
        # docstring): scrape holds it across the whole instrument
        # snapshot, so a single scrape record is internally consistent
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._sources: Dict[str, Callable[[], Dict]] = {}
        self._host_hb: Dict[str, Dict] = {}     # host -> last heartbeat vals
        self._engine_hb: Dict = {}              # last engine heartbeat vals
        self._summary_info: Dict = {}           # summary-only payloads

    # -- instrument construction (idempotent by name) ----------------------
    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, self._lock)
            return c

    def gauge(self, name: str, fn: Optional[Callable] = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, fn, self._lock)
            elif fn is not None:
                g.fn = fn
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, self._lock)
            return h

    def source(self, name: str, fn: Callable[[], Dict]) -> None:
        """Register a scrape-time provider returning {metric: value};
        later registrations under one name replace earlier ones (a re-run
        engine re-registers cleanly)."""
        with self._lock:
            self._sources[name] = fn

    # -- heartbeat promotion (the legacy log lines' values, shared) --------
    def record_host_heartbeat(self, host_name: str, vals: Dict) -> None:
        """Tracker heartbeat: store the SAME dict the log line was formatted
        from.  Scrape aggregates across hosts (sums), so a 10k-host run
        scrapes a handful of totals, not 10k series."""
        if not self.enabled:
            return
        with self._lock:
            self._host_hb[host_name] = vals

    def record_engine_heartbeat(self, vals: Dict) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._engine_hb = vals

    def set_summary_info(self, key: str, value) -> None:
        """Attach a summary-only payload (e.g. the ObjectCounter leak
        report) emitted with the final summary record."""
        with self._lock:
            self._summary_info[key] = value

    # -- scraping ----------------------------------------------------------
    def scrape(self) -> Dict:
        """One flat {metric: value} snapshot (histograms expand to nested
        dicts).  Works whether or not the registry is enabled."""
        # sorted everywhere: instrument registration order differs between
        # engine configurations, and the scrape reaches user-visible JSONL
        # — explicit ordering keeps reports byte-stable across runs.
        # The registry lock is held across the whole snapshot (reentrant:
        # gauge fns / sources read back through it), so one scrape record
        # is internally consistent even under concurrent increments.
        out: Dict = {}
        with self._lock:
            for name, c in sorted(self._counters.items()):
                out[name] = c.value
            for name, g in sorted(self._gauges.items()):
                try:
                    out[name] = g.read()
                except Exception as e:  # a broken gauge must not kill a run
                    out[name] = f"gauge_error: {e!r}"
            for name, h in sorted(self._histograms.items()):
                out[name] = h.snapshot()
            for sname, fn in sorted(self._sources.items()):
                try:
                    vals = fn() or {}
                except Exception as e:  # broken source must not kill a run
                    vals = {f"{sname}.scrape_error": repr(e)}
                out.update(vals)
            if self._host_hb:
                agg: Dict[str, int] = {}
                for vals in self._host_hb.values():
                    for k, v in vals.items():
                        if isinstance(v, (int, float)):
                            agg[k] = agg.get(k, 0) + v
                out.update({f"tracker.{k}": v
                            for k, v in sorted(agg.items())})
                out["tracker.hosts_reporting"] = len(self._host_hb)
            if self._engine_hb:
                out.update({f"engine_heartbeat.{k}": v
                            for k, v in sorted(self._engine_hb.items())})
        return out

    def summary(self) -> Dict:
        """The final-summary payload: a scrape + the summary-only info
        (leak report, supervision ledger, plane stats...).  One lock
        hold across both (reentrant into scrape) so the record cannot
        pair fresh info with a scrape from a different instant."""
        with self._lock:
            return {"metrics": self.scrape(), **dict(self._summary_info)}


class MetricsWriter:
    """JSONL writer on a round cadence: one record every ``every_rounds``
    engine rounds (0/1 = every round), plus a final ``summary`` record.
    The file is line-delimited so a crashed run still leaves every record
    written before the crash readable."""

    DEFAULT_EVERY = 50

    def __init__(self, path: str, every_rounds: int = 0):
        self.path = path
        self.every_rounds = int(every_rounds) or self.DEFAULT_EVERY
        self.records_written = 0
        self._t0 = _walltime.monotonic()
        # truncate up front so a run that crashes before the first cadence
        # point doesn't leave a stale previous run's file lying around
        with open(self.path, "w"):
            pass

    def _append(self, record: Dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
        self.records_written += 1

    def maybe_write(self, registry: MetricsRegistry, rounds_done: int,
                    sim_time_ns: int) -> bool:
        if rounds_done % self.every_rounds:
            return False
        self._append({"round": rounds_done,
                      "sim_time_ns": int(sim_time_ns),
                      "wall_s": round(_walltime.monotonic() - self._t0, 6),
                      "metrics": registry.scrape()})
        return True

    def write_summary(self, registry: MetricsRegistry, rounds_done: int,
                      sim_time_ns: int) -> None:
        self._append({"summary": True,
                      "round": rounds_done,
                      "sim_time_ns": int(sim_time_ns),
                      "wall_s": round(_walltime.monotonic() - self._t0, 6),
                      **registry.summary()})


def read_metrics_file(path: str) -> List[Dict]:
    """Parse a metrics JSONL file back into records (tools/tests)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def fleet_source(fleet_plane) -> Callable[[], Dict]:
    """Scrape-time provider for the shared fleet plane (ISSUE 18).

    The ``fleet.*`` namespace an engine run as a batch lane exposes:
    ``fleet.lanes`` (peak concurrent lanes), ``fleet.lane_occupancy``
    (mean filled fraction of the batched launches), ``fleet.launches`` /
    ``fleet.lane_dispatches`` / ``fleet.launches_amortized`` (how many
    per-lane dispatches each device launch carried), and
    ``fleet.shape_classes`` / ``fleet.compiles`` (how many programs XLA
    actually built — the re-arm-without-recompile proof).  The values
    are PLANE-global (every lane of one fleet scrapes the same numbers),
    which is why the fuzz oracles' scrape filter deliberately excludes
    the namespace: it describes the co-schedule, not the scenario."""
    def _scrape() -> Dict:
        return fleet_plane.metrics()
    return _scrape


_default: Optional[MetricsRegistry] = None


def get_metrics() -> MetricsRegistry:
    global _default
    if _default is None:
        _default = MetricsRegistry(enabled=False)
    return _default


def set_metrics(registry: MetricsRegistry) -> None:
    global _default
    _default = registry
