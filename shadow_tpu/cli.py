"""shadow-tpu command-line entry point (reference src/main/core/main.c
main_runShadow, minus the LD_PRELOAD/exec bootstrap which lives in the
native plugin plane).

Usage:
    shadow-tpu [options] config.xml|config.yaml
    shadow-tpu --test          # built-in example simulation
"""

from __future__ import annotations

import os
import sys
import textwrap
from typing import List, Optional

from .core import configuration
from .core.controller import run_simulation
from .core.logger import SimLogger, set_logger
from .core.options import parse_args

# The reference's --test serves /bin/ls (~100KB era-adjusted: we use 16KB)
# to 1000 clients x 10 downloads via a filetransfer plugin (examples.c:10);
# same workload shape here over the full TCP stack.
BUILTIN_TEST_CONFIG = textwrap.dedent("""\
    <shadow stoptime="600">
      <plugin id="filetransfer" path="python:filetransfer" />
      <plugin id="echo" path="python:echo" />
      <host id="server" bandwidthdown="1048576" bandwidthup="1048576">
        <process plugin="filetransfer" starttime="1" arguments="server 80 16384" />
      </host>
      <host id="client" quantity="100" bandwidthdown="10240" bandwidthup="5120">
        <process plugin="filetransfer" starttime="2"
                 arguments="client server 80 10" />
      </host>
      <host id="udpclient" bandwidthdown="10240" bandwidthup="5120">
        <process plugin="echo" starttime="2"
                 arguments="udp client server2 8000 5 512" />
      </host>
      <host id="server2">
        <process plugin="echo" starttime="1" arguments="udp server 8000" />
      </host>
    </shadow>
""")


def main(argv: Optional[List[str]] = None) -> int:
    opts = parse_args(argv)
    set_logger(SimLogger(level=opts.log_level))
    # fail fast on supervision/recovery flags that could only error after
    # minutes of setup: a bad --resume target or malformed --fault-inject
    if opts.resume_path and not (os.path.isfile(opts.resume_path)
                                 or os.path.isdir(opts.resume_path)):
        print(f"error: --resume target not found: {opts.resume_path}",
              file=sys.stderr)
        return 2
    if opts.fault_inject:
        from .core.supervision import parse_fault_inject
        try:
            parse_fault_inject(opts.fault_inject)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    # observability outputs are written at END of run: an unwritable
    # --trace/--metrics destination must fail now, not after the whole
    # simulation has been paid for.  Probe-open in append mode (no
    # truncation of an existing file) — catches a missing or read-only
    # directory, a path that IS a directory, and permission walls alike.
    for flag, path in (("--trace", opts.trace_path),
                       ("--metrics", opts.metrics_path)):
        if path:
            existed = os.path.exists(path)
            try:
                with open(path, "a"):
                    pass
            except OSError as e:
                print(f"error: {flag} {path!r} is not writable: {e}",
                      file=sys.stderr)
                return 2
            if not existed:
                # the probe must not leave a zero-byte artifact behind if
                # a LATER validation step rejects the invocation
                try:
                    os.unlink(path)
                except OSError:
                    pass
    if opts.test_mode:
        cfg = configuration.parse_xml(BUILTIN_TEST_CONFIG)
    elif opts.config_path:
        try:
            cfg = configuration.load(opts.config_path)
        except FileNotFoundError:
            print(f"error: config file not found: {opts.config_path}", file=sys.stderr)
            return 2
        except ValueError as e:
            print(f"error: bad config {opts.config_path}: {e}", file=sys.stderr)
            return 2
    else:
        print("error: provide a config file or --test", file=sys.stderr)
        return 2
    # an explicit --stop-time wins over the config; the config wins over the
    # Options default
    if opts.stop_time_explicit:
        cfg.stop_time_sec = opts.stop_time_sec
    elif not cfg.stop_time_sec:
        cfg.stop_time_sec = opts.stop_time_sec
    if opts.bootstrap_end_sec:
        cfg.bootstrap_end_sec = opts.bootstrap_end_sec
    opts.stop_time_sec = int(cfg.stop_time_sec)
    opts.bootstrap_end_sec = int(cfg.bootstrap_end_sec)
    return run_simulation(opts, cfg)


if __name__ == "__main__":
    sys.exit(main())
