"""SIM110 — the shard-protocol state-machine checker.

parallel/procs.py speaks a tag-based tuple protocol over multiprocessing
pipes: ``("run", ws, we)`` down, ``("out", boxes)`` up, and so on.  A tag
added on one side without a handler on the other, an arity change, or a
reordered round trip does not crash — it HANGS, and only the shard
watchdog turns that hang into a diagnostic.  This pass proves the
protocol at analysis time instead:

1. **extraction** — find the ``Process(target=f)`` spawn; compile the
   child side (``f`` plus the local functions it calls) and the parent
   side (the spawning function plus its local helpers) into small
   op-automata: SEND(tag, arity), RECV{tag -> branch, default}, END,
   ABORT.  ``conn.send(("tag", ...))`` is a SEND — and so is a literal
   tuple routed through a local send wrapper (``self._send(sid,
   ("tag", ...))`` where the wrapper's body sends a bound parameter:
   the self-healing controller wraps every parent-side send for death
   supervision); ``X = conn.recv()`` followed by ``if X[0] == "tag":``
   chains compiles into the RECV's branch table (the remaining
   statements are its default branch).  Calls to local
   functions/methods that (transitively) contain protocol ops are
   inlined; ``return`` is a function exit (jumping to the inline
   continuation, never a loop backedge).  When the ``Process`` spawn
   lives in a protocol-silent helper, the parent root hoists to the
   outermost local caller — the drive loop, not the fork.  Crash-retry
   guards (``if not sent[sid]: send; sent[sid] = True`` / ``if sid not
   in outs: outs[sid] = recv``) compile happy-path-unconditional: the
   flag starts false and flips only in the body, and the re-entry
   where it holds arrives via an except handler.  Fan-out over the
   connection list (``for c in conns: c.send(...)``, ``[recv(c) for c
   in conns]``) collapses to ONE logical peer — shards are symmetric.
   ``raise`` / ``os._exit`` are ABORT (crash states the shard
   supervision owns); sends inside ``except`` handlers register in the
   sent-tag set but stay out of the happy-path automaton.

2. **model check** — explore the product of the two automata with
   bounded message queues (sends never block on a pipe this small).
   Findings: a tag sent with no accepting branch on the peer recv; a
   subscript past the sent arity; a reachable mutual wait (both sides
   at RECV, both queues empty); a peer left at RECV after the other
   side ended CLEANLY.  A child that crashes (ABORT) while the parent
   waits is allowed — ``_recv_supervised`` exists exactly to catch it.

3. **coverage** — a tag a recv matches explicitly but no peer ever
   sends is drift in the other direction and is reported too.

The extraction is scoped to the statement shapes procs.py actually uses
(while/if/for/with/try, comprehension fan-outs, local-call inlining);
anything it cannot model is simply not modeled — the rule
under-approximates rather than guessing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .simlint import Finding, ModuleContext

MAX_PRODUCT_STATES = 50_000
QUEUE_BOUND = 8


# ---------------------------------------------------------------------------
# automaton nodes


class Node:
    __slots__ = ("kind", "tag", "arity", "branches", "branch_use",
                 "default", "succ", "use_idx", "node")

    def __init__(self, kind: str, ast_node: Optional[ast.AST] = None):
        self.kind = kind          # send | recv | branch | end | abort
        self.tag: Optional[str] = None
        self.arity: int = 0
        self.branches: Dict[str, "Node"] = {}
        self.branch_use: Dict[str, int] = {}  # per matched tag subscript
        self.default: Optional["Node"] = None
        self.succ: List["Node"] = []          # send/branch successors
        self.use_idx: int = 0                 # max subscript, default path
        self.node = ast_node                  # anchor for findings


class Automaton:
    def __init__(self, entry: Node, sent: Set[Tuple[str, int]],
                 matched: Dict[str, ast.AST]):
        self.entry = entry
        self.sent = sent          # every (tag, arity) incl. except-handlers
        self.matched = matched    # explicitly matched tag -> anchor node


class _Resume(ast.stmt):
    """Synthetic statement: a tag-branch body that falls through resumes
    the post-dispatch tail it was cut out of."""
    _fields = ()

    def __init__(self, rest, cont, loops, ret):
        super().__init__()
        self.rest = rest
        self.cont = cont
        self.loops = loops
        self.ret = ret


# ---------------------------------------------------------------------------
# extraction


class _SideExtractor:
    """Compile one side's protocol behavior into an automaton."""

    def __init__(self, ctx: ModuleContext, funcs: Dict[str, ast.AST],
                 root_qual: str):
        self.ctx = ctx
        self.funcs = funcs            # qualname -> FunctionDef (module-wide)
        self.root_qual = root_qual
        self.sent: Set[Tuple[str, int]] = set()
        self.matched: Dict[str, ast.AST] = {}
        self._inline_stack: List[str] = []
        self._has_ops_memo: Dict[str, bool] = {}

    # -- op recognition ----------------------------------------------------
    @staticmethod
    def _send_payload(call: ast.Call) -> Optional[Tuple[str, int]]:
        """(tag, arity) when ``call`` is ``X.send(("tag", ...))``."""
        if not (isinstance(call.func, ast.Attribute) and
                call.func.attr == "send" and len(call.args) == 1):
            return None
        arg = call.args[0]
        return _SideExtractor._literal_tag(arg)

    @staticmethod
    def _literal_tag(arg: ast.AST) -> Optional[Tuple[str, int]]:
        if isinstance(arg, ast.Tuple) and arg.elts and \
                isinstance(arg.elts[0], ast.Constant) and \
                isinstance(arg.elts[0].value, str):
            return arg.elts[0].value, len(arg.elts)
        return None

    def _wrapper_send_payload(self, call: ast.Call
                              ) -> Optional[Tuple[str, int]]:
        """(tag, arity) when ``call`` routes a literal tuple through a
        local send wrapper: ``self._send(sid, ("tag", ...))`` where the
        wrapper's body does ``X.send(msg)`` on a bound parameter (the
        self-healing controller wraps every parent-side send so pipe
        death is caught uniformly).  The literal payload is bound by
        parameter position, so the automaton sees the real tag."""
        qual = self._inlineable(call)
        if qual is None:
            return None
        fn = self.funcs[qual]
        params = [a.arg for a in fn.args.args]
        sent_param = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "send" and len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in params:
                sent_param = node.args[0].id
                break
        if sent_param is None:
            return None
        idx = params.index(sent_param)
        if isinstance(call.func, ast.Attribute):
            idx -= 1                       # self.X(...) binds `self`
        if 0 <= idx < len(call.args):
            return self._literal_tag(call.args[idx])
        return None

    @staticmethod
    def _is_recv_call(expr: ast.AST) -> Optional[ast.Call]:
        """The recv Call when ``expr`` is ``X.recv()`` / ``recv(c)`` —
        unwrapping one subscript (``recv(c)[1]``)."""
        if isinstance(expr, ast.Subscript):
            expr = expr.value
        if not isinstance(expr, ast.Call):
            return None
        f = expr.func
        if isinstance(f, ast.Attribute) and f.attr == "recv" and \
                not expr.args:
            return expr
        if isinstance(f, ast.Name) and f.id == "recv":
            return expr
        return None

    @staticmethod
    def _scope_walk(node: ast.AST):
        """Walk in document order without entering nested def bodies."""
        stack = [node]
        while stack:
            cur = stack.pop()
            yield cur
            if cur is not node and isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(reversed(list(ast.iter_child_nodes(cur))))

    def _actions(self, stmt: ast.stmt) -> List[Tuple[str, ast.Call]]:
        """In-order protocol actions under one plain statement: direct
        send/recv ops plus inlineable local calls that transitively
        contain ops."""
        out: List[Tuple[str, ast.Call]] = []
        for node in self._scope_walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if self._send_payload(node) is not None or \
                    self._wrapper_send_payload(node) is not None:
                out.append(("send", node))
            elif self._is_recv_call(node) is not None:
                out.append(("recv", node))
            else:
                qual = self._inlineable(node)
                if qual is not None and self._has_protocol_ops(qual):
                    out.append(("inline", node))
        return out

    def _inlineable(self, call: ast.Call) -> Optional[str]:
        """Qualname of the local function this call resolves to: a bare
        Name matching a known def, or ``self.method``."""
        f = call.func
        name = None
        if isinstance(f, ast.Name):
            name = f.id
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self":
            name = f.attr
        if name is None:
            return None
        for qual in self.funcs:
            if (qual == name or qual.endswith(f".{name}")) and \
                    qual not in self._inline_stack:
                return qual
        return None

    def _has_protocol_ops(self, qual: str) -> bool:
        """Does ``qual`` (transitively through local calls) send/recv?"""
        memo = self._has_ops_memo.get(qual)
        if memo is not None:
            return memo
        self._has_ops_memo[qual] = False        # cycle guard
        fn = self.funcs[qual]
        result = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if self._send_payload(node) is not None or \
                    self._is_recv_call(node) is not None:
                result = True
                break
            sub = self._inlineable(node)
            if sub is not None and sub != qual and \
                    self._has_protocol_ops(sub):
                result = True
                break
        self._has_ops_memo[qual] = result
        return result

    # -- compilation -------------------------------------------------------
    def build(self) -> Automaton:
        entry = self._compile_func(self.root_qual, Node("end"))
        return Automaton(entry, self.sent, self.matched)

    def _compile_func(self, qual: str, cont: Node) -> Node:
        self._inline_stack.append(qual)
        try:
            return self._compile_stmts(list(self.funcs[qual].body), cont,
                                       [], cont)
        finally:
            self._inline_stack.pop()

    def _compile_stmts(self, stmts: List[ast.stmt], cont: Node,
                       loops: List[Tuple[Node, Node]],
                       ret: Optional[Node] = None) -> Node:
        """Compile a statement list; ``loops`` is the (continue_target,
        break_target) stack and ``ret`` the enclosing function's exit
        continuation (``return`` jumps there — NOT the loop backedge;
        an unmodeled return inside ``_recv_supervised``'s watchdog loop
        would otherwise fall through into a phantom second recv)."""
        if not stmts:
            return cont
        stmt, rest = stmts[0], stmts[1:]

        if isinstance(stmt, _Resume):
            return self._compile_stmts(stmt.rest, stmt.cont, stmt.loops,
                                       stmt.ret)
        if isinstance(stmt, ast.Return):
            tail = ret if ret is not None else cont
            actions = self._actions(stmt)
            if actions:
                return self._chain_actions(stmt, actions, tail)
            return tail
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # a nested def is a DEFINITION, not execution — its body only
            # enters the automaton where the function is called
            return self._compile_stmts(rest, cont, loops, ret)

        # -- msg = conn.recv() followed by tag-dispatch ifs ----------------
        recv_assign = self._recv_assignment(stmt)
        if recv_assign is not None:
            var, recv_expr = recv_assign
            node = Node("recv", stmt)
            use = self._recv_use_idx(recv_expr)
            # `x = conn.recv()[1]` binds the PAYLOAD, not the message
            # tuple — its subscripts/comparisons must not be mistaken
            # for tag dispatch or message-arity use
            is_whole_msg = not isinstance(recv_expr, ast.Subscript)
            tagvars = {var} if var and is_whole_msg else set()
            i = 0
            while i < len(rest):            # kind = msg[0] aliases
                alias = self._tag_alias(rest[i], tagvars)
                if alias is None:
                    break
                tagvars.add(alias)
                i += 1
            else_body = None
            while i < len(rest):            # if kind == "x": dispatch
                parsed = self._tag_branch(rest[i], tagvars)
                if parsed is None:
                    break
                branches, else_body = parsed
                for tag, body in branches:
                    self.matched.setdefault(tag, rest[i])
                    node.branch_use[tag] = self._max_use(list(body),
                                                         tagvars)
                    node.branches[tag] = self._compile_stmts(
                        list(body) + [_Resume(rest[i + 1:], cont, loops, ret)],
                        cont, loops, ret)
                i += 1
                if else_body is not None:
                    break       # the else IS the unknown-tag path
            if else_body is not None:
                node.default = self._compile_stmts(
                    list(else_body) + [_Resume(rest[i:], cont, loops, ret)],
                    cont, loops, ret)
                node.use_idx = max(use, self._max_use(list(else_body),
                                                      tagvars))
            else:
                node.default = self._compile_stmts(rest[i:], cont,
                                                   loops, ret)
                node.use_idx = max(use, self._max_use(rest[i:], tagvars))
            return node

        # -- control flow --------------------------------------------------
        if isinstance(stmt, ast.While):
            after = self._compile_stmts(rest, cont, loops, ret)
            header = Node("branch", stmt)
            body = self._compile_stmts(list(stmt.body), header,
                                       loops + [(header, after)], ret)
            # `while True:` only exits through break — a phantom exit
            # edge would let the model skip mandatory protocol turns
            infinite = isinstance(stmt.test, ast.Constant) and \
                bool(stmt.test.value)
            header.succ = [body] if infinite else [body, after]
            return header
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            # fan-out loop over the symmetric peer set: body ONCE
            after = self._compile_stmts(rest, cont, loops, ret)
            return self._compile_stmts(list(stmt.body), after,
                                       loops + [(after, after)], ret)
        if isinstance(stmt, ast.If):
            if self._retry_guard(stmt):
                # a crash-retry guard (`if not sent[sid]: send(...);
                # sent[sid] = True` / `if sid not in outs: outs[sid] =
                # recv(...)`) is ALWAYS taken on the happy path: its flag
                # starts false and flips only inside the body, and the
                # re-entry where it can be true arrives via an except
                # handler — a path the automaton already scopes out as
                # crash-state coverage.  Compiling it as a nondeterministic
                # branch would let the model skip a mandatory send yet
                # still reach the paired recv: a phantom mutual wait.
                return self._compile_stmts(list(stmt.body) + rest, cont,
                                           loops, ret)
            after = self._compile_stmts(rest, cont, loops, ret)
            br = Node("branch", stmt)
            br.succ = [self._compile_stmts(list(stmt.body), after, loops,
                                           ret),
                       self._compile_stmts(list(stmt.orelse), after, loops,
                                           ret)]
            return br
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._compile_stmts(list(stmt.body) + rest, cont,
                                       loops, ret)
        if isinstance(stmt, ast.Try):
            # except-handler sends register as crash-path coverage only
            for h in stmt.handlers:
                for sub in ast.walk(h):
                    if isinstance(sub, ast.Call):
                        p = self._send_payload(sub) or \
                            self._wrapper_send_payload(sub)
                        if p is not None:
                            self.sent.add(p)
            return self._compile_stmts(
                list(stmt.body) + list(stmt.finalbody) + rest, cont, loops,
                ret)
        if isinstance(stmt, ast.Break):
            return loops[-1][1] if loops else cont
        if isinstance(stmt, ast.Continue):
            return loops[-1][0] if loops else cont
        if isinstance(stmt, ast.Raise):
            return Node("abort", stmt)
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            r = self.ctx.resolve(stmt.value.func)
            if r is not None and r[0] in ("os._exit", "sys.exit"):
                return Node("abort", stmt)

        # -- plain statement: chain its protocol actions in order ----------
        actions = self._actions(stmt)
        if actions:
            return self._chain_actions(stmt, actions,
                                       self._compile_stmts(rest, cont,
                                                           loops, ret))
        return self._compile_stmts(rest, cont, loops, ret)

    def _chain_actions(self, stmt: ast.stmt,
                       actions: List[Tuple[str, ast.Call]],
                       cont: Node) -> Node:
        head = cont
        for kind, call in reversed(actions):
            if kind == "send":
                payload = self._send_payload(call) or \
                    self._wrapper_send_payload(call)
                n = Node("send", call)
                n.tag, n.arity = payload
                self.sent.add(payload)
                n.succ = [head]
                head = n
            elif kind == "recv":
                n = Node("recv", call)
                n.use_idx = self._subscript_on(stmt, call)
                n.default = head
                head = n
            else:                          # inline
                qual = self._inlineable(call)
                if qual is not None:
                    head = self._compile_func(qual, head)
        return head

    @staticmethod
    def _recv_use_idx(expr: ast.AST) -> int:
        if isinstance(expr, ast.Subscript) and \
                isinstance(expr.slice, ast.Constant) and \
                isinstance(expr.slice.value, int):
            return expr.slice.value
        return 0

    @staticmethod
    def _subscript_on(stmt: ast.stmt, call: ast.Call) -> int:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Subscript) and node.value is call and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, int):
                return node.slice.value
        return 0

    def _recv_assignment(self, stmt: ast.stmt
                         ) -> Optional[Tuple[Optional[str], ast.AST]]:
        """``X = conn.recv()`` / ``X = conn.recv()[k]`` — the
        tag-dispatchable form (comprehension fan-outs bind lists and are
        handled as plain recv actions instead)."""
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                not self._contains_comprehension(stmt.value) and \
                self._is_recv_call(stmt.value) is not None:
            t = stmt.targets[0]
            return (t.id if isinstance(t, ast.Name) else None, stmt.value)
        return None

    @staticmethod
    def _contains_comprehension(expr: ast.AST) -> bool:
        return any(isinstance(n, (ast.ListComp, ast.SetComp,
                                  ast.GeneratorExp))
                   for n in ast.walk(expr))

    @staticmethod
    def _tag_alias(stmt: ast.stmt, tagvars: Set[str]) -> Optional[str]:
        """``kind = msg[0]`` -> 'kind'."""
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Subscript) and \
                isinstance(stmt.value.value, ast.Name) and \
                stmt.value.value.id in tagvars and \
                isinstance(stmt.value.slice, ast.Constant) and \
                stmt.value.slice.value == 0:
            return stmt.targets[0].id
        return None

    @staticmethod
    def _tag_branch(stmt: ast.stmt, tagvars: Set[str]
                    ) -> Optional[Tuple[List[Tuple[str, List[ast.stmt]]],
                                        Optional[List[ast.stmt]]]]:
        """``if kind == "x": ...`` / ``if msg[0] == "x": ...`` (elif
        chains included) -> ([(tag, body)], else_body).  A trailing
        non-If ``else`` is the unknown-tag path — its body must enter
        the automaton (a raising else means "no handler"; a sending
        else registers its tags), never be silently dropped."""
        out: List[Tuple[str, List[ast.stmt]]] = []
        cur = stmt
        while isinstance(cur, ast.If):
            t = cur.test
            tag = None
            if isinstance(t, ast.Compare) and len(t.ops) == 1 and \
                    isinstance(t.ops[0], ast.Eq) and \
                    isinstance(t.comparators[0], ast.Constant) and \
                    isinstance(t.comparators[0].value, str):
                left = t.left
                if isinstance(left, ast.Name) and left.id in tagvars:
                    tag = t.comparators[0].value
                elif isinstance(left, ast.Subscript) and \
                        isinstance(left.value, ast.Name) and \
                        left.value.id in tagvars and \
                        isinstance(left.slice, ast.Constant) and \
                        left.slice.value == 0:
                    tag = t.comparators[0].value
            if tag is None:
                # a non-tag If mid-chain: the remaining chain (this If
                # included) is the default path — compile it there so
                # its sends/raises are never silently dropped
                return (out, [cur]) if out else None
            out.append((tag, list(cur.body)))
            if len(cur.orelse) == 1 and isinstance(cur.orelse[0], ast.If):
                cur = cur.orelse[0]
            elif cur.orelse:
                return out, list(cur.orelse)
            else:
                break
        return (out, None) if out else None

    @staticmethod
    def _expr_key(node: ast.AST) -> str:
        """Structural identity for guard/target matching, Load/Store
        context ignored (``run_sent[sid]`` tested vs assigned)."""
        import re
        return re.sub(r",?\s*ctx=(Load|Store|Del)\(\)", "",
                      ast.dump(node))

    @staticmethod
    def _retry_guard(stmt: ast.If) -> bool:
        """``if not flag[i]: ...; flag[i] = True`` or ``if k not in d:
        d[k] = ...`` with no else — the self-healing re-drive idiom (the
        body sets the very condition it tested, so the first reach on the
        happy path always executes it)."""
        if stmt.orelse:
            return False
        t = stmt.test
        key = _SideExtractor._expr_key
        if isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not):
            flag = key(t.operand)
            for sub in stmt.body:
                for n in ast.walk(sub):
                    if isinstance(n, ast.Assign) and \
                            isinstance(n.value, ast.Constant) and \
                            n.value.value is True and \
                            any(key(tg) == flag for tg in n.targets):
                        return True
            return False
        if isinstance(t, ast.Compare) and len(t.ops) == 1 and \
                isinstance(t.ops[0], ast.NotIn) and \
                isinstance(t.comparators[0], ast.Name):
            needle, container = key(t.left), t.comparators[0].id
            for sub in stmt.body:
                for n in ast.walk(sub):
                    if isinstance(n, ast.Assign):
                        for tg in n.targets:
                            if isinstance(tg, ast.Subscript) and \
                                    isinstance(tg.value, ast.Name) and \
                                    tg.value.id == container and \
                                    key(tg.slice) == needle:
                                return True
        return False

    @staticmethod
    def _max_use(stmts: List[ast.stmt], tagvars: Set[str]) -> int:
        use = 0
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Subscript) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in tagvars and \
                        isinstance(node.slice, ast.Constant) and \
                        isinstance(node.slice.value, int):
                    use = max(use, node.slice.value)
        return use


# ---------------------------------------------------------------------------
# product model check


def _expand(node: Node, seen: Set[int]) -> List[Node]:
    """Skip over nondeterministic branch nodes to the reachable ops."""
    if id(node) in seen:
        return []
    seen.add(id(node))
    if node.kind != "branch":
        return [node]
    out: List[Node] = []
    for s in node.succ:
        out.extend(_expand(s, seen))
    return out


class _Check:
    def __init__(self, parent: Automaton, child: Automaton):
        self.parent = parent
        self.child = child
        self.findings: List[Tuple[str, ast.AST]] = []
        self._reported: Set[str] = set()

    def _report(self, key: str, msg: str, node: ast.AST) -> None:
        if key not in self._reported:
            self._reported.add(key)
            self.findings.append((msg, node))

    def run(self) -> List[Tuple[str, ast.AST]]:
        seen: Set[Tuple] = set()
        frontier = [(self.parent.entry, self.child.entry, (), ())]
        states = 0
        while frontier and states < MAX_PRODUCT_STATES:
            p, c, q_pc, q_cp = frontier.pop()
            for pn in _expand(p, set()):
                for cn in _expand(c, set()):
                    key = (id(pn), id(cn), q_pc, q_cp)
                    if key in seen:
                        continue
                    seen.add(key)
                    states += 1
                    frontier.extend(self._step(pn, cn, q_pc, q_cp))
        if frontier and states >= MAX_PRODUCT_STATES:
            # an exhausted budget must NOT read as "verified clean" —
            # unexplored interleavings could hide the very drift this
            # pass exists to catch
            self._report(
                "state-budget",
                f"protocol model check exhausted its "
                f"{MAX_PRODUCT_STATES}-state budget with interleavings "
                "unexplored — the protocol is too branchy to verify; "
                "simplify it or raise MAX_PRODUCT_STATES",
                self.parent.entry.node)
        return self.findings

    def _step(self, p: Node, c: Node, q_pc: Tuple,
              q_cp: Tuple) -> List[Tuple]:
        out: List[Tuple] = []
        progress = False
        if p.kind == "send" and len(q_pc) < QUEUE_BOUND:
            out.append((p.succ[0], c, q_pc + ((p.tag, p.arity, p.node),),
                        q_cp))
            progress = True
        if c.kind == "send" and len(q_cp) < QUEUE_BOUND:
            out.append((p, c.succ[0], q_pc,
                        q_cp + ((c.tag, c.arity, c.node),)))
            progress = True
        if p.kind == "recv" and q_cp:
            nxt = self._consume(p, q_cp[0], "parent")
            if nxt is not None:
                out.append((nxt, c, q_pc, q_cp[1:]))
            progress = True
        if c.kind == "recv" and q_pc:
            nxt = self._consume(c, q_pc[0], "child")
            if nxt is not None:
                out.append((p, nxt, q_pc[1:], q_cp))
            progress = True
        if not progress:
            self._stuck(p, c, q_pc, q_cp)
        return out

    def _consume(self, recv: Node, msg: Tuple, side: str) -> Optional[Node]:
        tag, arity, send_node = msg
        branch = recv.branches.get(tag)
        use = recv.branch_use.get(tag, 0) if branch is not None \
            else recv.use_idx
        if branch is None:
            # a default branch that immediately raises IS the
            # unknown-tag path — sending into it is a missing handler,
            # not a legitimate crash state
            if recv.default is None or recv.default.kind == "abort":
                self._report(
                    f"unhandled:{side}:{tag}",
                    f'tag "{tag}" is sent but the {side} recv at line '
                    f"{getattr(recv.node, 'lineno', '?')} has no handler "
                    "for it (protocol drift: this hangs at runtime)",
                    send_node)
                return None
            branch = recv.default
        if use >= arity:
            self._report(
                f"arity:{side}:{tag}",
                f'tag "{tag}" is sent with arity {arity} but the {side} '
                f"side reads element [{use}] — arity mismatch",
                send_node)
        return branch

    def _stuck(self, p: Node, c: Node, q_pc: Tuple, q_cp: Tuple) -> None:
        if q_pc or q_cp:
            return                # a message is in flight; not a wait
        if p.kind == "recv" and c.kind == "recv":
            self._report(
                "deadlock", "reachable mutual wait: parent and child are "
                "both blocked in recv with no message in flight — the "
                "round-trip ordering is inconsistent", p.node)
        elif p.kind == "recv" and c.kind == "end":
            self._report(
                "parent-hang", "child can finish cleanly while the "
                "parent still waits in recv — a reply or final message "
                "is missing from the child side", p.node)
        elif c.kind == "recv" and p.kind == "end":
            self._report(
                "child-hang", "parent can finish cleanly while the "
                "child still waits in recv — the stop tag never "
                "reaches it", c.node)


# ---------------------------------------------------------------------------
# the rule


class ShardProtocolRule:
    """Prove the parent<->shard tag protocol round-trips (see the module
    docstring): every sent tag handled, arities match, no reachable
    mutual wait, no handler for a tag nobody sends.

    Duck-typed against race_rules.PackageRule (not imported — this
    module must load standalone to avoid an import cycle with the
    catalog installation)."""

    id = "SIM110"
    severity = "error"
    short = ("shard-protocol drift: sent tag without a handler, arity "
             "mismatch, or inconsistent round-trip ordering")

    def finding(self, relpath: str, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, self.severity, relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)

    def run(self, pkg) -> List[Finding]:
        out: List[Finding] = []
        for rel, mc in sorted(pkg.concurrency.items()):
            pair = self._find_pair(mc)
            if pair is None:
                continue
            out.extend(self.check_module(mc.ctx, *pair))
        return out

    @staticmethod
    def _find_pair(mc) -> Optional[Tuple[str, str]]:
        """(parent_qual, child_qual) when this module spawns a
        ``Process(target=f)`` whose target is a local function."""
        ctx = mc.ctx
        for node in ctx.walk(ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name != "Process":
                continue
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            if not isinstance(target, ast.Name):
                continue
            child = next((q for q in sorted(mc.funcs)
                          if q == target.id or
                          q.endswith(f".{target.id}")), None)
            if child is None:
                continue
            fn = ctx.enclosing_function(node)
            if fn is None:
                continue
            parent = next((q for q, fi in mc.funcs.items()
                           if fi.node is fn), None)
            if parent is None:
                continue
            return ShardProtocolRule._hoist_root(mc, parent, child), child
        return None

    @staticmethod
    def _hoist_root(mc, parent: str, child: str) -> str:
        """Root the parent automaton at the OUTERMOST local caller of the
        spawning function.  The self-healing controller moved the
        ``Process(...)`` call into a respawn helper (``_spawn``) that is
        itself protocol-silent; the conversation lives in the drive loop
        that (transitively) calls it.  Hoisting walks the local call
        graph upward and picks the unique caller no other caller reaches;
        when the spawn already sits in the driver (no local callers),
        this is the identity."""
        edges: Dict[str, Set[str]] = {}
        for q, fi in mc.funcs.items():
            out: Set[str] = set()
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = None
                if isinstance(f, ast.Name):
                    name = f.id
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self":
                    name = f.attr
                if name is None:
                    continue
                for q2 in mc.funcs:
                    if q2 == name or q2.endswith(f".{name}"):
                        out.add(q2)
            edges[q] = out
        callers = {parent}
        changed = True
        while changed:
            changed = False
            for q, out in edges.items():
                if q != child and q not in callers and out & callers:
                    callers.add(q)
                    changed = True
        roots = [q for q in sorted(callers)
                 if not any(q in edges[o] for o in sorted(callers)
                            if o != q)]
        return roots[0] if len(roots) == 1 else parent

    def check_module(self, ctx: ModuleContext, parent_qual: str,
                     child_qual: str) -> List[Finding]:
        """Extract + model-check one module's protocol pair (also the
        fixture entry point used by the tests)."""
        funcs: Dict[str, ast.AST] = {}
        for node in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            names = [node.name]
            cur = ctx.parent(node)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    names.append(cur.name)
                cur = ctx.parent(cur)
            funcs[".".join(reversed(names))] = node
        parent = _SideExtractor(ctx, funcs, parent_qual).build()
        child = _SideExtractor(ctx, funcs, child_qual).build()
        findings: List[Finding] = []
        for msg, node in _Check(parent, child).run():
            findings.append(self.finding(ctx.relpath, node, msg))
        # drift in the other direction: matched-but-never-sent tags
        child_tags = {t for t, _ in child.sent}
        parent_tags = {t for t, _ in parent.sent}
        for tag, node in sorted(parent.matched.items()):
            if tag not in child_tags:
                findings.append(self.finding(
                    ctx.relpath, node,
                    f'parent matches tag "{tag}" but the child never '
                    "sends it — stale handler (protocol drift)"))
        for tag, node in sorted(child.matched.items()):
            if tag not in parent_tags:
                findings.append(self.finding(
                    ctx.relpath, node,
                    f'child matches tag "{tag}" but the parent never '
                    "sends it — stale handler (protocol drift)"))
        return findings
