"""simjit: whole-package compile-surface static analysis.

Where simlint proves per-file determinism contracts and simrace proves
package-wide concurrency contracts, simjit proves the COMPILE SURFACE:
it parses every module, resolves every jit program identity
(jit_rules.JitPackage — decorated defs, ``partial(jax.jit, ...)``
wrappers, vmapped/shard_map-wrapped variants, factory functions,
``self`` attribute handles, literal-capped variant caches) and runs the
SIM3xx catalog over it:

=======  ========  ====================================================
SIM301   error     recompile hazard (unbucketed widths at a jit
                   boundary, varying traced closures)
SIM302   error     implicit host<->device sync inside the pipelined
                   dispatch window
SIM303   error     dtype-promotion drift against the non-negative
                   int64 contract in kernel-tagged files
SIM304   error     donation misuse (shared donated jit, donation on
                   the CPU backend)
SIM305   error     compile-key count drifted from the checked-in
                   [tool.simjit.budget] table
=======  ========  ====================================================

Usage::

    python -m shadow_tpu.analysis.simjit [paths...] [--json]
        [--list-rules] [--config pyproject.toml] [--diff BASE]

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.

Everything else is shared with the family: the severity model, the
``# simjit: disable=SIMxxx -- <why>`` pragma syntax (one pragma
vocabulary across simlint/simrace/simtwin/simjit; each tool judges
staleness only for the rules it RUNS), the per-rule path allowlists
(``[tool.simjit.allow]``, unioned with ``[tool.simlint.allow]``), and
the JSON schema (``"tool": "simjit"``).  ``--diff BASE`` still analyzes
the WHOLE package (the model is cross-module — a second call site added
in an untouched file completes a SIM304 pair) but reports only findings
in files changed since the git ref.

Two config sections are simjit's own:

``[tool.simjit]`` — ``kernel = [globs]`` names the kernel-tagged files
SIM303's int64-contract arithmetic checks run over (default: the ops/
and mesh kernel planes).

``[tool.simjit.budget]`` — the checked-in compile budget.  Quoted keys
ending in ``.py`` are module paths whose statically enumerable compile-
key count must EQUAL the declared value (SIM305 fails on either
direction of drift).  Dotted non-module keys (``fleet.compiles``,
``device_plane.sharded_variants``) budget the RUNTIME caches; simjit
statically pins literal cache caps against them and ``simfleet smoke``
cross-checks the measured counts via :func:`crosscheck_budget`.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

from . import jit_rules
from .simlint import (Config, Finding, LintResult, ModuleContext,
                      _toml_section, apply_pragmas, changed_py_files,
                      iter_py_files, load_config)

# SIM303's default kernel-tagged set: the device-kernel planes where the
# non-negative int64 contract is load-bearing (overridden by
# [tool.simjit] kernel = [...])
DEFAULT_KERNEL = [
    "shadow_tpu/ops/*.py",
    "shadow_tpu/parallel/mesh/*.py",
    "shadow_tpu/fleet/plane.py",
]

# quoted-key scalar lines inside [tool.simjit.budget]:  "path" = 3
_BUDGET_LINE_RE = re.compile(r'^"((?:[^"\\]|\\.)+)"\s*=\s*(\d+)\s*(?:#.*)?$')


def parse_budget(text: str) -> Dict[str, int]:
    """The ``[tool.simjit.budget]`` table from a pyproject document.
    The shared ``_toml_section`` helper only parses bare-identifier
    array keys; budget keys are quoted paths mapping to integers, so
    this dedicated scan handles exactly that shape."""
    out: Dict[str, int] = {}
    in_section = False
    for raw in text.splitlines():
        line = raw.strip()
        if line.startswith("["):
            in_section = line == "[tool.simjit.budget]"
            continue
        if not in_section or not line or line.startswith("#"):
            continue
        m = _BUDGET_LINE_RE.match(line)
        if m:
            out[m.group(1)] = int(m.group(2))
    return out


def load_jit_config(path: Optional[str], start: Optional[str] = None
                    ) -> Tuple[Config, Dict[str, int], List[str]]:
    """(shared Config with [tool.simjit.allow] unioned in, budget table,
    kernel globs).  Missing file/sections degrade to the shared config,
    an empty budget, and the default kernel set."""
    config = load_config(path, start=start)
    if path is None:
        cand = os.path.join(config.root, "pyproject.toml")
        path = cand if os.path.isfile(cand) else None
    budget: Dict[str, int] = {}
    kernel = list(DEFAULT_KERNEL)
    if path is not None:
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            return config, budget, kernel
        budget = parse_budget(text)
        top = _toml_section(text, "tool.simjit")
        if "kernel" in top:
            kernel = top["kernel"]
        for rule_id, pats in _toml_section(text,
                                           "tool.simjit.allow").items():
            config.allow.setdefault(rule_id.upper(), []).extend(pats)
    return config, budget, kernel


def default_rules() -> List[jit_rules.JitRule]:
    return list(jit_rules.CATALOG)


def active_ids(rules: Optional[List] = None) -> Set[str]:
    return {r.id for r in (rules or default_rules())} | {"SIM000"}


def jit_contexts(contexts: List[ModuleContext],
                 config: Optional[Config] = None,
                 rules: Optional[List] = None,
                 budget: Optional[Dict[str, int]] = None,
                 kernel: Optional[List[str]] = None) -> List[Finding]:
    """Run the compile-surface passes over parsed modules and apply the
    pragma / allowlist machinery — the core shared by the CLI and the
    fixtures."""
    config = config or Config()
    rules = rules if rules is not None else default_rules()
    pkg = jit_rules.JitPackage(contexts, config, budget=budget,
                               kernel=kernel if kernel is not None
                               else DEFAULT_KERNEL)
    per_module: Dict[str, List[Finding]] = {c.relpath: [] for c in contexts}
    loose: List[Finding] = []
    for rule in rules:
        for f in rule.run(pkg):
            if config.is_allowed(f.rule, f.path):
                continue
            if f.path in per_module:
                per_module[f.path].append(f)
            else:
                # findings anchored outside the parsed set (the stale-
                # budget pyproject.toml anchor) can't carry pragmas
                loose.append(f)
    out: List[Finding] = list(loose)
    ids = {r.id for r in rules} | {"SIM000"}
    for ctx in contexts:
        out.extend(apply_pragmas(ctx, per_module.get(ctx.relpath, []), ids))
    return sorted(out, key=Finding.sort_key)


def jit_sources(sources: Dict[str, str],
                config: Optional[Config] = None,
                rules: Optional[List] = None,
                budget: Optional[Dict[str, int]] = None,
                kernel: Optional[List[str]] = None) -> List[Finding]:
    """Analyze in-memory modules ({relpath: source}) — the test-fixture
    entry point (the package analog of simlint.lint_source)."""
    contexts: List[ModuleContext] = []
    bad: List[Finding] = []
    for rel, src in sorted(sources.items()):
        try:
            contexts.append(ModuleContext(rel, src))
        except SyntaxError as e:
            bad.append(Finding("SIM000", "error", rel, e.lineno or 1,
                               (e.offset or 1) - 1,
                               f"file does not parse: {e.msg}"))
    return sorted(jit_contexts(contexts, config, rules, budget, kernel)
                  + bad, key=Finding.sort_key)


def jit_paths(paths: List[str], config: Optional[Config] = None,
              rules: Optional[List] = None,
              only: Optional[Set[str]] = None,
              budget: Optional[Dict[str, int]] = None,
              kernel: Optional[List[str]] = None) -> LintResult:
    """Analyze every .py under ``paths`` as one package.  ``only``
    restricts REPORTING (not analysis — the model is cross-module) to
    the given relpaths, the ``--diff BASE`` mode.  When ``budget`` /
    ``kernel`` are None they are loaded from the nearest pyproject."""
    if config is None or budget is None or kernel is None:
        lc, lb, lk = load_jit_config(None,
                                     start=paths[0] if paths else ".")
        config = config if config is not None else lc
        budget = budget if budget is not None else lb
        kernel = kernel if kernel is not None else lk
    files = iter_py_files(paths, config)
    contexts: List[ModuleContext] = []
    findings: List[Finding] = []
    for abspath, rel in files:
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("SIM000", "error", rel, 1, 0,
                                    f"file is unreadable: {e}"))
            continue
        try:
            contexts.append(ModuleContext(rel, source))
        except SyntaxError as e:
            findings.append(Finding("SIM000", "error", rel, e.lineno or 1,
                                    (e.offset or 1) - 1,
                                    f"file does not parse: {e.msg}"))
    findings.extend(jit_contexts(contexts, config, rules, budget, kernel))
    if only is not None:
        findings = [f for f in findings if f.path in only]
    findings.sort(key=Finding.sort_key)
    return LintResult(findings, len(files), tool="simjit")


# ---------------------------------------------------------------------------
# the runtime half of the SIM305 cross-check (wired into `simfleet smoke`)


def crosscheck_budget(measured: Dict[str, int],
                      budget: Dict[str, int],
                      require_nonzero: Tuple[str, ...] = ()) -> List[str]:
    """Compare RUNTIME cache counts against the checked-in budget's
    runtime keys (the dotted non-``.py`` entries) and fail on either
    direction of drift: a measured count ABOVE its budget means the
    compile surface grew without a conscious bump; a budgeted cache the
    run never even reported means the budget went stale against a
    dropped metric.  A measured ZERO is fine for mode-gated caches (the
    sharded-variant cache only engages on the mesh path — its VALUE is
    pinned statically by SIM305's literal-cap check) but fails for keys
    in ``require_nonzero``, the caches the calling smoke is guaranteed
    to exercise (``fleet.compiles``: the gate already demands launches,
    and a launch without a first compile is impossible).  Returns
    problem strings; empty = consistent."""
    problems: List[str] = []
    runtime = {k: v for k, v in sorted(budget.items())
               if not k.endswith(".py")}
    for key, declared in runtime.items():
        got = measured.get(key)
        if got is None:
            problems.append(
                f"budgeted runtime cache `{key}` (= {declared}) was not "
                "measured — stale budget entry or dropped metric")
        elif got > declared:
            problems.append(
                f"measured `{key}` = {got} exceeds its "
                f"[tool.simjit.budget] = {declared} — the compile "
                "surface grew; bump the budget consciously or fix the "
                "recompile churn")
        elif got == 0 and key in require_nonzero:
            problems.append(
                f"measured `{key}` = 0 against a budget of {declared} — "
                "the budgeted cache never compiled in a run that must "
                "exercise it (dead path or stale entry)")
    for key in sorted(measured):
        if "." in key and not key.endswith(".py") and key not in runtime:
            problems.append(
                f"runtime cache `{key}` = {measured[key]} has no "
                "[tool.simjit.budget] entry — declare it so drift is "
                "checkable")
    return problems


def load_runtime_budget(start: str = ".") -> Dict[str, int]:
    """The runtime (non-module) budget entries from the nearest
    pyproject — the `simfleet smoke` entry point."""
    _cfg, budget, _kernel = load_jit_config(None, start=start)
    return {k: v for k, v in budget.items() if not k.endswith(".py")}


# ---------------------------------------------------------------------------
# CLI


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="simjit",
        description="compile-surface static analysis (shadow-tpu)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: shadow_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON on stdout")
    ap.add_argument("--config", default=None,
                    help="pyproject.toml carrying [tool.simjit] "
                         "(default: nearest to the first path)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--diff", metavar="BASE", default=None,
                    help="report only findings in .py files changed "
                         "since git ref BASE (analysis stays package-"
                         "wide)")
    args = ap.parse_args(argv)
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.severity:<7}  {r.short}")
        return 0
    paths = args.paths or ["shadow_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"simjit: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    config, budget, kernel = load_jit_config(args.config, start=paths[0])
    only = None
    if args.diff is not None:
        try:
            only = changed_py_files(args.diff, config.root)
        except RuntimeError as e:
            print(f"simjit: --diff {args.diff}: {e}", file=sys.stderr)
            return 2
    result = jit_paths(paths, config, rules, only=only, budget=budget,
                       kernel=kernel)
    if args.json:
        json.dump(result.to_json(), sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in result.unsuppressed:
            print(f.render())
        print(f"simjit: {len(result.unsuppressed)} finding(s), "
              f"{len(result.suppressed)} suppressed, "
              f"{result.files} file(s)")
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
