"""simrace: whole-package concurrency & shard-protocol static analysis.

Where simlint proves per-file determinism contracts, simrace analyzes the
PACKAGE: it parses every module, builds the concurrency model
(race_rules.PackageContext — lock identities, lock regions, thread
targets, same-module call graphs) and runs the SIM1xx catalog over it:

=======  ========  ====================================================
SIM101   error     lock-order inversion anywhere in the package
SIM102   error     thread-shared state mutated/read without one lock
SIM103   warning   blocking call while holding a lock
SIM110   error     shard-protocol drift (tag/arity/ordering — see
                   protocol.py for the state-machine construction)
=======  ========  ====================================================

Usage::

    python -m shadow_tpu.analysis.simrace [paths...] [--json]
        [--list-rules] [--config pyproject.toml] [--diff BASE]

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.

Everything else is shared with simlint: the severity model, the
``# simlint: disable=SIMxxx -- <why>`` pragma syntax (one pragma
vocabulary for both tools; each judges staleness only for the rules it
runs), the ``[tool.simlint.allow]`` per-rule path allowlists, and the
JSON schema (``"tool": "simrace"``).  ``--diff BASE`` still analyzes the
WHOLE package (the rules are cross-module — a lock edge added in an
untouched file can complete an inversion) but reports only findings in
files changed since the git ref, which is what an incremental CI lane
wants.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Set

from . import race_rules
from .simlint import (Config, Finding, LintResult, ModuleContext,
                      apply_pragmas, changed_py_files, iter_py_files,
                      load_config)


def default_rules() -> List[race_rules.PackageRule]:
    return list(race_rules.CATALOG)


def active_ids(rules: Optional[List] = None) -> Set[str]:
    return {r.id for r in (rules or default_rules())} | {"SIM000"}


def race_contexts(contexts: List[ModuleContext],
                  config: Optional[Config] = None,
                  rules: Optional[List] = None) -> List[Finding]:
    """Run the package passes over parsed modules and apply pragma /
    allowlist machinery — the core shared by the CLI and the fixtures."""
    config = config or Config()
    rules = rules if rules is not None else default_rules()
    pkg = race_rules.PackageContext(contexts, config)
    per_module: Dict[str, List[Finding]] = {c.relpath: [] for c in contexts}
    for rule in rules:
        for f in rule.run(pkg):
            if not config.is_allowed(f.rule, f.path):
                per_module.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    ids = {r.id for r in rules} | {"SIM000"}
    for ctx in contexts:
        out.extend(apply_pragmas(ctx, per_module.get(ctx.relpath, []), ids))
    return sorted(out, key=Finding.sort_key)


def race_sources(sources: Dict[str, str],
                 config: Optional[Config] = None,
                 rules: Optional[List] = None) -> List[Finding]:
    """Analyze in-memory modules ({relpath: source}) — the test-fixture
    entry point (the package analog of simlint.lint_source)."""
    contexts: List[ModuleContext] = []
    bad: List[Finding] = []
    for rel, src in sorted(sources.items()):
        try:
            contexts.append(ModuleContext(rel, src))
        except SyntaxError as e:
            bad.append(Finding("SIM000", "error", rel, e.lineno or 1,
                               (e.offset or 1) - 1,
                               f"file does not parse: {e.msg}"))
    return sorted(race_contexts(contexts, config, rules) + bad,
                  key=Finding.sort_key)


def race_paths(paths: List[str], config: Optional[Config] = None,
               rules: Optional[List] = None,
               only: Optional[Set[str]] = None) -> LintResult:
    """Analyze every .py under ``paths`` as one package.  ``only``
    restricts REPORTING (not analysis — the model is cross-module) to
    the given relpaths, the ``--diff BASE`` mode."""
    config = config or load_config(None, start=paths[0] if paths else ".")
    files = iter_py_files(paths, config)
    contexts: List[ModuleContext] = []
    findings: List[Finding] = []
    for abspath, rel in files:
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("SIM000", "error", rel, 1, 0,
                                    f"file is unreadable: {e}"))
            continue
        try:
            contexts.append(ModuleContext(rel, source))
        except SyntaxError as e:
            findings.append(Finding("SIM000", "error", rel, e.lineno or 1,
                                    (e.offset or 1) - 1,
                                    f"file does not parse: {e.msg}"))
    findings.extend(race_contexts(contexts, config, rules))
    if only is not None:
        findings = [f for f in findings if f.path in only]
    findings.sort(key=Finding.sort_key)
    return LintResult(findings, len(files), tool="simrace")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="simrace",
        description="concurrency & shard-protocol static analysis "
                    "(shadow-tpu)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: shadow_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON on stdout")
    ap.add_argument("--config", default=None,
                    help="pyproject.toml carrying [tool.simlint] "
                         "(default: nearest to the first path)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--diff", metavar="BASE", default=None,
                    help="report only findings in .py files changed "
                         "since git ref BASE (analysis stays package-"
                         "wide)")
    args = ap.parse_args(argv)
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.severity:<7}  {r.short}")
        return 0
    paths = args.paths or ["shadow_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"simrace: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    config = load_config(args.config, start=paths[0])
    only = None
    if args.diff is not None:
        try:
            only = changed_py_files(args.diff, config.root)
        except RuntimeError as e:
            print(f"simrace: --diff {args.diff}: {e}", file=sys.stderr)
            return 2
    result = race_paths(paths, config, rules, only=only)
    if args.json:
        json.dump(result.to_json(), sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in result.unsuppressed:
            print(f.render())
        print(f"simrace: {len(result.unsuppressed)} finding(s), "
              f"{len(result.suppressed)} suppressed, "
              f"{result.files} file(s)")
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
