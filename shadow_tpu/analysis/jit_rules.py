"""jit_rules: the compile-surface model + the SIM3xx catalog.

Every remaining wall of this platform is a device-plane fact — per-launch
cost (~320 us, size-independent at our widths), jit-cache stability (the
fleet's zero-recompile detach/re-arm contract, ``fleet.compiles``), and
first-compile cost (20-40 s on accelerator boxes).  Those contracts were
enforced only at RUNTIME, after the wall is paid.  simjit makes the
compile surface a lint-time contract: a package-wide model resolves every
jit program identity — ``jax.jit(f, ...)``, ``@partial(jax.jit, ...)``,
vmapped/shard_map-wrapped variants, factory functions returning jits, and
the variant caches (device_plane's <=4-compile sharded-variant cache, the
fleet's sticky-width classes) — and five rules run over it:

=======  ========  ====================================================
rule     severity  invariant guarded
=======  ========  ====================================================
SIM301   error     no recompile hazard: static args fed from varying
                   shape-deriving sources, operand widths derived
                   per-call outside the pad/bucket contract, traced
                   closures over loop-varying Python values
SIM302   error     no implicit host<->device sync inside the pipelined
                   dispatch window: ``.item()``, ``float()/int()/
                   bool()`` on a device value, ``np.asarray`` of a live
                   jit result, traced-value branching — each silently
                   serializes the PR-1 async overlap
SIM303   error     dtype-promotion drift against the non-negative int64
                   contract in kernel-tagged files (true division /
                   float-literal arithmetic / float casts on sim-time
                   lanes — extends SIM204's carrier tracking to
                   arithmetic)
SIM304   error     donation misuse beyond SIM004: one donated jit
                   shared by two call-site owners, or donation pinned
                   to the CPU backend (the PR-1 copy+sync trap)
SIM305   error     compile-budget drift: the statically enumerated
                   compile-key count per module must EQUAL the
                   checked-in [tool.simjit.budget] table, unbounded
                   in-function jit creation is always a finding, and
                   literal cache caps must match their declared budget
=======  ========  ====================================================

The model is deliberately scoped to stay sound-ish without whole-program
dataflow: program identities resolve through module/class assignments,
``self`` attribute handles (``self._flush_step =
step_window_flush_for_backend()``), factory returns, and import aliases
(ModuleContext.resolve); device-value tracking for SIM302 is
per-function (a name assigned from a jit call or a ``jnp.*`` op is a
device value until explicitly synced); and the budget's unit is the JIT
PROGRAM IDENTITY (python-level compiled-callable objects), with bounded
variant caches contributing their literal cap — the runtime caches
(``fleet.compiles``, the sharded-variant dict) are cross-checked against
the same table by ``simfleet smoke``.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .simlint import Config, Finding, ModuleContext
from .twin_rules import _is_timey

# jax.jit spellings ModuleContext.resolve canonicalizes to
_JIT_NAMES = ("jax.jit", "jax.api.jit")
_PARTIAL_NAMES = ("functools.partial", "partial")
# transform wrappers a jit may trace through: jax.jit(jax.vmap(f)),
# jax.jit(shard_map(f, ...)) — the traced fn is the wrapped one
_TRANSFORM_NAMES = ("jax.vmap", "jax.experimental.shard_map.shard_map",
                    "jax.experimental.shard_map", "shard_map", "jax.pmap")
# the pad/bucket contract: a width that went through one of these is
# drawn from a bounded class set, so it cannot churn the jit cache
_PAD_CONTRACT_RE = re.compile(r"pad|pow2|bucket", re.IGNORECASE)
# shape-deriving calls/attrs that vary per call site
_SHAPE_FNS = {"len"}
# numpy/jnp array constructors whose FIRST argument is a width
_WIDTH_CTORS = {"zeros", "ones", "empty", "full", "arange"}
# python scalar coercions that force a host<->device sync on a device value
_SYNC_COERCIONS = {"float", "int", "bool"}
# numpy entry points that pull a device buffer to the host
_NP_PULLS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
             "numpy.copy"}
_FLOAT_DTYPES = {"float32", "float64", "float16", "bfloat16"}


# ---------------------------------------------------------------------------
# jit expression parsing


@dataclass
class JitSpec:
    """One parsed jax.jit(...) / partial(jax.jit, ...) expression."""
    static_argnums: Set[int] = field(default_factory=set)
    static_argnames: Set[str] = field(default_factory=set)
    donate_argnums: Set[int] = field(default_factory=set)
    backend: Optional[str] = None
    dynamic_static: bool = False     # static_argnums was not a literal
    fn_node: Optional[ast.AST] = None  # the traced callable expression


def _int_set(node: ast.AST) -> Optional[Set[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            out.add(e.value)
        return out
    return None


def _str_set(node: ast.AST) -> Optional[Set[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.add(e.value)
        return out
    return None


def _fill_spec(spec: JitSpec, call: ast.Call) -> None:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = _int_set(kw.value)
            if v is None:
                spec.dynamic_static = True
            else:
                spec.static_argnums |= v
        elif kw.arg == "static_argnames":
            v2 = _str_set(kw.value)
            if v2 is None:
                spec.dynamic_static = True
            else:
                spec.static_argnames |= v2
        elif kw.arg == "donate_argnums":
            v = _int_set(kw.value)
            if v:
                spec.donate_argnums |= v
        elif kw.arg == "backend" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            spec.backend = kw.value.value


def _unwrap_transform(node: ast.AST, ctx: ModuleContext) -> ast.AST:
    """See through jax.vmap(f)/shard_map(f, ...) to the traced fn."""
    while isinstance(node, ast.Call):
        r = ctx.resolve(node.func)
        name = r[0] if r else (node.func.id if isinstance(node.func,
                                                          ast.Name) else "")
        if name in _TRANSFORM_NAMES or name.endswith(".vmap") \
                or name.endswith("shard_map"):
            if node.args:
                node = node.args[0]
                continue
        break
    return node


def parse_jit_expr(node: ast.AST, ctx: ModuleContext) -> Optional[JitSpec]:
    """JitSpec if ``node`` is a jit-program-producing expression:
    ``jax.jit(f, ...)``, ``partial(jax.jit, ...)`` (decorator form, no
    fn), or ``partial(jax.jit, ...)(f)`` (the ops/ idiom)."""
    if not isinstance(node, ast.Call):
        return None
    r = ctx.resolve(node.func)
    if r is not None and r[0] in _JIT_NAMES:
        spec = JitSpec()
        _fill_spec(spec, node)
        if node.args:
            spec.fn_node = _unwrap_transform(node.args[0], ctx)
        return spec
    is_partial = (r is not None and r[0] in _PARTIAL_NAMES) or (
        isinstance(node.func, ast.Name) and node.func.id == "partial")
    if is_partial and node.args:
        inner = ctx.resolve(node.args[0])
        if inner is not None and inner[0] in _JIT_NAMES:
            spec = JitSpec()
            _fill_spec(spec, node)
            if len(node.args) > 1:
                spec.fn_node = _unwrap_transform(node.args[1], ctx)
            return spec
    # partial(jax.jit, ...)(fn): the OUTER call applies the wrapper
    inner_spec = parse_jit_expr(node.func, ctx)
    if inner_spec is not None:
        if node.args:
            inner_spec.fn_node = _unwrap_transform(node.args[0], ctx)
        return inner_spec
    return None


# ---------------------------------------------------------------------------
# the per-module jit surface


@dataclass
class JitProgram:
    """One jit program identity (a python-level compiled callable)."""
    name: str                 # qualname within its module ("Cls.attr" ok)
    relpath: str
    line: int
    spec: JitSpec
    scope: str                # "module" | "class" | "function"
    owner: Optional[str] = None      # enclosing function qualname
    traced_def: Optional[ast.AST] = None   # the FunctionDef it traces
    cache_cap: Optional[int] = None  # literal bound when cache-guarded
    attr_store: bool = False  # held on an object attribute (replacement
    #                           semantics: one live identity per attr)


def _qualname(ctx: ModuleContext, node: ast.AST) -> Tuple[str, Optional[str]]:
    """(scope, enclosing function qualname) for a node: walks parents."""
    parts: List[str] = []
    fn_qual: Optional[str] = None
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parts.append(cur.name)
            if fn_qual is None:
                fn_qual = cur.name
        elif isinstance(cur, ast.ClassDef):
            parts.append(cur.name)
        cur = ctx.parent(cur)
    if fn_qual is not None:
        rest = [p for p in reversed(parts)]
        return "function", ".".join(rest)
    if parts:
        return "class", None
    return "module", None


def _cache_cap_for(ctx: ModuleContext, node: ast.AST) -> Optional[int]:
    """A literal variant-cache bound guarding ``node``: the enclosing
    function contains ``len(X) >= N`` / ``len(X) < N`` with the jit
    creation on the bounded side — the _pick_sharded_step idiom.  The
    cap found is N (+1 for the always-present full program is the
    caller's business)."""
    fn = ctx.enclosing_function(node)
    if fn is None:
        return None
    for n in ast.walk(fn):
        if not (isinstance(n, ast.Compare) and len(n.ops) == 1):
            continue
        left, op, right = n.left, n.ops[0], n.comparators[0]
        if isinstance(left, ast.Call) and isinstance(left.func, ast.Name) \
                and left.func.id == "len" \
                and isinstance(right, ast.Constant) \
                and isinstance(right.value, int) \
                and isinstance(op, (ast.GtE, ast.Lt, ast.LtE, ast.Gt)):
            return right.value
    return None


class ModuleJits:
    """The jit surface of one module: programs, factories, handles,
    traced defs, and resolved call sites."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.programs: Dict[str, JitProgram] = {}
        # functions whose return value is a jit program (factories);
        # qualname -> the JitSpec of the returned program
        self.factories: Dict[str, JitSpec] = {}
        # obj.<attr> names holding a program or a factory() result; may
        # include BORROWED entries (stored by another module) after the
        # package link pass — those resolve call sites but never count
        # toward this module's compile budget (only ``programs`` does)
        self.handles: Dict[str, JitProgram] = {}
        # obj.<attr> names holding a FACTORY itself (the
        # ``plane._mesh_make_step = make_step`` idiom): calling one
        # mints a program
        self.attr_factories: Dict[str, JitSpec] = {}
        # factory names consumed by a store/creation in this module
        # (their identities are counted at the store, not as a floor)
        self.consumed_factories: Set[str] = set()
        # jit-traced function defs (for SIM301 closure + SIM303 scoping)
        self.traced: List[Tuple[ast.AST, JitProgram]] = []
        self._collect()
        # call sites are collected by JitPackage AFTER the cross-module
        # link pass settles (imported factories, borrowed attr handles)
        self.call_sites: List[Tuple[JitProgram, ast.Call,
                                    Optional[str], str]] = []

    # -- collection --------------------------------------------------------

    def _local_functions(self) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for node in self.ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            out.setdefault(node.name, node)
        return out

    def _collect(self) -> None:
        ctx = self.ctx
        local_fns = self._local_functions()

        def add_program(name: str, node: ast.AST, spec: JitSpec,
                        traced: Optional[ast.AST]) -> JitProgram:
            scope, owner = _qualname(ctx, node)
            prog = JitProgram(name, ctx.relpath,
                              getattr(node, "lineno", 1), spec, scope,
                              owner, traced)
            if scope == "function":
                prog.cache_cap = _cache_cap_for(ctx, node)
            self.programs[name] = prog
            if traced is not None:
                self.traced.append((traced, prog))
            return prog

        # decorated defs
        for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            for dec in fn.decorator_list:
                spec = None
                if isinstance(dec, ast.Call):
                    spec = parse_jit_expr(dec, ctx)
                else:
                    r = ctx.resolve(dec)
                    if r is not None and r[0] in _JIT_NAMES:
                        spec = JitSpec()
                if spec is not None:
                    add_program(fn.name, fn, spec, fn)
                    break
        # assignments: name = jit_expr / self.attr = jit_expr
        for node in ctx.walk(ast.Assign):
            if len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            spec = parse_jit_expr(node.value, ctx)
            traced = None
            if spec is not None and spec.fn_node is not None \
                    and isinstance(spec.fn_node, ast.Name):
                traced = local_fns.get(spec.fn_node.id)
            if spec is None:
                continue
            if isinstance(tgt, ast.Name):
                add_program(tgt.id, node, spec, traced)
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name):
                prog = add_program(tgt.attr, node, spec, traced)
                prog.attr_store = True
                self.handles[tgt.attr] = prog
        # factories: functions returning a jit expr or a program name —
        # ALL returns are merged (the backend-picking factory returns
        # the donating program on accelerators and the non-donating twin
        # on cpu: the merged spec donates only when every branch does)
        for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            specs: List[JitSpec] = []
            first_line = fn.lineno
            for node in ast.walk(fn):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                spec = parse_jit_expr(node.value, ctx)
                if spec is None and isinstance(node.value, ast.Name) \
                        and node.value.id in self.programs:
                    spec = self.programs[node.value.id].spec
                if spec is not None:
                    specs.append(spec)
                    if not len(specs) - 1:
                        first_line = node.lineno
                    if spec.fn_node is not None \
                            and isinstance(spec.fn_node, ast.Name):
                        traced = local_fns.get(spec.fn_node.id)
                        if traced is not None and not any(
                                t is traced for t, _ in self.traced):
                            prog = JitProgram(
                                f"{fn.name}.<returned>", ctx.relpath,
                                node.lineno, spec, "function", fn.name,
                                traced)
                            self.traced.append((traced, prog))
            if not specs:
                continue
            merged = specs[0]
            if len(specs) > 1:
                merged = JitSpec()
                for s in specs:
                    merged.static_argnums |= s.static_argnums
                    merged.static_argnames |= s.static_argnames
                    merged.dynamic_static |= s.dynamic_static
                donate = specs[0].donate_argnums
                for s in specs[1:]:
                    donate = donate & s.donate_argnums
                merged.donate_argnums = donate
                backends = {s.backend for s in specs}
                merged.backend = backends.pop() if len(backends) == 1 \
                    else None
            self.factories[fn.name] = merged
        # handles: obj.attr = <program name>
        for node in ctx.walk(ast.Assign):
            if len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute) and
                    isinstance(tgt.value, ast.Name)):
                continue
            val = node.value
            if isinstance(val, ast.Name) and val.id in self.programs:
                self.handles.setdefault(tgt.attr, self.programs[val.id])

    # -- the package link pass ---------------------------------------------

    def link(self, factories_by_symbol: Dict[str, JitSpec],
             attr_factories: Dict[str, JitSpec],
             attr_handles: Dict[str, JitProgram]) -> bool:
        """One round of cross-module resolution: imported factories
        (``step_window_flush_for_backend`` called from device_plane),
        factory-valued attributes (``plane._mesh_make_step =
        make_step``), and borrowed attr handles (the device plane calls
        ``self._sharded_step`` that meshplane stored).  Returns True
        when anything new resolved — JitPackage iterates to fixpoint."""
        ctx = self.ctx
        changed = False

        def factory_spec(name: str) -> Optional[JitSpec]:
            if name in self.factories:
                return self.factories[name]
            spec = factories_by_symbol.get(name)
            if spec is None:
                return None
            target = ctx.aliases.get(name)
            if target is None or not target.endswith("." + name):
                return None     # bare-name collision, not an import
            return spec

        # new factories: a return calling a known factory
        for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            if fn.name in self.factories:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and \
                        isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func, ast.Name):
                    spec = factory_spec(node.value.func.id)
                    if spec is not None:
                        self.factories[fn.name] = spec
                        self.consumed_factories.add(node.value.func.id)
                        changed = True
                        break

        for node in ctx.walk(ast.Assign):
            if len(node.targets) != 1:
                continue
            tgt, val = node.targets[0], node.value
            # obj.attr = factory(...)  -> a stored program identity
            # obj.attr = factory       -> a factory-valued attribute
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name):
                if isinstance(val, ast.Call) and \
                        isinstance(val.func, ast.Name):
                    spec = factory_spec(val.func.id)
                    if spec is not None and tgt.attr not in self.programs:
                        scope, owner = _qualname(ctx, node)
                        prog = JitProgram(tgt.attr, ctx.relpath,
                                          node.lineno, spec, scope, owner,
                                          attr_store=True)
                        self.programs[tgt.attr] = prog
                        self.handles[tgt.attr] = prog
                        self.consumed_factories.add(val.func.id)
                        changed = True
                elif isinstance(val, ast.Name):
                    spec = factory_spec(val.id)
                    if spec is not None and \
                            tgt.attr not in self.attr_factories:
                        self.attr_factories[tgt.attr] = spec
                        self.consumed_factories.add(val.id)
                        changed = True
            # local = obj.attr_factory(...)  -> a minted program (the
            # _pick_sharded_step variant-cache idiom)
            elif isinstance(tgt, ast.Name) and isinstance(val, ast.Call) \
                    and isinstance(val.func, ast.Attribute):
                spec = self.attr_factories.get(val.func.attr) or \
                    attr_factories.get(val.func.attr)
                if spec is not None:
                    scope, owner = _qualname(ctx, node)
                    key = f"{owner or '<module>'}.{tgt.id}"
                    if key not in self.programs:
                        prog = JitProgram(key, ctx.relpath, node.lineno,
                                          spec, scope, owner)
                        if scope == "function":
                            prog.cache_cap = _cache_cap_for(ctx, node)
                        self.programs[key] = prog
                        changed = True
        # borrow attr handles other modules stored, for call resolution
        for attr, prog in sorted(attr_handles.items()):
            if attr not in self.handles:
                self.handles[attr] = prog
                changed = True
        return changed

    def collect_calls(self) -> None:
        """(program, call node, enclosing function name, kind) for every
        resolvable jit-program call in this module: direct names
        (kind="name") and attr handles, own or borrowed (kind="handle").
        Factory calls mint programs and are NOT call sites."""
        out: List[Tuple[JitProgram, ast.Call, Optional[str], str]] = []
        ctx = self.ctx
        local_factories = set(self.factories)
        for call in ctx.walk(ast.Call):
            prog = None
            kind = "name"
            f = call.func
            if isinstance(f, ast.Name) and f.id in self.programs \
                    and f.id not in local_factories:
                prog = self.programs[f.id]
            elif isinstance(f, ast.Attribute) and f.attr in self.handles \
                    and f.attr not in self.attr_factories:
                prog = self.handles[f.attr]
                kind = "handle"
            if prog is None:
                continue
            fn = ctx.enclosing_function(call)
            out.append((prog, call, fn.name if fn is not None else None,
                        kind))
        self.call_sites = out


# ---------------------------------------------------------------------------
# the package model


class JitPackage:
    """All parsed modules + their jit surfaces + the simjit config
    (kernel-tagged globs, the [tool.simjit.budget] table)."""

    def __init__(self, contexts: List[ModuleContext],
                 config: Optional[Config] = None,
                 budget: Optional[Dict[str, int]] = None,
                 kernel: Optional[List[str]] = None):
        self.contexts = {c.relpath: c for c in contexts}
        self.config = config or Config()
        self.budget = dict(budget or {})
        self.kernel = list(kernel or [])
        self.modules: Dict[str, ModuleJits] = {}
        for rel, ctx in sorted(self.contexts.items()):
            self.modules[rel] = ModuleJits(ctx)
        # cross-module link to fixpoint: each round shares every
        # module's factories and attribute-stored handles with every
        # other module, so chains like exchange.make_mesh_span_flush ->
        # meshplane.make_step -> plane._mesh_make_step ->
        # device_plane._pick_sharded_step resolve (bounded rounds; the
        # tree's deepest chain is three hops)
        for _round in range(4):
            factories_by_symbol: Dict[str, JitSpec] = {}
            attr_factories: Dict[str, JitSpec] = {}
            attr_handles: Dict[str, JitProgram] = {}
            for rel, mj in sorted(self.modules.items()):
                for fname, spec in sorted(mj.factories.items()):
                    factories_by_symbol.setdefault(fname, spec)
                attr_factories.update(mj.attr_factories)
                for attr, prog in sorted(mj.handles.items()):
                    if prog.relpath == rel:     # own stores only
                        attr_handles.setdefault(attr, prog)
            changed = False
            for rel, mj in sorted(self.modules.items()):
                changed |= mj.link(factories_by_symbol, attr_factories,
                                   attr_handles)
            if not changed:
                break
        for rel, mj in sorted(self.modules.items()):
            mj.collect_calls()
        # package-wide donated-program registry keyed by symbol name so
        # imported call sites resolve (symbol names are unique here)
        self.by_symbol: Dict[str, List[JitProgram]] = {}
        for rel, mj in sorted(self.modules.items()):
            for name, prog in sorted(mj.programs.items()):
                self.by_symbol.setdefault(name.split(".")[-1],
                                          []).append(prog)

    def is_kernel(self, relpath: str) -> bool:
        return any(fnmatch.fnmatch(relpath, p) for p in self.kernel)

    def static_key_count(self, rel: str
                         ) -> Tuple[int, List[Tuple[JitProgram, str]]]:
        """(enumerable compile-key count, [(program, problem)]) for one
        module.  Each module/class-scope program identity is one key; a
        function-scope creation guarded by a literal cache cap
        contributes the cap; an unguarded function-scope creation is an
        unbounded-growth problem."""
        mj = self.modules.get(rel)
        if mj is None:
            return 0, []
        count = 0
        problems: List[Tuple[JitProgram, str]] = []
        seen: Set[int] = set()
        for name, prog in sorted(mj.programs.items()):
            if id(prog) in seen:
                continue
            seen.add(id(prog))
            if prog.scope in ("module", "class"):
                count += 1
            elif prog.attr_store or (
                    prog.owner is not None and
                    prog.owner.split(".")[-1] == "__init__"):
                # one live program per attribute / constructed object:
                # replacement semantics (self._x = factory() re-stores,
                # it doesn't accumulate identities)
                count += 1
            elif prog.cache_cap is not None:
                count += prog.cache_cap
            else:
                problems.append((prog, (
                    f"jit program `{name}` is created inside "
                    f"`{prog.owner}` with no literal cache bound — "
                    "every call mints a fresh compiled program "
                    "(unbounded compile-key growth); cache it with a "
                    "`len(cache) >= N` cap or hoist the creation")))
        # factory functions themselves are not keys (their stores are),
        # but a factory neither stored nor wrapped anywhere in ITS OWN
        # module is reachable only through consumers this module can't
        # see — count one key as the conservative floor so the defining
        # module keeps a budget presence
        stored = {p.name for p in mj.programs.values()}
        for fname in sorted(mj.factories):
            if fname in stored or fname in mj.consumed_factories:
                continue
            if any(p.owner == fname for p in mj.programs.values()):
                continue
            count += 1
        return count, problems


class JitRule:
    """One compile-surface invariant checked over the whole package."""

    id: str = "SIM300"
    severity: str = "error"
    short: str = ""

    def run(self, pkg: JitPackage) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, relpath: str, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, self.severity, relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# shared expression predicates


def _contains_shape_derivation(node: ast.AST,
                               ctx: ModuleContext) -> Optional[str]:
    """The spelling of a per-call shape/width derivation inside ``node``
    (``len(...)``, ``.shape`` access), unless the derivation is wrapped
    in a pad/bucket-contract call.  Returns the offending spelling or
    None."""
    padded: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            fname = ""
            if isinstance(n.func, ast.Name):
                fname = n.func.id
            elif isinstance(n.func, ast.Attribute):
                fname = n.func.attr
            if _PAD_CONTRACT_RE.search(fname):
                for sub in ast.walk(n):
                    padded.add(id(sub))
    for n in ast.walk(node):
        if id(n) in padded:
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in _SHAPE_FNS:
            return f"{n.func.id}(...)"
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return ".shape"
    return None


def _expr_root(node: ast.AST) -> Optional[str]:
    cur = node
    while isinstance(cur, (ast.Attribute, ast.Subscript, ast.Call)):
        cur = cur.func if isinstance(cur, ast.Call) else cur.value
    return cur.id if isinstance(cur, ast.Name) else None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# ---------------------------------------------------------------------------
# SIM301 — recompile hazard


class RecompileHazardRule(JitRule):
    """A jit program recompiles whenever a static argument takes a new
    value or an operand takes a new shape.  The platform's contract is
    that widths are PADDED/BUCKETED into a bounded class set (pad_state,
    pow2 shape classes) before they reach a jit boundary — a raw
    ``len(...)``/``.shape`` feeding a static arg or an operand
    constructor mints one compilation per distinct value (20-40 s each
    on accelerator boxes), and a traced closure over a loop-varying
    Python value silently bakes iteration-N state into the compiled
    program (or retraces on every flip when used as a hashable
    static)."""

    id = "SIM301"
    severity = "error"
    short = ("recompile hazard: unbucketed shape feeding a jit boundary "
             "or traced closure over a varying value")

    def run(self, pkg: JitPackage) -> List[Finding]:
        out: List[Finding] = []
        for rel, mj in sorted(pkg.modules.items()):
            out.extend(self._check_call_sites(rel, mj))
            out.extend(self._check_closures(rel, mj))
        return out

    def _check_call_sites(self, rel: str, mj: ModuleJits) -> List[Finding]:
        out: List[Finding] = []
        for prog, call, _fn, _kind in mj.call_sites:
            spec = prog.spec
            # static args fed from shape-deriving expressions
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred):
                    continue
                is_static = i in spec.static_argnums
                sd = _contains_shape_derivation(arg, mj.ctx)
                if is_static and sd:
                    out.append(self.finding(
                        rel, arg,
                        f"static arg {i} of jit program `{prog.name}` is "
                        f"fed from `{sd}` — one compilation per distinct "
                        "value; bucket/pad the width first (the pad_state "
                        "contract) or make it a traced operand"))
                elif sd and self._is_width_ctor(arg):
                    out.append(self.finding(
                        rel, arg,
                        f"operand {i} of jit program `{prog.name}` is "
                        f"constructed with a per-call `{sd}` width — one "
                        "compilation per distinct shape; pad to the "
                        "bucketed class set first"))
            for kw in call.keywords:
                if kw.arg is None:
                    continue
                sd = _contains_shape_derivation(kw.value, mj.ctx)
                if kw.arg in spec.static_argnames and sd:
                    out.append(self.finding(
                        rel, kw.value,
                        f"static argname `{kw.arg}` of jit program "
                        f"`{prog.name}` is fed from `{sd}` — one "
                        "compilation per distinct value; bucket/pad the "
                        "width first or make it a traced operand"))
        return out

    @staticmethod
    def _is_width_ctor(arg: ast.AST) -> bool:
        """``jnp.zeros(len(x))``-shaped operand expressions."""
        for n in ast.walk(arg):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _WIDTH_CTORS:
                return True
        return False

    def _check_closures(self, rel: str, mj: ModuleJits) -> List[Finding]:
        """A traced function reading a free variable that its enclosing
        scope rebinds per iteration (loop body / AugAssign) — the value
        is baked at trace time and silently stale afterwards."""
        out: List[Finding] = []
        for traced, prog in mj.traced:
            encl = mj.ctx.enclosing_function(traced)
            if encl is None:
                # module-level traced fn: globals mutated via `global X`
                mutated = {g for n in mj.ctx.walk(ast.Global)
                           for g in n.names}
                if not mutated:
                    continue
                free = self._free_reads(traced)
                for name in sorted(free & mutated):
                    out.append(self.finding(
                        rel, traced,
                        f"jit-traced `{prog.name}` closes over global "
                        f"`{name}` which is mutated via `global` — the "
                        "traced value is frozen at compile time; pass it "
                        "as an operand"))
                continue
            varying = self._loop_varying(encl, traced)
            if not varying:
                continue
            free = self._free_reads(traced)
            for name in sorted(free & varying):
                out.append(self.finding(
                    rel, traced,
                    f"jit-traced `{prog.name}` closes over `{name}`, "
                    f"which `{encl.name}` rebinds per iteration — each "
                    "trace bakes one iteration's value (stale or "
                    "retraced per flip); pass it as an operand or make "
                    "the factory take it as a parameter"))
        return out

    @staticmethod
    def _free_reads(fn: ast.AST) -> Set[str]:
        local = {a.arg for a in fn.args.args + fn.args.kwonlyargs +
                 fn.args.posonlyargs}
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        reads: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Store):
                    local.add(n.id)
                else:
                    reads.add(n.id)
        return reads - local

    @staticmethod
    def _loop_varying(encl: ast.AST, traced: ast.AST) -> Set[str]:
        """Names the enclosing function rebinds inside a loop body or
        via AugAssign — per-iteration-varying values."""
        varying: Set[str] = set()
        for n in ast.walk(encl):
            if isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name):
                varying.add(n.target.id)
            elif isinstance(n, (ast.For, ast.While)):
                if any(sub is traced for sub in ast.walk(n)):
                    continue   # the traced def itself lives in the loop
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Name) and \
                            isinstance(sub.ctx, ast.Store):
                        varying.add(sub.id)
                if isinstance(n, ast.For):
                    for sub in ast.walk(n.target):
                        if isinstance(sub, ast.Name):
                            varying.add(sub.id)
        return varying


# ---------------------------------------------------------------------------
# SIM302 — implicit host<->device sync in the dispatch window


class HiddenSyncRule(JitRule):
    """The PR-1 pipelined dispatch computes the kernel BEHIND the
    round's host work; the overlap survives only while nothing touches
    the in-flight result.  ``.item()``, ``float()/int()/bool()`` on a
    device value, ``np.asarray`` of a live jit result, and branching on
    a traced value each force a blocking device sync exactly where the
    launch was supposed to overlap — silently serializing the pipeline.
    Tracking is per-function: a name assigned from a jit-program call or
    a ``jnp.*`` op is a device value; the deliberate collect point reads
    from the in-flight slot (an attribute), which this rule never
    tracks, so designed syncs stay quiet."""

    id = "SIM302"
    severity = "error"
    short = ("implicit host<->device sync on a live device value inside "
             "the dispatch window")

    def run(self, pkg: JitPackage) -> List[Finding]:
        out: List[Finding] = []
        for rel, mj in sorted(pkg.modules.items()):
            fns = list(mj.ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef))
            for fn in fns:
                out.extend(self._check_function(rel, mj, fn))
        return out

    def _device_names(self, mj: ModuleJits, fn: ast.AST) -> Dict[str, int]:
        """Names holding device values in ``fn`` mapped to the first
        line where they become one: jit-call results, jnp-op results,
        and direct derivations of either.  The line matters — code ABOVE
        the device assignment (the uniform_jnp host-dispatch idiom:
        ``np.asarray(counter)`` before ``counter = jnp.asarray(...)``)
        is host-side and must stay quiet."""
        ctx = mj.ctx
        tracked: Dict[str, int] = {}
        jit_calls = {id(call) for prog, call, _fn, _kind in mj.call_sites}

        def produces_device(value: ast.AST) -> bool:
            if isinstance(value, ast.Call):
                if id(value) in jit_calls:
                    return True
                r = ctx.resolve(value.func)
                if r is not None and (
                        r[0].startswith("jax.numpy.") or
                        r[0] == "jax.device_put"):
                    return True
            if isinstance(value, (ast.Subscript, ast.Attribute)):
                root = _expr_root(value)
                return root in tracked
            if isinstance(value, ast.Name):
                return value.id in tracked
            if isinstance(value, ast.Tuple):
                return any(produces_device(e) for e in value.elts)
            return False

        def bound_names(t: ast.AST) -> Set[str]:
            # only plain-name bindings: `self.x = ...` persists past the
            # function (per-function tracking can't follow it) and a
            # subscript target's index names are not bindings at all
            if isinstance(t, ast.Name):
                return {t.id}
            if isinstance(t, (ast.Tuple, ast.List)):
                out: Set[str] = set()
                for e in t.elts:
                    out |= bound_names(e)
                return out
            if isinstance(t, ast.Starred):
                return bound_names(t.value)
            return set()

        # two passes so `a = step(s); b = a[0]` settles
        for _ in range(2):
            for n in self._own_walk(fn):
                if isinstance(n, ast.Assign) and produces_device(n.value):
                    for t in n.targets:
                        for name in bound_names(t):
                            prev = tracked.get(name, n.lineno)
                            tracked[name] = min(prev, n.lineno)
        return tracked

    @staticmethod
    def _own_walk(fn: ast.AST):
        """Walk ``fn`` skipping nested def subtrees — each function is
        checked exactly once (nested defs get their own pass)."""
        skip: Set[int] = set()
        for n in ast.walk(fn):
            if n is not fn and isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
                for sub in ast.walk(n):
                    skip.add(id(sub))
        for n in ast.walk(fn):
            if id(n) not in skip:
                yield n

    def _check_function(self, rel: str, mj: ModuleJits,
                        fn: ast.AST) -> List[Finding]:
        tracked = self._device_names(mj, fn)
        if not tracked:
            return []
        ctx = mj.ctx
        out: List[Finding] = []
        # an EXPLICIT `jax.block_until_ready(...)` names the sync point;
        # pulls after it are reads of settled buffers, not implicit syncs
        blocked_at: Optional[int] = None
        for n in self._own_walk(fn):
            if isinstance(n, ast.Call):
                r = ctx.resolve(n.func)
                if r is not None and r[0] == "jax.block_until_ready":
                    if blocked_at is None or n.lineno < blocked_at:
                        blocked_at = n.lineno

        def live(node: ast.AST, name: Optional[str]) -> bool:
            line = getattr(node, "lineno", 0)
            if blocked_at is not None and line >= blocked_at:
                return False
            return name in tracked and line >= tracked[name]

        for n in self._own_walk(fn):
            if isinstance(n, ast.Call):
                f = n.func
                # x.item()
                if isinstance(f, ast.Attribute) and f.attr == "item" \
                        and live(n, _expr_root(f.value)):
                    out.append(self.finding(
                        rel, n,
                        f"`.item()` on device value "
                        f"`{_expr_root(f.value)}` blocks until the "
                        "in-flight kernel finishes — an implicit sync "
                        "inside the dispatch window; collect first, then "
                        "read host-side"))
                # float(x) / int(x) / bool(x)
                elif isinstance(f, ast.Name) and \
                        f.id in _SYNC_COERCIONS and n.args and \
                        live(n, _expr_root(n.args[0])):
                    out.append(self.finding(
                        rel, n,
                        f"`{f.id}()` of device value "
                        f"`{_expr_root(n.args[0])}` is an implicit "
                        "host sync — it serializes the pipelined "
                        "dispatch; keep the value on device or collect "
                        "explicitly"))
                else:
                    r = ctx.resolve(f)
                    if r is not None and r[0] in _NP_PULLS and n.args and \
                            live(n, _expr_root(n.args[0])):
                        out.append(self.finding(
                            rel, n,
                            f"`{r[1]}.{r[0].rsplit('.', 1)[1]}` of live "
                            f"jit result "
                            f"`{_expr_root(n.args[0])}` pulls the buffer "
                            "to the host mid-window — if this is the "
                            "designed collect point, say so with a "
                            "pragma"))
            elif isinstance(n, (ast.If, ast.While)):
                test = n.test
                if (blocked_at is None or
                        getattr(test, "lineno", 0) < blocked_at) and \
                        self._branches_on_device(test, tracked):
                    out.append(self.finding(
                        rel, test,
                        f"branching on device value "
                        f"`{sorted(_names_in(test) & set(tracked))[0]}` "
                        "forces "
                        "a blocking sync (traced-value branch) — compute "
                        "the predicate host-side or use lax.cond in the "
                        "kernel"))
        return out

    @staticmethod
    def _branches_on_device(test: ast.AST, tracked: Dict[str, int]) -> bool:
        line = getattr(test, "lineno", 0)
        if not any(line >= tracked[nm]
                   for nm in sorted(_names_in(test) & set(tracked))):
            return False
        # identity tests against None are shape-free host checks
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return False
        # len()/.shape/isinstance predicates read metadata (or the host
        # type), not the buffer: exempt names that only appear there
        shallow: Set[int] = set()
        for n in ast.walk(test):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in ("len", "isinstance", "getattr",
                                      "hasattr"):
                shallow.update(id(s) for s in ast.walk(n))
            elif isinstance(n, ast.Attribute) and n.attr in ("shape",
                                                            "ndim",
                                                            "dtype"):
                shallow.update(id(s) for s in ast.walk(n))
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and n.id in tracked \
                    and getattr(n, "lineno", 0) >= tracked[n.id] \
                    and id(n) not in shallow:
                return True
        return False


# ---------------------------------------------------------------------------
# SIM303 — dtype-promotion drift in kernel-tagged files


class PromotionDriftRule(JitRule):
    """The kernel plane's contract is non-negative int64 arithmetic —
    what makes ``py // == C / == numpy int64`` exact (the logic-IR
    foundation).  A Python float literal or true division touching a
    sim-time lane weak-type-promotes the whole expression to float —
    ns timestamps silently lose integer exactness above 2**53 and the
    three planes drift.  This extends SIM204's carrier tracking from
    casts to ARITHMETIC, scoped to kernel-tagged files
    ([tool.simjit] kernel globs)."""

    id = "SIM303"
    severity = "error"
    short = ("float promotion on a sim-time lane in a kernel-tagged "
             "file (int64 contract)")

    def run(self, pkg: JitPackage) -> List[Finding]:
        out: List[Finding] = []
        for rel, mj in sorted(pkg.modules.items()):
            if not pkg.is_kernel(rel):
                continue
            out.extend(self._check_module(rel, mj))
        return out

    def _timey_in(self, node: ast.AST) -> Optional[str]:
        for n in ast.walk(node):
            nm = None
            if isinstance(n, ast.Name):
                nm = n.id
            elif isinstance(n, ast.Attribute):
                nm = n.attr
            if nm and _is_timey(nm):
                return nm
        return None

    def _check_module(self, rel: str, mj: ModuleJits) -> List[Finding]:
        out: List[Finding] = []
        for node in mj.ctx.walk(ast.BinOp):
            if isinstance(node.op, ast.Div):
                nm = self._timey_in(node.left) or self._timey_in(node.right)
                if nm:
                    out.append(self.finding(
                        rel, node,
                        f"true division on sim-time lane `{nm}` promotes "
                        "the int64 ns value to float — use `//` (the "
                        "non-negative int64 contract keeps all three "
                        "planes bit-exact)"))
                continue
            if isinstance(node.op, (ast.Mult, ast.Add, ast.Sub)):
                for side, other in ((node.left, node.right),
                                    (node.right, node.left)):
                    if isinstance(side, ast.Constant) and \
                            isinstance(side.value, float):
                        nm = self._timey_in(other)
                        if nm:
                            out.append(self.finding(
                                rel, node,
                                f"float literal {side.value!r} in "
                                f"arithmetic with sim-time lane `{nm}` "
                                "weak-type-promotes the int64 ns value "
                                "to float — spell the coefficient as an "
                                "integer ratio (num // den)"))
                        break
        for node in mj.ctx.walk(ast.Call):
            f = node.func
            # x.astype(float32) / jnp.float32(x) on a timey expression
            if isinstance(f, ast.Attribute) and f.attr == "astype" \
                    and node.args and self._float_dtype(node.args[0]):
                nm = self._timey_in(f.value)
                if nm:
                    out.append(self.finding(
                        rel, node,
                        f"sim-time lane `{nm}` cast to "
                        f"{self._float_dtype(node.args[0])} — ns "
                        "timestamps lose integer exactness above 2**53; "
                        "keep the lane int64"))
            elif isinstance(f, ast.Attribute) and \
                    f.attr in _FLOAT_DTYPES and node.args:
                nm = self._timey_in(node.args[0])
                if nm:
                    out.append(self.finding(
                        rel, node,
                        f"sim-time lane `{nm}` cast to {f.attr} — ns "
                        "timestamps lose integer exactness above 2**53; "
                        "keep the lane int64"))
        return out

    @staticmethod
    def _float_dtype(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and node.attr in _FLOAT_DTYPES:
            return node.attr
        if isinstance(node, ast.Name) and node.id in _FLOAT_DTYPES:
            return node.id
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and node.value in _FLOAT_DTYPES:
            return node.value
        return None


# ---------------------------------------------------------------------------
# SIM304 — donation misuse


class DonationMisuseRule(JitRule):
    """``donate_argnums`` hands the operand buffers to XLA.  Two call
    sites sharing ONE donated program means two owners of the same
    aliasing contract — the second caller's pre-donation reads race the
    first caller's invalidated buffers the moment the call order
    changes (SIM004 sees each site in isolation; this rule sees the
    pair).  And donation pinned to the CPU backend is the PR-1 trap:
    a donated PJRT-CPU call executes SYNCHRONOUSLY and still copies
    (measured 114 ms vs 0.33 ms undonated), destroying the pipeline
    it was meant to feed — the backend-gated non-donating twin
    (step_window_flush_for_backend) exists precisely for this."""

    id = "SIM304"
    severity = "error"
    short = ("donated jit shared by two call-site owners, or donation "
             "pinned to the CPU backend")

    def run(self, pkg: JitPackage) -> List[Finding]:
        out: List[Finding] = []
        # (b) donation + backend="cpu" at the creation site
        for rel, mj in sorted(pkg.modules.items()):
            for name, prog in sorted(mj.programs.items()):
                if prog.spec.donate_argnums and prog.spec.backend == "cpu":
                    anchor = ast.Module(body=[], type_ignores=[])
                    anchor.lineno, anchor.col_offset = prog.line, 0
                    out.append(self.finding(
                        rel, anchor,
                        f"jit program `{name}` donates buffers on the "
                        "CPU backend — donated PJRT-CPU calls execute "
                        "synchronously AND copy (the PR-1 trap); use a "
                        "non-donating variant on cpu "
                        "(step_window_flush_for_backend pattern)"))
        # (a) one donated program, two call-site owners (package-wide:
        # call sites of imported names resolve by trailing symbol)
        owners: Dict[int, Set[Tuple[str, str]]] = {}
        sites: Dict[int, List[Tuple[str, ast.Call]]] = {}
        progs: Dict[int, JitProgram] = {}
        for rel, mj in sorted(pkg.modules.items()):
            for prog, call, fn, kind in mj.call_sites:
                # handle dispatch (self._step(...)) has one owner object
                # by construction; only direct-name sharing pairs alias
                if kind != "name" or not prog.spec.donate_argnums:
                    continue
                progs[id(prog)] = prog
                owners.setdefault(id(prog), set()).add((rel, fn or "<module>"))
                sites.setdefault(id(prog), []).append((rel, call))
            # imported donated programs called by bare name
            for call in mj.ctx.walk(ast.Call):
                if not isinstance(call.func, ast.Name):
                    continue
                cands = pkg.by_symbol.get(call.func.id, ())
                for cand in cands:
                    if cand.relpath == rel or not cand.spec.donate_argnums:
                        continue
                    r = mj.ctx.aliases.get(call.func.id)
                    if r is None or not r.endswith(call.func.id):
                        continue
                    fn2 = mj.ctx.enclosing_function(call)
                    progs[id(cand)] = cand
                    owners.setdefault(id(cand), set()).add(
                        (rel, fn2.name if fn2 else "<module>"))
                    sites.setdefault(id(cand), []).append((rel, call))
        for pid, own in sorted(owners.items(),
                               key=lambda kv: progs[kv[0]].name):
            if len(own) < 2:
                continue
            prog = progs[pid]
            names = ", ".join(f"{r}:{f}" for r, f in sorted(own))
            for rel, call in sorted(sites[pid],
                                    key=lambda s: (s[0], s[1].lineno)):
                out.append(self.finding(
                    rel, call,
                    f"donated jit program `{prog.name}` is called from "
                    f"multiple owners ({names}) — two callers of one "
                    "donation contract alias each other's invalidated "
                    "buffers; give each owner its own jit (or route "
                    "through one owner)"))
        return out


# ---------------------------------------------------------------------------
# SIM305 — compile-budget audit


class CompileBudgetRule(JitRule):
    """The checked-in ``[tool.simjit.budget]`` table declares, per
    module, how many jit program identities the module may mint; this
    rule statically enumerates the actual surface and fails on ANY
    drift — a new jit site without a conscious budget bump (a code path
    adding unbounded cache keys fails lint instead of churning
    ``fleet.compiles`` at 2 a.m. on a TPU box), AND a stale over-
    declared entry after a surface shrinks.  Unbounded in-function jit
    creation is always a finding.  The runtime halves of the same table
    (dotted keys: ``fleet.compiles``, ``device_plane.sharded_variants``)
    are cross-checked by ``simfleet smoke``; here the sharded-variant
    literal cap must match its declared budget."""

    id = "SIM305"
    severity = "error"
    short = ("compile-key count drifted from the checked-in "
             "[tool.simjit.budget] table")

    def run(self, pkg: JitPackage) -> List[Finding]:
        out: List[Finding] = []
        module_budget = {k: v for k, v in pkg.budget.items()
                         if k.endswith(".py")}
        counted: Dict[str, int] = {}
        for rel, mj in sorted(pkg.modules.items()):
            count, problems = pkg.static_key_count(rel)
            for prog, msg in problems:
                anchor = ast.Module(body=[], type_ignores=[])
                anchor.lineno, anchor.col_offset = prog.line, 0
                out.append(self.finding(rel, anchor, msg))
            if count:
                counted[rel] = count
        for rel, count in sorted(counted.items()):
            declared = module_budget.get(rel)
            anchor = ast.Module(body=[], type_ignores=[])
            anchor.lineno, anchor.col_offset = 1, 0
            if declared is None:
                out.append(self.finding(
                    rel, anchor,
                    f"module mints {count} jit compile key(s) but has no "
                    "[tool.simjit.budget] entry — declare the budget in "
                    "pyproject.toml so growth is a conscious decision"))
            elif declared != count:
                direction = "grew past" if count > declared else \
                    "shrank below"
                out.append(self.finding(
                    rel, anchor,
                    f"compile surface {direction} its budget: "
                    f"{count} enumerated key(s) vs "
                    f"[tool.simjit.budget] = {declared} — "
                    "update the table to match the surface"))
        for rel in sorted(set(module_budget) - set(counted)):
            # a budgeted module OUTSIDE this run's analysis subset (a
            # single-file invocation) is unknowable, not stale — only an
            # analyzed module minting zero keys, or one gone from the
            # tree entirely, means the entry went stale
            if rel not in pkg.modules and \
                    os.path.isfile(os.path.join(pkg.config.root, rel)):
                continue
            anchor = ast.Module(body=[], type_ignores=[])
            anchor.lineno, anchor.col_offset = 1, 0
            out.append(self.finding(
                "pyproject.toml", anchor,
                f"[tool.simjit.budget] entry `{rel}` = "
                f"{module_budget[rel]} is stale — the module mints no "
                "enumerable jit compile keys (removed surface? drop the "
                "entry)"))
        # literal variant-cache caps must match their declared runtime
        # budget (the static half of the fleet-smoke cross-check)
        for key, declared in sorted(pkg.budget.items()):
            if not key.endswith(".sharded_variants"):
                continue
            for rel, mj in sorted(pkg.modules.items()):
                if not rel.endswith("device_plane.py"):
                    continue
                for prog in mj.programs.values():
                    if prog.cache_cap is not None and \
                            prog.cache_cap != declared:
                        anchor = ast.Module(body=[], type_ignores=[])
                        anchor.lineno, anchor.col_offset = prog.line, 0
                        out.append(self.finding(
                            rel, anchor,
                            f"variant-cache literal cap "
                            f"{prog.cache_cap} != [tool.simjit.budget] "
                            f"`{key}` = {declared} — the checked-in "
                            "budget and the code bound must agree"))
        return out


CATALOG: List[JitRule] = [
    RecompileHazardRule(),
    HiddenSyncRule(),
    PromotionDriftRule(),
    DonationMisuseRule(),
    CompileBudgetRule(),
]
