"""Static-analysis layer: lint-time proofs of the simulator's invariants.

The whole value of this engine is *deterministic* parallel discrete-event
simulation — every random stream derives from one master seed
(core/rng.py, mirroring the reference's utility/random.c + master.c:417),
simulation time is an integer nanosecond clock (core/stime.py), and the
digest-parity tests pin bit-identical state across every execution seam.
Those contracts are enforced dynamically by tests, but a test only checks
where it happens to look; one ``time.monotonic()`` on a sim path or one
read of a donated JAX buffer silently breaks reproducibility.

``simlint`` (python -m shadow_tpu.analysis.simlint) proves the invariants
statically, codebase-wide, on every PR — see simlint.py for the engine and
rules.py for the rule catalog (SIM001-SIM006).

``simrace`` (python -m shadow_tpu.analysis.simrace) reuses the same
engine, severity model, pragma and allowlist machinery for the
CONCURRENCY contracts, analyzing the package as a whole: lock identities
and lock-order edges, thread-shared state, blocking calls under locks
(race_rules.py, SIM101-SIM103) and the parent<->shard tag protocol
model-checked as a pair of communicating state machines (protocol.py,
SIM110).

Import ``shadow_tpu.analysis.simlint`` / ``.simrace`` directly for the
APIs (lint_paths, lint_source, race_paths, race_sources); the package
module stays import-free so ``python -m`` execution of the submodules is
clean.
"""
