"""simgen: spec-authoritative protocol codegen for the three planes.

PR 6 (simtwin) extracted ONE table-driven IR from the three hand-synced
protocol planes and diffed them at lint time; ``spec/protocol.json`` was
the *extracted* seed artifact.  simgen inverts the direction (ROADMAP
item 3): ``spec/protocol_spec.json`` is now AUTHORITATIVE, and the
protocol surfaces it names — the canonical constants, the TCP
state-transition table, the token-bucket/CoDel hop-math coefficients,
and the congestion-control coefficient families — are *emitted* into
fenced, checksummed regions of the Python plane, the native C plane and
the JAX/numpy kernel modules.  A protocol change is now one spec edit +
``make gen``, not three hand-synced transcriptions.

The verification stack, outermost first:

* ``make gen-check`` (== ``simgen --check``, wired into ``make lint``):
  every declared region byte-matches what the generator would emit
  today (stale spec or hand edit both fail), and the *read-back* gate
  re-extracts the planes with simtwin's extractors and diffs the IR
  against the spec — the generated code must mean what the spec says,
  not merely look generated.
* SIM205 (twin_rules): lint-time detection of hand edits inside a
  fenced region (``body=`` digest drift) and of regions older than the
  spec (``spec=`` digest drift), with the shared pragma vocabulary.
* SIM201-204 keep diffing the planes against each other, and
  ``spec/protocol.json`` (the extracted IR) stays checked in and
  byte-stable — regeneration after ``make gen`` is part of the flow.

Usage::

    python -m shadow_tpu.analysis.simgen [--check | --write | --list]
        [--spec PATH] [--root PATH] [--no-readback]

Exit status: 0 = clean, 1 = stale/hand-edited/IR-drift, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

from . import logic_ir
from .genmark import (SPEC_RELPATH, begin_marker, end_marker, scan_regions,
                      sha12)

PY, C = "#", "//"


# ---------------------------------------------------------------------------
# spec loading

def load_spec(path: str) -> Tuple[Dict, str]:
    """(spec dict, sha12 of the exact file bytes)."""
    with open(path, "rb") as f:
        blob = f.read()
    return json.loads(blob.decode("utf-8")), sha12(blob)


def canonical_spec_bytes(spec: Dict) -> bytes:
    return (json.dumps(spec, indent=2, sort_keys=True) + "\n").encode()


# ---------------------------------------------------------------------------
# renderers: spec -> region body lines (indent included where non-zero)

def _pairs(spec: Dict) -> List[Tuple[str, str]]:
    out = []
    for p in spec["transitions"]["pairs"]:
        frm, _, to = p.partition(" -> ")
        out.append((frm, to))
    return out


def _variant_class_name(name: str, base: str) -> str:
    # "cubicx" extending "cubic" -> CubicX
    return base.capitalize() + name[len(base):].upper()


def _r_wire_defs(spec: Dict) -> List[str]:
    c = spec["constants"]
    assert c["MSS"] == c["MTU"] - (c["HDR_TCP"] - 14), \
        "spec MSS must equal MTU - (HDR_TCP - 14)"
    ms = 1000000
    return [
        "# Ethernet/IP framing (reference definitions.h:169-193).",
        f"CONFIG_HEADER_SIZE_UDPIPETH = {c['HDR_UDP']}    "
        "# UDP+IP+ETH header bytes",
        f"CONFIG_HEADER_SIZE_TCPIPETH = {c['HDR_TCP']}    "
        "# TCP+IP+ETH header bytes (with options)",
        f"CONFIG_MTU = {c['MTU']}",
        f"CONFIG_DATAGRAM_MAX_SIZE = {c['DGRAM_MAX']}",
        "CONFIG_TCP_MAX_SEGMENT_SIZE = CONFIG_MTU - "
        f"(CONFIG_HEADER_SIZE_TCPIPETH - 14)  # {c['MSS']}",
        "",
        "# Interface token bucket "
        "(reference network_interface.c:93-95, 207-214).",
        f"INTERFACE_REFILL_INTERVAL_NS = {c['REFILL_INTERVAL_NS']}"
        "        # 1 ms token refill",
        f"INTERFACE_CAPACITY_FACTOR = {c['CAPACITY_FACTOR']}"
        "                   # capacity = refill*factor + MTU",
        "",
        "# TCP buffer caps (reference definitions.h:109-114).",
        f"CONFIG_TCP_WMEM_MAX = {c['WMEM_MAX']}",
        f"CONFIG_TCP_RMEM_MAX = {c['RMEM_MAX']}",
        "",
        "# TCP retransmit-timer bounds, ms "
        "(reference definitions.h:115-131).",
        f"CONFIG_TCP_RTO_INIT_MS = {c['RTO_INIT_NS'] // ms}",
        f"CONFIG_TCP_RTO_MIN_MS = {c['RTO_MIN_NS'] // ms}",
        f"CONFIG_TCP_RTO_MAX_MS = {c['RTO_MAX_NS'] // ms}",
    ]


def _r_clock(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "# One simulated nanosecond is the base unit.",
        "SIM_TIME_NS = 1",
        f"SIM_TIME_US = {c['SIM_TIME_MS'] // 1000}",
        f"SIM_TIME_MS = {c['SIM_TIME_MS']}",
        f"SIM_TIME_SEC = {c['SIM_TIME_SEC']}",
    ]


def _r_tcp_flags(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "# TCP header flag bits (reference tcp.c enum ProtocolTCPFlags).",
        "TCP_NONE = 0",
        f"TCP_RST = {c['FLAG_RST']}",
        f"TCP_SYN = {c['FLAG_SYN']}",
        f"TCP_ACK = {c['FLAG_ACK']}",
        f"TCP_FIN = {c['FLAG_FIN']}",
    ]


def _r_status_bits(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "# Status bits (reference descriptor.h DS_*).",
        "S_NONE = 0",
        f"S_ACTIVE = {c['S_ACTIVE']}",
        f"S_READABLE = {c['S_READABLE']}",
        f"S_WRITABLE = {c['S_WRITABLE']}",
        f"S_CLOSED = {c['S_CLOSED']}",
    ]


def _r_epoll_bits(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"EPOLLIN = 0x{c['EPOLLIN']:03x}",
        f"EPOLLOUT = 0x{c['EPOLLOUT']:03x}",
        f"EPOLLERR = 0x{c['EPOLLERR']:03x}",
        f"EPOLLHUP = 0x{c['EPOLLHUP']:03x}",
    ]


def _r_c_epoll_bits(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "// epoll readiness bits (descriptor/epoll.py) — the C-side",
        "// readiness cache (ISSUE 12) computes revents for epoll-watched",
        "// native sockets with these",
        f"enum {{ EPOLLIN = 0x{c['EPOLLIN']:03x}, "
        f"EPOLLOUT = 0x{c['EPOLLOUT']:03x}, "
        f"EPOLLERR = 0x{c['EPOLLERR']:03x}, "
        f"EPOLLHUP = 0x{c['EPOLLHUP']:03x} }};",
    ]


def _r_port_alloc(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"MIN_EPHEMERAL_PORT = {c['MIN_EPHEMERAL_PORT']}",
        f"MAX_PORT = {c['MAX_PORT']}",
    ]


def _r_threefry(spec: Dict) -> List[str]:
    c = spec["constants"]
    rots = ", ".join(str(r) for r in c["THREEFRY_ROTATIONS"])
    return [
        "# Threefry-2x32 rotation constants (Salmon et al., Table 2).",
        f"_ROTATIONS = ({rots})",
        f"_PARITY = 0x{c['THREEFRY_PARITY']:X}  # SKEIN_KS_PARITY32",
    ]


def _r_tcp_states(spec: Dict) -> List[str]:
    lines = ["# states (reference tcp.c enum TCPState :42-47)"]
    for st in spec["transitions"]["states"]:
        lines.append(f"{st.upper()} = \"{st}\"")
    lines += [
        "",
        "# The spec's legal (from, to) transition pairs; \"?\" = an",
        "# assignment no state guard encloses.",
        "TCP_TRANSITIONS = (",
    ]
    for frm, to in _pairs(spec):
        lines.append(f"    (\"{frm}\", \"{to}\"),")
    lines.append(")")
    return lines


def _r_tcp_timers(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"RTO_INIT_NS = {c['RTO_INIT_NS']}",
        f"RTO_MIN_NS = {c['RTO_MIN_NS']}",
        f"RTO_MAX_NS = {c['RTO_MAX_NS']}",
        f"TIME_WAIT_NS = {c['TIME_WAIT_NS']}"
        "        # 2*MSL teardown hold",
        f"MAX_SYN_RETRIES = {c['MAX_SYN_RETRIES']}"
        "                           # Linux tcp_syn_retries default",
        f"MAX_RETRIES = {c['MAX_RETRIES']}"
        "                              # Linux tcp_retries2",
        f"MAX_SACK_BLOCKS = {c['MAX_SACK_BLOCKS']}",
    ]


def _r_codel_params(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"    TARGET_NS = {c['CODEL_TARGET_NS']}",
        f"    INTERVAL_NS = {c['CODEL_INTERVAL_NS']}",
        f"    HARD_LIMIT = {c['CODEL_HARD_LIMIT']}  # packets",
    ]


def _r_router_static(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"STATIC_CAPACITY = {c['STATIC_CAPACITY']}"
        "  # packets (reference router_queue_static.c)",
    ]


def _r_congestion_params(spec: Dict) -> List[str]:
    c = spec["constants"]
    lines = ["# CUBIC coefficient families (RFC 9438 §4.1 / §4.6)."]
    for name, var in sorted(spec["congestion"]["variants"].items()):
        lines.append(f"{var['c_const']} = {c[var['c_const']]!r}"
                     f"      # {name}: scaling constant")
        lines.append(f"{var['beta_const']} = {c[var['beta_const']]!r}"
                     f"   # {name}: multiplicative decrease")
    return lines


def _r_congestion_variants(spec: Dict) -> List[str]:
    c = spec["constants"]
    lines: List[str] = []
    generated: List[Tuple[str, str]] = []
    for name, var in sorted(spec["congestion"]["variants"].items()):
        base = var.get("base")
        if base is None:
            continue              # the base algorithm is hand-written
        cls = _variant_class_name(name, base)
        generated.append((name, cls))
        lines += [
            f"class {cls}({base.capitalize()}):",
            f"    \"\"\"Spec-defined CUBIC variant {name!r}: "
            f"(C, beta) = ({c[var['c_const']]!r}, "
            f"{c[var['beta_const']]!r}).",
            "",
            f"    Same window-growth machinery as {base.capitalize()} "
            "(the base class reads",
            "    ``self.C``/``self.BETA``); only the coefficients "
            "differ.",
            "    \"\"\"",
            "",
            f"    name = \"{name}\"",
            f"    C = {var['c_const']}",
            f"    BETA = {var['beta_const']}",
            "",
            "",
        ]
    lines += _r_family_classes(spec)
    for name in sorted(spec["congestion"].get("families", {})):
        generated.append((name, _family_class_name(spec, name)))
    lines.append("# config token -> generated class "
                 "(make_congestion_control consults this)")
    lines.append("CC_GENERATED = {")
    for name, cls in sorted(generated):
        lines.append(f"    \"{name}\": {cls},")
    lines.append("}")
    return lines


def _logic_functions(spec: Dict, group: Optional[str] = None
                     ) -> List[Tuple[str, Dict]]:
    fns = spec.get("logic", {}).get("functions", {})
    return [(name, fns[name]) for name in sorted(fns)
            if group is None or fns[name].get("group") == group]


def _resolved_expr(spec: Dict, fn: Dict):
    logic_ir.validate(fn["expr"], fn["args"], spec["constants"])
    return logic_ir.resolve(fn["expr"], spec["constants"])


def _bbrx_const_names(spec: Dict) -> List[str]:
    return sorted(n for n in spec["constants"] if n.startswith("BBRX_"))


def _py_logic_lines(spec: Dict, group: str) -> List[str]:
    lines: List[str] = []
    for name, fn in _logic_functions(spec, group):
        expr = logic_ir.emit_py(_resolved_expr(spec, fn))
        lines += [
            f"def {logic_ir.plane_symbol(name, 'py')}"
            f"({', '.join(fn['args'])}):",
            f"    \"\"\"{fn['doc']}\"\"\"",
            f"    return {expr}",
            "",
            "",
        ]
    while lines and lines[-1] == "":
        lines.pop()
    return lines


def _r_tcp_logic(spec: Dict) -> List[str]:
    lines = [
        "# RTT/RTO update logic, generated from the spec's expression IR",
        "# (SIM206 parses these bodies back and compares them to the "
        "spec).",
        "",
    ]
    lines += _py_logic_lines(spec, "rtt")
    return lines


def _r_congestion_logic(spec: Dict) -> List[str]:
    c = spec["constants"]
    lines = ["# bbrx estimator parameters (spec surface: congestion)"]
    for name in _bbrx_const_names(spec):
        lines.append(f"{name} = {c[name]}")
    lines += [
        "",
        "",
        "# congestion update logic, generated from the spec's "
        "expression IR",
        "",
    ]
    lines += _py_logic_lines(spec, "cc")
    return lines


def _family_class_name(spec: Dict, name: str) -> str:
    return spec["congestion"]["families"][name]["class"]


def _r_family_classes(spec: Dict) -> List[str]:
    """The generated CC family classes (ISSUE 19).  The expressions come
    from the spec's logic IR (via the ``_g_*`` helpers emitted into the
    congestion-logic region); the hook scaffold below is the generator's
    one estimator shape, so an unknown family fails generation loudly
    instead of emitting garbage."""
    fams = spec["congestion"].get("families", {})
    unknown = sorted(set(fams) - {"bbrx"})
    if unknown:
        raise ValueError(
            f"no generator scaffold for congestion families {unknown}; "
            f"teach simgen._r_family_classes before adding them")
    if "bbrx" not in fams:
        return []
    cls = _family_class_name(spec, "bbrx")
    return [
        f"class {cls}(CongestionControl):",
        "    \"\"\"Spec-defined 'bbrx' (ISSUE 19): a BBR-flavored "
        "family — windowed",
        "    bandwidth (max filter + loss decay), min-RTT from ACK "
        "spacing, a",
        "    pacing-gain cycle, and an inflight cap from the BDP.  "
        "Every update",
        "    expression is generated from the spec's logic IR; this "
        "class holds",
        "    only the estimator state and the hook wiring.",
        "    \"\"\"",
        "",
        "    name = \"bbrx\"",
        "",
        "    def __init__(self, mss, ssthresh=0,",
        "                 init_segments=INIT_CWND_SEGMENTS):",
        "        super().__init__(mss, ssthresh, init_segments)",
        "        self.btl_bw_bps = 0",
        "        self.min_rtt_ns = BBRX_RTT_CAP_NS",
        "        self.last_ack_ns = 0",
        "        self.cycle_idx = 0",
        "        self.cycle_start_ns = 0",
        "",
        "    def on_new_ack(self, acked_bytes, snd_una, now_ns):",
        "        if self.in_fast_recovery:",
        "            if snd_una >= self.recovery_point:",
        "                self._exit_recovery()",
        "            else:",
        "                return  # partial ACK: stay in recovery",
        "        if self.last_ack_ns > 0:",
        "            interval_ns = now_ns - self.last_ack_ns",
        "            self.btl_bw_bps = _g_bbrx_btl_bw(",
        "                self.btl_bw_bps,",
        "                _g_bbrx_bw_sample(acked_bytes, interval_ns))",
        "            self.min_rtt_ns = _g_bbrx_min_rtt(self.min_rtt_ns,",
        "                                              interval_ns)",
        "        self.last_ack_ns = now_ns",
        "        if now_ns - self.cycle_start_ns >= BBRX_CYCLE_NS:",
        "            self.cycle_idx = _g_bbrx_next_cycle(self.cycle_idx)",
        "            self.cycle_start_ns = now_ns",
        "        if self.btl_bw_bps > 0:",
        "            self.cwnd = _g_bbrx_inflight_cap(",
        "                _g_bbrx_bdp_bytes(self.btl_bw_bps, "
        "self.min_rtt_ns),",
        "                _g_bbrx_gain_num(self.cycle_idx), self.mss)",
        "",
        "    def _enter_recovery(self, snd_nxt):",
        "        self.btl_bw_bps = _g_bbrx_bw_decay(self.btl_bw_bps)",
        "        self.ssthresh = _g_ssthresh_after_loss(self.cwnd, "
        "self.mss)",
        "        self.cwnd = _g_recovery_cwnd(self.ssthresh, self.mss)",
        "        self.in_fast_recovery = True",
        "        self.recovery_point = snd_nxt",
        "",
        "    def on_timeout(self):",
        "        self.btl_bw_bps = _g_bbrx_bw_decay(self.btl_bw_bps)",
        "        self.ssthresh = _g_ssthresh_after_loss(self.cwnd, "
        "self.mss)",
        "        self.cwnd = self.mss",
        "        self.in_fast_recovery = False",
        "        self._avoid_acc = 0",
        "",
        "",
    ]


def _r_token_bucket_kernel(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"REFILL_NS = {c['REFILL_INTERVAL_NS']}"
        "   # == defs.INTERFACE_REFILL_INTERVAL_NS (1 ms)",
    ]


def _r_protocol_tables(spec: Dict) -> List[str]:
    c = spec["constants"]
    states = spec["transitions"]["states"]
    lines = [
        "# TCP state universe, reference-enum order; the tuple index IS",
        "# the C-plane TcpState id.",
        "TCP_STATES = (",
    ]
    for st in states:
        lines.append(f"    \"{st}\",")
    lines += [
        ")",
        "",
        "# Legal (from, to) transition pairs; \"?\" = unguarded.",
        "TCP_TRANSITIONS = (",
    ]
    for frm, to in _pairs(spec):
        lines.append(f"    (\"{frm}\", \"{to}\"),")
    lines += [")", "", "# Congestion-control coefficient families "
              "+ config-token kind ids."]
    variants = sorted(spec["congestion"]["variants"].items())
    for name, var in variants:
        lines.append(f"{var['c_const']} = {c[var['c_const']]!r}")
        lines.append(f"{var['beta_const']} = {c[var['beta_const']]!r}")
    kinds = sorted(spec["congestion"]["kinds"].items())
    lines.append("CC_KIND_IDS = {"
                 + ", ".join(f"\"{k}\": {v}" for k, v in kinds) + "}")
    by_kind = {var["kind"]: var for _, var in variants}
    lines.append("# (C, beta) per kind id; non-cubic kinds carry the "
                 "cubic defaults (unused)")
    lines.append("CC_COEFFS = {")
    for k, kid in kinds:
        var = by_kind.get(kid, dict(spec["congestion"]["variants"]["cubic"]))
        lines.append(f"    {kid}: ({var['c_const']}, "
                     f"{var['beta_const']}),  # {k}")
    lines.append("}")
    return lines


def _r_c_constants(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "// ---- constants (mirror core/defs.py / descriptor/tcp.py) "
        "------------------",
        f"constexpr int64_t SIM_MS = {c['SIM_TIME_MS']}LL;",
        f"constexpr int64_t SIM_SEC = {c['SIM_TIME_SEC']}LL;",
        f"constexpr int HDR_UDP = {c['HDR_UDP']};",
        f"constexpr int HDR_TCP = {c['HDR_TCP']};",
        f"constexpr int64_t MTU = {c['MTU']};",
        f"constexpr int64_t MSS = {c['MTU']} - ({c['HDR_TCP']} - 14);"
        f"          // {c['MSS']}",
        f"constexpr int64_t RTO_INIT = {c['RTO_INIT_NS']}LL;",
        f"constexpr int64_t RTO_MIN = {c['RTO_MIN_NS']}LL;",
        f"constexpr int64_t RTO_MAX = {c['RTO_MAX_NS']}LL;",
        f"constexpr int64_t TIME_WAIT_NS = {c['TIME_WAIT_NS']}LL;",
        f"constexpr int MAX_SYN_RETRIES = {c['MAX_SYN_RETRIES']};",
        f"constexpr int MAX_RETRIES = {c['MAX_RETRIES']};"
        "                    // Linux tcp_retries2",
        f"constexpr int MAX_SACK_BLOCKS = {c['MAX_SACK_BLOCKS']};",
        f"constexpr int64_t RMEM_MAX = {c['RMEM_MAX']};",
        f"constexpr int64_t WMEM_MAX = {c['WMEM_MAX']};",
        f"constexpr int64_t REFILL_INTERVAL = {c['REFILL_INTERVAL_NS']}LL;"
        "     // 1 ms",
        f"constexpr int64_t CAPACITY_FACTOR = {c['CAPACITY_FACTOR']};",
        f"constexpr int64_t DGRAM_MAX = {c['DGRAM_MAX']};",
        f"constexpr int64_t CODEL_TARGET = {c['CODEL_TARGET_NS']}LL;",
        f"constexpr int64_t CODEL_INTERVAL = {c['CODEL_INTERVAL_NS']}LL;",
        f"constexpr int CODEL_HARD_LIMIT = {c['CODEL_HARD_LIMIT']};",
        f"constexpr int STATIC_CAPACITY = {c['STATIC_CAPACITY']};",
        "",
        "// descriptor status bits (descriptor/base.py)",
        f"enum {{ S_ACTIVE = {c['S_ACTIVE']}, "
        f"S_READABLE = {c['S_READABLE']}, "
        f"S_WRITABLE = {c['S_WRITABLE']}, S_CLOSED = {c['S_CLOSED']} }};",
        "// TCP header flags (routing/packet.py)",
        f"enum {{ F_RST = {c['FLAG_RST']}, F_SYN = {c['FLAG_SYN']}, "
        f"F_ACK = {c['FLAG_ACK']}, F_FIN = {c['FLAG_FIN']} }};",
    ]


def _chunked(tokens: List[str], per_line: int = 5) -> List[str]:
    return ["  " + ", ".join(tokens[i:i + per_line]) + ","
            for i in range(0, len(tokens), per_line)]


def _r_c_tcp_states(spec: Dict) -> List[str]:
    states = spec["transitions"]["states"]
    lines = ["enum TcpState {"]
    lines += _chunked([f"ST_{s.upper()}" + (" = 0" if i == 0 else "")
                       for i, s in enumerate(states)])
    lines += ["};", "const char *const STATE_NAMES[] = {"]
    lines += _chunked([f"\"{s}\"" for s in states])
    lines += [
        "};",
        "// the spec's legal transition table; 255 = any state ('?')",
        "struct TcpTransition { unsigned char from, to; };",
        "constexpr TcpTransition TCP_TRANSITIONS[] = {",
    ]
    for frm, to in _pairs(spec):
        f_tok = "255" if frm == "?" else f"ST_{frm.upper()}"
        lines.append(f"  {{{f_tok}, ST_{to.upper()}}},")
    lines += [
        "};",
        "constexpr int TCP_TRANSITION_COUNT =",
        "    (int)(sizeof(TCP_TRANSITIONS) / sizeof(TCP_TRANSITIONS[0]));",
    ]
    return lines


def _r_c_congestion_params(spec: Dict) -> List[str]:
    c = spec["constants"]
    kinds = sorted(spec["congestion"]["kinds"].items(), key=lambda kv: kv[1])
    enum_body = ", ".join(f"CC_{k.upper()} = {v}" for k, v in kinds)
    lines = [f"enum CcKind {{ {enum_body} }};",
             "// CUBIC coefficient families (RFC 9438 §4.1 / §4.6)"]
    cubics = [(n, v) for n, v in sorted(spec["congestion"]["variants"]
                                        .items())]
    for name, var in cubics:
        lines.append(f"constexpr double {var['c_const']} = "
                     f"{c[var['c_const']]!r};")
        lines.append(f"constexpr double {var['beta_const']} = "
                     f"{c[var['beta_const']]!r};")
    is_cubic = " || ".join(f"kind == CC_{n.upper()}" for n, _ in cubics)
    lines += [f"inline bool cc_is_cubic(int kind) {{ return {is_cubic}; }}"]
    for field in ("c", "beta"):
        expr = f"CUBIC_{field.upper()}"
        for name, var in cubics:
            if var.get("base") is None:
                continue
            expr = (f"kind == CC_{name.upper()} ? "
                    f"{var[field + '_const']} : " + expr)
        lines.append(f"inline double cc_{field}(int kind) "
                     f"{{ return {expr}; }}")
    return lines


def _r_c_protocol_logic(spec: Dict) -> List[str]:
    """All spec logic functions as pure int64 free functions, plus the
    bbrx parameter constants.  ``gen_i64_min/max`` exist so the emitted
    expressions stay call-shaped (parseable by the SIM206 read-back)
    instead of template-instantiated ``std::max<int64_t>`` spellings."""
    c = spec["constants"]
    lines = [
        "// generated int64 protocol-update logic (spec 'logic' IR); "
        "SIM206",
        "// parses each body back to the IR and compares it to the spec.",
        "static inline int64_t gen_i64_min(int64_t a, int64_t b) "
        "{ return a < b ? a : b; }",
        "static inline int64_t gen_i64_max(int64_t a, int64_t b) "
        "{ return a > b ? a : b; }",
        "// bbrx estimator parameters (spec surface: congestion)",
    ]
    for name in _bbrx_const_names(spec):
        lines.append(f"constexpr int64_t {name} = {c[name]}LL;")
    for name, fn in _logic_functions(spec):
        expr = logic_ir.emit_c(_resolved_expr(spec, fn))
        args = ", ".join(f"int64_t {a}" for a in fn["args"])
        lines += [
            f"// {fn['doc']}",
            f"static inline int64_t "
            f"{logic_ir.plane_symbol(name, 'c')}({args}) {{",
            f"  return {expr};",
            "}",
        ]
    return lines


def _r_c_congestion_logic(spec: Dict) -> List[str]:
    """The generated-family estimator state + hook dispatch, emitted
    INSIDE ``struct Cong`` (the hand hooks call ``gen_on_*`` first and
    return when a generated family handled the event).  Mirrors the
    Python ``BbrX`` scaffold statement for statement."""
    fams = spec["congestion"].get("families", {})
    unknown = sorted(set(fams) - {"bbrx"})
    if unknown:
        raise ValueError(
            f"no generator scaffold for congestion families {unknown}; "
            f"teach simgen._r_c_congestion_logic before adding them")
    if "bbrx" not in fams:
        return ["  // no generated congestion families in the spec",
                "  void gen_init() {}",
                "  bool gen_on_new_ack(int64_t, int64_t, int64_t) "
                "{ return false; }",
                "  bool gen_on_duplicate_ack(int, int64_t, bool*) "
                "{ return false; }",
                "  bool gen_on_timeout() { return false; }"]
    return [
        "  // generated 'bbrx' estimator state + dispatch (spec "
        "congestion.families)",
        "  int64_t gx_btl_bw_bps = 0;",
        "  int64_t gx_min_rtt_ns = BBRX_RTT_CAP_NS;",
        "  int64_t gx_last_ack_ns = 0;",
        "  int64_t gx_cycle_idx = 0;",
        "  int64_t gx_cycle_start_ns = 0;",
        "",
        "  void gen_init() {",
        "    gx_btl_bw_bps = 0;",
        "    gx_min_rtt_ns = BBRX_RTT_CAP_NS;",
        "    gx_last_ack_ns = 0;",
        "    gx_cycle_idx = 0;",
        "    gx_cycle_start_ns = 0;",
        "  }",
        "",
        "  // each hook returns true when a generated family handled "
        "the event",
        "  bool gen_on_new_ack(int64_t acked_bytes, int64_t snd_una, "
        "int64_t now_ns) {",
        "    if (kind != CC_BBRX) return false;",
        "    if (in_fast_recovery) {",
        "      if (snd_una >= recovery_point) exit_recovery();",
        "      else return true;  // partial ACK: stay in recovery",
        "    }",
        "    if (gx_last_ack_ns > 0) {",
        "      int64_t interval_ns = now_ns - gx_last_ack_ns;",
        "      gx_btl_bw_bps = gen_bbrx_btl_bw(",
        "          gx_btl_bw_bps, gen_bbrx_bw_sample(acked_bytes, "
        "interval_ns));",
        "      gx_min_rtt_ns = gen_bbrx_min_rtt(gx_min_rtt_ns, "
        "interval_ns);",
        "    }",
        "    gx_last_ack_ns = now_ns;",
        "    if (now_ns - gx_cycle_start_ns >= BBRX_CYCLE_NS) {",
        "      gx_cycle_idx = gen_bbrx_next_cycle(gx_cycle_idx);",
        "      gx_cycle_start_ns = now_ns;",
        "    }",
        "    if (gx_btl_bw_bps > 0) {",
        "      cwnd = gen_bbrx_inflight_cap(",
        "          gen_bbrx_bdp_bytes(gx_btl_bw_bps, gx_min_rtt_ns),",
        "          gen_bbrx_gain_num(gx_cycle_idx), mss);",
        "    }",
        "    return true;",
        "  }",
        "",
        "  bool gen_on_duplicate_ack(int count, int64_t snd_nxt, "
        "bool* retransmit) {",
        "    if (kind != CC_BBRX) return false;",
        "    *retransmit = false;",
        "    if (count == 3 && !in_fast_recovery) {",
        "      gx_btl_bw_bps = gen_bbrx_bw_decay(gx_btl_bw_bps);",
        "      ssthresh = gen_ssthresh_after_loss(cwnd, mss);",
        "      cwnd = gen_recovery_cwnd(ssthresh, mss);",
        "      in_fast_recovery = true;",
        "      recovery_point = snd_nxt;",
        "      *retransmit = true;",
        "      return true;",
        "    }",
        "    if (in_fast_recovery) cwnd += mss;",
        "    return true;",
        "  }",
        "",
        "  bool gen_on_timeout() {",
        "    if (kind != CC_BBRX) return false;",
        "    gx_btl_bw_bps = gen_bbrx_bw_decay(gx_btl_bw_bps);",
        "    ssthresh = gen_ssthresh_after_loss(cwnd, mss);",
        "    cwnd = mss;",
        "    in_fast_recovery = false;",
        "    avoid_acc = 0;",
        "    return true;",
        "  }",
    ]


def _r_kernel_logic(spec: Dict) -> List[str]:
    """The kernel plane's numpy mirror of every logic function (int64
    in, int64 out; ``np.where``/``np.minimum``/``np.maximum`` spell
    select/min/max so the same read-back grammar covers this plane)."""
    c = spec["constants"]
    lines = ["# bbrx estimator parameters (mirrors descriptor/"
             "tcp_cong.py)"]
    for name in _bbrx_const_names(spec):
        lines.append(f"{name} = {c[name]}")
    lines += [
        "",
        "",
        "# protocol-update logic, generated from the spec's expression "
        "IR;",
        "# elementwise over int64 arrays (device-vs-numpy parity is "
        "pinned in tests)",
        "",
    ]
    for name, fn in _logic_functions(spec):
        expr = logic_ir.emit_np(_resolved_expr(spec, fn))
        lines += [
            f"def {logic_ir.plane_symbol(name, 'kernel')}"
            f"({', '.join(fn['args'])}):",
            f"    \"\"\"{fn['doc']}\"\"\"",
            f"    return {expr}",
            "",
            "",
        ]
    while lines and lines[-1] == "":
        lines.pop()
    return lines


# ---------------------------------------------------------------------------
# the emission table: every declared region, in file order

RegionDef = Tuple[str, str, str, Callable[[Dict], List[str]]]
#             (relpath, region name, comment lead, renderer)

REGIONS: List[RegionDef] = [
    ("shadow_tpu/core/defs.py", "wire-defs", PY, _r_wire_defs),
    ("shadow_tpu/core/stime.py", "clock", PY, _r_clock),
    ("shadow_tpu/routing/packet.py", "tcp-flags", PY, _r_tcp_flags),
    ("shadow_tpu/descriptor/base.py", "status-bits", PY, _r_status_bits),
    ("shadow_tpu/descriptor/epoll.py", "epoll-bits", PY, _r_epoll_bits),
    ("shadow_tpu/host/host.py", "port-alloc", PY, _r_port_alloc),
    ("shadow_tpu/core/rng.py", "threefry", PY, _r_threefry),
    ("shadow_tpu/descriptor/tcp.py", "tcp-states", PY, _r_tcp_states),
    ("shadow_tpu/descriptor/tcp.py", "tcp-timers", PY, _r_tcp_timers),
    ("shadow_tpu/descriptor/tcp.py", "tcp-logic", PY, _r_tcp_logic),
    ("shadow_tpu/host/router.py", "router-static", PY, _r_router_static),
    ("shadow_tpu/host/router.py", "codel-params", PY, _r_codel_params),
    ("shadow_tpu/descriptor/tcp_cong.py", "congestion-params", PY,
     _r_congestion_params),
    ("shadow_tpu/descriptor/tcp_cong.py", "congestion-logic", PY,
     _r_congestion_logic),
    ("shadow_tpu/descriptor/tcp_cong.py", "congestion-variants", PY,
     _r_congestion_variants),
    ("shadow_tpu/ops/bandwidth.py", "token-bucket-kernel", PY,
     _r_token_bucket_kernel),
    ("shadow_tpu/ops/protocol_tables.py", "protocol-tables", PY,
     _r_protocol_tables),
    ("shadow_tpu/ops/protocol_tables.py", "kernel-logic", PY,
     _r_kernel_logic),
    ("native/dataplane.cc", "c-protocol-constants", C, _r_c_constants),
    ("native/dataplane.cc", "c-epoll-bits", C, _r_c_epoll_bits),
    ("native/dataplane.cc", "c-tcp-states", C, _r_c_tcp_states),
    ("native/dataplane.cc", "c-congestion-params", C,
     _r_c_congestion_params),
    ("native/dataplane.cc", "c-protocol-logic", C, _r_c_protocol_logic),
    ("native/dataplane.cc", "c-congestion-logic", C,
     _r_c_congestion_logic),
]

SURFACE_OF_REGION: Dict[str, str] = {
    "wire-defs": "constants", "clock": "constants",
    "tcp-flags": "constants", "status-bits": "constants",
    "port-alloc": "constants", "threefry": "constants",
    "tcp-timers": "constants", "c-protocol-constants": "constants",
    "epoll-bits": "constants", "c-epoll-bits": "constants",
    "token-bucket-kernel": "hop-math", "router-static": "hop-math",
    "codel-params": "hop-math",
    "tcp-states": "transitions", "c-tcp-states": "transitions",
    "protocol-tables": "transitions",
    "congestion-params": "congestion", "congestion-variants": "congestion",
    "c-congestion-params": "congestion",
    "tcp-logic": "logic", "congestion-logic": "logic",
    "kernel-logic": "logic", "c-protocol-logic": "logic",
    "c-congestion-logic": "logic",
}


def render_body(name: str, spec: Dict) -> str:
    for _, rname, _, renderer in REGIONS:
        if rname == name:
            return "".join(ln + "\n" for ln in renderer(spec))
    raise KeyError(f"no renderer for region {name!r}")


# ---------------------------------------------------------------------------
# apply / check

def _regions_by_file() -> Dict[str, List[RegionDef]]:
    out: Dict[str, List[RegionDef]] = {}
    for rd in REGIONS:
        out.setdefault(rd[0], []).append(rd)
    return out


def rewrite_text(text: str, defs: List[RegionDef], spec: Dict,
                 spec_hash: str) -> Tuple[str, List[str], List[str]]:
    """Replace every declared region of one file's text.

    Returns (new_text, changed region names, problems)."""
    regions, scan_problems = scan_regions(text)
    problems = [f"line {ln}: {msg}" for ln, msg in scan_problems]
    by_name = {r.name: r for r in regions}
    lines = text.splitlines()
    changed: List[str] = []
    # replace bottom-up so earlier line numbers stay valid
    def _key(d):
        reg = by_name.get(d[1])
        return -reg.begin_line if reg is not None else 0

    for _, name, lead, renderer in sorted(defs, key=_key):
        reg = by_name.get(name)
        if reg is None:
            problems.append(f"region {name!r}: markers not found")
            continue
        body = "".join(ln + "\n" for ln in renderer(spec))
        bh = sha12(body)
        if reg.body == body and reg.body_hash == bh \
                and reg.spec_hash == spec_hash:
            continue
        changed.append(name)
        new_block = [begin_marker(name, lead, spec_hash, bh, reg.indent)]
        new_block += body.splitlines()
        new_block.append(end_marker(name, lead, reg.indent))
        lines[reg.begin_line - 1:reg.end_line] = new_block
    return "".join(ln + "\n" for ln in lines), changed, problems


def check_text(path: str, text: str, defs: List[RegionDef], spec: Dict,
               spec_hash: str) -> List[str]:
    """Diagnostics for one file (empty = clean)."""
    out: List[str] = []
    regions, scan_problems = scan_regions(text)
    for ln, msg in scan_problems:
        out.append(f"{path}:{ln}: {msg}")
    by_name = {r.name: r for r in regions}
    declared = {d[1] for d in defs}
    for name in sorted(set(by_name) - declared):
        out.append(f"{path}:{by_name[name].begin_line}: region {name!r} "
                   f"is not declared in simgen's emission table")
    for _, name, _, renderer in defs:
        reg = by_name.get(name)
        if reg is None:
            out.append(f"{path}: region {name!r} markers not found — "
                       f"add the fence and run `make gen`")
            continue
        body = "".join(ln + "\n" for ln in renderer(spec))
        if sha12(reg.body) != reg.body_hash:
            out.append(f"{path}:{reg.begin_line}: region {name!r} was "
                       f"edited by hand (body digest drift) — edit "
                       f"{SPEC_RELPATH} instead and run `make gen`")
        elif reg.body != body:
            out.append(f"{path}:{reg.begin_line}: region {name!r} is "
                       f"stale — the spec or the generator changed; "
                       f"run `make gen`")
        elif reg.spec_hash != spec_hash:
            out.append(f"{path}:{reg.begin_line}: region {name!r} was "
                       f"emitted from an older spec "
                       f"(spec={reg.spec_hash}, current={spec_hash}) — "
                       f"run `make gen`")
    return out


# ---------------------------------------------------------------------------
# read-back: the generated planes must extract to the spec's IR

def readback_diffs(root: str, spec: Dict) -> List[str]:
    """Re-extract the planes with simtwin's extractors and diff the IR
    against the authoritative spec (values, transition tables, and the
    congestion coefficient families)."""
    from .simlint import load_config
    from .simtwin import _load_mapped_sources, load_map
    from .twin_rules import TwinModel
    config = load_config(os.path.join(root, "pyproject.toml"))
    surface_map = load_map(None, config)
    sources = _load_mapped_sources(config, surface_map)
    twin = TwinModel(sources, surface_map)
    out: List[str] = []
    want = spec["constants"]
    got = twin.constants_by_canonical()
    # constants referenced by the logic IR are verified structurally by
    # the expression read-back below (their regex probes are retired, so
    # a plane no longer "spells" them as a named constant)
    logic_covered = set()
    for _name, fn in _logic_functions(spec):
        logic_covered.update(logic_ir.referenced_constants(fn["expr"]))
    for canon in sorted(want):
        sites = got.get(canon)
        if not sites:
            if canon in logic_covered:
                continue
            out.append(f"readback: constant {canon} is in the spec but "
                       f"no plane spells it")
            continue
        for path, val, _line, anchor in sites:
            if not _values_equal(val, want[canon]):
                out.append(f"readback: {canon} = {val!r} at "
                           f"{path}#{anchor} but the spec says "
                           f"{want[canon]!r}")
    for canon in sorted(set(got) - set(want)):
        out.append(f"readback: extracted constant {canon} has no spec "
                   f"entry — add it to {SPEC_RELPATH}")
    want_pairs = set(spec["transitions"]["pairs"])
    want_states = set(spec["transitions"]["states"])
    tables = twin.transition_tables()
    if not tables:
        out.append("readback: no transition tables extracted")
    for path, table in sorted(tables.items()):
        have = {f"{f} -> {t}" for f, t in table["pairs"]}
        for p in sorted(want_pairs - have):
            out.append(f"readback: transition `{p}` is in the spec but "
                       f"not in {path}")
        for p in sorted(have - want_pairs):
            out.append(f"readback: {path} makes transition `{p}` which "
                       f"the spec does not allow")
        if set(table["states"]) != want_states:
            out.append(f"readback: state universe of {path} differs "
                       f"from the spec")
    out.extend(logic_readback_diffs(root, spec))
    return out


def _logic_plane_files() -> Dict[str, List[str]]:
    """plane -> list of relpaths carrying emitted logic functions (from
    the emission table, so the read-back can never drift from what the
    generator emits)."""
    out: Dict[str, List[str]] = {"py": [], "c": [], "kernel": []}
    for path, rname, lead, _ in REGIONS:
        if SURFACE_OF_REGION.get(rname) != "logic":
            continue
        plane = ("c" if lead == C
                 else "kernel" if "/ops/" in path else "py")
        if path not in out[plane]:
            out[plane].append(path)
    return out


def logic_readback_diffs(root: str, spec: Dict) -> List[str]:
    """The expression read-back (ISSUE 19): parse every emitted logic
    function on every plane back to IR and structurally compare against
    the spec.  This is the same comparison SIM206 makes at lint time —
    two independent processes, one meaning."""
    from .cspec import parse_c_logic_functions
    out: List[str] = []
    fns = dict(_logic_functions(spec))
    if not fns:
        return out
    planes: Dict[str, Dict] = {"py": {}, "c": {}, "kernel": {}}
    for plane, paths in _logic_plane_files().items():
        for path in paths:
            try:
                with open(os.path.join(root, path),
                          encoding="utf-8") as f:
                    text = f.read()
            except OSError as e:
                out.append(f"readback: {path}: unreadable: {e}")
                continue
            if plane == "c":
                planes["c"].update(parse_c_logic_functions(text))
            else:
                planes[plane].update(
                    logic_ir.parse_py_functions(text, plane))
    for name in sorted(fns):
        fn = fns[name]
        resolved = _resolved_expr(spec, fn)
        for plane in ("py", "c", "kernel"):
            sym = logic_ir.plane_symbol(name, plane)
            got = planes[plane].get(name)
            if got is None:
                out.append(f"readback: logic fn {name} ({sym}) missing "
                           f"on the {plane} plane — run `make gen`")
                continue
            args, ir, _line = got
            if list(args) != list(fn["args"]):
                out.append(f"readback: {sym} args {list(args)} != spec "
                           f"args {list(fn['args'])}")
            elif ir is None:
                out.append(f"readback: {sym} body is not a single "
                           f"portable-IR expression")
            else:
                d = logic_ir.structural_diff(resolved, ir)
                if d:
                    out.append(f"readback: logic fn {name} drifted on "
                               f"the {plane} plane: {d}")
    return out


def _values_equal(a, b) -> bool:
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(
            _values_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    return a == b


# ---------------------------------------------------------------------------
# tree-level entry points (the API tests/bench use)

def check_tree(root: str, spec: Dict, spec_hash: str,
               readback: bool = True) -> List[str]:
    out: List[str] = []
    for path, defs in sorted(_regions_by_file().items()):
        abspath = os.path.join(root, path)
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            out.append(f"{path}: unreadable: {e}")
            continue
        out.extend(check_text(path, text, defs, spec, spec_hash))
    if readback and not out:
        out.extend(readback_diffs(root, spec))
    return out


def write_tree(root: str, spec: Dict, spec_hash: str
               ) -> Tuple[List[str], List[str]]:
    """Returns (list of 'path:region' written, problems)."""
    written: List[str] = []
    problems: List[str] = []
    for path, defs in sorted(_regions_by_file().items()):
        abspath = os.path.join(root, path)
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            problems.append(f"{path}: unreadable: {e}")
            continue
        new_text, changed, probs = rewrite_text(text, defs, spec, spec_hash)
        problems.extend(f"{path}: {p}" for p in probs)
        if changed:
            with open(abspath, "w", encoding="utf-8") as f:
                f.write(new_text)
            written.extend(f"{path}:{name}" for name in changed)
    return written, problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="simgen",
        description="spec-authoritative protocol codegen (shadow-tpu): "
                    "emit the protocol surfaces of spec/protocol_spec.json "
                    "into fenced regions of the Python/C/kernel planes")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--write", action="store_true",
                      help="materialize every declared region (make gen)")
    mode.add_argument("--check", action="store_true",
                      help="verify regions are current + hand-edit-free "
                           "and the planes read back to the spec's IR "
                           "(make gen-check; the default)")
    mode.add_argument("--list", action="store_true",
                      help="print the emission table and exit")
    ap.add_argument("--spec", default=None,
                    help=f"authoritative spec path (default: "
                         f"{SPEC_RELPATH} under the config root)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: walk up to pyproject.toml)")
    ap.add_argument("--no-readback", action="store_true",
                    help="skip the IR read-back diff (marker checks only)")
    args = ap.parse_args(argv)

    if args.root is None:
        from .simlint import load_config
        args.root = load_config(None, start=".").root
    spec_path = args.spec or os.path.join(args.root, SPEC_RELPATH)
    if not os.path.isfile(spec_path):
        print(f"simgen: no spec at {spec_path}", file=sys.stderr)
        return 2
    try:
        spec, spec_hash = load_spec(spec_path)
    except (ValueError, OSError) as e:
        print(f"simgen: unreadable spec {spec_path}: {e}", file=sys.stderr)
        return 2

    if args.list:
        for path, name, _, _renderer in REGIONS:
            surface = SURFACE_OF_REGION.get(name, "?")
            print(f"{surface:<12} {name:<22} {path}")
        return 0

    if args.write:
        written, problems = write_tree(args.root, spec, spec_hash)
        for p in problems:
            print(f"simgen: {p}", file=sys.stderr)
        for w in written:
            print(f"simgen: wrote {w}")
        print(f"simgen: {len(written)} region(s) updated, "
              f"{len(REGIONS) - len(written)} already current")
        return 1 if problems else 0

    diags = check_tree(args.root, spec, spec_hash,
                       readback=not args.no_readback)
    for d in diags:
        print(d)
    n_surfaces = len({SURFACE_OF_REGION[n] for _, n, _, _ in REGIONS})
    print(f"simgen: {len(diags)} problem(s), {len(REGIONS)} region(s), "
          f"{n_surfaces} surface(s)")
    return 1 if diags else 0


if __name__ == "__main__":
    sys.exit(main())
