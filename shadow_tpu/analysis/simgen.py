"""simgen: spec-authoritative protocol codegen for the three planes.

PR 6 (simtwin) extracted ONE table-driven IR from the three hand-synced
protocol planes and diffed them at lint time; ``spec/protocol.json`` was
the *extracted* seed artifact.  simgen inverts the direction (ROADMAP
item 3): ``spec/protocol_spec.json`` is now AUTHORITATIVE, and the
protocol surfaces it names — the canonical constants, the TCP
state-transition table, the token-bucket/CoDel hop-math coefficients,
and the congestion-control coefficient families — are *emitted* into
fenced, checksummed regions of the Python plane, the native C plane and
the JAX/numpy kernel modules.  A protocol change is now one spec edit +
``make gen``, not three hand-synced transcriptions.

The verification stack, outermost first:

* ``make gen-check`` (== ``simgen --check``, wired into ``make lint``):
  every declared region byte-matches what the generator would emit
  today (stale spec or hand edit both fail), and the *read-back* gate
  re-extracts the planes with simtwin's extractors and diffs the IR
  against the spec — the generated code must mean what the spec says,
  not merely look generated.
* SIM205 (twin_rules): lint-time detection of hand edits inside a
  fenced region (``body=`` digest drift) and of regions older than the
  spec (``spec=`` digest drift), with the shared pragma vocabulary.
* SIM201-204 keep diffing the planes against each other, and
  ``spec/protocol.json`` (the extracted IR) stays checked in and
  byte-stable — regeneration after ``make gen`` is part of the flow.

Usage::

    python -m shadow_tpu.analysis.simgen [--check | --write | --list]
        [--spec PATH] [--root PATH] [--no-readback]

Exit status: 0 = clean, 1 = stale/hand-edited/IR-drift, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

from .genmark import (SPEC_RELPATH, begin_marker, end_marker, scan_regions,
                      sha12)

PY, C = "#", "//"


# ---------------------------------------------------------------------------
# spec loading

def load_spec(path: str) -> Tuple[Dict, str]:
    """(spec dict, sha12 of the exact file bytes)."""
    with open(path, "rb") as f:
        blob = f.read()
    return json.loads(blob.decode("utf-8")), sha12(blob)


def canonical_spec_bytes(spec: Dict) -> bytes:
    return (json.dumps(spec, indent=2, sort_keys=True) + "\n").encode()


# ---------------------------------------------------------------------------
# renderers: spec -> region body lines (indent included where non-zero)

def _pairs(spec: Dict) -> List[Tuple[str, str]]:
    out = []
    for p in spec["transitions"]["pairs"]:
        frm, _, to = p.partition(" -> ")
        out.append((frm, to))
    return out


def _variant_class_name(name: str, base: str) -> str:
    # "cubicx" extending "cubic" -> CubicX
    return base.capitalize() + name[len(base):].upper()


def _r_wire_defs(spec: Dict) -> List[str]:
    c = spec["constants"]
    assert c["MSS"] == c["MTU"] - (c["HDR_TCP"] - 14), \
        "spec MSS must equal MTU - (HDR_TCP - 14)"
    ms = 1000000
    return [
        "# Ethernet/IP framing (reference definitions.h:169-193).",
        f"CONFIG_HEADER_SIZE_UDPIPETH = {c['HDR_UDP']}    "
        "# UDP+IP+ETH header bytes",
        f"CONFIG_HEADER_SIZE_TCPIPETH = {c['HDR_TCP']}    "
        "# TCP+IP+ETH header bytes (with options)",
        f"CONFIG_MTU = {c['MTU']}",
        f"CONFIG_DATAGRAM_MAX_SIZE = {c['DGRAM_MAX']}",
        "CONFIG_TCP_MAX_SEGMENT_SIZE = CONFIG_MTU - "
        f"(CONFIG_HEADER_SIZE_TCPIPETH - 14)  # {c['MSS']}",
        "",
        "# Interface token bucket "
        "(reference network_interface.c:93-95, 207-214).",
        f"INTERFACE_REFILL_INTERVAL_NS = {c['REFILL_INTERVAL_NS']}"
        "        # 1 ms token refill",
        f"INTERFACE_CAPACITY_FACTOR = {c['CAPACITY_FACTOR']}"
        "                   # capacity = refill*factor + MTU",
        "",
        "# TCP buffer caps (reference definitions.h:109-114).",
        f"CONFIG_TCP_WMEM_MAX = {c['WMEM_MAX']}",
        f"CONFIG_TCP_RMEM_MAX = {c['RMEM_MAX']}",
        "",
        "# TCP retransmit-timer bounds, ms "
        "(reference definitions.h:115-131).",
        f"CONFIG_TCP_RTO_INIT_MS = {c['RTO_INIT_NS'] // ms}",
        f"CONFIG_TCP_RTO_MIN_MS = {c['RTO_MIN_NS'] // ms}",
        f"CONFIG_TCP_RTO_MAX_MS = {c['RTO_MAX_NS'] // ms}",
    ]


def _r_clock(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "# One simulated nanosecond is the base unit.",
        "SIM_TIME_NS = 1",
        f"SIM_TIME_US = {c['SIM_TIME_MS'] // 1000}",
        f"SIM_TIME_MS = {c['SIM_TIME_MS']}",
        f"SIM_TIME_SEC = {c['SIM_TIME_SEC']}",
    ]


def _r_tcp_flags(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "# TCP header flag bits (reference tcp.c enum ProtocolTCPFlags).",
        "TCP_NONE = 0",
        f"TCP_RST = {c['FLAG_RST']}",
        f"TCP_SYN = {c['FLAG_SYN']}",
        f"TCP_ACK = {c['FLAG_ACK']}",
        f"TCP_FIN = {c['FLAG_FIN']}",
    ]


def _r_status_bits(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "# Status bits (reference descriptor.h DS_*).",
        "S_NONE = 0",
        f"S_ACTIVE = {c['S_ACTIVE']}",
        f"S_READABLE = {c['S_READABLE']}",
        f"S_WRITABLE = {c['S_WRITABLE']}",
        f"S_CLOSED = {c['S_CLOSED']}",
    ]


def _r_epoll_bits(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"EPOLLIN = 0x{c['EPOLLIN']:03x}",
        f"EPOLLOUT = 0x{c['EPOLLOUT']:03x}",
        f"EPOLLERR = 0x{c['EPOLLERR']:03x}",
        f"EPOLLHUP = 0x{c['EPOLLHUP']:03x}",
    ]


def _r_c_epoll_bits(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "// epoll readiness bits (descriptor/epoll.py) — the C-side",
        "// readiness cache (ISSUE 12) computes revents for epoll-watched",
        "// native sockets with these",
        f"enum {{ EPOLLIN = 0x{c['EPOLLIN']:03x}, "
        f"EPOLLOUT = 0x{c['EPOLLOUT']:03x}, "
        f"EPOLLERR = 0x{c['EPOLLERR']:03x}, "
        f"EPOLLHUP = 0x{c['EPOLLHUP']:03x} }};",
    ]


def _r_port_alloc(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"MIN_EPHEMERAL_PORT = {c['MIN_EPHEMERAL_PORT']}",
        f"MAX_PORT = {c['MAX_PORT']}",
    ]


def _r_threefry(spec: Dict) -> List[str]:
    c = spec["constants"]
    rots = ", ".join(str(r) for r in c["THREEFRY_ROTATIONS"])
    return [
        "# Threefry-2x32 rotation constants (Salmon et al., Table 2).",
        f"_ROTATIONS = ({rots})",
        f"_PARITY = 0x{c['THREEFRY_PARITY']:X}  # SKEIN_KS_PARITY32",
    ]


def _r_tcp_states(spec: Dict) -> List[str]:
    lines = ["# states (reference tcp.c enum TCPState :42-47)"]
    for st in spec["transitions"]["states"]:
        lines.append(f"{st.upper()} = \"{st}\"")
    lines += [
        "",
        "# The spec's legal (from, to) transition pairs; \"?\" = an",
        "# assignment no state guard encloses.",
        "TCP_TRANSITIONS = (",
    ]
    for frm, to in _pairs(spec):
        lines.append(f"    (\"{frm}\", \"{to}\"),")
    lines.append(")")
    return lines


def _r_tcp_timers(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"RTO_INIT_NS = {c['RTO_INIT_NS']}",
        f"RTO_MIN_NS = {c['RTO_MIN_NS']}",
        f"RTO_MAX_NS = {c['RTO_MAX_NS']}",
        f"TIME_WAIT_NS = {c['TIME_WAIT_NS']}"
        "        # 2*MSL teardown hold",
        f"MAX_SYN_RETRIES = {c['MAX_SYN_RETRIES']}"
        "                           # Linux tcp_syn_retries default",
        f"MAX_RETRIES = {c['MAX_RETRIES']}"
        "                              # Linux tcp_retries2",
        f"MAX_SACK_BLOCKS = {c['MAX_SACK_BLOCKS']}",
    ]


def _r_codel_params(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"    TARGET_NS = {c['CODEL_TARGET_NS']}",
        f"    INTERVAL_NS = {c['CODEL_INTERVAL_NS']}",
        f"    HARD_LIMIT = {c['CODEL_HARD_LIMIT']}  # packets",
    ]


def _r_router_static(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"STATIC_CAPACITY = {c['STATIC_CAPACITY']}"
        "  # packets (reference router_queue_static.c)",
    ]


def _r_congestion_params(spec: Dict) -> List[str]:
    c = spec["constants"]
    lines = ["# CUBIC coefficient families (RFC 9438 §4.1 / §4.6)."]
    for name, var in sorted(spec["congestion"]["variants"].items()):
        lines.append(f"{var['c_const']} = {c[var['c_const']]!r}"
                     f"      # {name}: scaling constant")
        lines.append(f"{var['beta_const']} = {c[var['beta_const']]!r}"
                     f"   # {name}: multiplicative decrease")
    return lines


def _r_congestion_variants(spec: Dict) -> List[str]:
    c = spec["constants"]
    lines: List[str] = []
    generated: List[Tuple[str, str]] = []
    for name, var in sorted(spec["congestion"]["variants"].items()):
        base = var.get("base")
        if base is None:
            continue              # the base algorithm is hand-written
        cls = _variant_class_name(name, base)
        generated.append((name, cls))
        lines += [
            f"class {cls}({base.capitalize()}):",
            f"    \"\"\"Spec-defined CUBIC variant {name!r}: "
            f"(C, beta) = ({c[var['c_const']]!r}, "
            f"{c[var['beta_const']]!r}).",
            "",
            f"    Same window-growth machinery as {base.capitalize()} "
            "(the base class reads",
            "    ``self.C``/``self.BETA``); only the coefficients "
            "differ.",
            "    \"\"\"",
            "",
            f"    name = \"{name}\"",
            f"    C = {var['c_const']}",
            f"    BETA = {var['beta_const']}",
            "",
            "",
        ]
    lines.append("# config token -> generated class "
                 "(make_congestion_control consults this)")
    lines.append("CC_GENERATED = {")
    for name, cls in generated:
        lines.append(f"    \"{name}\": {cls},")
    lines.append("}")
    return lines


def _r_token_bucket_kernel(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        f"REFILL_NS = {c['REFILL_INTERVAL_NS']}"
        "   # == defs.INTERFACE_REFILL_INTERVAL_NS (1 ms)",
    ]


def _r_protocol_tables(spec: Dict) -> List[str]:
    c = spec["constants"]
    states = spec["transitions"]["states"]
    lines = [
        "# TCP state universe, reference-enum order; the tuple index IS",
        "# the C-plane TcpState id.",
        "TCP_STATES = (",
    ]
    for st in states:
        lines.append(f"    \"{st}\",")
    lines += [
        ")",
        "",
        "# Legal (from, to) transition pairs; \"?\" = unguarded.",
        "TCP_TRANSITIONS = (",
    ]
    for frm, to in _pairs(spec):
        lines.append(f"    (\"{frm}\", \"{to}\"),")
    lines += [")", "", "# Congestion-control coefficient families "
              "+ config-token kind ids."]
    variants = sorted(spec["congestion"]["variants"].items())
    for name, var in variants:
        lines.append(f"{var['c_const']} = {c[var['c_const']]!r}")
        lines.append(f"{var['beta_const']} = {c[var['beta_const']]!r}")
    kinds = sorted(spec["congestion"]["kinds"].items())
    lines.append("CC_KIND_IDS = {"
                 + ", ".join(f"\"{k}\": {v}" for k, v in kinds) + "}")
    by_kind = {var["kind"]: var for _, var in variants}
    lines.append("# (C, beta) per kind id; non-cubic kinds carry the "
                 "cubic defaults (unused)")
    lines.append("CC_COEFFS = {")
    for k, kid in kinds:
        var = by_kind.get(kid, dict(spec["congestion"]["variants"]["cubic"]))
        lines.append(f"    {kid}: ({var['c_const']}, "
                     f"{var['beta_const']}),  # {k}")
    lines.append("}")
    return lines


def _r_c_constants(spec: Dict) -> List[str]:
    c = spec["constants"]
    return [
        "// ---- constants (mirror core/defs.py / descriptor/tcp.py) "
        "------------------",
        f"constexpr int64_t SIM_MS = {c['SIM_TIME_MS']}LL;",
        f"constexpr int64_t SIM_SEC = {c['SIM_TIME_SEC']}LL;",
        f"constexpr int HDR_UDP = {c['HDR_UDP']};",
        f"constexpr int HDR_TCP = {c['HDR_TCP']};",
        f"constexpr int64_t MTU = {c['MTU']};",
        f"constexpr int64_t MSS = {c['MTU']} - ({c['HDR_TCP']} - 14);"
        f"          // {c['MSS']}",
        f"constexpr int64_t RTO_INIT = {c['RTO_INIT_NS']}LL;",
        f"constexpr int64_t RTO_MIN = {c['RTO_MIN_NS']}LL;",
        f"constexpr int64_t RTO_MAX = {c['RTO_MAX_NS']}LL;",
        f"constexpr int64_t TIME_WAIT_NS = {c['TIME_WAIT_NS']}LL;",
        f"constexpr int MAX_SYN_RETRIES = {c['MAX_SYN_RETRIES']};",
        f"constexpr int MAX_RETRIES = {c['MAX_RETRIES']};"
        "                    // Linux tcp_retries2",
        f"constexpr int MAX_SACK_BLOCKS = {c['MAX_SACK_BLOCKS']};",
        f"constexpr int64_t RMEM_MAX = {c['RMEM_MAX']};",
        f"constexpr int64_t WMEM_MAX = {c['WMEM_MAX']};",
        f"constexpr int64_t REFILL_INTERVAL = {c['REFILL_INTERVAL_NS']}LL;"
        "     // 1 ms",
        f"constexpr int64_t CAPACITY_FACTOR = {c['CAPACITY_FACTOR']};",
        f"constexpr int64_t DGRAM_MAX = {c['DGRAM_MAX']};",
        f"constexpr int64_t CODEL_TARGET = {c['CODEL_TARGET_NS']}LL;",
        f"constexpr int64_t CODEL_INTERVAL = {c['CODEL_INTERVAL_NS']}LL;",
        f"constexpr int CODEL_HARD_LIMIT = {c['CODEL_HARD_LIMIT']};",
        f"constexpr int STATIC_CAPACITY = {c['STATIC_CAPACITY']};",
        "",
        "// descriptor status bits (descriptor/base.py)",
        f"enum {{ S_ACTIVE = {c['S_ACTIVE']}, "
        f"S_READABLE = {c['S_READABLE']}, "
        f"S_WRITABLE = {c['S_WRITABLE']}, S_CLOSED = {c['S_CLOSED']} }};",
        "// TCP header flags (routing/packet.py)",
        f"enum {{ F_RST = {c['FLAG_RST']}, F_SYN = {c['FLAG_SYN']}, "
        f"F_ACK = {c['FLAG_ACK']}, F_FIN = {c['FLAG_FIN']} }};",
    ]


def _chunked(tokens: List[str], per_line: int = 5) -> List[str]:
    return ["  " + ", ".join(tokens[i:i + per_line]) + ","
            for i in range(0, len(tokens), per_line)]


def _r_c_tcp_states(spec: Dict) -> List[str]:
    states = spec["transitions"]["states"]
    lines = ["enum TcpState {"]
    lines += _chunked([f"ST_{s.upper()}" + (" = 0" if i == 0 else "")
                       for i, s in enumerate(states)])
    lines += ["};", "const char *const STATE_NAMES[] = {"]
    lines += _chunked([f"\"{s}\"" for s in states])
    lines += [
        "};",
        "// the spec's legal transition table; 255 = any state ('?')",
        "struct TcpTransition { unsigned char from, to; };",
        "constexpr TcpTransition TCP_TRANSITIONS[] = {",
    ]
    for frm, to in _pairs(spec):
        f_tok = "255" if frm == "?" else f"ST_{frm.upper()}"
        lines.append(f"  {{{f_tok}, ST_{to.upper()}}},")
    lines += [
        "};",
        "constexpr int TCP_TRANSITION_COUNT =",
        "    (int)(sizeof(TCP_TRANSITIONS) / sizeof(TCP_TRANSITIONS[0]));",
    ]
    return lines


def _r_c_congestion_params(spec: Dict) -> List[str]:
    c = spec["constants"]
    kinds = sorted(spec["congestion"]["kinds"].items(), key=lambda kv: kv[1])
    enum_body = ", ".join(f"CC_{k.upper()} = {v}" for k, v in kinds)
    lines = [f"enum CcKind {{ {enum_body} }};",
             "// CUBIC coefficient families (RFC 9438 §4.1 / §4.6)"]
    cubics = [(n, v) for n, v in sorted(spec["congestion"]["variants"]
                                        .items())]
    for name, var in cubics:
        lines.append(f"constexpr double {var['c_const']} = "
                     f"{c[var['c_const']]!r};")
        lines.append(f"constexpr double {var['beta_const']} = "
                     f"{c[var['beta_const']]!r};")
    is_cubic = " || ".join(f"kind == CC_{n.upper()}" for n, _ in cubics)
    lines += [f"inline bool cc_is_cubic(int kind) {{ return {is_cubic}; }}"]
    for field in ("c", "beta"):
        expr = f"CUBIC_{field.upper()}"
        for name, var in cubics:
            if var.get("base") is None:
                continue
            expr = (f"kind == CC_{name.upper()} ? "
                    f"{var[field + '_const']} : " + expr)
        lines.append(f"inline double cc_{field}(int kind) "
                     f"{{ return {expr}; }}")
    return lines


# ---------------------------------------------------------------------------
# the emission table: every declared region, in file order

RegionDef = Tuple[str, str, str, Callable[[Dict], List[str]]]
#             (relpath, region name, comment lead, renderer)

REGIONS: List[RegionDef] = [
    ("shadow_tpu/core/defs.py", "wire-defs", PY, _r_wire_defs),
    ("shadow_tpu/core/stime.py", "clock", PY, _r_clock),
    ("shadow_tpu/routing/packet.py", "tcp-flags", PY, _r_tcp_flags),
    ("shadow_tpu/descriptor/base.py", "status-bits", PY, _r_status_bits),
    ("shadow_tpu/descriptor/epoll.py", "epoll-bits", PY, _r_epoll_bits),
    ("shadow_tpu/host/host.py", "port-alloc", PY, _r_port_alloc),
    ("shadow_tpu/core/rng.py", "threefry", PY, _r_threefry),
    ("shadow_tpu/descriptor/tcp.py", "tcp-states", PY, _r_tcp_states),
    ("shadow_tpu/descriptor/tcp.py", "tcp-timers", PY, _r_tcp_timers),
    ("shadow_tpu/host/router.py", "router-static", PY, _r_router_static),
    ("shadow_tpu/host/router.py", "codel-params", PY, _r_codel_params),
    ("shadow_tpu/descriptor/tcp_cong.py", "congestion-params", PY,
     _r_congestion_params),
    ("shadow_tpu/descriptor/tcp_cong.py", "congestion-variants", PY,
     _r_congestion_variants),
    ("shadow_tpu/ops/bandwidth.py", "token-bucket-kernel", PY,
     _r_token_bucket_kernel),
    ("shadow_tpu/ops/protocol_tables.py", "protocol-tables", PY,
     _r_protocol_tables),
    ("native/dataplane.cc", "c-protocol-constants", C, _r_c_constants),
    ("native/dataplane.cc", "c-epoll-bits", C, _r_c_epoll_bits),
    ("native/dataplane.cc", "c-tcp-states", C, _r_c_tcp_states),
    ("native/dataplane.cc", "c-congestion-params", C,
     _r_c_congestion_params),
]

SURFACE_OF_REGION: Dict[str, str] = {
    "wire-defs": "constants", "clock": "constants",
    "tcp-flags": "constants", "status-bits": "constants",
    "port-alloc": "constants", "threefry": "constants",
    "tcp-timers": "constants", "c-protocol-constants": "constants",
    "epoll-bits": "constants", "c-epoll-bits": "constants",
    "token-bucket-kernel": "hop-math", "router-static": "hop-math",
    "codel-params": "hop-math",
    "tcp-states": "transitions", "c-tcp-states": "transitions",
    "protocol-tables": "transitions",
    "congestion-params": "congestion", "congestion-variants": "congestion",
    "c-congestion-params": "congestion",
}


def render_body(name: str, spec: Dict) -> str:
    for _, rname, _, renderer in REGIONS:
        if rname == name:
            return "".join(ln + "\n" for ln in renderer(spec))
    raise KeyError(f"no renderer for region {name!r}")


# ---------------------------------------------------------------------------
# apply / check

def _regions_by_file() -> Dict[str, List[RegionDef]]:
    out: Dict[str, List[RegionDef]] = {}
    for rd in REGIONS:
        out.setdefault(rd[0], []).append(rd)
    return out


def rewrite_text(text: str, defs: List[RegionDef], spec: Dict,
                 spec_hash: str) -> Tuple[str, List[str], List[str]]:
    """Replace every declared region of one file's text.

    Returns (new_text, changed region names, problems)."""
    regions, scan_problems = scan_regions(text)
    problems = [f"line {ln}: {msg}" for ln, msg in scan_problems]
    by_name = {r.name: r for r in regions}
    lines = text.splitlines()
    changed: List[str] = []
    # replace bottom-up so earlier line numbers stay valid
    def _key(d):
        reg = by_name.get(d[1])
        return -reg.begin_line if reg is not None else 0

    for _, name, lead, renderer in sorted(defs, key=_key):
        reg = by_name.get(name)
        if reg is None:
            problems.append(f"region {name!r}: markers not found")
            continue
        body = "".join(ln + "\n" for ln in renderer(spec))
        bh = sha12(body)
        if reg.body == body and reg.body_hash == bh \
                and reg.spec_hash == spec_hash:
            continue
        changed.append(name)
        new_block = [begin_marker(name, lead, spec_hash, bh, reg.indent)]
        new_block += body.splitlines()
        new_block.append(end_marker(name, lead, reg.indent))
        lines[reg.begin_line - 1:reg.end_line] = new_block
    return "".join(ln + "\n" for ln in lines), changed, problems


def check_text(path: str, text: str, defs: List[RegionDef], spec: Dict,
               spec_hash: str) -> List[str]:
    """Diagnostics for one file (empty = clean)."""
    out: List[str] = []
    regions, scan_problems = scan_regions(text)
    for ln, msg in scan_problems:
        out.append(f"{path}:{ln}: {msg}")
    by_name = {r.name: r for r in regions}
    declared = {d[1] for d in defs}
    for name in sorted(set(by_name) - declared):
        out.append(f"{path}:{by_name[name].begin_line}: region {name!r} "
                   f"is not declared in simgen's emission table")
    for _, name, _, renderer in defs:
        reg = by_name.get(name)
        if reg is None:
            out.append(f"{path}: region {name!r} markers not found — "
                       f"add the fence and run `make gen`")
            continue
        body = "".join(ln + "\n" for ln in renderer(spec))
        if sha12(reg.body) != reg.body_hash:
            out.append(f"{path}:{reg.begin_line}: region {name!r} was "
                       f"edited by hand (body digest drift) — edit "
                       f"{SPEC_RELPATH} instead and run `make gen`")
        elif reg.body != body:
            out.append(f"{path}:{reg.begin_line}: region {name!r} is "
                       f"stale — the spec or the generator changed; "
                       f"run `make gen`")
        elif reg.spec_hash != spec_hash:
            out.append(f"{path}:{reg.begin_line}: region {name!r} was "
                       f"emitted from an older spec "
                       f"(spec={reg.spec_hash}, current={spec_hash}) — "
                       f"run `make gen`")
    return out


# ---------------------------------------------------------------------------
# read-back: the generated planes must extract to the spec's IR

def readback_diffs(root: str, spec: Dict) -> List[str]:
    """Re-extract the planes with simtwin's extractors and diff the IR
    against the authoritative spec (values, transition tables, and the
    congestion coefficient families)."""
    from .simlint import load_config
    from .simtwin import _load_mapped_sources, load_map
    from .twin_rules import TwinModel
    config = load_config(os.path.join(root, "pyproject.toml"))
    surface_map = load_map(None, config)
    sources = _load_mapped_sources(config, surface_map)
    twin = TwinModel(sources, surface_map)
    out: List[str] = []
    want = spec["constants"]
    got = twin.constants_by_canonical()
    for canon in sorted(want):
        sites = got.get(canon)
        if not sites:
            out.append(f"readback: constant {canon} is in the spec but "
                       f"no plane spells it")
            continue
        for path, val, _line, anchor in sites:
            if not _values_equal(val, want[canon]):
                out.append(f"readback: {canon} = {val!r} at "
                           f"{path}#{anchor} but the spec says "
                           f"{want[canon]!r}")
    for canon in sorted(set(got) - set(want)):
        out.append(f"readback: extracted constant {canon} has no spec "
                   f"entry — add it to {SPEC_RELPATH}")
    want_pairs = set(spec["transitions"]["pairs"])
    want_states = set(spec["transitions"]["states"])
    tables = twin.transition_tables()
    if not tables:
        out.append("readback: no transition tables extracted")
    for path, table in sorted(tables.items()):
        have = {f"{f} -> {t}" for f, t in table["pairs"]}
        for p in sorted(want_pairs - have):
            out.append(f"readback: transition `{p}` is in the spec but "
                       f"not in {path}")
        for p in sorted(have - want_pairs):
            out.append(f"readback: {path} makes transition `{p}` which "
                       f"the spec does not allow")
        if set(table["states"]) != want_states:
            out.append(f"readback: state universe of {path} differs "
                       f"from the spec")
    return out


def _values_equal(a, b) -> bool:
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(
            _values_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    return a == b


# ---------------------------------------------------------------------------
# tree-level entry points (the API tests/bench use)

def check_tree(root: str, spec: Dict, spec_hash: str,
               readback: bool = True) -> List[str]:
    out: List[str] = []
    for path, defs in sorted(_regions_by_file().items()):
        abspath = os.path.join(root, path)
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            out.append(f"{path}: unreadable: {e}")
            continue
        out.extend(check_text(path, text, defs, spec, spec_hash))
    if readback and not out:
        out.extend(readback_diffs(root, spec))
    return out


def write_tree(root: str, spec: Dict, spec_hash: str
               ) -> Tuple[List[str], List[str]]:
    """Returns (list of 'path:region' written, problems)."""
    written: List[str] = []
    problems: List[str] = []
    for path, defs in sorted(_regions_by_file().items()):
        abspath = os.path.join(root, path)
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            problems.append(f"{path}: unreadable: {e}")
            continue
        new_text, changed, probs = rewrite_text(text, defs, spec, spec_hash)
        problems.extend(f"{path}: {p}" for p in probs)
        if changed:
            with open(abspath, "w", encoding="utf-8") as f:
                f.write(new_text)
            written.extend(f"{path}:{name}" for name in changed)
    return written, problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="simgen",
        description="spec-authoritative protocol codegen (shadow-tpu): "
                    "emit the protocol surfaces of spec/protocol_spec.json "
                    "into fenced regions of the Python/C/kernel planes")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--write", action="store_true",
                      help="materialize every declared region (make gen)")
    mode.add_argument("--check", action="store_true",
                      help="verify regions are current + hand-edit-free "
                           "and the planes read back to the spec's IR "
                           "(make gen-check; the default)")
    mode.add_argument("--list", action="store_true",
                      help="print the emission table and exit")
    ap.add_argument("--spec", default=None,
                    help=f"authoritative spec path (default: "
                         f"{SPEC_RELPATH} under the config root)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: walk up to pyproject.toml)")
    ap.add_argument("--no-readback", action="store_true",
                    help="skip the IR read-back diff (marker checks only)")
    args = ap.parse_args(argv)

    if args.root is None:
        from .simlint import load_config
        args.root = load_config(None, start=".").root
    spec_path = args.spec or os.path.join(args.root, SPEC_RELPATH)
    if not os.path.isfile(spec_path):
        print(f"simgen: no spec at {spec_path}", file=sys.stderr)
        return 2
    try:
        spec, spec_hash = load_spec(spec_path)
    except (ValueError, OSError) as e:
        print(f"simgen: unreadable spec {spec_path}: {e}", file=sys.stderr)
        return 2

    if args.list:
        for path, name, _, _renderer in REGIONS:
            surface = SURFACE_OF_REGION.get(name, "?")
            print(f"{surface:<12} {name:<22} {path}")
        return 0

    if args.write:
        written, problems = write_tree(args.root, spec, spec_hash)
        for p in problems:
            print(f"simgen: {p}", file=sys.stderr)
        for w in written:
            print(f"simgen: wrote {w}")
        print(f"simgen: {len(written)} region(s) updated, "
              f"{len(REGIONS) - len(written)} already current")
        return 1 if problems else 0

    diags = check_tree(args.root, spec, spec_hash,
                       readback=not args.no_readback)
    for d in diags:
        print(d)
    n_surfaces = len({SURFACE_OF_REGION[n] for _, n, _, _ in REGIONS})
    print(f"simgen: {len(diags)} problem(s), {len(REGIONS)} region(s), "
          f"{n_surfaces} surface(s)")
    return 1 if diags else 0


if __name__ == "__main__":
    sys.exit(main())
