"""cspec: a lightweight protocol-spec extractor for the C data plane.

The native twin (``native/dataplane.cc``, ``native/retransmit_tally.cc``)
is a hand transcription of the Python protocol modules; simtwin diffs the
two (plus the JAX kernel family) against ONE extracted IR.  This module is
the C side of that extraction: regex + brace matching only — no libclang,
no compiler, nothing the container doesn't already have — tuned to the
subset of C++ the data plane actually uses.

What it pulls out of a translation unit:

* **constants** — ``constexpr T NAME = EXPR;`` / ``#define NAME EXPR`` /
  ``const int NAME[n] = {...};`` with the expressions *evaluated* (suffix-
  stripped and folded through the same arithmetic evaluator the Python
  extractor uses), so ``RTO_INIT = 1000 * SIM_MS`` compares as the integer
  nanosecond value, not as a token string;
* **enums** — named and anonymous, implicit-increment members evaluated;
  an enum whose members are ``ST_*`` is the TCP state universe;
* **functions / structs** — every defined symbol, for the SIM203 surface
  map;
* **state transitions** — each ``...->state = ST_X`` assignment paired
  with the states named by its *enclosing* ``if`` guards (conditions are
  attributed to their if-block or single guarded statement only — never to
  an ``else`` body), mirroring the Python AST walk in twin_rules so a
  faithful transcription produces the identical (from, to) table;
* **probes** — per-canonical regex probes for update coefficients that are
  spelled inline (RTT gains, ssthresh math, CUBIC C/beta, thresholds);
* **pragmas** — ``// simtwin: disable=SIM2xx -- why`` suppression comments
  with the same reason-required / stale-is-a-finding semantics as the
  Python pragma machinery.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# expression folding (shared shape with twin_rules._fold: C constant
# expressions in this codebase are valid Python arithmetic once the integer
# suffixes and casts are stripped)

# the whole numeric literal is matched (hex digits greedily — a trailing
# F in 0xFF is a DIGIT, not a float suffix; hex ints take no f suffix in
# C) and only the real type-suffix tail is stripped
_NUM_SUFFIX_RE = re.compile(
    r"\b(0[xX][0-9a-fA-F]+|(?:\d+\.\d*|\.\d+|\d+))[uUlLfF]*")
_CAST_RE = re.compile(r"\(\s*(?:u?int(?:8|16|32|64)_t|double|float|int|long"
                      r"|unsigned|size_t|char)\s*\)")


def eval_c_expr(expr: str, env: Dict[str, object]) -> Optional[object]:
    """Evaluate a C constant expression with ``env`` providing previously
    defined constant values.  Returns None when it doesn't fold."""
    text = _CAST_RE.sub(
        "", _NUM_SUFFIX_RE.sub(lambda m: m.group(1), expr)).strip()
    try:
        tree = ast.parse(text, mode="eval")
    except SyntaxError:
        return None
    return _fold_pyast(tree.body, env)


def _fold_pyast(node: ast.AST, env: Dict[str, object]) -> Optional[object]:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _fold_pyast(node.operand, env)
        return -v if isinstance(v, (int, float)) else None
    if isinstance(node, ast.BinOp):
        a = _fold_pyast(node.left, env)
        b = _fold_pyast(node.right, env)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            return None
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.Div):
                # C integer division truncates; both operands int => int
                if isinstance(a, int) and isinstance(b, int):
                    return a // b
                return a / b
            if isinstance(node.op, ast.FloorDiv):
                return a // b
            if isinstance(node.op, ast.Mod):
                return a % b
            if isinstance(node.op, ast.LShift):
                return a << b
            if isinstance(node.op, ast.RShift):
                return a >> b
            if isinstance(node.op, ast.BitOr):
                return a | b
            if isinstance(node.op, ast.BitAnd):
                return a & b
            if isinstance(node.op, ast.BitXor):
                return a ^ b
        except (ZeroDivisionError, TypeError, ValueError):
            return None
    return None


# ---------------------------------------------------------------------------
# comment stripping (line numbers preserved) + pragma collection

_PRAGMA_RE = re.compile(
    r"//\s*sim(?:lint|race|twin):\s*disable=([A-Za-z0-9_,\s]*?)"
    r"\s*(?:--\s*(.*))?$")


@dataclass
class CPragma:
    rule: str
    reason: str
    target: int      # line the pragma covers
    line: int
    col: int
    used: bool = False


def strip_comments(text: str) -> Tuple[str, List[Tuple[int, int, str]]]:
    """Blank out // and /* */ comments (and string/char literals) while
    preserving every newline, so downstream regex line numbers are real.
    Returns (stripped_text, [(line, col, comment_text)] for // comments)."""
    out: List[str] = []
    comments: List[Tuple[int, int, str]] = []
    i, n = 0, len(text)
    line, col = 1, 0
    while i < n:
        c = text[i]
        two = text[i:i + 2]
        if two == "//":
            j = text.find("\n", i)
            j = n if j < 0 else j
            comments.append((line, col, text[i:j]))
            out.append(" " * (j - i))
            col += j - i
            i = j
            continue
        if two == "/*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            seg = text[i:j]
            out.append(re.sub(r"[^\n]", " ", seg))
            line += seg.count("\n")
            nl = seg.rfind("\n")
            col = (len(seg) - nl - 1) if nl >= 0 else col + len(seg)
            i = j
            continue
        if c in "\"'":
            quote = c
            j = i + 1
            while j < n:
                if text[j] == "\\":
                    j += 2
                    continue
                if text[j] == quote:
                    j += 1
                    break
                if text[j] == "\n":
                    break
                j += 1
            out.append(quote + " " * (j - i - 2 if j - i >= 2 else 0)
                       + (quote if j > i + 1 else ""))
            col += j - i
            i = j
            continue
        out.append(c)
        if c == "\n":
            line += 1
            col = 0
        else:
            col += 1
        i += 1
    return "".join(out), comments


def collect_c_pragmas(text: str, known_ids: Set[str]
                      ) -> Tuple[List[CPragma], List[Tuple[int, int, str]]]:
    """(pragmas, malformed) from // comments.  ``malformed`` entries are
    (line, col, message) — the caller turns them into SIM000 findings.
    A comment-only line covers the NEXT line; a trailing comment covers
    its own line (same convention as the Python tokenizer path)."""
    _, comments = strip_comments(text)
    lines = text.splitlines()
    pragmas: List[CPragma] = []
    bad: List[Tuple[int, int, str]] = []
    for ln, col, ctext in comments:
        m = _PRAGMA_RE.search(ctext)
        if not m:
            continue
        ids = [s.strip().upper() for s in m.group(1).split(",") if s.strip()]
        reason = (m.group(2) or "").strip()
        pcol = col + m.start()
        if not ids:
            bad.append((ln, pcol, "suppression pragma names no rule ids"))
            continue
        unknown = [r for r in ids if r not in known_ids]
        if unknown:
            bad.append((ln, pcol, "suppression pragma names unknown rule(s) "
                        + ", ".join(unknown)))
        if not reason:
            bad.append((ln, pcol, "suppression pragma is missing its reason "
                        "— justify it: // simtwin: disable="
                        f"{','.join(ids)} -- <why>"))
            continue
        standalone = (ln <= len(lines)
                      and not lines[ln - 1][:col].strip())
        target = ln + 1 if standalone else ln
        for rid in ids:
            if rid in known_ids:
                pragmas.append(CPragma(rid, reason, target, ln, pcol))
    return pragmas, bad


# ---------------------------------------------------------------------------
# the extraction result

@dataclass
class CExtract:
    path: str
    constants: Dict[str, Tuple[object, int]] = field(default_factory=dict)
    enums: Dict[str, List[Tuple[str, int, int]]] = field(default_factory=dict)
    symbols: Dict[str, int] = field(default_factory=dict)
    transitions: List[Tuple[str, str, int]] = field(default_factory=list)
    probes: Dict[str, Tuple[object, int]] = field(default_factory=dict)
    states: List[str] = field(default_factory=list)

    def env(self) -> Dict[str, object]:
        e = {k: v for k, (v, _) in self.constants.items()}
        for members in self.enums.values():
            for name, val, _ in members:
                e[name] = val
        return e


_CONSTEXPR_RE = re.compile(
    r"^\s*(?:static\s+)?constexpr\s+[\w:<>\s]+?\b([A-Za-z_]\w*\s*=\s*"
    r"[^;{]+);", re.M)
_DEFINE_RE = re.compile(r"^\s*#\s*define\s+([A-Za-z_]\w*)\s+(.+?)\s*$", re.M)
# arrays: `const int X[8] = {...};` and the constexpr spelling the
# simgen-generated regions use; bodies may span lines ([^}]* crosses \n)
_ARRAY_RE = re.compile(
    r"^\s*(?:static\s+)?const(?:expr)?\s+[\w\s]+?\b([A-Za-z_]\w*)"
    r"\s*\[\s*\d*\s*\]\s*=\s*\{([^}]*)\}\s*;", re.M)
_ENUM_RE = re.compile(r"\benum\s+([A-Za-z_]\w*)?\s*\{([^}]*)\}", re.S)
_FUNC_RE = re.compile(
    r"^[ \t]*(?:[A-Za-z_][\w:<>,*&\s]*?[\s*&])?([A-Za-z_]\w*)\s*"
    r"\(([^;{}]*)\)\s*(?:const\s*)?\{", re.M)
_STRUCT_RE = re.compile(r"^\s*struct\s+([A-Za-z_]\w*)\s*[:{]", re.M)

_KEYWORDS = {"if", "else", "for", "while", "switch", "return", "sizeof",
             "do", "case", "new", "delete", "catch"}


def _split_toplevel_commas(text: str) -> List[str]:
    out, depth, start = [], 0, 0
    for i, c in enumerate(text):
        if c in "([":
            depth += 1
        elif c in ")]":
            depth -= 1
        elif c == "," and depth == 0:
            out.append(text[start:i])
            start = i + 1
    out.append(text[start:])
    return out


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def extract(path: str, text: str,
            probe_patterns: Optional[Dict[str, object]] = None) -> CExtract:
    """Run the whole extraction over one C/C++ source file."""
    stripped, _ = strip_comments(text)
    out = CExtract(path)
    env: Dict[str, object] = {}

    # declarations are folded in TEXTUAL order (a constexpr may reference
    # an earlier #define and vice versa), and line attribution uses the
    # NAME group's position — the leading ``^\s*`` can swallow preceding
    # blank/blanked-comment lines across newlines, which made constants
    # drift to the line of whatever sat above them (e.g. a generated
    # fenced region's marker) whenever that region changed length
    decls: List[Tuple[int, str, "re.Match"]] = []
    for m in _CONSTEXPR_RE.finditer(stripped):
        decls.append((m.start(1), "constexpr", m))
    for m in _DEFINE_RE.finditer(stripped):
        decls.append((m.start(1), "define", m))
    for m in _ARRAY_RE.finditer(stripped):
        decls.append((m.start(1), "array", m))
    for pos, kind, m in sorted(decls, key=lambda d: d[0]):
        line = _line_of(stripped, pos)
        if kind == "constexpr":
            # one declaration may bind several names:
            # `constexpr int A = 1, B = 2;`
            for decl in _split_toplevel_commas(m.group(1)):
                name, _, expr = decl.partition("=")
                name = name.strip()
                if not name or not expr:
                    continue
                val = eval_c_expr(expr, env)
                if val is not None:
                    env[name] = val
                    out.constants[name] = (val, line)
        elif kind == "define":
            name, expr = m.group(1), m.group(2)
            val = eval_c_expr(expr, env)
            if val is not None:
                env[name] = val
                out.constants[name] = (val, line)
        else:
            name, body = m.group(1), m.group(2)
            vals = []
            for item in body.split(","):
                item = item.strip()
                if not item:
                    continue           # trailing comma / blank item
                v = eval_c_expr(item, env)
                if v is None:
                    vals = None
                    break
                vals.append(v)
            if vals:
                env[name] = vals
                out.constants[name] = (vals, line)

    for m in _ENUM_RE.finditer(stripped):
        ename = m.group(1) or ""
        members: List[Tuple[str, int, int]] = []
        nxt = 0
        base_line = _line_of(stripped, m.start())
        for item in m.group(2).split(","):
            item = item.strip()
            if not item:
                continue
            if "=" in item:
                name, _, expr = item.partition("=")
                name = name.strip()
                v = eval_c_expr(expr.strip(), env)
                if v is None:
                    continue
                nxt = int(v)
            else:
                name = item
            members.append((name, nxt, base_line))
            env[name] = nxt
            nxt += 1
        if members:
            out.enums[ename or f"@{base_line}"] = members
            # the TCP state universe: an enum of ST_* members
            if all(n.startswith("ST_") for n, _, _ in members):
                out.states = [n[3:].lower() for n, _, _ in members]

    for m in _STRUCT_RE.finditer(stripped):
        out.symbols.setdefault(m.group(1), _line_of(stripped, m.start()))
    for m in _FUNC_RE.finditer(stripped):
        name = m.group(1)
        if name in _KEYWORDS:
            continue
        out.symbols.setdefault(name, _line_of(stripped, m.start()))

    out.transitions = _extract_transitions(stripped)

    for canon, pattern in (probe_patterns or {}).items():
        hit = _run_probe(stripped, pattern, env)
        if hit is not None:
            out.probes[canon] = hit
    return out


def _run_probe(stripped: str, pattern, env) -> Optional[Tuple[object, int]]:
    """A probe is (regex, combine) — regex capture groups are evaluated
    through ``env``; ``combine`` folds all matches into one value:
    'one' / 'pair' (all matches must agree; a disagreement returns the
    list of distinct spellings so the comparator sees UNEQUAL values and
    reports drift, instead of the canon silently vanishing from this
    plane), 'max', 'set' (sorted uniques)."""
    regex, combine = pattern
    vals: List[object] = []
    first_line = None
    for m in re.finditer(regex, stripped):
        if first_line is None:
            first_line = _line_of(stripped, m.start())
        groups = [eval_c_expr(g, env) for g in m.groups() if g is not None]
        if any(g is None for g in groups):
            return None
        vals.append(groups[0] if len(groups) == 1 else groups)
    if not vals:
        return None
    if combine in ("one", "pair"):
        if len(set(map(repr, vals))) == 1:
            return (vals[0], first_line)
        distinct: List[object] = []
        for v in vals:                 # text order — deterministic
            if v not in distinct:
                distinct.append(v)
        return (distinct, first_line)
    if combine == "max":
        return (max(vals), first_line)
    if combine == "set":
        return (sorted(set(vals)), first_line)
    return None


# ---------------------------------------------------------------------------
# logic-function read-back (ISSUE 19): parse the generator's
# ``static inline int64_t gen_<name>(...) { return <expr>; }`` bodies
# back into logic IR so SIM206 / simgen's readback can structurally
# compare them against the spec.  Comments inside the expression are
# blanked by strip_comments first, so a comment-split expression parses
# the same as a one-liner; identity casts like ``(int64_t)`` are
# stripped (every IR value is int64 by contract).

_LOGIC_FN_RE = re.compile(
    r"static\s+inline\s+int64_t\s+gen_([A-Za-z_]\w*)\s*\(([^)]*)\)\s*"
    r"\{\s*return\s+(.*?);\s*\}", re.S)
# the two call-shaped min/max helpers the emitter leans on — they match
# the function regex but are vocabulary, not logic functions
_LOGIC_HELPERS = {"i64_min", "i64_max"}

_C_TOK_RE = re.compile(
    r"\s*(?:(?P<num>0[xX][0-9a-fA-F]+[uUlL]*|\d+[uUlL]*)"
    r"|(?P<name>[A-Za-z_]\w*)"
    r"|(?P<op><<|>>|<=|>=|==|!=|[-+*/%<>?:(),]))")

_C_CMP_OPS = {"==": "eq", "!=": "ne", "<": "lt", "<=": "le",
              ">": "gt", ">=": "ge"}
_C_MUL_OPS = {"*": "mul", "/": "floordiv", "%": "mod"}
_C_ADD_OPS = {"+": "add", "-": "sub"}
_C_SHIFT_OPS = {"<<": "shl", ">>": "shr"}


class CExprError(ValueError):
    pass


def _c_tokens(text: str) -> List[str]:
    toks: List[str] = []
    pos = 0
    while pos < len(text):
        m = _C_TOK_RE.match(text, pos)
        if not m or m.end() == pos:
            rest = text[pos:].strip()
            if not rest:
                break
            raise CExprError(f"unexpected token at {rest[:20]!r}")
        pos = m.end()
        toks.append(m.group("num") or m.group("name") or m.group("op"))
    return toks


class _CExprParser:
    """Recursive descent over the emitted C expression subset, with real
    C precedence (mul > add > shift > relational > equality > ternary) so
    hand-edited spellings still parse to the tree they mean."""

    def __init__(self, toks: List[str]):
        self.toks = toks
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def take(self, want: Optional[str] = None) -> str:
        tok = self.peek()
        if tok is None:
            raise CExprError("unexpected end of expression")
        if want is not None and tok != want:
            raise CExprError(f"expected {want!r}, got {tok!r}")
        self.i += 1
        return tok

    def parse(self):
        ir = self.ternary()
        if self.peek() is not None:
            raise CExprError(f"trailing tokens at {self.peek()!r}")
        return ir

    def ternary(self):
        cond = self.equality()
        if self.peek() != "?":
            return cond
        self.take("?")
        t = self.ternary()
        self.take(":")
        f = self.ternary()
        if not (isinstance(cond, list) and cond[0] in _C_CMP_OPS.values()):
            raise CExprError("ternary condition must be a comparison")
        return ["select", cond, t, f]

    def _binchain(self, ops: Dict[str, str], sub):
        ir = sub()
        while self.peek() in ops:
            op = ops[self.take()]
            ir = [op, ir, sub()]
        return ir

    def equality(self):
        return self._binchain({"==": "eq", "!=": "ne"}, self.relational)

    def relational(self):
        return self._binchain({"<": "lt", "<=": "le", ">": "gt",
                               ">=": "ge"}, self.shift)

    def shift(self):
        return self._binchain(_C_SHIFT_OPS, self.additive)

    def additive(self):
        return self._binchain(_C_ADD_OPS, self.multiplicative)

    def multiplicative(self):
        return self._binchain(_C_MUL_OPS, self.primary)

    def primary(self):
        tok = self.take()
        if tok == "(":
            ir = self.ternary()
            self.take(")")
            return ir
        if re.fullmatch(r"0[xX][0-9a-fA-F]+[uUlL]*|\d+[uUlL]*", tok):
            return int(tok.rstrip("uUlL"), 0)
        if not re.fullmatch(r"[A-Za-z_]\w*", tok):
            raise CExprError(f"unexpected token {tok!r}")
        if self.peek() != "(":
            return tok                     # argument reference
        self.take("(")
        args = [self.ternary()]
        while self.peek() == ",":
            self.take(",")
            args.append(self.ternary())
        self.take(")")
        if tok in ("gen_i64_min", "gen_i64_max") and len(args) == 2:
            return [tok[len("gen_i64_"):], args[0], args[1]]
        raise CExprError(f"unsupported call {tok!r}")


def parse_c_expr(text: str):
    """One C expression -> logic IR.  Raises :class:`CExprError` when the
    spelling falls outside the portable vocabulary."""
    return _CExprParser(_c_tokens(_CAST_RE.sub("", text))).parse()


def parse_c_logic_functions(text: str
                            ) -> Dict[str, Tuple[List[str], object, int]]:
    """Extract every emitted logic function from a C translation unit:
    ``{logic_name: (arg_names, ir_or_None, lineno)}`` — the same shape as
    :func:`logic_ir.parse_py_functions`, with ``ir=None`` for a body the
    expression parser can't read (a finding, not a crash)."""
    stripped, _ = strip_comments(text)
    out: Dict[str, Tuple[List[str], object, int]] = {}
    for m in _LOGIC_FN_RE.finditer(stripped):
        name = m.group(1)
        if name in _LOGIC_HELPERS:
            continue
        args: List[str] = []
        for param in m.group(2).split(","):
            words = re.findall(r"[A-Za-z_]\w*", param)
            if words:
                args.append(words[-1])
        try:
            ir = parse_c_expr(m.group(3))
        except CExprError:
            ir = None
        out[name] = (args, ir, _line_of(stripped, m.start()))
    return out


# ---------------------------------------------------------------------------
# transition extraction: ...->state = ST_X under enclosing if-guards

_TOK_RE = re.compile(
    r"\b(?P<kw>if|else|for|while|switch)\b"
    r"|(?P<assign>(?:->|\.)\s*state\s*=(?!=)\s*(?P<target>ST_[A-Za-z0-9_]+))"
    r"|(?P<open>\{)|(?P<close>\})|(?P<semi>;)|(?P<lp>\()|(?P<rp>\))")
_GUARD_STATE_RE = re.compile(r"state\s*==\s*ST_([A-Za-z0-9_]+)")


def _extract_transitions(stripped: str) -> List[Tuple[str, str, int]]:
    """(from_state|'?', to_state, line) for every state assignment.  The
    from-set is the union of states named positively (``== ST_X``) by the
    enclosing if-conditions; an unguarded assignment records '?'."""
    transitions: List[Tuple[str, str, int]] = []
    # frames: (kind 'block'|'stmt', guard frozenset)
    stack: List[Tuple[str, frozenset]] = []
    pending: Optional[frozenset] = None
    paren_depth = 0
    pos = 0
    n = len(stripped)
    while pos < n:
        m = _TOK_RE.search(stripped, pos)
        if not m:
            break
        pos = m.end()
        if m.group("lp"):
            paren_depth += 1
            continue
        if m.group("rp"):
            paren_depth = max(0, paren_depth - 1)
            continue
        if paren_depth > 0 and not m.group("assign"):
            continue
        kw = m.group("kw")
        if kw == "if":
            # parse the balanced condition
            i = stripped.find("(", m.end())
            if i < 0:
                continue
            depth, j = 1, i + 1
            while j < n and depth:
                if stripped[j] == "(":
                    depth += 1
                elif stripped[j] == ")":
                    depth -= 1
                j += 1
            cond = stripped[i + 1:j - 1]
            guards = frozenset(g.lower()
                               for g in _GUARD_STATE_RE.findall(cond))
            # block or single guarded statement?
            k = j
            while k < n and stripped[k].isspace():
                k += 1
            if k < n and stripped[k] == "{":
                pending = guards          # consumed by the '{'
            else:
                stack.append(("stmt", guards))
            pos = j
            continue
        if kw == "else":
            k = m.end()
            while k < n and stripped[k].isspace():
                k += 1
            if stripped.startswith("if", k):
                continue                  # else-if: the if takes over
            if k < n and stripped[k] == "{":
                pending = frozenset()     # braced else: empty guard
            else:
                stack.append(("stmt", frozenset()))
            continue
        if kw in ("for", "while", "switch"):
            continue                      # their '(' / '{' handled generically
        if m.group("open"):
            stack.append(("block", pending if pending is not None
                          else frozenset()))
            pending = None
            continue
        if m.group("close"):
            while stack and stack[-1][0] == "stmt":
                stack.pop()
            if stack:
                stack.pop()
            continue
        if m.group("semi"):
            while stack and stack[-1][0] == "stmt":
                stack.pop()
            continue
        if m.group("assign"):
            target = m.group("target")[3:].lower()
            guards: Set[str] = set()
            for _, g in stack:
                guards |= g
            line = _line_of(stripped, m.start())
            if guards:
                for g in sorted(guards):
                    transitions.append((g, target, line))
            else:
                transitions.append(("?", target, line))
    return transitions
